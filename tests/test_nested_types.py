"""Device STRUCT/MAP columns + higher-order array functions
(columnar/nested.py, ops/nested.py — reference: complexTypeCreator.scala,
higherOrderFunctions.scala, collectionOperations.scala map family).

Every test compares the device path against the CPU oracle, including
nested null propagation."""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.session import TpuSession


@pytest.fixture(scope="module")
def tpu():
    return TpuSession()


@pytest.fixture(scope="module")
def cpu():
    return TpuSession({"spark.rapids.sql.enabled": "false"})


def _data(n=200, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "a": rng.integers(-50, 50, n).astype(np.int64),
        "b": rng.random(n),
        "c": rng.integers(0, 5, n).astype(np.int32),
    }


def _check(tpu, cpu, make, data=None):
    data = data or _data()
    got = make(tpu.create_dataframe(data)).collect()
    want = make(cpu.create_dataframe(data)).collect()
    assert repr(got) == repr(want), f"\n tpu={got[:4]}\n cpu={want[:4]}"
    return got


# -- struct ------------------------------------------------------------------

def test_struct_scan_roundtrip(tpu, cpu):
    st = T.StructType([T.StructField("x", T.LONG),
                       T.StructField("y", T.DOUBLE)])
    vals = [(1, 2.5), None, (3, None), (-7, 0.0)]
    for s in (tpu, cpu):
        got = s.create_dataframe({"s": vals}, dtypes={"s": st}).collect()
        assert [r[0] for r in got] == vals


def test_create_struct_and_get_field(tpu, cpu):
    _check(tpu, cpu, lambda df: df.select(
        F.struct(col("a"), col("b"), names=["x", "y"]).alias("s")))
    _check(tpu, cpu, lambda df: df.select(
        F.get_field(F.struct(col("a"), col("b"), names=["x", "y"]),
                    "x").alias("v")))
    # field access null propagation: null struct row -> null field
    st = T.StructType([T.StructField("x", T.LONG)])
    for s in (tpu, cpu):
        got = s.create_dataframe(
            {"s": [(5,), None, (None,)]}, dtypes={"s": st}).select(
            F.get_field(col("s"), "x").alias("v")).collect()
        assert [r[0] for r in got] == [5, None, None]


def test_struct_field_in_filter_predicate(tpu, cpu):
    _check(tpu, cpu, lambda df: df.select(
        F.struct(col("a"), col("c"), names=["x", "y"]).alias("s"),
        col("a"))
        .filter(F.get_field(col("s"), "x") > lit(0))
        .select(col("a")))


def test_named_struct(tpu, cpu):
    _check(tpu, cpu, lambda df: df.select(
        F.named_struct("p", col("a"), "q", col("c")).alias("s")))


# -- map ---------------------------------------------------------------------

def test_map_scan_roundtrip(tpu, cpu):
    mt = T.MapType(key_type=T.LONG, value_type=T.DOUBLE)
    vals = [{1: 2.0, 3: None}, None, {}, {9: -1.5}]
    for s in (tpu, cpu):
        got = s.create_dataframe({"m": vals}, dtypes={"m": mt}).collect()
        assert [r[0] for r in got] == vals


def test_create_map_keys_values(tpu, cpu):
    _check(tpu, cpu, lambda df: df.select(
        F.create_map(col("a"), col("b")).alias("m")))
    _check(tpu, cpu, lambda df: df.select(
        F.map_keys(F.create_map(col("a"), col("b"),
                                col("a") + lit(100), col("b"))).alias("k")))
    _check(tpu, cpu, lambda df: df.select(
        F.map_values(F.create_map(col("a"), col("b"))).alias("v")))


def test_get_map_value(tpu, cpu):
    _check(tpu, cpu, lambda df: df.select(F.get_map_value(
        F.create_map(col("a"), col("b"), col("a") + lit(1),
                     col("b") + lit(1.0)),
        col("a") + lit(1)).alias("v")))
    # missing key -> null
    _check(tpu, cpu, lambda df: df.select(F.get_map_value(
        F.create_map(col("a"), col("b")), col("a") + lit(999)).alias("v")))


def test_map_concat_last_win(tpu, cpu):
    _check(tpu, cpu, lambda df: df.select(F.map_concat(
        F.create_map(col("a"), col("b")),
        F.create_map(col("a"), col("b") + lit(10.0)),  # same key: last wins
        F.create_map(col("a") + lit(1), col("b"))).alias("m")))


def test_map_entries_cpu_fallback(tpu, cpu):
    got = _check(tpu, cpu, lambda df: df.select(F.map_entries(
        F.create_map(col("a"), col("b"))).alias("e")))
    assert isinstance(got[0][0], list)


# -- higher-order functions --------------------------------------------------

def test_transform_with_outer_ref(tpu, cpu):
    _check(tpu, cpu, lambda df: df.select(F.transform(
        F.array(col("a"), col("a") + lit(1), col("c").cast("bigint")),
        lambda x: x * lit(2) + col("a")).alias("t")))


def test_transform_with_index(tpu, cpu):
    _check(tpu, cpu, lambda df: df.select(F.transform(
        F.array(col("a"), col("a") * lit(3)),
        lambda x, i: x + i).alias("t")))


def test_transform_null_elements(tpu, cpu):
    at = T.ArrayType(T.LONG)
    data = {"arr": [[1, None, 3], None, [], [None]]}
    for s in (tpu, cpu):
        got = s.create_dataframe(data, dtypes={"arr": at}).select(
            F.transform(col("arr"), lambda x: x + lit(10)).alias("t")
        ).collect()
        assert [r[0] for r in got] == [[11, None, 13], None, [], [None]]


def test_filter_array(tpu, cpu):
    _check(tpu, cpu, lambda df: df.select(F.filter_array(
        F.array(col("a"), col("a") + lit(1), col("a") + lit(2)),
        lambda x: x % lit(2) == lit(0)).alias("t")))


def test_exists_forall_three_valued(tpu, cpu):
    at = T.ArrayType(T.LONG)
    data = {"arr": [[1, 2], [None, 2], [None, 5], [], None, [7]]}
    for s in (tpu, cpu):
        got = s.create_dataframe(data, dtypes={"arr": at}).select(
            F.exists(col("arr"), lambda x: x == lit(2)).alias("e"),
            F.forall(col("arr"), lambda x: x > lit(0)).alias("f"),
        ).collect()
        # exists: [T, T, null, F, null-row, F]
        assert [r[0] for r in got] == [True, True, None, False, None, False]
        # forall: [T, null, null, T, null-row, T]
        assert [r[1] for r in got] == [True, None, None, True, None, True]


def test_map_filter_and_transforms(tpu, cpu):
    mk = lambda: F.create_map(col("a"), col("b"),
                              col("a") + lit(7), col("b") + lit(2.0))
    _check(tpu, cpu, lambda df: df.select(
        F.map_filter(mk(), lambda k, v: k > lit(0)).alias("m")))
    _check(tpu, cpu, lambda df: df.select(
        F.transform_values(mk(), lambda k, v: v * lit(3.0) + k.cast(
            "double")).alias("m")))
    _check(tpu, cpu, lambda df: df.select(
        F.transform_keys(mk(), lambda k, v: k * lit(2)).alias("m")))


def test_arrays_zip_cpu(tpu, cpu):
    _check(tpu, cpu, lambda df: df.select(F.arrays_zip(
        F.array(col("a")), F.array(col("c").cast("bigint"),
                                   col("a"))).alias("z")))


def test_nested_fallback_tagging(tpu):
    """Sorting BY a raw struct column tags fallback (device kernels sort
    flat buffers only) but the query still answers via CPU."""
    st = T.StructType([T.StructField("x", T.LONG)])
    df = tpu.create_dataframe({"s": [(3,), (1,), (2,)]}, dtypes={"s": st})
    got = df.select(F.get_field(col("s"), "x").alias("x")).sort("x").collect()
    assert [r[0] for r in got] == [1, 2, 3]


def test_hof_survives_masked_input(tpu, cpu):
    """HOF over a masked (filtered, uncompacted) batch."""
    _check(tpu, cpu, lambda df: df.filter(col("a") > lit(0)).select(
        F.transform(F.array(col("a"), col("c").cast("bigint")),
                    lambda x: x + lit(1)).alias("t")))
