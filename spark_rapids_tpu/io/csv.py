"""CSV scan + writer (reference: GpuCSVScan.scala over
GpuTextBasedPartitionReader — SURVEY.md §2.4: CPU line splitting + parse).

The reference splits lines on CPU and parses on device; for the TPU build
the Arrow CSV parser is the host decode and the parsed columns upload as
one batch. The SPARK OPTIONS MATRIX is honored (GpuCSVScan's tagging
checks; options Arrow cannot express are emulated or rejected loudly,
never silently ignored):

  sep/delimiter, quote, escape, header, comment (line pre-filter),
  nullValue/emptyValue, nanValue/positiveInf/negativeInf (custom float
  spellings parse via string + host convert), dateFormat/timestampFormat
  (Spark pattern -> strptime translation for the common tokens),
  ignoreLeadingWhiteSpace/ignoreTrailingWhiteSpace,
  mode = PERMISSIVE | DROPMALFORMED | FAILFAST.
"""

from __future__ import annotations

import io as _io
from typing import List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.csv as pcsv

from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.conf import RapidsConf, str_conf
from spark_rapids_tpu import types as T
from spark_rapids_tpu.io.arrow_convert import (
    arrow_schema_to_spark,
    decode_to_schema,
    host_table_to_arrow,
    spark_type_to_arrow,
)
from spark_rapids_tpu.io.common import FileScanNode
from spark_rapids_tpu.io.writer import write_partitioned
from spark_rapids_tpu.plan.nodes import Schema

CSV_READER_TYPE = str_conf(
    "spark.rapids.sql.format.csv.reader.type", "AUTO",
    "PERFILE, COALESCING, MULTITHREADED or AUTO.")

import re as _re

#: Spark datetime pattern tokens -> strptime (the common subset the
#: reference's tagging accepts; any other LETTER RUN raises loudly — runs
#: are matched exactly, so e.g. MMMM cannot half-translate)
_PATTERN_TOKENS = {
    "yyyy": "%Y", "yy": "%y", "MM": "%m", "dd": "%d",
    "HH": "%H", "mm": "%M", "ss": "%S", "SSSSSS": "%f",
    "SSS": "%f", "a": "%p",
}

def spark_pattern_to_strptime(pattern: str) -> str:
    out = []
    for piece in _re.split(r"([A-Za-z]+)", pattern):
        if piece and piece[0].isalpha():
            rep = _PATTERN_TOKENS.get(piece)
            if rep is None:
                raise ValueError(
                    f"datetime pattern {pattern!r}: token {piece!r} is "
                    "outside the supported subset "
                    f"({' '.join(_PATTERN_TOKENS)})")
            out.append(rep)
        else:
            out.append(piece)
    return "".join(out)


class CsvScanNode(FileScanNode):
    format_name = "csv"

    def __init__(self, paths, conf: RapidsConf, columns=None, reader_type=None,
                 schema: Optional[Schema] = None, header: bool = True,
                 delimiter: str = ",", sep: Optional[str] = None,
                 quote: str = '"', escape: Optional[str] = None,
                 comment: Optional[str] = None,
                 null_value: str = "", empty_value: Optional[str] = None,
                 nan_value: str = "NaN",
                 positive_inf: str = "Inf", negative_inf: str = "-Inf",
                 timestamp_format: Optional[str] = None,
                 ignore_leading_whitespace: bool = False,
                 ignore_trailing_whitespace: bool = False,
                 mode: str = "PERMISSIVE", **options):
        self.user_schema = schema
        self.header = header
        self.delimiter = sep if sep is not None else delimiter
        self.quote = quote
        self.escape = escape
        self.comment = comment
        self.null_value = null_value
        self.empty_value = empty_value
        self.nan_value = nan_value
        self.positive_inf = positive_inf
        self.negative_inf = negative_inf
        self.timestamp_format = timestamp_format
        self.ignore_leading_ws = ignore_leading_whitespace
        self.ignore_trailing_ws = ignore_trailing_whitespace
        self.mode = str(mode).upper()
        if self.mode not in ("PERMISSIVE", "DROPMALFORMED", "FAILFAST"):
            raise ValueError(f"unknown CSV mode {mode!r}")
        if len(self.delimiter) != 1:
            raise ValueError("CSV sep must be a single character")
        super().__init__(paths, conf, columns=columns, reader_type=reader_type,
                         **options)

    def _conf_reader_type(self) -> str:
        return self.conf.get_entry(CSV_READER_TYPE)

    def _newlines_in_values(self) -> bool:
        return False  # Spark CSV multiLine=false semantics

    def _cache_key_extra(self) -> tuple:
        return (tuple(self.user_schema or ()), self.header, self.delimiter,
                self.quote, self.escape, self.comment, self.null_value,
                self.empty_value, self.nan_value, self.positive_inf,
                self.negative_inf, self.timestamp_format,
                self.ignore_leading_ws, self.ignore_trailing_ws, self.mode)

    # -- option plumbing ----------------------------------------------------
    @property
    def _custom_floats(self) -> bool:
        return (self.nan_value != "NaN" or self.positive_inf != "Inf"
                or self.negative_inf != "-Inf")

    def _read_opts(self):
        read_opts = pcsv.ReadOptions()
        if not self.header:
            if not self.user_schema:
                raise ValueError("headerless CSV requires an explicit schema")
            read_opts = pcsv.ReadOptions(
                column_names=[n for n, _ in self.user_schema])
        parse_opts = pcsv.ParseOptions(
            delimiter=self.delimiter,
            quote_char=self.quote if self.quote else False,
            escape_char=self.escape if self.escape else False,
            double_quote=self.escape is None,
            # False for Spark CSV (multiLine=false: newlines always end
            # records, and the comment pre-filter relies on it — see
            # _load_bytes); hive text overrides when escape.delim is set
            newlines_in_values=self._newlines_in_values(),
        )
        salvage = []
        if self.mode == "DROPMALFORMED":
            parse_opts.invalid_row_handler = lambda row: "skip"
        elif self.mode == "PERMISSIVE":
            # Spark PERMISSIVE null-fills ragged rows: capture the row text
            # and rebuild it with nulls appended after the arrow pass
            def _capture(row, _s=salvage):
                if row.text is not None:
                    _s.append(row.text)
                return "skip"
            parse_opts.invalid_row_handler = _capture

        null_values = [self.null_value]
        if self.empty_value is not None:
            null_values.append(self.empty_value)
        types = {}
        timestamp_parsers = None
        if self.user_schema:
            for n, dt in self.user_schema:
                if isinstance(dt, (T.FloatType, T.DoubleType)) \
                        and self._custom_floats:
                    types[n] = pa.string()  # host converts spellings below
                elif isinstance(dt, T.TimestampType):
                    # parse naive (no zone column in CSV); values are
                    # UTC-epoch micros like Spark's session-UTC convention
                    types[n] = pa.timestamp("us")
                else:
                    types[n] = spark_type_to_arrow(dt)
        if self.timestamp_format:
            timestamp_parsers = [
                spark_pattern_to_strptime(self.timestamp_format)]
        convert = pcsv.ConvertOptions(
            column_types=types or None,
            null_values=null_values,
            strings_can_be_null=True,
            quoted_strings_can_be_null=False,
            timestamp_parsers=timestamp_parsers or None,
        )
        return read_opts, parse_opts, convert, salvage

    def file_schema(self, path: str) -> Schema:
        if self.user_schema:
            return list(self.user_schema)
        tbl, _ = self._read_arrow(path)
        return arrow_schema_to_spark(tbl.schema)

    def _load_bytes(self, path: str) -> bytes:
        # comment filtering is LINE-based; quoted fields spanning newlines
        # are already unsupported by the parser config (newlines_in_values
        # stays False), so a dropped continuation line fails parsing loudly
        # rather than corrupting rows
        with open(path, "rb") as f:
            data = f.read()
        cb = self.comment.encode()
        lines = [ln for ln in data.split(b"\n")
                 if not ln.lstrip().startswith(cb)]
        return b"\n".join(lines)

    def _read_arrow(self, path: str):
        read_opts, parse_opts, convert, salvage = self._read_opts()
        # stream straight from the file unless the comment pre-filter
        # requires materializing the text
        source = (_io.BytesIO(self._load_bytes(path)) if self.comment
                  else path)
        tbl = pcsv.read_csv(source,
                            read_options=read_opts,
                            parse_options=parse_opts,
                            convert_options=convert)
        return tbl, salvage

    def read_file(self, path: str) -> HostTable:
        tbl, salvage = self._read_arrow(path)
        host = decode_to_schema(tbl, self._pre_float_schema())
        host = self._post_process(host)
        if salvage:
            host = self._append_null_filled(host, salvage)
        return host

    def _append_null_filled(self, host: HostTable, rows) -> HostTable:
        """PERMISSIVE ragged rows: parse what fields exist (naive split —
        these rows already failed structured parsing) against the FILE's
        physical column order, then project into the (possibly pruned or
        reordered) output columns; appended at the end (row order within a
        file is not part of the engine's contract)."""
        # physical file order = the full user/file schema, NOT host.names
        file_schema = list(self.user_schema) if self.user_schema else \
            list(self.data_schema)
        file_pos = {n: j for j, (n, _) in enumerate(file_schema)}
        schema = [(n, c.dtype) for n, c in zip(host.names, host.columns)]
        extra = []
        for text in rows:
            parts = text.split(self.delimiter)
            row = []
            for n, dt in schema:
                j = file_pos.get(n)
                raw = (parts[j].strip()
                       if j is not None and j < len(parts) else None)
                if raw in (None, self.null_value):
                    row.append(None)
                    continue
                try:
                    from spark_rapids_tpu.ops.cast import parse_string_cast
                    v = (raw if isinstance(dt, T.StringType)
                         else parse_string_cast(raw, dt))
                except Exception:
                    v = None
                row.append(v)
            extra.append(row)
        cols = []
        for j, (n, dt) in enumerate(schema):
            vals = [r[j] for r in extra]
            cols.append(HostColumn.from_pylist(vals, dt))
        return HostTable(host.names, [
            HostColumn(c.dtype,
                       np.concatenate([c.data, e.data]),
                       np.concatenate([c.validity, e.validity]))
            for c, e in zip(host.columns, cols)])

    def _pre_float_schema(self) -> Schema:
        """Schema for the arrow decode: custom-float columns arrive as
        STRING and convert in _post_process."""
        if not (self.user_schema and self._custom_floats):
            return self.data_schema
        fcols = {n for n, dt in self.user_schema
                 if isinstance(dt, (T.FloatType, T.DoubleType))}
        return [(n, T.STRING if n in fcols else dt)
                for n, dt in self.data_schema]

    def _post_process(self, host: HostTable) -> HostTable:
        cols = list(host.columns)
        names = list(host.names)
        target = dict(self.data_schema)
        drop_mask = None  # DROPMALFORMED: rows with unparseable floats
        for i, (n, c) in enumerate(zip(names, cols)):
            if isinstance(c.dtype, T.StringType) and (
                    self.ignore_leading_ws or self.ignore_trailing_ws):
                data = c.data.copy()
                for j in range(len(data)):
                    if c.validity[j] and data[j] is not None:
                        if self.ignore_leading_ws:
                            data[j] = data[j].lstrip()
                        if self.ignore_trailing_ws:
                            data[j] = data[j].rstrip()
                c = HostColumn(T.STRING, data, c.validity.copy())
            want = target.get(n)
            if isinstance(c.dtype, T.StringType) and isinstance(
                    want, (T.FloatType, T.DoubleType)) and self._custom_floats:
                c, bad = self._convert_custom_floats(c, want)
                if drop_mask is None:
                    drop_mask = bad
                else:
                    drop_mask = drop_mask | bad
            cols[i] = c
        if self.mode == "DROPMALFORMED" and drop_mask is not None \
                and drop_mask.any():
            keep = ~drop_mask
            cols = [HostColumn(c.dtype, c.data[keep], c.validity[keep])
                    for c in cols]
        return HostTable(names, cols)

    def _convert_custom_floats(self, c: HostColumn, dt):
        specials = {self.nan_value: np.nan, self.positive_inf: np.inf,
                    self.negative_inf: -np.inf}
        out = np.zeros(len(c), dtype=dt.np_dtype)
        validity = np.zeros(len(c), dtype=np.bool_)
        malformed = np.zeros(len(c), dtype=np.bool_)
        for i in range(len(c)):
            if not c.validity[i] or c.data[i] is None:
                continue
            s = c.data[i].strip()
            if s in specials:
                out[i] = specials[s]
                validity[i] = True
            else:
                try:
                    out[i] = float(s)
                    validity[i] = True
                except ValueError:
                    if self.mode == "FAILFAST":
                        raise ValueError(
                            f"malformed float {s!r} (FAILFAST mode)")
                    malformed[i] = True
        return HostColumn(dt, out, validity), malformed


def write_csv(table: HostTable, path: str,
              partition_by: Optional[Sequence[str]] = None,
              header: bool = True, committer=None) -> List[str]:
    def _write_one(tbl: HostTable, file_path: str):
        opts = pcsv.WriteOptions(include_header=header)
        pcsv.write_csv(host_table_to_arrow(tbl), file_path, opts)

    return write_partitioned(table, path, _write_one, "csv", partition_by,
                             committer=committer)
