"""``tools vacuum`` — find and remove un-referenced/staged output files.

Three directory shapes, auto-detected:

* **Delta table** (``_delta_log/`` present): orphans are files the
  latest snapshot does not reference — overwritten versions' data
  files, failed/conflicted transactions' staged writes, orphaned
  deletion vectors (delta/commands.vacuum_table; the retention window
  comes from ``spark.rapids.delta.vacuum.retentionHours``).
* **Committed write directory** (``_SUCCESS`` manifest from the
  transactional committer): orphans are files the manifest does not
  list — leftovers of older jobs into the same directory — plus
  anything under ``_temporary/`` (staging of jobs that died without
  abort).
* **Anything else**: only ``_temporary/`` staging trees are provably
  garbage; nothing else is touched.

DRY RUN is the default — the report lists what ``--delete`` would
remove. Removal never touches ``_delta_log/``, the manifest itself, or
change-data-feed files.
"""

from __future__ import annotations

import os
from typing import List, Optional


def _manifest_orphans(path: str, manifest: dict) -> List[str]:
    from spark_rapids_tpu.io.committer import SUCCESS_MARKER, TEMP_DIR
    referenced = set(manifest.get("files", ()))
    orphans: List[str] = []
    for root, dirs, files in os.walk(path):
        # EVERYTHING under _temporary/ is an orphan candidate,
        # hidden names included (.backup/ trees of dead jobs); outside
        # it, other _/. dirs (foreign markers) are left alone
        in_temp = os.path.relpath(root, path).split(os.sep)[0] == TEMP_DIR
        if not in_temp:
            dirs[:] = [d for d in dirs
                       if not d.startswith(("_", ".")) or d == TEMP_DIR]
        for f in sorted(files):
            full = os.path.join(root, f)
            rel = os.path.relpath(full, path)
            if rel == SUCCESS_MARKER or rel in referenced:
                continue
            if f.startswith(("_", ".")) \
                    and not rel.startswith(TEMP_DIR + os.sep):
                continue
            orphans.append(rel)
    return orphans


def run_vacuum(path: str, delete: bool = False,
               retention_hours: Optional[float] = None) -> dict:
    """Returns the vacuum report dict; ``delete=False`` (the default)
    only reports. ``retention_hours`` (default: the
    ``spark.rapids.delta.vacuum.retentionHours`` conf) applies in
    EVERY mode — an orphan younger than the window may belong to a
    writer in another process that has not committed yet. Jobs in
    flight in THIS process are never touched regardless: neither
    their staging trees nor files they have promoted but not yet
    recorded in a manifest (committer.vacuum_protection)."""
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.io.committer import (
        DELTA_VACUUM_RETENTION_HOURS,
        WRITE_METRICS,
        find_staging_orphans,
        read_manifest,
        unlink_and_prune,
        vacuum_protection,
    )
    if not os.path.isdir(path):
        raise SystemExit(f"tools vacuum: {path} is not a directory")
    if retention_hours is None:
        retention_hours = float(
            RapidsConf().get_entry(DELTA_VACUUM_RETENTION_HOURS))
    if os.path.isdir(os.path.join(path, "_delta_log")):
        from spark_rapids_tpu.delta.commands import vacuum_table
        res = vacuum_table(path, dry_run=not delete,
                           retention_hours=retention_hours)
        return {"path": path, "mode": "delta",
                "orphans": res["orphans"],
                "deleted": res["files_deleted"],
                "dryRun": not delete,
                "retentionHours": res["retention_hours"]}

    manifest = read_manifest(path)
    if manifest is not None:
        orphans = _manifest_orphans(path, manifest)
        mode = "manifest"
    else:
        orphans = [os.path.relpath(p, path)
                   for p in find_staging_orphans(path)]
        mode = "staging-only"
    protected = vacuum_protection(path, retention_hours)
    orphans = [rel for rel in orphans
               if not protected(os.path.join(path, rel))]
    deleted = 0
    if delete:
        deleted = unlink_and_prune(path, orphans)
        if deleted:
            WRITE_METRICS.add("vacuumedFiles", deleted)
    return {"path": path, "mode": mode, "orphans": orphans,
            "deleted": deleted, "dryRun": not delete,
            "retentionHours": retention_hours}


def render_vacuum(report: dict) -> str:
    lines = [f"vacuum {report['path']} ({report['mode']})"
             + ("  [DRY RUN — pass --delete to remove]"
                if report["dryRun"] else "")]
    if not report["orphans"]:
        lines.append("  zero orphans — directory is clean")
    for rel in report["orphans"]:
        verb = "would remove" if report["dryRun"] else "removed"
        lines.append(f"  {verb}  {rel}")
    if not report["dryRun"]:
        lines.append(f"  {report['deleted']} file(s) removed")
    return "\n".join(lines)
