"""Profiling report over query event logs.

Reads the JSONL records ``TpuSession.execute`` writes (obs/events.py)
and builds the per-query / aggregate profile: top operators by SELF
time (opTime minus children's opTime, computed from the recorded plan
tree), compute vs transfer vs shuffle vs spill breakdown, per-exchange
byte/skew summary, spill/retry/recovery counters, the fallback
inventory with reasons, and span attribution (how much of each query's
wall time is covered by named spans — the ≥95% contract; the remainder
is reported as untracked, never silently absorbed)."""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional

from spark_rapids_tpu.obs.events import EVENT_SCHEMA_VERSION


def load_events(path: str) -> List[dict]:
    """Load event records from a .jsonl file or a directory of them
    (recursive). A schema NEWER than this build raises (the tools
    refuse to silently misread fields they don't know about); OLDER
    schemas load with one warning for the whole call — the analyzers
    treat every per-version field as 0/absent via ``.get`` defaults,
    so a mixed-version dir (a long-lived eventlog dir spanning an
    engine upgrade) compares/profiles instead of crashing."""
    files: List[str] = []
    if os.path.isdir(path):
        for dirpath, _dirs, names in os.walk(path):
            for n in sorted(names):
                if n.endswith(".jsonl"):
                    files.append(os.path.join(dirpath, n))
    elif os.path.exists(path):
        files = [path]
    else:
        raise FileNotFoundError(f"no event log at {path}")
    if not files:
        raise FileNotFoundError(f"no .jsonl event logs under {path}")
    records: List[dict] = []
    old_schemas: set = set()
    for f in files:
        with open(f) as fh:
            for lineno, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                schema = rec.get("schema")
                if not isinstance(schema, int) or schema < 1 \
                        or schema > EVENT_SCHEMA_VERSION:
                    raise ValueError(
                        f"{f}:{lineno}: unsupported event schema "
                        f"{schema!r} (this tools build reads schemas "
                        f"1..{EVENT_SCHEMA_VERSION})")
                if schema < EVENT_SCHEMA_VERSION:
                    old_schemas.add(schema)
                records.append(rec)
    if old_schemas:
        import sys
        print(
            f"tools: {path} contains records with older event "
            f"schema(s) {sorted(old_schemas)} (current "
            f"{EVENT_SCHEMA_VERSION}); fields those versions lack "
            "are treated as 0/absent", file=sys.stderr)
    return records


def query_label(rec: dict) -> str:
    tag = rec.get("queryTag")
    return tag if tag else f"query_{rec.get('queryIndex')}"


# ---------------------------------------------------------------------------
# per-record analysis
# ---------------------------------------------------------------------------


def _metric(node: dict, name: str, default=0):
    m = node.get("metrics") or {}
    entry = m.get(name)
    if entry is None:
        return default
    return entry.get("value", default)


def iter_plan_nodes(plan: dict):
    yield plan
    for c in plan.get("children", ()):
        yield from iter_plan_nodes(c)


def op_self_times(plan: dict) -> List[dict]:
    """Per-operator self time: opTime minus the DIRECT children's
    opTime, clamped at zero (a child re-pulled during recovery can
    exceed its parent's accounted window)."""
    out: List[dict] = []

    def walk(node: dict):
        own = float(_metric(node, "opTime", 0.0))
        child_total = sum(float(_metric(c, "opTime", 0.0))
                          for c in node.get("children", ()))
        if "opTime" in (node.get("metrics") or {}):
            out.append({
                "op": node.get("op"),
                "describe": node.get("describe"),
                "loreId": node.get("loreId"),
                "selfTimeS": round(max(own - child_total, 0.0), 6),
                "opTimeS": round(own, 6),
                "rows": int(_metric(node, "numOutputRows", 0)),
                "batches": int(_metric(node, "numOutputBatches", 0)),
            })
        for c in node.get("children", ()):
            walk(c)

    walk(plan)
    out.sort(key=lambda e: -e["selfTimeS"])
    return out


#: metric names summed into each breakdown bucket (tree-wide)
_BREAKDOWN_METRICS = {
    "transfer": ("h2dTime", "d2hTime", "scanUploadTime", "d2hArrowTime",
                 "h2dArrowTime"),
    "shuffle": ("shuffleWriteTime", "shuffleReadTime", "iciExchangeTime",
                "localSplitTime"),
}


def time_breakdown(rec: dict) -> Dict[str, float]:
    """Compute vs transfer vs shuffle vs spill vs untracked, in seconds.
    Transfer/shuffle come from the tree's timing metrics, spill from the
    per-query spill-scope delta; compute is the attributed remainder."""
    plan = rec.get("plan") or {}
    totals = {k: 0.0 for k in _BREAKDOWN_METRICS}
    for node in iter_plan_nodes(plan):
        for bucket, names in _BREAKDOWN_METRICS.items():
            for n in names:
                totals[bucket] += float(_metric(node, n, 0.0))
    spill = float((rec.get("scopes") or {}).get("spill", {})
                  .get("spillTime", 0.0))
    spans = rec.get("spans") or {}
    wall = float(rec.get("wallS", 0.0))
    untracked = float(spans.get("untrackedS", 0.0))
    compute = max(wall - untracked - totals["transfer"]
                  - totals["shuffle"] - spill, 0.0)
    return {
        "computeS": round(compute, 6),
        "transferS": round(totals["transfer"], 6),
        "shuffleS": round(totals["shuffle"], 6),
        "spillS": round(spill, 6),
        "untrackedS": round(untracked, 6),
        "wallS": round(wall, 6),
    }


def analyze_query(rec: dict, top_n: int = 10) -> dict:
    spans = rec.get("spans") or {}
    wall = float(rec.get("wallS", 0.0))
    attributed = float(spans.get("attributedS", 0.0))
    coverage = (attributed / wall) if wall > 0 else 1.0
    retries = dict(rec.get("recovery") or {})
    return {
        "query": query_label(rec),
        "queryIndex": rec.get("queryIndex"),
        "wallS": round(wall, 6),
        "phasesS": rec.get("phasesS") or {},
        "dispatches": rec.get("dispatches", 0),
        "compileMs": round(float(rec.get("compileMs", 0.0)), 3),
        "executableCacheHit": bool(rec.get("executableCacheHit", False)),
        "padWasteRows": int(rec.get("padWasteRows", 0)),
        "healthState": rec.get("healthState", "HEALTHY"),
        "quarantined": bool(rec.get("quarantined", False)),
        "deviceReinits": int(rec.get("deviceReinits", 0)),
        "workerRestarts": int(rec.get("workerRestarts", 0)),
        "meshShape": rec.get("meshShape"),
        "iciBytes": int(rec.get("iciBytes", 0)),
        "shardSkew": float(rec.get("shardSkew", 0.0)),
        "meshDegradations": int(rec.get("meshDegradations", 0)),
        "shardRetries": int(rec.get("shardRetries", 0)),
        "gatherChecksFailed": int(rec.get("gatherChecksFailed", 0)),
        "hostTopology": rec.get("hostTopology"),
        "hostsLost": int(rec.get("hostsLost", 0)),
        "hostRelands": int(rec.get("hostRelands", 0)),
        "dcnExchanges": int(rec.get("dcnExchanges", 0)),
        "hostScans": rec.get("hostScans") or {},
        # schema v10 (out-of-core): the per-query memory-scope deltas
        "oomRetries": int(rec.get("oomRetries", 0)),
        "splitRetries": int(rec.get("splitRetries", 0)),
        "spillBytes": int(rec.get("spillBytes", 0)),
        "unspills": int(rec.get("unspills", 0)),
        "budgetPeak": int(rec.get("budgetPeak", 0)),
        # schema v11 (streaming): micro-batch/MV/sink work under this wall
        "microBatches": int(rec.get("microBatches", 0)),
        "mvRefreshes": int(rec.get("mvRefreshes", 0)),
        "mvIncrementalRefreshes": int(rec.get("mvIncrementalRefreshes", 0)),
        "mvFullRecomputes": int(rec.get("mvFullRecomputes", 0)),
        "sinkCommits": int(rec.get("sinkCommits", 0)),
        "sinkReplays": int(rec.get("sinkReplays", 0)),
        "mvEpoch": rec.get("mvEpoch"),
        "attribution": {
            "attributedS": round(attributed, 6),
            "untrackedS": round(float(spans.get("untrackedS", 0.0)), 6),
            "coverage": round(coverage, 4),
        },
        "breakdown": time_breakdown(rec),
        "topOpsBySelfTime": op_self_times(rec.get("plan") or {})[:top_n],
        "exchanges": rec.get("exchanges") or [],
        "fallbacks": rec.get("fallbacks") or [],
        "demotions": rec.get("demotions") or {},
        "aqe": rec.get("aqe") or {},
        "recovery": retries,
        "scopes": rec.get("scopes") or {},
        "faultReplays": rec.get("faultReplays", 0),
    }


# ---------------------------------------------------------------------------
# aggregate profile
# ---------------------------------------------------------------------------


def build_profile(records: Iterable[dict], top_n: int = 10,
                  coverage_floor: float = 0.95) -> dict:
    """The full report dict. ``coverage_floor`` marks queries whose span
    attribution falls below the contract (reported, never hidden)."""
    queries = []
    agg_ops: Dict[str, dict] = {}
    cache_hits = 0
    for r in records:
        if r.get("cacheHit"):
            # a cache-hit serve REPLAYS the filling run's plan metrics
            # with a near-zero serve wall (schema v2): aggregating it
            # would double-count every operator and produce coverage
            # ratios far above 1 — count it as served traffic instead
            cache_hits += 1
            continue
        queries.append(analyze_query(r, top_n=top_n))
        # aggregate from the FULL per-record op list — truncation is
        # display-only, or an op just below every per-query top-N would
        # vanish from the headline ranking
        for e in op_self_times(r.get("plan") or {}):
            a = agg_ops.setdefault(
                e["op"], {"op": e["op"], "selfTimeS": 0.0, "rows": 0,
                          "batches": 0, "queries": 0})
            a["selfTimeS"] = round(a["selfTimeS"] + e["selfTimeS"], 6)
            a["rows"] += e["rows"]
            a["batches"] += e["batches"]
            a["queries"] += 1
    top_ops = sorted(agg_ops.values(), key=lambda e: -e["selfTimeS"])
    total_wall = round(sum(q["wallS"] for q in queries), 6)
    fallback_ops: Dict[str, set] = {}
    for q in queries:
        for fb in q["fallbacks"]:
            fallback_ops.setdefault(fb["op"], set()).update(fb["reasons"])
    low_coverage = [q["query"] for q in queries
                    if q["attribution"]["coverage"] < coverage_floor]
    cold = [q["query"] for q in queries if q["compileMs"] > 0]
    def _compile_scope(q, key):
        return int((q["scopes"].get("compile") or {}).get(key, 0))

    compile_summary = {
        "totalCompileMs": round(sum(q["compileMs"] for q in queries), 3),
        "coldQueries": cold,
        "executableCacheHits": sum(
            1 for q in queries if q["executableCacheHit"]),
        "padWasteRows": sum(q["padWasteRows"] for q in queries),
        # which path each primitive resolved to at trace time
        # (kernels/): a demoted Pallas kernel is visible offline as
        # hloFallbacks > 0 plus a 'pallas:<name>' demotion entry
        "pallasKernels": sum(
            _compile_scope(q, "pallasKernels") for q in queries),
        "hloFallbacks": sum(
            _compile_scope(q, "hloFallbacks") for q in queries),
    }
    # mesh-native execution (schema v6): which queries ran on the mesh,
    # how much payload rode ICI collectives, the worst per-shard skew
    # the collectives measured, and how many requested exchanges
    # demoted to the host shuffle (from the per-record mesh scope)
    mesh_summary = {
        "meshShapes": sorted({q["meshShape"] for q in queries
                              if q["meshShape"]}),
        "meshQueries": sum(1 for q in queries if q["meshShape"]),
        "iciBytes": sum(q["iciBytes"] for q in queries),
        "maxShardSkew": round(max((q["shardSkew"] for q in queries),
                                  default=0.0), 4),
        "hostShuffleFallbacks": sum(
            int((q["scopes"].get("mesh") or {})
                .get("hostShuffleFallbacks", 0)) for q in queries),
    }
    # mesh resilience (schema v7): the fault-domain counters — how much
    # recovery work the distributed path paid and which queries rode
    # through a degradation
    mesh_resilience = {
        "meshDegradations": sum(q["meshDegradations"] for q in queries),
        "shardRetries": sum(q["shardRetries"] for q in queries),
        "gatherChecksFailed": sum(
            q["gatherChecksFailed"] for q in queries),
        "degradedQueries": sorted(
            {q["query"] for q in queries if q["meshDegradations"]}),
    }
    # host resilience (schema v8): the multi-host fault-domain counters
    # — hosts lost and shards re-landed during the run, plus how many
    # collectives crossed the DCN axis (cluster-spanning meshes)
    # per-executor-host scan attribution (schema v9): each host's
    # dispatch/frame/byte/wall totals summed over the run — the
    # per-host breakdown a skewed or flaky executor shows up in
    per_host: Dict[str, dict] = {}
    for q in queries:
        for host, st in (q["hostScans"] or {}).items():
            agg = per_host.setdefault(
                host, {"scans": 0, "files": 0, "bytes": 0,
                       "wallS": 0.0, "execWallS": 0.0, "crcRetries": 0})
            for k in agg:
                v = st.get(k, 0)
                agg[k] = (round(agg[k] + float(v), 6)
                          if isinstance(agg[k], float)
                          else agg[k] + int(v))
    host_resilience = {
        "hostTopologies": sorted({q["hostTopology"] for q in queries
                                  if q["hostTopology"]}),
        "hostsLost": sum(q["hostsLost"] for q in queries),
        "hostRelands": sum(q["hostRelands"] for q in queries),
        "dcnExchanges": sum(q["dcnExchanges"] for q in queries),
        "degradedQueries": sorted(
            {q["query"] for q in queries
             if q["hostsLost"] or q["hostRelands"]}),
        "perHost": {h: per_host[h] for h in sorted(per_host)},
    }
    # out-of-core memory (schema v10): retry/split/spill/unspill work
    # the run paid under the device budget, and which queries paid it
    memory_summary = {
        "oomRetries": sum(q["oomRetries"] for q in queries),
        "splitRetries": sum(q["splitRetries"] for q in queries),
        "spillBytes": sum(q["spillBytes"] for q in queries),
        "unspills": sum(q["unspills"] for q in queries),
        "budgetPeak": max((q["budgetPeak"] for q in queries), default=0),
        "spilledQueries": sorted(
            {q["query"] for q in queries
             if q["spillBytes"] or q["oomRetries"]}),
    }
    # streaming (schema v11): micro-batches, MV maintenance strategy
    # split, and the sink's exactly-once replay count
    streaming_summary = {
        "microBatches": sum(q["microBatches"] for q in queries),
        "mvRefreshes": sum(q["mvRefreshes"] for q in queries),
        "mvIncrementalRefreshes": sum(
            q["mvIncrementalRefreshes"] for q in queries),
        "mvFullRecomputes": sum(q["mvFullRecomputes"] for q in queries),
        "sinkCommits": sum(q["sinkCommits"] for q in queries),
        "sinkReplays": sum(q["sinkReplays"] for q in queries),
        "mvServes": sorted(
            {q["query"] for q in queries if q["mvEpoch"] is not None}),
    }
    # survivability (schema v4): how healthy was the process this run,
    # and which queries rode through recovery events
    survivability = {
        "deviceReinits": sum(q["deviceReinits"] for q in queries),
        "workerRestarts": sum(q["workerRestarts"] for q in queries),
        "quarantinedQueries": sorted(
            {q["query"] for q in queries if q["quarantined"]}),
        "healthStates": sorted({q["healthState"] for q in queries}),
        "nonHealthyQueries": sorted(
            {q["query"] for q in queries
             if q["healthState"] != "HEALTHY"}),
    }
    return {
        "queryCount": len(queries),
        "cacheHitRecords": cache_hits,
        "totalWallS": total_wall,
        "compile": compile_summary,
        "mesh": mesh_summary,
        "meshResilience": mesh_resilience,
        "hostResilience": host_resilience,
        "memory": memory_summary,
        "streaming": streaming_summary,
        "survivability": survivability,
        "minCoverage": round(min((q["attribution"]["coverage"]
                                  for q in queries), default=1.0), 4),
        "coverageFloor": coverage_floor,
        "queriesBelowCoverageFloor": low_coverage,
        "topOpsBySelfTime": top_ops[:top_n],
        "breakdown": {
            k: round(sum(q["breakdown"][k] for q in queries), 6)
            for k in ("computeS", "transferS", "shuffleS", "spillS",
                      "untrackedS", "wallS")},
        "fallbackInventory": {op: sorted(reasons)
                              for op, reasons in sorted(fallback_ops.items())},
        "queries": queries,
    }


def _fmt_s(v: float) -> str:
    return f"{v:9.4f}s"


def render_profile(report: dict) -> str:
    """Human rendering of a build_profile() report."""
    lines: List[str] = []
    if report.get("cacheHitRecords"):
        lines.append(f"Cache-hit serves (excluded from op stats): "
                     f"{report['cacheHitRecords']}")
    lines.append(f"Queries: {report['queryCount']}   total wall "
                 f"{report['totalWallS']:.4f}s   min span coverage "
                 f"{report['minCoverage'] * 100:.1f}%")
    if report["queriesBelowCoverageFloor"]:
        lines.append(
            f"  BELOW {report['coverageFloor'] * 100:.0f}% coverage: "
            + ", ".join(report["queriesBelowCoverageFloor"]))
    b = report["breakdown"]
    lines.append("Breakdown: "
                 f"compute {b['computeS']:.4f}s | transfer "
                 f"{b['transferS']:.4f}s | shuffle {b['shuffleS']:.4f}s | "
                 f"spill {b['spillS']:.4f}s | untracked "
                 f"{b['untrackedS']:.4f}s")
    c = report["compile"]
    lines.append(
        f"Compile: {c['totalCompileMs']:.1f}ms across "
        f"{len(c['coldQueries'])} cold queries | executable-cache hits "
        f"{c['executableCacheHits']}/{report['queryCount']} | pad waste "
        f"{c['padWasteRows']} rows")
    if c.get("pallasKernels") or c.get("hloFallbacks"):
        lines.append(
            f"Pallas kernels: {c['pallasKernels']} primitive sites on "
            f"the kernel path | {c['hloFallbacks']} HLO fallbacks "
            "(disabled / ineligible shape / demoted — demotions show "
            "per query below)")
    me = report["mesh"]
    if me["meshQueries"]:
        lines.append(
            f"Mesh: {me['meshQueries']}/{report['queryCount']} queries "
            f"on {','.join(me['meshShapes'])} | ICI "
            f"{me['iciBytes']} bytes | max shard skew "
            f"{me['maxShardSkew']:.2f} | host-shuffle fallbacks "
            f"{me['hostShuffleFallbacks']}")
    mr = report.get("meshResilience") or {}
    if (mr.get("meshDegradations") or mr.get("shardRetries")
            or mr.get("gatherChecksFailed")):
        lines.append(
            f"Mesh resilience: degradations {mr['meshDegradations']} | "
            f"shard retries {mr['shardRetries']} | gather checks failed "
            f"{mr['gatherChecksFailed']}"
            + (f" | degraded: {', '.join(mr['degradedQueries'])}"
               if mr.get("degradedQueries") else ""))
    hr = report.get("hostResilience") or {}
    if (hr.get("hostsLost") or hr.get("hostRelands")
            or hr.get("dcnExchanges") or hr.get("perHost")):
        lines.append(
            f"Host resilience: hosts lost {hr['hostsLost']} | shard "
            f"re-lands {hr['hostRelands']} | DCN exchanges "
            f"{hr['dcnExchanges']}"
            + (f" | topologies {','.join(hr['hostTopologies'])}"
               if hr.get("hostTopologies") else "")
            + (f" | degraded: {', '.join(hr['degradedQueries'])}"
               if hr.get("degradedQueries") else ""))
        for host, st in (hr.get("perHost") or {}).items():
            lines.append(
                f"  host {host}: {st['scans']} dispatches, "
                f"{st['files']} frames, {st['bytes']} bytes, wall "
                f"{st['wallS']:.4f}s (executor {st['execWallS']:.4f}s)"
                + (f", CRC retries {st['crcRetries']}"
                   if st.get("crcRetries") else ""))
    mm = report.get("memory") or {}
    if (mm.get("oomRetries") or mm.get("splitRetries")
            or mm.get("spillBytes") or mm.get("unspills")):
        lines.append(
            f"Memory: oom retries {mm['oomRetries']} | split retries "
            f"{mm['splitRetries']} | spilled {mm['spillBytes']} bytes | "
            f"unspills {mm['unspills']} | budget peak "
            f"{mm['budgetPeak']} bytes"
            + (f" | spilled: {', '.join(mm['spilledQueries'])}"
               if mm.get("spilledQueries") else ""))
    sm = report.get("streaming") or {}
    if (sm.get("microBatches") or sm.get("mvRefreshes")
            or sm.get("sinkCommits") or sm.get("sinkReplays")):
        lines.append(
            f"Streaming: micro-batches {sm['microBatches']} | sink "
            f"commits {sm['sinkCommits']} (replays {sm['sinkReplays']}) "
            f"| MV refreshes {sm['mvRefreshes']} "
            f"(incremental {sm['mvIncrementalRefreshes']}, full "
            f"{sm['mvFullRecomputes']})"
            + (f" | MV serves: {', '.join(sm['mvServes'])}"
               if sm.get("mvServes") else ""))
    sv = report["survivability"]
    if (sv["deviceReinits"] or sv["workerRestarts"]
            or sv["quarantinedQueries"]
            or sv["healthStates"] != ["HEALTHY"]):
        lines.append(
            f"Survivability: device reinits {sv['deviceReinits']} | "
            f"worker restarts {sv['workerRestarts']} | health states "
            f"{','.join(sv['healthStates'])}"
            + (f" | quarantined: {', '.join(sv['quarantinedQueries'])}"
               if sv["quarantinedQueries"] else ""))
    lines.append("")
    lines.append("Top operators by self time:")
    for e in report["topOpsBySelfTime"]:
        lines.append(f"  {_fmt_s(e['selfTimeS'])}  {e['op']:32s} "
                     f"rows={e['rows']} batches={e['batches']} "
                     f"queries={e['queries']}")
    if report["fallbackInventory"]:
        lines.append("")
        lines.append("Fallbacks:")
        for op, reasons in report["fallbackInventory"].items():
            for r in reasons:
                lines.append(f"  {op}: {r}")
    lines.append("")
    lines.append("Per query:")
    for q in report["queries"]:
        cov = q["attribution"]["coverage"] * 100
        qb = q["breakdown"]
        lines.append(
            f"  {q['query']:16s} wall {_fmt_s(q['wallS'])}  "
            f"coverage {cov:5.1f}%  dispatches {q['dispatches']:4d}  "
            f"shuffle {qb['shuffleS']:.4f}s  transfer "
            f"{qb['transferS']:.4f}s")
        for e in q["topOpsBySelfTime"][:3]:
            lines.append(f"      {_fmt_s(e['selfTimeS'])}  {e['describe']}")
        for ex in q["exchanges"]:
            parts = [f"{k}={v}" for k, v in ex.items()
                     if k not in ("op", "loreId")]
            lines.append(f"      exchange loreId={ex.get('loreId')} "
                         + " ".join(parts))
        recov = {k: v for k, v in q["recovery"].items() if v}
        if recov:
            lines.append(f"      recovery {recov}")
        if q["demotions"]:
            lines.append(f"      demotions {sorted(q['demotions'])}")
    return "\n".join(lines)


def profile_path(path: str, top_n: int = 10) -> dict:
    return build_profile(load_events(path), top_n=top_n)
