"""Pallas kernel layer (ISSUE 11 tentpole): per-primitive bit-identity
vs the HLO paths, demotion-on-crash, and executable-cache isolation.

Everything runs in Pallas INTERPRET mode on the CPU backend (the
kernels resolve interpret=True there), which is exactly what makes the
bit-identity contract testable in tier-1 without TPU hardware: the
interpreter evaluates the same jnp program the kernel traces, so any
divergence from the HLO path is an algorithmic bug, not a backend
artifact."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_tpu import kernels
from spark_rapids_tpu.kernels import KernelsConfig
from spark_rapids_tpu.runtime.faults import FAULTS
from spark_rapids_tpu.session import TpuSession

pytestmark = pytest.mark.kernels

ON = {f"spark.rapids.tpu.kernels.{n}.enabled": "true"
      for n in kernels.PRIMITIVES}
OFF = {f"spark.rapids.tpu.kernels.{n}.enabled": "false"
       for n in kernels.PRIMITIVES}


@pytest.fixture(autouse=True)
def _clean_kernel_state():
    kernels.reset()
    FAULTS.disarm()
    yield
    kernels.reset()
    FAULTS.disarm()


class _forced:
    """Force the kernel enablement contextvar for direct (no-session)
    primitive calls. ``_forced()`` with no names means ALL HLO."""

    def __init__(self, *names, **kw):
        self.cfg = KernelsConfig(enabled=names, **kw)

    def __enter__(self):
        self.tok = kernels.KERNELS_ENABLED.set(self.cfg)

    def __exit__(self, *exc):
        kernels.KERNELS_ENABLED.reset(self.tok)


def _edge_i64(n, rng):
    x = rng.integers(-(2 ** 62), 2 ** 62, n).astype(np.int64)
    x[:6] = [2 ** 63 - 1, -(2 ** 63), 0, -1, 1, -(2 ** 31)]
    return x


def _edge_f64(n, rng):
    x = rng.standard_normal(n) * 1e18
    # NaN / signed zero / infinities / subnormal / beyond-f32 magnitude
    x[:8] = [np.nan, -0.0, 0.0, np.inf, -np.inf, 5e-324, 1e300, -1e300]
    return x


def _eq(a, b):
    a, b = np.asarray(a), np.asarray(b)
    if a.dtype.kind == "f":
        return ((a == b) | (np.isnan(a) & np.isnan(b))).all()
    return (a == b).all()


# ---------------------------------------------------------------------------
# per-primitive bit-identity
# ---------------------------------------------------------------------------


def test_sort_bit_identity_vs_lax_sort():
    from spark_rapids_tpu.ops.ordering import (
        comparable_operands,
        descending_operands,
        lex_sort,
    )
    rng = np.random.default_rng(0)
    n = 64
    i64 = _edge_i64(n, rng)
    f64 = _edge_f64(n, rng)
    dup = (rng.integers(0, 4, n)).astype(np.int32)  # ties -> stability
    # ONE program covers everything (each extra pallas build costs
    # seconds of tier-1 XLA compile): heavy ties (stability via the
    # payload tiebreak), ascending i64 limb pairs with extremes, and a
    # DESCENDING f64 limb pair with NaN/±0/±inf/subnormal edges
    ops = ([jnp.asarray(dup)] + comparable_operands(jnp.asarray(i64))
           + descending_operands(comparable_operands(jnp.asarray(f64))))
    payload = jnp.arange(n, dtype=jnp.int32)
    ref = jax.lax.sort(list(ops) + [payload], num_keys=len(ops))
    with _forced("sort"):
        got = lex_sort(ops, payload)
    for r, g in zip(ref, got):
        assert _eq(r, g)


def test_sort_ineligible_shape_falls_back_bit_identically():
    from spark_rapids_tpu.ops.ordering import lex_sort
    n = 384  # 3 * 128: a valid bucket under explicit lists, not pow2
    ops = [jnp.asarray(np.arange(n)[::-1].copy().astype(np.int32))]
    payload = jnp.arange(n, dtype=jnp.int32)
    ref = jax.lax.sort(list(ops) + [payload], num_keys=1)
    with _forced("sort"):
        got = lex_sort(ops, payload)
    for r, g in zip(ref, got):
        assert _eq(r, g)
    assert kernels.demoted_ops() == {}  # ineligible != demoted


def test_segment_minmax_bit_identity():
    from spark_rapids_tpu.ops.segsum import segment_minmax_64
    rng = np.random.default_rng(1)
    n, nseg = 128, 8
    gid = jnp.asarray(rng.integers(0, nseg - 2, n), jnp.int32)  # 2 empty
    sv = jnp.asarray(rng.random(n) > 0.25)
    i64 = jnp.asarray(_edge_i64(n, rng))
    # f64 edges PLUS an all-NaN segment (Spark: min ignores NaN unless
    # the segment is all-NaN)
    f64_np = _edge_f64(n, rng)
    f64_np[np.asarray(gid) == 3] = np.nan
    for vals in (i64, jnp.asarray(f64_np)):
        for is_min in (True, False):
            with _forced("segreduce"):
                got = segment_minmax_64(is_min, vals, sv, gid, nseg)
            with _forced():  # empty set = all HLO
                ref = segment_minmax_64(is_min, vals, sv, gid, nseg)
            assert _eq(got, ref), (str(vals.dtype), is_min)


def test_split_sum_onehot_bit_identity():
    from spark_rapids_tpu.ops.segsum import batched_segment_sum_f64
    rng = np.random.default_rng(2)
    n, nseg = 1024, 8
    gid = jnp.asarray(rng.integers(0, nseg, n), jnp.int32)
    well = [jnp.asarray(np.abs(rng.standard_normal(n))),
            jnp.asarray(rng.standard_normal(n) * 1e6)]
    # catastrophic cancellation: the runtime guard must reroute BOTH
    # paths to the exact sum identically
    cancel = np.zeros(n)
    cancel[0::2], cancel[1::2] = 1e16, -1e16
    cancel[0] += 1.0
    for cols in (well, [jnp.asarray(cancel)]):
        with _forced("segreduce"):
            got = batched_segment_sum_f64(cols, gid, nseg, n, True)
        with _forced():
            ref = batched_segment_sum_f64(cols, gid, nseg, n, True)
        assert _eq(got, ref)


def test_compact_bit_identity_dtype_zoo():
    from spark_rapids_tpu.ops.scatter32 import compact_pairs
    rng = np.random.default_rng(3)
    n = 256
    sv = jnp.asarray(rng.random(n) > 0.3)
    dec128 = jnp.asarray(
        rng.integers(-(2 ** 62), 2 ** 62, (n, 2)).astype(np.int64))
    datas = [jnp.asarray(_edge_i64(n, rng)),
             jnp.asarray(_edge_f64(n, rng)),
             jnp.asarray(rng.integers(0, 99, n), jnp.int32),
             jnp.asarray(rng.random(n) > 0.5),
             dec128]
    valids = [sv] * len(datas)
    for keep_np in (rng.random(n) > 0.5, np.ones(n, bool),
                    np.zeros(n, bool)):
        keep = jnp.asarray(keep_np)
        with _forced("compact"):
            got, n_got = compact_pairs(datas, valids, keep, n)
        with _forced():
            ref, n_ref = compact_pairs(datas, valids, keep, n)
        assert int(n_got) == int(n_ref)
        for (gd, gv), (rd, rv) in zip(got, ref):
            assert _eq(gd, rd) and _eq(gv, rv)


def test_hashprobe_matches_and_flags_duplicates():
    from spark_rapids_tpu.kernels import hashprobe as khash
    rng = np.random.default_rng(4)
    cap_l, cap_r, H = 256, 128, 512
    rkeys = (rng.choice(10 ** 9, cap_r, replace=False).astype(np.int64)
             - 5 * 10 ** 8)
    lkeys = np.concatenate([
        rkeys[rng.integers(0, cap_r, cap_l // 2)],
        rng.integers(10 ** 10, 10 ** 11, cap_l - cap_l // 2),
    ]).astype(np.int64)
    lv = rng.random(cap_l) > 0.1  # some null probe keys
    with _forced("hashprobe"):
        lo, counts, total, matched, rs_perm, fail = khash.probe_ranges(
            (jnp.asarray(lkeys), jnp.asarray(lv)),
            (jnp.asarray(rkeys), jnp.ones(cap_r, bool)),
            jnp.ones(cap_l, bool), jnp.ones(cap_r, bool), H, 4)
        assert not bool(fail)
        m, lo_n = np.asarray(matched), np.asarray(lo)
        for i in range(cap_l):
            hits = np.nonzero(rkeys == lkeys[i])[0] if lv[i] else []
            assert m[i] == (len(hits) > 0)
            if m[i]:
                assert lo_n[i] == hits[0]
        assert int(total) == int(m.sum())
        # a duplicated build key must raise the device fail flag
        rdup = rkeys.copy()
        rdup[5] = rdup[7]
        *_, fail2 = khash.probe_ranges(
            (jnp.asarray(lkeys), jnp.asarray(lv)),
            (jnp.asarray(rdup), jnp.ones(cap_r, bool)),
            jnp.ones(cap_l, bool), jnp.ones(cap_r, bool), H, 4)
        assert bool(fail2)


# ---------------------------------------------------------------------------
# end-to-end: the same queries with kernels on vs off
# ---------------------------------------------------------------------------


def _tables(n=600, seed=5, tag=""):
    """``tag`` renames the columns: exec kernel traces are shared
    process-wide by STRUCTURE, so a test that needs cold traces (to
    observe trace-time counters or fire a trace-time fault) must use a
    structurally distinct plan."""
    rng = np.random.default_rng(seed)
    fact = {f"k{tag}": rng.integers(0, 40, n).astype(np.int64),
            f"v{tag}": rng.standard_normal(n) * 1e9,
            f"q{tag}": rng.integers(-(2 ** 40), 2 ** 40, n).astype(np.int64)}
    dim = {f"k{tag}": np.arange(40, dtype=np.int64),
           f"name{tag}": np.asarray([f"n{i}" for i in range(40)], object)}
    return fact, dim


def _pipeline(s, fact, dim, tag=""):
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.ops.expr import col, lit
    df = s.create_dataframe(dict(fact))
    dd = s.create_dataframe(dict(dim))
    return (df.filter(col(f"v{tag}") > lit(-1e9))
              .join(dd, on=f"k{tag}", how="inner")
              .group_by(f"name{tag}")
              .agg(F.sum(f"v{tag}").alias("s"),
                   F.min(f"q{tag}").alias("mn"),
                   F.max(f"q{tag}").alias("mx"),
                   F.count(f"v{tag}").alias("c"))
              .order_by(f"name{tag}"))


def _collect(s, fact, dim, tag=""):
    return _pipeline(s, fact, dim, tag).collect_table().to_pydict()


def test_kernel_path_counters_surface_in_compile_scope():
    from spark_rapids_tpu.dispatch import COMPILE_SCOPE
    from spark_rapids_tpu.ops.ordering import lex_sort
    # trace-time resolution counters, pinned on a fresh shape: the
    # kernel path books pallasKernels, the disabled path hloFallbacks
    ops = [jnp.asarray(np.arange(64)[::-1].copy().astype(np.int32))]
    payload = jnp.arange(64, dtype=jnp.int32)
    before = dict(COMPILE_SCOPE)
    with _forced("sort"):
        lex_sort(ops, payload)
    assert (COMPILE_SCOPE.get("pallasKernels", 0)
            > before.get("pallasKernels", 0))
    before = dict(COMPILE_SCOPE)
    with _forced():
        lex_sort(ops, payload)
    assert (COMPILE_SCOPE.get("hloFallbacks", 0)
            > before.get("hloFallbacks", 0))
    # ...and the per-query event record carries the same counters (the
    # offline `tools profile` surface). Cold structure (tag) so the
    # query actually traces — scope deltas are zero on warm replays.
    import tempfile
    rng = np.random.default_rng(6)
    s = TpuSession({**ON, "spark.rapids.sql.eventLog.enabled": "true",
                    "spark.rapids.sql.eventLog.dir": tempfile.mkdtemp()})
    from spark_rapids_tpu.ops.expr import col, lit
    df = s.create_dataframe(
        {"cnt": rng.integers(0, 9, 256).astype(np.int64)})
    df.filter(col("cnt") > lit(4)).collect_table()
    scopes = s.last_event_record["scopes"]
    assert scopes.get("compile", {}).get("pallasKernels", 0) > 0


# ---------------------------------------------------------------------------
# demotion on crash (the PR-3 circuit-breaker contract, per primitive)
# ---------------------------------------------------------------------------


def test_seeded_kernel_crash_demotes_and_query_completes():
    # a COLD capacity bucket (n=1100 -> 2048 vs the other tests' 1024):
    # the fault point fires at TRACE time, and exec kernel traces are
    # shared process-wide by structure + capacity — column names alone
    # don't cold them (expressions bind to ordinals)
    fact, dim = _tables(n=1100, seed=7, tag="c")
    ref = _collect(TpuSession(dict(OFF)), fact, dim, tag="c")
    import tempfile
    crashy = TpuSession({
        **ON, "spark.rapids.test.faults": "kernels.compact:crash:1",
        "spark.rapids.sql.eventLog.enabled": "true",
        "spark.rapids.sql.eventLog.dir": tempfile.mkdtemp()})
    got = _collect(crashy, fact, dim, tag="c")
    assert got == ref or all(
        a == b or (isinstance(a, float) and np.isnan(a) and np.isnan(b))
        for k in ref for a, b in zip(got[k], ref[k]))
    # demoted for the process, with the reason surfaced...
    assert "pallas:compact" in kernels.demoted_ops()
    reason = kernels.demoted_ops()["pallas:compact"]
    assert "demoted to HLO" in reason and "KernelCrashError" in reason
    # ...in the event record's demotions map...
    assert "pallas:compact" in crashy.last_event_record["demotions"]
    # ...and in explain() as a root note
    text = _pipeline(crashy, fact, dim, tag="c").explain()
    assert "demoted to HLO" in text
    # the demoted primitive stays off; the others keep their kernels
    assert not kernels.enabled("compact")
    with _forced(*kernels.PRIMITIVES):
        assert kernels.enabled("sort") and not kernels.enabled("compact")


# ---------------------------------------------------------------------------
# cache isolation: enablement + demotions fold into every cache key
# ---------------------------------------------------------------------------


def test_fingerprints_never_cross_kernel_paths():
    from spark_rapids_tpu.plan.fingerprint import template_fingerprint
    fact, dim = _tables(seed=8)
    s_on, s_off = TpuSession(dict(ON)), TpuSession(dict(OFF))
    fp_on = template_fingerprint(_pipeline(s_on, fact, dim).plan,
                                 s_on.conf)
    fp_off = template_fingerprint(_pipeline(s_off, fact, dim).plan,
                                  s_off.conf)
    assert fp_on is not None and fp_on != fp_off
    # a runtime demotion re-keys cached trees even under identical conf
    kernels.demote("sort", RuntimeError("synthetic"))
    fp_dem = template_fingerprint(_pipeline(s_on, fact, dim).plan,
                                  s_on.conf)
    assert fp_dem != fp_on


def test_execute_time_failure_demotes_captured_primitives():
    """Mosaic lowering / backend compile happens when the ENCLOSING jit
    first runs, outside the kernels layer's guarded() — tpu_jit's
    trace-capture frame must demote the embedded primitives and convert
    the failure into a replayable KernelCrashError."""
    from spark_rapids_tpu.dispatch import tpu_jit
    from spark_rapids_tpu.errors import KernelCrashError

    def body(x):
        kernels.note_used("sort")  # what guarded() records on success
        raise RuntimeError("synthetic backend-compile failure")

    with _forced("sort", "compact"):
        with pytest.raises(KernelCrashError, match="demoted"):
            tpu_jit(body)(jnp.arange(8))
    assert "pallas:sort" in kernels.demoted_ops()
    assert "pallas:compact" not in kernels.demoted_ops()


def test_hashprobe_attempts_out_of_range_is_ineligible_not_a_crash():
    from spark_rapids_tpu.kernels import KernelIneligible
    from spark_rapids_tpu.kernels import hashprobe as khash
    k = (jnp.arange(8, dtype=jnp.int64), jnp.ones(8, bool))
    with _forced("hashprobe"):
        with pytest.raises(KernelIneligible):
            khash.probe_ranges(k, k, jnp.ones(8, bool), jnp.ones(8, bool),
                               32, 9)
    assert kernels.demoted_ops() == {}


def test_trace_token_tracks_enablement_and_demotion():
    with _forced("sort", "compact"):
        t0 = kernels.trace_token()
        kernels.demote("sort", RuntimeError("synthetic"))
        t1 = kernels.trace_token()
    assert t0 != t1
    with _forced():
        assert kernels.trace_token()[0] == ()
