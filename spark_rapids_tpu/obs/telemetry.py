"""Continuous telemetry ring + flight recorder (the cluster black box).

PR 4 built the per-QUERY observability surface (event log, spans,
metrics); PRs 7-12 then grew degradation ladders, a mesh fault domain
and a multi-host runtime whose LIVE state those per-query snapshots
cannot see — when a host dies or a kernel demotes mid-serve, the *why*
is scattered across process-wide counters nobody sampled at the time.
This module is the between-queries half of observability:

* :class:`TelemetryRing` / the process-wide :data:`TELEMETRY` — a
  PASSIVE background sampler: every ``spark.rapids.obs.telemetry.
  intervalMs`` it records one bounded sample — the per-scope DELTAS of
  every MetricRegistry scope (compile / mesh / cluster / health /
  spill / shuffle / write / service / semaphore / recovery) plus the
  health state and mesh/cluster topology — into a bounded ring,
  exportable as JSONL. Sampling must never perturb execution: the
  RL-OBS-PASSIVE lint rule forbids this module device syncs, query
  execution, and the query-path locks (the sampler reads only the
  snapshot surfaces every subsystem already exposes, each of which
  bounds its own lock hold to a dict copy).
* **Flight recorder** (:func:`record_incident`) — any degradation-
  ladder action (mesh / host / whole-backend), quarantine strike, or
  Pallas kernel demotion dumps one bounded INCIDENT BUNDLE (JSON) to
  ``spark.rapids.obs.flightRecorder.dir``: the trigger (kind, ladder
  action, error, the fault point parsed from an injected error),
  ladder + fault-point state, health/mesh/cluster topology, the
  telemetry tail, recent event-record summaries, and the live query
  table of any registered QueryService. ``python -m spark_rapids_tpu.
  tools incident`` renders bundles offline; the chaos harnesses assert
  one bundle per injected ladder action. Bundles are pruned to
  ``spark.rapids.obs.flightRecorder.maxBundles`` and recording is
  best-effort — an unwritable dir never masks the recovery it
  documents.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional

from spark_rapids_tpu.conf import RapidsConf, bool_conf, int_conf, str_conf
from spark_rapids_tpu.obs.metrics import scopes_snapshot
from spark_rapids_tpu.lockorder import ordered_lock

TELEMETRY_ENABLED = bool_conf(
    "spark.rapids.obs.telemetry.enabled", False,
    "Run the passive background telemetry sampler: every intervalMs it "
    "appends one bounded sample (per-scope metric deltas + health/mesh/"
    "cluster topology) to the in-memory ring obs/telemetry.py exports "
    "as JSONL, the query service serves at /telemetry, and the flight "
    "recorder embeds as the incident tail. The sampler takes no "
    "query-path locks and never touches the device (RL-OBS-PASSIVE).",
    commonly_used=True)

TELEMETRY_INTERVAL_MS = int_conf(
    "spark.rapids.obs.telemetry.intervalMs", 500,
    "Telemetry sampling period. Each tick costs a handful of dict "
    "snapshots on the host — no device work, no query-path locks — so "
    "the floor is bounded at 10ms.")

TELEMETRY_RING_SIZE = int_conf(
    "spark.rapids.obs.telemetry.ringSize", 720,
    "Samples the telemetry ring retains (oldest dropped first); the "
    "default holds 6 minutes at the default 500ms interval.")

FLIGHT_RECORDER_ENABLED = bool_conf(
    "spark.rapids.obs.flightRecorder.enabled", True,
    "Dump a bounded incident bundle (trigger, ladder + fault-point "
    "state, topology, telemetry tail, recent event summaries, live "
    "query table) on every degradation-ladder action, quarantine "
    "strike, and kernel demotion — the black box `python -m "
    "spark_rapids_tpu.tools incident` renders. Best-effort: recording "
    "can never fail or slow the recovery it documents.")

FLIGHT_RECORDER_DIR = str_conf(
    "spark.rapids.obs.flightRecorder.dir", "/tmp/rapids_tpu_flightrec",
    "Directory for flight-recorder incident bundles (one "
    "incident-<ms>-<seq>-<kind>.json per incident, pruned oldest-first "
    "to flightRecorder.maxBundles).")

FLIGHT_RECORDER_MAX_BUNDLES = int_conf(
    "spark.rapids.obs.flightRecorder.maxBundles", 64,
    "Incident bundles retained under flightRecorder.dir; recording the "
    "N+1st deletes the oldest (a crash-looping process must bound its "
    "own black box).")

FLIGHT_RECORDER_TELEMETRY_TAIL = int_conf(
    "spark.rapids.obs.flightRecorder.telemetryTail", 60,
    "Telemetry-ring samples embedded in each incident bundle (the "
    "most recent N — 30s of context at the default interval).")


def _scope_delta(before: Optional[Dict[str, dict]],
                 after: Dict[str, dict]) -> Dict[str, dict]:
    """Per-scope numeric deltas between two scopes_snapshot() calls —
    the event log's scope_delta (one definition of delta semantics),
    with a first-sample guard (no baseline yet -> no movement)."""
    if before is None:
        return {}
    from spark_rapids_tpu.obs.events import scope_delta
    return scope_delta(before, after)


class TelemetryRing:
    """The process-wide passive sampler. ``configure(conf)`` is cheap
    when nothing changed (the FAULTS.arm contract) — the session and
    the query service both call it, so whichever constructs first
    starts the sampler and the flight recorder inherits the same
    conf's recorder settings for conf-less trigger sites."""

    def __init__(self):
        self._lock = ordered_lock("obs.telemetry.ring")
        self._cfg = None
        self._interval_s = 0.5
        self._ring: deque = deque(maxlen=720)
        self._prev_scopes: Optional[Dict[str, dict]] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._samples = 0
        self._errors = 0

    # -- configuration -------------------------------------------------------
    def configure(self, conf: RapidsConf) -> None:
        enabled = bool(conf.get_entry(TELEMETRY_ENABLED))
        interval = int(conf.get_entry(TELEMETRY_INTERVAL_MS))
        size = max(1, int(conf.get_entry(TELEMETRY_RING_SIZE)))
        # the flight recorder's process defaults ride the same call so
        # conf-less trigger sites (quarantine strikes, kernel
        # demotions) land bundles where the operator pointed the dir
        _configure_flight_recorder(conf)
        key = (enabled, interval, size)
        start = stop = False
        with self._lock:
            if key == self._cfg:
                return
            self._cfg = key
            self._interval_s = max(0.01, interval / 1000.0)
            if size != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=size)
            # "alive" means a thread that has NOT been told to stop: a
            # disable->enable toggle must start a fresh thread even
            # while the stopped one lingers inside its last wait —
            # keying on is_alive() alone would record the enabled cfg,
            # start nothing, and leave the sampler dead forever (each
            # loop holds its own stop event, so a brief overlap of old
            # and new thread is harmless)
            alive = (self._thread is not None and self._thread.is_alive()
                     and not self._stop.is_set())
            if enabled and not alive:
                self._stop = threading.Event()
                self._thread = threading.Thread(
                    target=self._loop, args=(self._stop,),
                    name="rapids-telemetry-sampler", daemon=True)
                start = True
            elif not enabled and alive:
                stop = True
        if start:
            self._thread.start()
        if stop:
            self._stop.set()

    @property
    def enabled(self) -> bool:
        with self._lock:
            return bool(self._cfg and self._cfg[0])

    # -- sampling ------------------------------------------------------------
    def _loop(self, stop: threading.Event) -> None:
        while True:
            with self._lock:
                interval = self._interval_s
            if stop.wait(interval):
                return
            self.sample_once()

    def sample_once(self) -> Optional[dict]:
        """One sample: per-scope deltas since the previous sample plus
        the health/topology view — every read a bounded snapshot, no
        device work, no query-path locks (RL-OBS-PASSIVE)."""
        try:
            from spark_rapids_tpu.parallel.mesh import MESH
            from spark_rapids_tpu.runtime.cluster import CLUSTER
            from spark_rapids_tpu.runtime.faults import FAULTS
            from spark_rapids_tpu.runtime.health import HEALTH
            from spark_rapids_tpu.runtime.memory import MEMORY
            snap = scopes_snapshot()
            mem = MEMORY.snapshot()  # bounded dict copy, no locks held
            sample = {
                "t": round(time.time(), 3),
                "deltas": _scope_delta(self._prev_scopes, snap),
                "health": HEALTH.state(),
                "meshShape": MESH.shape_str(),
                "hostTopology": CLUSTER.topology_str(),
                "faultFires": sum(FAULTS.counters().values()),
                # device-budget occupancy riding every sample: the
                # between-queries view of out-of-core pressure
                "memOccupancy": mem["occupancyBytes"],
                "memBudget": mem["budgetBytes"],
            }
            with self._lock:
                self._prev_scopes = snap
                self._ring.append(sample)
                self._samples += 1
            return sample
        except Exception:
            with self._lock:
                self._errors += 1
            return None

    # -- reads ---------------------------------------------------------------
    def tail(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            samples = list(self._ring)
        if n is None:
            return samples
        n = int(n)
        return samples[-n:] if n > 0 else []  # [-0:] would be ALL

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": bool(self._cfg and self._cfg[0]),
                "intervalMs": int(self._interval_s * 1000),
                "ringSize": self._ring.maxlen,
                "samples": self._samples,
                "buffered": len(self._ring),
                "errors": self._errors,
            }

    def export_jsonl(self, path: str) -> str:
        """Dump the current ring, one sample per line."""
        samples = self.tail()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            for s in samples:
                f.write(json.dumps(s, sort_keys=True) + "\n")
        return path

    def reset(self) -> None:
        """Test support: drop buffered samples and the delta baseline."""
        with self._lock:
            self._ring.clear()
            self._prev_scopes = None
            self._samples = 0
            self._errors = 0


TELEMETRY = TelemetryRing()


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

#: registered QueryServices (weak — a shut-down service just drops
#: out); the recorder snapshots their live query tables best-effort
_SERVICES: "weakref.WeakSet" = weakref.WeakSet()
_SERVICES_LOCK = ordered_lock("obs.telemetry.services")


def register_service(service) -> None:
    """Called by QueryService.__init__ so incident bundles can embed
    the live query table of every service in the process."""
    with _SERVICES_LOCK:
        _SERVICES.add(service)


#: process defaults for conf-less trigger sites (quarantine strikes,
#: kernel demotions), refreshed by TELEMETRY.configure
_FR_LOCK = ordered_lock("obs.flightrec")
_FR_STATE = {
    "enabled": bool(FLIGHT_RECORDER_ENABLED.default),
    "dir": str(FLIGHT_RECORDER_DIR.default),
    "max_bundles": int(FLIGHT_RECORDER_MAX_BUNDLES.default),
    "tail": int(FLIGHT_RECORDER_TELEMETRY_TAIL.default),
}
_FR_SEQ = [0]

#: the fault-point pattern injected errors carry ("injected host loss
#: at host.dispatch") — parsed into the bundle's triggering fault point
_FAULT_POINT_RE = re.compile(r"\bat ([a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+)")

#: bundle-kind prefix → fault domain.  Cross-domain closures match
#: "one bundle per ladder action" by (seq, faultDomain) instead of
#: timestamp windows, so the attribution must be total: anything not
#: claimed by a hardware/memory/stream prefix belongs to the service
#: plane (backend ladder, quarantine, kernel demotion).
_FAULT_DOMAIN_PREFIXES = (
    ("host.", "host"),
    ("mesh.", "mesh"),
    ("memory.", "memory"),
    ("stream.", "stream"),
)


def fault_domain(kind: str) -> str:
    kind = str(kind)
    for prefix, domain in _FAULT_DOMAIN_PREFIXES:
        if kind.startswith(prefix):
            return domain
    return "service"


def _configure_flight_recorder(conf: RapidsConf) -> None:
    with _FR_LOCK:
        _FR_STATE["enabled"] = bool(conf.get_entry(FLIGHT_RECORDER_ENABLED))
        _FR_STATE["dir"] = str(conf.get_entry(FLIGHT_RECORDER_DIR))
        _FR_STATE["max_bundles"] = int(
            conf.get_entry(FLIGHT_RECORDER_MAX_BUNDLES))
        _FR_STATE["tail"] = int(
            conf.get_entry(FLIGHT_RECORDER_TELEMETRY_TAIL))


def _recorder_settings(conf: Optional[RapidsConf]) -> dict:
    if conf is not None:
        try:
            return {
                "enabled": bool(conf.get_entry(FLIGHT_RECORDER_ENABLED)),
                "dir": str(conf.get_entry(FLIGHT_RECORDER_DIR)),
                "max_bundles": int(
                    conf.get_entry(FLIGHT_RECORDER_MAX_BUNDLES)),
                "tail": int(
                    conf.get_entry(FLIGHT_RECORDER_TELEMETRY_TAIL)),
            }
        except Exception:
            pass
    with _FR_LOCK:
        return dict(_FR_STATE)


def _active_query_tables() -> List[dict]:
    """Live query tables of every registered service. NON-BLOCKING by
    contract: a quarantine strike is recorded while the scheduler's
    condition lock is held, and a blocking re-acquire from the same
    thread would deadlock — a service whose lock is busy reports
    'unavailable' instead."""
    out: List[dict] = []
    with _SERVICES_LOCK:
        services = list(_SERVICES)
    for svc in services:
        try:
            table = svc.query_table(blocking=False)
        except Exception:
            table = None
        out.append({"pools": sorted(getattr(svc, "pools", {})),
                    "queries": table,
                    "available": table is not None})
    return out


def _prune_bundles(directory: str, max_bundles: int) -> None:
    names = sorted(n for n in os.listdir(directory)
                   if n.startswith("incident-") and n.endswith(".json"))
    for n in names[:max(0, len(names) - max_bundles)]:
        try:
            os.unlink(os.path.join(directory, n))
        except OSError:
            pass


def record_incident(kind: str, action: str, reason: str,
                    conf: Optional[RapidsConf] = None,
                    error: Optional[BaseException] = None,
                    extra: Optional[dict] = None) -> Optional[str]:
    """Dump one incident bundle; returns its path (None when disabled
    or the dump failed — recording is strictly best-effort and must
    never raise into a recovery path). Callers must NOT hold the
    health/quarantine locks (the bundle re-reads their snapshots)."""
    try:
        settings = _recorder_settings(conf)
        if not settings["enabled"]:
            return None
        from spark_rapids_tpu.parallel.mesh import MESH
        from spark_rapids_tpu.runtime.cluster import CLUSTER
        from spark_rapids_tpu.runtime.faults import (
            CIRCUIT_BREAKER,
            FAULTS,
            RECOVERY,
        )
        from spark_rapids_tpu.runtime.health import HEALTH, QUARANTINE
        reason = str(reason)
        m = _FAULT_POINT_RE.search(reason)
        # the sequence id is allocated BEFORE the bundle is built and
        # embedded in-band: process-monotonic, so a closure can assert
        # exact bundle↔ladder-action correspondence even when wall
        # clocks collide across domains
        with _FR_LOCK:
            _FR_SEQ[0] += 1
            seq = _FR_SEQ[0]
        bundle = {
            "schema": 2,
            "seq": seq,
            "faultDomain": fault_domain(kind),
            "kind": str(kind),
            "action": str(action),
            "reason": reason[:2000],
            "errorType": type(error).__name__ if error is not None
            else None,
            "faultPoint": m.group(1) if m else None,
            "wallClock": round(time.time(), 3),
            "pid": os.getpid(),
            "health": {
                "state": HEALTH.state(),
                "cpuOnlyReason": HEALTH.cpu_only_reason(),
                "backend": HEALTH.snapshot(),
                "meshLadder": HEALTH.mesh_snapshot(),
                "hostLadder": HEALTH.host_snapshot(),
                "memoryLadder": HEALTH.memory_snapshot(),
            },
            "mesh": MESH.health_snapshot(),
            "cluster": CLUSTER.health_snapshot(),
            "memory": _memory_snapshot(),
            "quarantine": QUARANTINE.snapshot(),
            # exec circuit-breaker + Pallas kernel demotions in one
            # map, the event record's convention (keys 'pallas:<name>')
            "demotions": {**CIRCUIT_BREAKER.demoted_ops(),
                          **_kernel_demotions()},
            "recovery": RECOVERY.snapshot(),
            "faultFires": FAULTS.counters(),
            "scopes": scopes_snapshot(),
            "telemetry": {
                "sampler": TELEMETRY.stats(),
                "tail": TELEMETRY.tail(settings["tail"]),
            },
            "recentEvents": _recent_event_summaries(),
            "activeQueries": _active_query_tables(),
        }
        if extra:
            bundle["extra"] = extra
        directory = settings["dir"]
        os.makedirs(directory, exist_ok=True)
        safe_kind = re.sub(r"[^A-Za-z0-9._-]", "_", str(kind))
        path = os.path.join(
            directory,
            f"incident-{int(time.time() * 1000):013d}-{seq:06d}-"
            f"{safe_kind}.json")
        with open(path, "w") as f:
            json.dump(bundle, f, sort_keys=True)
        _prune_bundles(directory, settings["max_bundles"])
        return path
    except Exception:
        return None  # the black box must never take the plane down


def record_incident_async(kind: str, action: str, reason: str,
                          conf: Optional[RapidsConf] = None,
                          error: Optional[BaseException] = None,
                          extra: Optional[dict] = None) -> None:
    """Fire-and-forget :func:`record_incident` on a short-lived daemon
    thread — for trigger sites that run under a hot lock (the
    quarantine strike records while the scheduler's condition lock is
    held; a slow flight-recorder dir must never stall the service's
    submit/pick/finish paths for the duration of a bundle write)."""
    try:
        threading.Thread(
            target=record_incident,
            args=(kind, action, reason),
            kwargs={"conf": conf, "error": error, "extra": extra},
            name="rapids-flightrec-dump", daemon=True).start()
    except Exception:
        pass  # thread-spawn failure must not mask the strike


def _recent_event_summaries() -> List[dict]:
    from spark_rapids_tpu.obs.events import recent_records
    return recent_records()


def _kernel_demotions() -> Dict[str, str]:
    from spark_rapids_tpu import kernels
    return kernels.demoted_ops()


def _memory_snapshot() -> dict:
    from spark_rapids_tpu.runtime.memory import MEMORY
    return MEMORY.snapshot()
