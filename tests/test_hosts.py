"""Tier-1 multi-host slice: the driver/executor protocol and the host
fault domain (runtime/cluster.py).

The full closure is ``python scale_test.py --hosts 2 --chaos`` (q1-q22
through N executor subprocesses under the seeded host.* schedule with
a scripted mid-corpus SIGKILL + rejoin — MULTIHOST_r01); this
marker-gated slice keeps every host recovery mechanism exercised in
the tier-1 gate without the corpus cost:

* 2 REAL executor subprocesses scanning their by-host file
  assignments, bit-identical to a single-process scan over the same
  files (and the v8 event-log hostTopology field);
* injected host losses (``device_lost`` at a ``host.*`` point) walking
  the ladder retry -> re-land-on-survivors, converging bit-identically
  with the loss visible in the health surfaces;
* corrupt shard landings caught by the TPAK CRC and re-landed;
* a real SIGKILL: the heartbeat machinery declares the host lost, a
  respawned executor REJOINS through the registration path, and the
  topology returns to full strength;
* missed-beat sweep eviction (the wedged-but-connected path);
* typed-error classification: HostLostError vs MeshDeviceLostError vs
  whole-backend DeviceLostError, and the full ladder walk down to the
  single-process latch + escalation;
* RL-FAULT-POINT covers the ``host.*`` domain in both directions;
* ``scale_test.py validate_flags`` rejects the --hosts combos the
  harness does not implement.
"""

import os
import time

import numpy as np
import pytest

from spark_rapids_tpu.runtime.faults import CIRCUIT_BREAKER, FAULTS

pytestmark = [pytest.mark.multihost, pytest.mark.chaos]

_HB_MS = 200


@pytest.fixture(autouse=True)
def _clean_host_fault_state():
    """Host chaos mutates PROCESS state (fault registry, breaker,
    health ladders, cluster topology, quarantine) — restore all of it
    so the rest of the suite sees a healthy full-strength process."""
    from spark_rapids_tpu.runtime.cluster import CLUSTER
    from spark_rapids_tpu.runtime.health import HEALTH, QUARANTINE
    from spark_rapids_tpu.session import TpuSession
    FAULTS.disarm()
    CIRCUIT_BREAKER.reset()
    HEALTH.reset()
    QUARANTINE.reset()
    CLUSTER.restore()
    yield
    FAULTS.disarm()
    CIRCUIT_BREAKER.reset()
    HEALTH.reset()
    QUARANTINE.reset()
    CLUSTER.restore()
    # leave the process-wide cluster (and mesh) OFF for the suite
    TpuSession().placement.prepare()


def _wait_for(predicate, timeout_s=30.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.05)
    return predicate()


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """A small parquet table split across 4 files (row slices in
    order) — real by-host partitioning work for 2 hosts."""
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.io.parquet import write_parquet
    base = tmp_path_factory.mktemp("hosts_corpus")
    n = 600
    t = HostTable.from_pydict({
        "k": [f"k{i % 7}" for i in range(n)],
        "v": np.arange(n, dtype=np.int64),
        "x": np.arange(n, dtype=np.float64) * 0.5,
    })
    chunk = n // 4
    for i in range(4):
        length = chunk if i < 3 else n - 3 * chunk
        write_parquet(t.slice(i * chunk, length),
                      str(base / f"c{i:03d}"))
    return str(base)


@pytest.fixture(scope="module")
def cluster2():
    """Driver + 2 REAL executor subprocesses, registered and attached
    (the 2-process sim harness, shared across this module's tests).
    The missed-beat window is huge on purpose — the driver process
    runs jax compiles that hold the GIL for seconds, and a spurious
    eviction would flake the module; real kills are detected through
    the beat-connection EOF path, which this window does not gate."""
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.runtime.cluster import (
        CLUSTER,
        ClusterDriver,
        spawn_executor,
    )
    driver = ClusterDriver(2, RapidsConf({
        "spark.rapids.cluster.heartbeatIntervalMs": str(_HB_MS),
        "spark.rapids.cluster.missedBeats": "150",
    }))
    executors = {f"h{i}": spawn_executor(driver.address, f"h{i}",
                                         heartbeat_ms=_HB_MS,
                                         mode="process")
                 for i in range(2)}
    driver.wait_ready(2, timeout_s=90.0)
    CLUSTER.attach_driver(driver)
    yield driver, executors
    CLUSTER.attach_driver(None)
    driver.shutdown()
    for h in executors.values():
        try:
            h.terminate()
        except Exception:
            pass


def _session(extra=None):
    from spark_rapids_tpu.session import TpuSession
    conf = {"spark.rapids.cluster.enabled": "true",
            "spark.rapids.cluster.hosts": "2",
            "spark.rapids.cluster.heartbeatIntervalMs": str(_HB_MS),
            "spark.rapids.cluster.missedBeats": "150"}
    conf.update(extra or {})
    return TpuSession(conf)


def _agg(s, corpus):
    from spark_rapids_tpu import functions as F
    return (s.read_parquet(corpus).group_by("k")
            .agg(F.sum("v").alias("sv"), F.sum("x").alias("sx"),
                 F.count("v").alias("n")))


def _cluster_scope():
    from spark_rapids_tpu.obs.metrics import scopes_snapshot
    return dict(scopes_snapshot().get("cluster", {}))


def test_two_process_scan_bit_identity(cluster2, corpus, tmp_path):
    """The core sim-harness contract: a scan fanned out to 2 executor
    SUBPROCESSES reassembles byte-identically to a local scan of the
    same files — and the v8 event record carries the host topology."""
    import scale_test as st
    from spark_rapids_tpu.session import TpuSession
    single = TpuSession()
    expected_scan = single.read_parquet(corpus).collect_table()
    expected_agg = _agg(single, corpus).collect_table()

    s = _session({"spark.rapids.sql.eventLog.enabled": "true",
                  "spark.rapids.sql.eventLog.dir": str(tmp_path)})
    before = _cluster_scope()
    got_scan = s.read_parquet(corpus).collect_table()
    assert st.tables_differ(expected_scan, got_scan) is None
    got_agg = _agg(s, corpus).collect_table()
    assert st.tables_differ(expected_agg, got_agg) is None
    after = _cluster_scope()
    # one batch per file, every file through an executor
    assert after.get("hostShardsLanded", 0) - before.get(
        "hostShardsLanded", 0) == 8
    rec = s.last_event_record
    assert rec["schema"] == 11
    assert rec["hostTopology"] == "2"
    assert rec["hostsLost"] == 0 and rec["hostRelands"] == 0


def test_injected_host_loss_walks_ladder_and_recovers(cluster2, corpus):
    """device_lost at a host.* point raises the typed HostLostError
    and the ladder walks retry -> re-land-on-survivors: the query
    converges bit-identically, the loss is visible in the health
    surfaces, and the provably-alive host is restored by the sweep."""
    import scale_test as st
    from spark_rapids_tpu.runtime.cluster import CLUSTER
    from spark_rapids_tpu.runtime.health import HEALTH
    from spark_rapids_tpu.session import TpuSession
    expected = _agg(TpuSession(), corpus).collect_table()
    s = _session({
        "spark.rapids.test.faults": "host.dispatch:device_lost:2:3",
        "spark.rapids.sql.runtimeFallback.enabled": "true"})
    before = _cluster_scope()
    got = _agg(s, corpus).collect_table()
    assert st.tables_differ(expected, got) is None
    snap = HEALTH.host_snapshot()
    assert snap["hostsLost"] == 2  # retry rung + reland rung
    after = _cluster_scope()
    assert after.get("hostsLost", 0) - before.get("hostsLost", 0) >= 1
    assert after.get("hostRelands", 0) - before.get(
        "hostRelands", 0) >= 1
    # the marked host's executor never died: the sweep restores it on
    # evidence of health (beating, open channels)
    assert _wait_for(
        lambda: not CLUSTER.health_snapshot()["lostHosts"], 20.0), \
        CLUSTER.health_snapshot()


def test_corrupt_shard_landing_caught_and_relanded(cluster2, corpus):
    """A corrupted host shard frame trips the TPAK CRC at the
    host.shard.land boundary and re-lands from the intact received
    frame instead of feeding the scan garbage rows."""
    import scale_test as st
    from spark_rapids_tpu.session import TpuSession
    expected = _agg(TpuSession(), corpus).collect_table()
    s = _session({
        "spark.rapids.test.faults": "host.shard.land:corrupt:2:5"})
    before = _cluster_scope()
    got = _agg(s, corpus).collect_table()
    assert st.tables_differ(expected, got) is None
    after = _cluster_scope()
    assert after.get("hostShardRetries", 0) - before.get(
        "hostShardRetries", 0) == 2


def test_kill_rejoin_restore(cluster2, corpus):
    """A real SIGKILL: the heartbeat machinery declares the host lost
    promptly (beat-connection EOF), scans re-land its shards onto the
    survivor bit-identically, and a respawned executor REJOINS through
    the registration path — topology back at full strength."""
    import scale_test as st
    from spark_rapids_tpu.runtime.cluster import CLUSTER, spawn_executor
    from spark_rapids_tpu.session import TpuSession
    driver, executors = cluster2
    expected = _agg(TpuSession(), corpus).collect_table()

    executors["h1"].terminate()
    assert _wait_for(
        lambda: "h1" in CLUSTER.health_snapshot()["lostHosts"], 30.0), \
        CLUSTER.health_snapshot()
    before = _cluster_scope()
    got = _agg(_session(), corpus).collect_table()
    assert st.tables_differ(expected, got) is None
    after = _cluster_scope()
    assert after.get("hostRelands", 0) - before.get(
        "hostRelands", 0) >= 1
    assert CLUSTER.topology_str() == "1/2"

    executors["h1"] = spawn_executor(driver.address, "h1",
                                     heartbeat_ms=_HB_MS,
                                     mode="process")
    assert _wait_for(
        lambda: not CLUSTER.health_snapshot()["lostHosts"], 60.0), \
        CLUSTER.health_snapshot()
    assert CLUSTER.topology_str() == "2"
    got2 = _agg(_session(), corpus).collect_table()
    assert st.tables_differ(expected, got2) is None


def test_missed_beat_sweep_declares_host_lost():
    """The wedged-but-connected path: an executor that registered but
    stops beating is evicted by the missed-beat sweep and its host
    declared lost (no sockets involved — the ledger half alone)."""
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.runtime.cluster import CLUSTER, ClusterDriver
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.shuffle.transport import PeerInfo
    drv = ClusterDriver(3, RapidsConf({
        "spark.rapids.cluster.heartbeatIntervalMs": "100",
        "spark.rapids.cluster.missedBeats": "2"}))
    try:
        # a 3-host topology: h2 exists only in this driver's ledger,
        # so the module cluster's sweep (h2 never beats there, no data
        # channel) cannot auto-restore it as provably alive
        _session({"spark.rapids.cluster.hosts": "3"}).placement.prepare()
        drv._hb.register_executor(PeerInfo(executor_id="h2"))
        time.sleep(0.5)  # > missedBeats * interval
        # the driver's own sweeper (or this explicit sweep — whichever
        # wins the race) must have evicted the silent executor and
        # declared its host lost
        drv.sweep_once()
        assert _wait_for(
            lambda: "h2" in CLUSTER.health_snapshot()["lostHosts"], 10.0)
    finally:
        drv.shutdown()


def test_typed_error_classification():
    """host.* device_lost raises HostLostError — a DeviceLostError
    (the service requeue machinery applies) but NOT the mesh's partial
    loss, and carrying the host attribution the ladder uses."""
    from spark_rapids_tpu.errors import (
        DeviceLostError,
        HostLostError,
        MeshDeviceLostError,
    )
    from spark_rapids_tpu.runtime.faults import fault_point
    FAULTS.arm("host.dispatch:device_lost:1:1")
    with pytest.raises(HostLostError) as ei:
        fault_point("host.dispatch")
    assert isinstance(ei.value, DeviceLostError)
    assert not isinstance(ei.value, MeshDeviceLostError)
    assert ei.value.host_id is None  # injected: ladder picks victim
    FAULTS.disarm()
    FAULTS.arm("mesh.gather:device_lost:1:1")
    with pytest.raises(MeshDeviceLostError) as ei2:
        fault_point("mesh.gather")
    assert not isinstance(ei2.value, HostLostError)


def test_host_ladder_rungs_and_single_process_latch():
    """The full ladder contract on HEALTH.on_host_loss: retry ->
    reland -> shrink (bounded by maxHostLosses) -> single-process
    latch -> escalation to the whole-backend ladder; a cluster-native
    success resets the consecutive count."""
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.errors import HostLostError
    from spark_rapids_tpu.runtime.cluster import CLUSTER
    from spark_rapids_tpu.runtime.health import HEALTH
    _session().placement.prepare()  # declared 2-host topology
    conf = RapidsConf({"spark.rapids.cluster.maxHostLosses": "1"})
    e = HostLostError("injected", host_id="h1")
    assert HEALTH.on_host_loss(e, conf) == "retry"
    assert HEALTH.on_host_loss(e, conf) == "reland"
    assert "h1" in CLUSTER.health_snapshot()["lostHosts"]
    assert HEALTH.on_host_loss(e, conf) == "shrink"
    assert "h1" in CLUSTER.health_snapshot()["excludedHosts"]
    # shrink reset the consecutive count — a fresh ladder
    assert HEALTH.on_host_loss(e, conf) == "retry"
    assert HEALTH.on_host_loss(e, conf) == "reland"
    # shrink budget (1) spent: the bottom cluster rung latches
    assert HEALTH.on_host_loss(e, conf) == "single_process"
    snap = CLUSTER.health_snapshot()
    assert snap["singleProcessReason"] is not None
    # losses under the latch escalate to the whole-backend ladder
    assert HEALTH.on_host_loss(e, conf) in ("DEGRADED", "CPU_ONLY")
    # a cluster-native success resets the consecutive count
    HEALTH.reset()
    CLUSTER.restore()
    assert HEALTH.on_host_loss(e, conf) == "retry"
    HEALTH.note_success(cluster_native=True)
    assert HEALTH.on_host_loss(e, conf) == "retry"


def test_rl_fault_point_host_domain():
    """The host fault domain rides the SAME two-direction audit as
    every other point class: an UNREGISTERED host point at a call site
    is flagged, and a registered ``host.*`` point whose call site
    disappears (the multi-host path silently losing chaos coverage)
    is flagged from the registry side."""
    import ast

    from spark_rapids_tpu.lint.repo_lint import (
        _check_fault_registry,
        _check_fault_sites,
    )
    from spark_rapids_tpu.runtime.faults import FAULT_POINTS

    # direction 1: a host-looking point nobody registered
    src = ("from spark_rapids_tpu.runtime.faults import fault_point\n"
           "fault_point('host.reland.unregistered')\n")
    diags = []
    _check_fault_sites("spark_rapids_tpu/runtime/foo.py",
                       ast.parse(src), {}, diags)
    hits = [d for d in diags if d.rule_id == "RL-FAULT-POINT"]
    assert len(hits) == 1 and "not registered" in hits[0].message

    # direction 2: every registered host.* point with NO call site ->
    # one registry-side diagnostic each (the points exist)
    host_points = [n for n in FAULT_POINTS if n.startswith("host.")]
    assert len(host_points) == 4, host_points
    calls2 = {name: [f"{module}:1"]
              for name, (module, _) in FAULT_POINTS.items()
              if not name.startswith("host.")}
    diags2 = []
    _check_fault_registry(calls2, diags2)
    uncalled = [d for d in diags2 if "no fault_point" in d.message]
    assert len(uncalled) == len(host_points)
    assert any("host.heartbeat" in d.message for d in uncalled)


def test_hosts_flag_validation():
    """validate_flags rejects the --hosts combinations the harness
    does not implement, naming the supported modes."""
    from types import SimpleNamespace

    import scale_test as st

    def args(**kw):
        base = dict(mesh=0, hosts=0, streaming=False, concurrency=0,
                    service_faults=False,
                    cpu_baseline=False, require_tpu=False, chaos=False,
                    device_budget=0)
        base.update(kw)
        return SimpleNamespace(**base)

    st.validate_flags(args(hosts=2))  # supported
    st.validate_flags(args(hosts=2, chaos=True))  # supported
    for bad in (args(hosts=1),
                args(hosts=2, mesh=4),
                args(hosts=2, concurrency=2),
                args(hosts=2, cpu_baseline=True),
                args(hosts=2, require_tpu=True),
                args(hosts=2, chaos=True, service_faults=True)):
        with pytest.raises(SystemExit) as ei:
            st.validate_flags(bad)
        assert "supported modes" in str(ei.value)
