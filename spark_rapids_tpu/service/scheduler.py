"""QueryService: worker pool + admission control + weighted fair queueing.

Reference: the reference plugin leans on Spark's scheduler — FAIR
scheduler pools (``spark.scheduler.pool``) queue jobs per tenant, the
driver bounds concurrent tasks, and ``GpuSemaphore`` bounds how many of
those touch the device at once. This engine owns its sessions, so this
module provides that stack natively:

* **Admission**: bounded per-pool queue depth; a full queue raises the
  typed :class:`~spark_rapids_tpu.errors.QueryRejectedError` carrying a
  ``retry_after_ms`` backpressure hint. Before a worker takes a query,
  admission consults the spill catalog's device-resident bytes
  (``spark.rapids.service.admission.maxDeviceBytes``): over the high
  water mark, queued queries HOLD until a running query finishes —
  unless nothing is running (forward progress beats the gate).
* **Scheduling**: two-level weighted fair queueing. Pools come from
  ``spark.rapids.service.pools`` (``name[:weight=W]`` entries); tenants
  weight via ``spark.rapids.service.tenantWeights``. Each completed
  query charges its wall time / weight to its pool and tenant virtual
  clocks; the next admitted query comes from the least-charged pool,
  then the least-charged tenant within it. A newly active tenant joins
  at the pool's current minimum clock so it can neither starve veterans
  nor be starved by them.
* **Execution**: ``maxConcurrentQueries`` daemon workers share ONE
  TpuSession — `TpuSession.execute` is concurrency-safe (thread-local
  envelopes, worker-scoped span attribution) and the TpuSemaphore
  finally sees real concurrent acquirers. Results optionally come from
  / fill the plan-fingerprint result cache (result_cache.py).
* **Lifecycle**: deadlines (``defaultTimeoutMs`` or per-submit) expire
  queued queries at the sweep and running ones cooperatively at exec
  boundaries; ``QueryHandle.cancel()`` likewise.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.conf import (
    RapidsConf,
    bool_conf,
    float_conf,
    int_conf,
    str_conf,
)
from spark_rapids_tpu.errors import (
    ColumnarProcessingError,
    DeviceLostError,
    QueryCancelledError,
    QueryQuarantinedError,
    QueryRejectedError,
    QueryTimeoutError,
    WorkerLostError,
)
from spark_rapids_tpu.runtime.faults import fault_point
from spark_rapids_tpu.runtime.health import (
    HEALTH,
    QUARANTINE,
    QUARANTINE_MAX_STRIKES,
)
from spark_rapids_tpu.service.query import (
    QueryHandle,
    QueryState,
    cancel_scope,
)
from spark_rapids_tpu.service.result_cache import (
    ResultCache,
    epoch_snapshot,
    fingerprint,
    invalidation_epoch,  # noqa: F401  (stable import surface for tests)
    plan_table_ids,
)
from spark_rapids_tpu.service.watchdog import WorkerWatchdog, _Worker
from spark_rapids_tpu.lockorder import ordered_condition, ordered_lock


def _mesh_shape():
    """The active mesh topology for serve-time event records (None when
    mesh-native execution is off)."""
    from spark_rapids_tpu.parallel.mesh import MESH
    return MESH.shape_str()


def _host_topology():
    """The active cluster host topology for serve-time event records
    (None when cluster execution is off)."""
    from spark_rapids_tpu.runtime.cluster import CLUSTER
    return CLUSTER.topology_str()


def _mem_budget_peak() -> int:
    """The memory arbiter's peak accounted device bytes for serve-time
    event records (schema v10 budgetPeak)."""
    from spark_rapids_tpu.runtime.memory import MEMORY
    return int(MEMORY.peak_bytes())


SERVICE_POOLS = str_conf(
    "spark.rapids.service.pools", "default",
    "Named scheduling pools: semicolon-separated 'name[:weight=W]' "
    "entries (weight defaults to 1.0). Submissions name a pool; the "
    "scheduler shares workers across pools by weighted fair queueing "
    "on measured query wall time (FAIR scheduler pools analog).",
    commonly_used=True)

SERVICE_MAX_CONCURRENT = int_conf(
    "spark.rapids.service.maxConcurrentQueries", 4,
    "Worker threads executing admitted queries concurrently against "
    "the shared session. Device residency within them is still gated "
    "by spark.rapids.sql.concurrentGpuTasks (TpuSemaphore).",
    commonly_used=True)

SERVICE_QUEUE_DEPTH = int_conf(
    "spark.rapids.service.queueDepth", 64,
    "Max queued (not yet running) queries per pool; submission beyond "
    "it raises QueryRejectedError with a retry_after_ms backpressure "
    "hint instead of queueing unboundedly.")

SERVICE_DEFAULT_TIMEOUT_MS = int_conf(
    "spark.rapids.service.defaultTimeoutMs", 0,
    "Default per-query deadline from submission, milliseconds; expiry "
    "times the query out while queued or cooperatively between batches "
    "while running. 0 = no deadline; submit(timeout_ms=...) overrides "
    "per query.")

SERVICE_TENANT_WEIGHTS = str_conf(
    "spark.rapids.service.tenantWeights", "",
    "Per-tenant fair-share weights inside a pool: comma-separated "
    "'tenant=W' entries; unlisted tenants weigh 1.0. A tenant with "
    "weight 2 receives twice the service of a weight-1 tenant under "
    "contention.")

SERVICE_ADMISSION_MAX_DEVICE_BYTES = int_conf(
    "spark.rapids.service.admission.maxDeviceBytes", 0,
    "Memory-pressure admission gate: while the spill catalog reports "
    "more device-resident spillable bytes than this, queued queries "
    "hold instead of dispatching (a query is always released when "
    "nothing is running, so the gate cannot deadlock). 0 disables.")

SERVICE_RESULT_CACHE_ENABLED = bool_conf(
    "spark.rapids.service.resultCache.enabled", True,
    "Serve repeated queries from the plan-fingerprint result cache "
    "(service/result_cache.py): structurally identical plans under "
    "result-identical conf return the cached HostTable without "
    "executing. Invalidated by catalog mutations and table writes.")

SERVICE_RESULT_CACHE_MAX_BYTES = int_conf(
    "spark.rapids.service.resultCache.maxBytes", 256 << 20,
    "LRU byte bound on cached result tables (HostTable.nbytes sum); "
    "results larger than this never cache.")

SERVICE_INTROSPECT_ENABLED = bool_conf(
    "spark.rapids.service.introspect.enabled", False,
    "Serve the service's live surface (health/stats/SLOs/query table/"
    "telemetry tail) as JSON on a loopback-only HTTP endpoint "
    "(service/introspect.py) polled by `python -m spark_rapids_tpu."
    "tools top`. The bound port is QueryService.introspect_port.",
    commonly_used=True)

SERVICE_INTROSPECT_PORT = int_conf(
    "spark.rapids.service.introspect.port", 0,
    "Port for the loopback introspection endpoint; 0 (default) binds "
    "an ephemeral port, reported as QueryService.introspect_port.")

SERVICE_DEGRADE_ON_HOST_LOSS = bool_conf(
    "spark.rapids.service.degrade.onHostLoss", True,
    "Driver/service unification: while the cluster runtime serves "
    "below its declared host strength (lost or excluded hosts, or the "
    "single-process latch), the service reports DEGRADED and sheds "
    "its lowest-weight pool under load, exactly as it does for its "
    "own worker losses. Off restores the pre-fleet behavior where "
    "the service was blind to host topology.")

SERVICE_DEGRADE_MEMORY_FRACTION = float_conf(
    "spark.rapids.service.degrade.memoryOccupancyFraction", 0.0,
    "While the memory arbiter's live occupancy exceeds this fraction "
    "of its device budget, the service reports DEGRADED and sheds its "
    "lowest-weight pool under load — backpressure from the memory "
    "fault domain into admission control. 0 (default) disables.")


def parse_pools(spec: str) -> "OrderedDict[str, float]":
    """'name[:weight=W];...' -> {name: weight}. Raises on duplicates,
    empty names, or non-positive weights (a typo'd pool spec must fail
    service construction, not silently rebalance)."""
    pools: "OrderedDict[str, float]" = OrderedDict()
    for entry in (e.strip() for e in str(spec).split(";")):
        if not entry:
            continue
        name, _, rest = entry.partition(":")
        name = name.strip()
        weight = 1.0
        if rest:
            key, _, val = rest.partition("=")
            if key.strip() != "weight" or not val:
                raise ColumnarProcessingError(
                    f"bad pool spec entry {entry!r} (want "
                    "'name[:weight=W]')")
            try:
                weight = float(val)
            except ValueError:
                raise ColumnarProcessingError(
                    f"pool {name!r} weight {val!r} is not a number "
                    "(spark.rapids.service.pools)")
        if not name:
            raise ColumnarProcessingError(
                f"bad pool spec entry {entry!r}: empty pool name")
        if name in pools:
            raise ColumnarProcessingError(
                f"duplicate pool {name!r} in spark.rapids.service.pools")
        if weight <= 0:
            raise ColumnarProcessingError(
                f"pool {name!r} weight must be positive, got {weight}")
        pools[name] = weight
    if not pools:
        raise ColumnarProcessingError(
            "spark.rapids.service.pools defines no pools")
    return pools


def parse_tenant_weights(spec: str) -> Dict[str, float]:
    """'tenant=W,tenant=W' -> {tenant: weight}; unlisted tenants 1.0."""
    out: Dict[str, float] = {}
    for entry in (e.strip() for e in str(spec).split(",")):
        if not entry:
            continue
        name, sep, val = entry.partition("=")
        if not sep or not name.strip():
            raise ColumnarProcessingError(
                f"bad tenant weight entry {entry!r} (want 'tenant=W')")
        try:
            w = float(val)
        except ValueError:
            raise ColumnarProcessingError(
                f"tenant {name.strip()!r} weight {val!r} is not a "
                "number (spark.rapids.service.tenantWeights)")
        if w <= 0:
            raise ColumnarProcessingError(
                f"tenant {name.strip()!r} weight must be positive, got {w}")
        out[name.strip()] = w
    return out


def _default_memory_probe() -> int:
    """Admission's device-occupancy read: the memory arbiter's LIVE
    ledger (every accounted landing and kernel intermediate, not only
    spill-catalog-registered buffers) — the max with the catalog's own
    view covers any spillable registered before its table was ever
    accounted. The forward-progress escape (admit when nothing runs)
    lives in the gate, unchanged."""
    from spark_rapids_tpu.runtime.memory import MEMORY
    from spark_rapids_tpu.runtime.spill import BufferCatalog
    return max(BufferCatalog.get().device_bytes(), MEMORY.occupancy())


class QueryService:
    """Concurrent multi-tenant front end over one TpuSession.

    >>> svc = QueryService({"spark.rapids.service.maxConcurrentQueries": 4})
    >>> h = svc.submit(df, tenant="alice")
    >>> table = h.result(timeout=60)

    Accepts DataFrames, raw PlanNodes, or SQL text (lowered through the
    shared session's catalog at submit time, so parse/analysis errors
    surface to the submitter immediately)."""

    #: how long an idle worker sleeps between deadline sweeps
    _SWEEP_INTERVAL_S = 0.05

    def __init__(self, conf=None, session=None,
                 max_concurrent: Optional[int] = None,
                 queue_depth: Optional[int] = None):
        if session is None:
            from spark_rapids_tpu.session import TpuSession
            session = TpuSession(conf)
        elif conf is not None:
            raise ColumnarProcessingError(
                "pass conf or a session, not both (the service reads "
                "its knobs from the session's conf)")
        self.session = session
        self.conf: RapidsConf = session.conf
        # arm the runtime lock witness FIRST (construction-time
        # election): every lock this __init__ builds — the scheduler
        # condition, the streams lock, the result cache's — is wrapped
        # iff the conf arms it
        from spark_rapids_tpu import lockorder
        lockorder.configure(self.conf)
        self.pools = parse_pools(self.conf.get_entry(SERVICE_POOLS))
        self.tenant_weights = parse_tenant_weights(
            self.conf.get_entry(SERVICE_TENANT_WEIGHTS))
        self.max_concurrent = max(1, int(
            max_concurrent if max_concurrent is not None
            else self.conf.get_entry(SERVICE_MAX_CONCURRENT)))
        self.queue_depth = max(1, int(
            queue_depth if queue_depth is not None
            else self.conf.get_entry(SERVICE_QUEUE_DEPTH)))
        self.default_timeout_ms = int(
            self.conf.get_entry(SERVICE_DEFAULT_TIMEOUT_MS))
        self.admission_max_device_bytes = int(
            self.conf.get_entry(SERVICE_ADMISSION_MAX_DEVICE_BYTES))
        # fleet-degrade knobs — read BEFORE workers spawn (workers
        # consult _health_state_locked from their first pick)
        self._degrade_on_host_loss = bool(
            self.conf.get_entry(SERVICE_DEGRADE_ON_HOST_LOSS))
        self._degrade_memory_fraction = float(
            self.conf.get_entry(SERVICE_DEGRADE_MEMORY_FRACTION))
        # exclusive mesh occupancy: a multi-device computation's
        # collective rendezvous requires every device to reach ITS
        # launch, but each device executes launches in arrival order —
        # two concurrent mesh queries can interleave arrival per-device
        # and deadlock both rendezvous. When this service drives a
        # mesh topology, workers serialize the device-launch window
        # (admission, queues, watchdog and SLO machinery stay fully
        # concurrent); single-chip services skip the gate entirely.
        from spark_rapids_tpu.parallel.mesh import MESH_ENABLED
        self._mesh_gate = None
        if bool(self.conf.get_entry(MESH_ENABLED)):
            self._mesh_gate = ordered_lock("service.mesh_gate")
        self.result_cache: Optional[ResultCache] = None
        if bool(self.conf.get_entry(SERVICE_RESULT_CACHE_ENABLED)):
            self.result_cache = ResultCache(
                int(self.conf.get_entry(SERVICE_RESULT_CACHE_MAX_BYTES)))
        #: injectable for tests; production consults the spill catalog
        self._memory_probe = _default_memory_probe
        #: recurring tenants (streaming/query.py StreamingQuery
        #: registers itself for its lifetime): name -> stream object
        #: exposing describe() — surfaced by streams()/stats()//top so
        #: long-lived micro-batch streams are visible next to one-shot
        #: queries
        self._streams_lock = ordered_lock("service.scheduler.streams")
        self._streams: Dict[str, object] = {}
        self._mvs = None

        self._cond = ordered_condition("service.scheduler.cond")
        #: (pool, tenant) -> FIFO of queued handles
        self._queues: Dict[Tuple[str, str], deque] = {}
        #: per-pool queued-handle count (admission bound)
        self._queued_per_pool: Dict[str, int] = {p: 0 for p in self.pools}
        #: WFQ virtual clocks: seconds of service / weight
        self._tenant_clock: Dict[Tuple[str, str], float] = {}
        self._pool_clock: Dict[str, float] = {p: 0.0 for p in self.pools}
        self._running = 0
        self._held_for_memory = 0
        self._memory_gate_was_open = True
        self._shutdown = False
        self._recent_run_s: deque = deque(maxlen=32)
        self.counters = {"submitted": 0, "finished": 0, "failed": 0,
                         "cancelled": 0, "timed_out": 0, "rejected": 0,
                         "requeued": 0, "quarantineRejected": 0,
                         "hardTimeouts": 0}
        # survivability state (runtime/health.py, service/watchdog.py):
        # worker lifecycle counters, the DEGRADED latch (cleared by
        # _DEGRADE_CLEAR_SUCCESSES completed queries — event-count
        # based, so tests and chaos runs are wall-clock free), and the
        # quarantine strike budget. ALL mutated under _cond.
        from spark_rapids_tpu.obs.metrics import metric_scope
        self._health_metrics = metric_scope("health")
        self._workers_lost = 0
        self._workers_respawned = 0
        self._degraded_pending = 0
        self.quarantine_max_strikes = int(
            self.conf.get_entry(QUARANTINE_MAX_STRIKES))
        #: the pool DEGRADED mode sheds first (lowest weight; name
        #: breaks ties) — None with a single pool (nothing to shed to)
        self._shed_pool = (min(self.pools,
                               key=lambda p: (self.pools[p], p))
                           if len(self.pools) > 1 else None)

        # arm the chaos registry NOW: the service-level fault points
        # (service.worker_crash) fire in the scheduler BEFORE the first
        # session.execute would have armed it from the same conf
        # (re-arming an identical spec later is a no-op by contract)
        from spark_rapids_tpu.conf import TEST_FAULTS
        from spark_rapids_tpu.runtime.faults import FAULTS
        FAULTS.arm(str(self.conf.get_entry(TEST_FAULTS) or ""))

        self._worker_seq = 0
        self._workers: List[_Worker] = []
        with self._cond:
            for _ in range(self.max_concurrent):
                self._spawn_worker_locked()
        # dedicated deadline sweeper: idle workers sweep too, but when
        # EVERY worker is busy a queued query's deadline must still
        # expire on time (the backpressure signal is useless late)
        self._sweeper = threading.Thread(target=self._sweeper_loop,
                                         name="rapids-svc-sweeper",
                                         daemon=True)
        self._sweeper.start()
        # the watchdog: hard wall limits on RUNNING queries + the
        # dead-worker liveness backstop (service/watchdog.py)
        self._watchdog = WorkerWatchdog(self)

        # rolling SLO window: (pool, tenant) -> deque of
        # (latency_s, run_s) for recently FINISHED handles — the
        # introspection endpoint's p50/p95 source. Mutated under _cond.
        self._finished_lat: Dict[Tuple[str, str], deque] = {}

        # observability plumbing (obs/telemetry.py): the sampler +
        # flight-recorder defaults follow this service's conf, and the
        # recorder embeds this service's live query table in incident
        # bundles (weak registration — shutdown just drops out)
        from spark_rapids_tpu.obs.telemetry import (
            TELEMETRY,
            register_service,
        )
        TELEMETRY.configure(self.conf)
        register_service(self)
        # the device memory arbiter's budget follows this service's
        # conf too (admission consults its live occupancy)
        from spark_rapids_tpu.runtime.memory import MEMORY
        MEMORY.configure(self.conf)
        # the service runs AS the cluster driver: constructing it
        # configures the host-cluster runtime from the same conf, so
        # admission control, quarantine, the /slo surface, and the
        # three degradation ladders all see ONE topology — and the
        # DEGRADED/shedding decision below consults live host strength
        # and arbiter occupancy from that shared view
        from spark_rapids_tpu.runtime.cluster import CLUSTER
        CLUSTER.configure(self.conf)

        # live introspection endpoint (service/introspect.py):
        # loopback-only HTTP JSON, polled by `tools top`
        self.introspect = None
        self.introspect_port: Optional[int] = None
        if bool(self.conf.get_entry(SERVICE_INTROSPECT_ENABLED)):
            from spark_rapids_tpu.service.introspect import (
                IntrospectionServer,
            )
            self.introspect = IntrospectionServer(
                self, int(self.conf.get_entry(SERVICE_INTROSPECT_PORT)))
            self.introspect_port = self.introspect.port

    # -- submission ----------------------------------------------------------
    def submit(self, query, *, tenant: str = "default",
               pool: Optional[str] = None,
               timeout_ms: Optional[int] = None,
               tag: Optional[str] = None) -> QueryHandle:
        """Admit one query. ``query`` is a DataFrame, a PlanNode, or SQL
        text. Raises QueryRejectedError when the pool queue is full (or
        when DEGRADED mode is shedding this pool's load) and
        QueryQuarantinedError when the query's template is
        quarantined."""
        pool = pool if pool is not None else next(iter(self.pools))
        if pool not in self.pools:
            raise ColumnarProcessingError(
                f"unknown scheduling pool {pool!r} "
                f"(configured: {', '.join(self.pools)})")
        plan, sql_text = self._resolve(query)
        if timeout_ms is None:
            timeout_ms = self.default_timeout_ms
        deadline = (time.monotonic() + timeout_ms / 1000.0
                    if timeout_ms and timeout_ms > 0 else None)
        handle = QueryHandle(tenant=tenant, pool=pool, tag=tag,
                             sql_text=sql_text, plan=plan,
                             deadline=deadline)
        handle._service = self
        # poison-query quarantine (runtime/health.py): templates that
        # killed workers/the device quarantine.maxStrikes times are
        # refused outright, with the strike history attached. The
        # template fingerprint walk runs OUTSIDE the scheduler lock,
        # and ONLY when something is actually quarantined — the clean
        # process pays one snapshot call per submit
        if QUARANTINE.snapshot()["quarantined"]:
            quarantined = QUARANTINE.is_quarantined(
                self._template_fp(handle))
            if quarantined is not None:
                with self._cond:
                    self.counters["quarantineRejected"] += 1
                raise QueryQuarantinedError(
                    f"query template is quarantined after "
                    f"{len(quarantined)} worker/device kills; "
                    "submission refused", strikes=quarantined)
        with self._cond:
            if self._shutdown:
                raise ColumnarProcessingError(
                    "query service is shut down")
            # DEGRADED mode sheds the lowest-weight pool's load first:
            # a service recovering from worker/device loss keeps its
            # high-weight tenants served and pushes back on the rest.
            # Forward progress beats the shed (memory-gate precedent):
            # the DEGRADED latch only pays down as queries FINISH, so
            # an otherwise-idle service must admit the shed pool — its
            # completions are the only way back to HEALTHY when no
            # higher-weight traffic is flowing
            if (pool == self._shed_pool
                    and (self._running > 0
                         or any(self._queued_per_pool.values()))
                    and self._health_state_locked() == "DEGRADED"):
                self.counters["rejected"] += 1
                raise QueryRejectedError(
                    f"service is DEGRADED; shedding lowest-weight pool "
                    f"{pool!r} load — retry later",
                    retry_after_ms=self._retry_after_ms_locked(pool))
            if self._queued_per_pool[pool] >= self.queue_depth:
                self.counters["rejected"] += 1
                raise QueryRejectedError(
                    f"pool {pool!r} queue is full "
                    f"({self.queue_depth} queued); retry later",
                    retry_after_ms=self._retry_after_ms_locked(pool))
            self._activate_locked(pool, tenant)
            self._queues.setdefault((pool, tenant),
                                    deque()).append(handle)
            self._queued_per_pool[pool] += 1
            self.counters["submitted"] += 1
            # notify_all: the deadline sweeper shares the condition, so
            # a single notify could wake it instead of a worker
            self._cond.notify_all()
        return handle

    def _resolve(self, query):
        from spark_rapids_tpu.plan import DataFrame
        from spark_rapids_tpu.plan.nodes import PlanNode
        if isinstance(query, str):
            df = self.session.sql(query)
            return df.plan, query
        if isinstance(query, DataFrame):
            return query.plan, getattr(query, "sql_text", None)
        if isinstance(query, PlanNode):
            return query, None
        raise TypeError(
            f"cannot submit {type(query).__name__}; want DataFrame, "
            "PlanNode, or SQL text")

    def _template_fp(self, handle: QueryHandle) -> Optional[str]:
        """The quarantine key: the handle's literal-stripped structural
        template (plan/fingerprint.py — PR 6), computed AT MOST ONCE
        and only when actually needed (a clean process's submit path
        pays no plan walk). None for plans too dynamic to fingerprint;
        those cannot be quarantined (each run is structurally unique,
        so a strike ledger would never match)."""
        if not handle._template_fp_done:
            from spark_rapids_tpu.plan.fingerprint import (
                template_fingerprint,
            )
            handle.template_fp = template_fingerprint(handle.plan,
                                                      self.conf)
            handle._template_fp_done = True
        return handle.template_fp

    def _handle_has_strikes(self, handle: QueryHandle) -> bool:
        """Does this handle's template carry poison strikes? (the v4
        event-log ``quarantined`` flag). Fingerprint computed only when
        the ledger has any strikes at all."""
        if not QUARANTINE.snapshot()["strikes"]:
            return False
        return QUARANTINE.strike_count(self._template_fp(handle)) > 0

    def _retry_after_ms_locked(self, pool: str) -> int:
        mean_run = (sum(self._recent_run_s) / len(self._recent_run_s)
                    if self._recent_run_s else 0.1)
        backlog = self._queued_per_pool[pool] + self._running
        est = mean_run * backlog / max(self.max_concurrent, 1)
        return max(50, int(est * 1000))

    #: tenant-clock entries kept for idle tenants before pruning — the
    #: re-activation lift makes pruning fairness-neutral, so this only
    #: bounds memory/scan cost under ephemeral per-user tenant ids
    _MAX_IDLE_CLOCKS = 4096

    def _activate_locked(self, pool: str, tenant: str) -> None:
        """A tenant (re)gaining queued work joins the fair-share race at
        no less than the pool's ACTIVE minimum clock: idle time must not
        bank credit a returning burst could spend monopolizing workers
        (standard WFQ virtual-time lift). Pools likewise."""
        key = (pool, tenant)
        busy = [c for (p, t), c in self._tenant_clock.items()
                if p == pool and (p, t) != key
                and self._queues.get((p, t))]
        cur = self._tenant_clock.get(key, 0.0)
        self._tenant_clock[key] = max(cur, min(busy)) if busy else cur
        busy_pools = [self._pool_clock[p] for p in self.pools
                      if p != pool and self._queued_per_pool.get(p)]
        if busy_pools and not self._queued_per_pool.get(pool):
            self._pool_clock[pool] = max(self._pool_clock[pool],
                                         min(busy_pools))
        if len(self._tenant_clock) > self._MAX_IDLE_CLOCKS:
            # ephemeral tenant ids: drop idle entries (no queued work);
            # if they return, the lift above restores a fair position
            for k in [k for k in self._tenant_clock
                      if k != key and not self._queues.get(k)]:
                del self._tenant_clock[k]

    def _drop_if_empty_locked(self, key) -> None:
        """Empty per-tenant deques are DELETED so the sweep and pick
        scans stay proportional to tenants with pending work, not to
        every tenant ever seen."""
        dq = self._queues.get(key)
        if dq is not None and not dq:
            del self._queues[key]

    # -- scheduling ----------------------------------------------------------
    def _remove_queued(self, handle: QueryHandle) -> bool:
        """Pull a still-queued handle out (cancel path). True when the
        handle was queued and is now removed."""
        with self._cond:
            key = (handle.pool, handle.tenant)
            dq = self._queues.get(key)
            if dq is not None:
                try:
                    dq.remove(handle)
                except ValueError:
                    return False
                self._queued_per_pool[handle.pool] -= 1
                self._drop_if_empty_locked(key)
                return True
            return False

    def _sweep_expired_locked(self) -> None:
        """Time out / cancel queued handles whose deadline passed or
        whose cancel flag is set, without running them."""
        for (pool, _tenant), dq in list(self._queues.items()):
            kept = [h for h in dq]
            for h in kept:
                if h.scope.cancelled.is_set():
                    dq.remove(h)
                    self._queued_per_pool[pool] -= 1
                    if h._transition(QueryState.CANCELLED,
                                     error=QueryCancelledError(
                                         "cancelled while queued")):
                        self.counters["cancelled"] += 1
                elif h.scope.expired():
                    dq.remove(h)
                    self._queued_per_pool[pool] -= 1
                    if h._transition(QueryState.TIMED_OUT,
                                     error=QueryTimeoutError(
                                         "deadline expired while "
                                         "queued")):
                        self.counters["timed_out"] += 1
            self._drop_if_empty_locked((pool, _tenant))

    def _memory_gate_open_locked(self) -> bool:
        """The spill-catalog admission gate: admit when under the high
        water mark, or when nothing is running (forward progress)."""
        limit = self.admission_max_device_bytes
        if limit <= 0 or self._running == 0:
            return True
        try:
            used = int(self._memory_probe())
        except Exception:
            return True  # a broken probe must not wedge the service
        return used <= limit

    def _pick_locked(self) -> Optional[QueryHandle]:
        """WFQ pop: least-charged pool, then least-charged tenant
        within it, FIFO within the tenant. The virtual clocks are
        ALREADY weight-normalized (_charge_locked adds elapsed/weight),
        so the pick compares them raw — dividing again here would give
        a weight-W party a W^2 share."""
        candidates = [(p, t, dq) for (p, t), dq in self._queues.items()
                      if dq]
        if not candidates:
            return None
        if not self._memory_gate_open_locked():
            if self._memory_gate_was_open:
                # count held ADMISSION EPISODES, not poll wakeups
                self._held_for_memory += 1
                self._memory_gate_was_open = False
            return None
        self._memory_gate_was_open = True
        best = min(
            candidates,
            key=lambda c: (
                self._pool_clock[c[0]],
                self._tenant_clock[(c[0], c[1])],
                c[2][0].query_id,
            ))
        pool, tenant, dq = best
        handle = dq.popleft()
        self._queued_per_pool[pool] -= 1
        self._drop_if_empty_locked((pool, tenant))
        return handle

    def _count_event(self, name: str, n: int = 1) -> None:
        """All lifecycle counter bumps funnel here: counters are read
        under the condition lock (stats, retry-after), so every writer
        must hold it too or concurrent workers lose increments. A
        completed query also pays down the DEGRADED latch — the
        service proved it can finish work again."""
        with self._cond:
            self.counters[name] += n
            if name == "finished" and self._degraded_pending > 0:
                self._degraded_pending -= 1

    def _charge_locked(self, handle: QueryHandle, elapsed_s: float):
        w_t = self.tenant_weights.get(handle.tenant, 1.0)
        key = (handle.pool, handle.tenant)
        self._pool_clock[handle.pool] += elapsed_s / self.pools[handle.pool]
        # .get: the idle-clock prune may have dropped the entry while
        # this query ran (the tenant had nothing else queued)
        self._tenant_clock[key] = (self._tenant_clock.get(key, 0.0)
                                   + elapsed_s / w_t)
        self._recent_run_s.append(max(elapsed_s, 1e-4))

    # -- workers -------------------------------------------------------------
    def _sweeper_loop(self):
        while True:
            with self._cond:
                if self._shutdown:
                    return
                self._sweep_expired_locked()
                self._cond.wait(timeout=self._SWEEP_INTERVAL_S)

    # -- survivability plumbing (watchdog + health, PR 7) --------------------

    #: times a handle is requeued after its worker/device died under it
    #: before it fails with the typed error (a bound, not a conf: the
    #: quarantine strike budget is the operator-facing knob)
    _DEVICE_LOSS_REPLAYS = 3
    _WORKER_LOSS_REPLAYS = 3
    #: completed queries that clear the DEGRADED latch after a
    #: worker/device loss (event-count based — deterministic in tests)
    _DEGRADE_CLEAR_SUCCESSES = 2

    def _spawn_worker_locked(self) -> "_Worker":
        self._worker_seq += 1
        w = _Worker(f"rapids-svc-worker-{self._worker_seq}")
        w.thread = threading.Thread(target=self._worker_loop, args=(w,),
                                    name=w.name, daemon=True)
        self._workers.append(w)
        w.thread.start()
        return w

    def _drop_worker_locked(self, w: "_Worker") -> None:
        if w in self._workers:
            self._workers.remove(w)

    def _note_worker_lost_locked(self, w: "_Worker") -> None:
        """One worker is gone (dead thread or watchdog-abandoned):
        count it, latch DEGRADED, and spawn a replacement so pool
        capacity holds. Caller holds the condition lock."""
        self._drop_worker_locked(w)
        self._workers_lost += 1
        self._health_metrics.add("workersLost", 1)
        self._degraded_pending = self._DEGRADE_CLEAR_SUCCESSES
        if not self._shutdown:
            self._spawn_worker_locked()
            self._workers_respawned += 1
            self._health_metrics.add("workersRespawned", 1)

    def _strike_locked(self, handle: QueryHandle, reason: str) -> bool:
        """Record a poison strike against the handle's template
        (fingerprint computed here on first need); returns True when
        this strike quarantined it."""
        return QUARANTINE.strike(self._template_fp(handle), reason,
                                 self.quarantine_max_strikes)

    def _requeue_locked(self, handle: QueryHandle) -> bool:
        """Put a handle whose worker/device died under it back at the
        FRONT of its queue (it already waited once; retrying promptly
        beats re-joining behind the backlog). Gated on the QUEUED
        transition: a handle some other path already drove terminal
        (e.g. the watchdog's hard timeout) must not be re-enqueued —
        a worker would pop it only to discard it, and the requeued
        counter the chaos bounds assert against would inflate."""
        if not handle._transition(QueryState.QUEUED):
            return False
        handle.requeues += 1
        self._activate_locked(handle.pool, handle.tenant)
        self._queues.setdefault((handle.pool, handle.tenant),
                                deque()).appendleft(handle)
        self._queued_per_pool[handle.pool] += 1
        self.counters["requeued"] += 1
        self._cond.notify_all()
        return True

    def _on_worker_death(self, w: "_Worker", handle: QueryHandle,
                         exc: BaseException) -> None:
        """The worker's runner machinery raised OUTSIDE the query (the
        ``service.worker_crash`` chaos point, or something genuinely
        broken): the thread is about to exit. Correct the pool
        accounting, respawn, strike the query's template, and requeue
        the handle — or fail it once its replay budget (or the
        quarantine budget) is spent."""
        fail_with = None
        with self._cond:
            if not w.lost:
                # the watchdog may have abandoned this worker already
                # (hard timeout fired while the runner was dying) — it
                # then owns both corrections
                w.lost = True
                self._running -= 1
                self._note_worker_lost_locked(w)
            else:
                self._drop_worker_locked(w)
            if not handle.done:
                quarantined_now = self._strike_locked(
                    handle, f"worker {w.name} killed by "
                            f"{type(exc).__name__}: {exc}")
                blocked = (quarantined_now or QUARANTINE.is_quarantined(
                    handle.template_fp) is not None)
                if (not self._shutdown and not blocked
                        and handle.requeues < self._WORKER_LOSS_REPLAYS
                        and self._requeue_locked(handle)):
                    pass
                elif blocked:
                    fail_with = QueryQuarantinedError(
                        "query template quarantined: it killed "
                        f"{len(QUARANTINE.history(handle.template_fp))}"
                        " worker(s)/device(s)",
                        strikes=QUARANTINE.history(handle.template_fp))
                else:
                    fail_with = WorkerLostError(
                        f"worker {w.name} died running this query "
                        f"({type(exc).__name__}: {exc}); replay budget "
                        f"spent after {handle.requeues} requeues")
            self._cond.notify_all()
        if fail_with is not None:
            if handle._transition(QueryState.FAILED, error=fail_with):
                self._count_event("failed")

    def _on_device_lost(self, handle: QueryHandle,
                        exc: DeviceLostError) -> None:
        """The device died under this query. The session's recovery
        (runtime/health.py) already reinitialized the backend and
        invalidated the device-referencing caches — DeviceLostError is
        RETRYABLE, so the service replays the query against the
        recovered backend up to its budget (CPU-only latch included:
        the replay then plans onto the CPU path and completes)."""
        fail_with: BaseException = exc
        with self._cond:
            self._degraded_pending = self._DEGRADE_CLEAR_SUCCESSES
            if handle.done:
                # already terminal (the watchdog's hard timeout beat
                # this loss to the handle): the device recovery
                # happened, but there is nothing to strike or replay —
                # a phantom strike would push an innocent template
                # toward quarantine
                return
            quarantined_now = self._strike_locked(
                handle, f"device loss during execution: {exc}")
            blocked = (quarantined_now or QUARANTINE.is_quarantined(
                handle.template_fp) is not None)
            if (not self._shutdown and not blocked
                    and handle.requeues < self._DEVICE_LOSS_REPLAYS
                    and self._requeue_locked(handle)):
                return
            if blocked:
                fail_with = QueryQuarantinedError(
                    "query template quarantined: it killed the device "
                    f"{len(QUARANTINE.history(handle.template_fp))} "
                    "time(s)",
                    strikes=QUARANTINE.history(handle.template_fp))
        if handle._transition(QueryState.FAILED, error=fail_with):
            self._count_event("failed")

    def _worker_loop(self, w: "_Worker"):
        while True:
            with self._cond:
                handle = None
                while handle is None:
                    if self._shutdown or w.lost:
                        self._drop_worker_locked(w)
                        return
                    self._sweep_expired_locked()
                    handle = self._pick_locked()
                    if handle is None:
                        self._cond.wait(timeout=self._SWEEP_INTERVAL_S)
                if not handle._transition(QueryState.ADMITTED):
                    continue  # terminal while queued; take another
                self._running += 1
                w.handle = handle
            died = False
            try:
                self._run(handle)
            except BaseException as exc:
                # the RUNNER died, not the query (_run absorbs query
                # failures): hand off to the death protocol and exit
                # this thread — a replacement is already spawned
                died = True
                self._on_worker_death(w, handle, exc)
                return
            finally:
                if not died:
                    with self._cond:
                        w.handle = None
                        lost = w.lost
                        if lost:
                            # the watchdog abandoned us mid-query and
                            # already corrected the running count;
                            # this thread just disappears
                            self._drop_worker_locked(w)
                        else:
                            self._running -= 1
                        self._cond.notify_all()
                    if lost:
                        return

    def _run(self, handle: QueryHandle):
        # mesh services serialize the WHOLE launch window, and do it
        # BEFORE the RUNNING transition: the hard wall measures from
        # RUNNING, so gate wait books as queue time — one wedged
        # holder (abandoned by the watchdog mid-dispatch) must not
        # cascade-abandon every worker queued behind the gate while
        # its stalled dispatch drains
        if self._mesh_gate is not None:
            with self._mesh_gate:
                self._run_exclusive(handle)
        else:
            self._run_exclusive(handle)

    def _run_exclusive(self, handle: QueryHandle):
        if not handle._transition(QueryState.RUNNING):
            return
        # RL-FAULT-POINT service.worker_crash: an exception HERE is the
        # WORKER dying (outside the query's own try), so it propagates
        # to _worker_loop's death protocol — respawn + requeue, not a
        # query failure
        fault_point("service.worker_crash")
        t0 = time.monotonic()
        try:
            # a cancel/deadline that raced the pop must win BEFORE any
            # serve — a cache hit is still a completion the caller was
            # told would not happen
            handle.scope.check()
            # epoch VECTOR before execution (global + the epochs of
            # every table this plan reads): a write landing while this
            # query runs must stale the entry we fill, not be masked by
            # it — and entries scoped to their read set survive commits
            # to unrelated tables
            epochs = (epoch_snapshot(plan_table_ids(handle.plan))
                      if self.result_cache is not None else None)
            fp = (fingerprint(handle.plan, self.conf)
                  if self.result_cache is not None else None)
            cached = (self.result_cache.get(fp)
                      if self.result_cache is not None else None)
            if cached is not None:
                handle.cache_hit = True
                self._emit_cache_hit_record(
                    handle, cached, time.monotonic() - t0)
                if handle._transition(QueryState.FINISHED,
                                      result=cached.table):
                    self._count_event("finished")
                    self._note_finished(handle)
                return
            with cancel_scope(handle.scope):
                self.session.next_query_tag = handle.tag
                if handle.sql_text:
                    self.session.next_query_sql = handle.sql_text
                self.session.next_query_service = {
                    "tenant": handle.tenant,
                    "pool": handle.pool,
                    "queueWaitS": round(handle.queue_wait_s or 0.0, 6),
                    "cacheHit": False,
                    "quarantined": self._handle_has_strikes(handle),
                }
                table = self.session.execute(handle.plan)
            # raw thread-local read: THIS query's record or None, never
            # the session-wide mirror of some other worker's query
            handle.event_record = self.session._q.event_record
            if self.result_cache is not None:
                self.result_cache.put(fp, table, handle.event_record,
                                      epochs=epochs)
            if handle._transition(QueryState.FINISHED, result=table):
                self._count_event("finished")
                self._note_finished(handle)
        except QueryCancelledError as exc:
            if handle._transition(QueryState.CANCELLED, error=exc):
                self._count_event("cancelled")
        except QueryTimeoutError as exc:
            if handle._transition(QueryState.TIMED_OUT, error=exc):
                self._count_event("timed_out")
        except DeviceLostError as exc:
            # retryable by contract: the backend already recovered
            # (runtime/health.py) — requeue against it, or fail typed
            # once the replay/quarantine budget is spent
            self._on_device_lost(handle, exc)
        except BaseException as exc:
            if handle._transition(QueryState.FAILED, error=exc):
                self._count_event("failed")
        finally:
            with self._cond:
                self._charge_locked(handle, time.monotonic() - t0)

    def _emit_cache_hit_record(self, handle: QueryHandle, entry,
                               serve_s: float) -> None:
        """A cache hit still shows up in the query event log: the
        filling run's record replays with hit attribution (tenant,
        pool, queue wait, cacheHit=true, serve wall time) so offline
        tools see served traffic, not just executed traffic."""
        from spark_rapids_tpu.obs import events as E
        if entry.event_record is None or not bool(
                self.conf.get_entry(E.EVENT_LOG_ENABLED)):
            return
        s = self.session
        with s._obs_lock:
            idx = s._obs_query_seq
            s._obs_query_seq += 1
        rec = dict(entry.event_record)
        rec.update({
            "queryIndex": idx,
            "queryTag": handle.tag,
            "wallS": round(serve_s, 6),
            "tenant": handle.tenant,
            "pool": handle.pool,
            "queueWaitS": round(handle.queue_wait_s or 0.0, 6),
            "cacheHit": True,
            # nothing executed on a result-cache serve: the filling
            # run's compile/bucket numbers must not replay as traffic
            "compileMs": 0.0,
            "executableCacheHit": False,
            "padWasteRows": 0,
            # v4 survivability fields at SERVE time (the filling run's
            # health deltas must not replay either)
            "healthState": HEALTH.state(),
            "quarantined": self._handle_has_strikes(handle),
            "deviceReinits": 0,
            "workerRestarts": 0,
            # v6 mesh fields at SERVE time: nothing crossed ICI for a
            # cached serve; meshShape reflects the mesh now active
            "meshShape": _mesh_shape(),
            "iciBytes": 0,
            "shardSkew": 0.0,
            # v7 mesh fault-domain fields: a cached serve gathers
            # nothing, so it can neither retry nor trip a checksum
            "meshDegradations": 0,
            "shardRetries": 0,
            "gatherChecksFailed": 0,
            # v8 host fault-domain fields at SERVE time (the schema's
            # documented contract — the filling run's host losses must
            # not replay as this serve's degradation events) and the
            # v9 per-host scan table: a cached serve dispatches nothing
            "hostTopology": _host_topology(),
            "hostsLost": 0,
            "hostRelands": 0,
            "dcnExchanges": 0,
            "hostScans": {},
            # v10 out-of-core fields: a cached serve lands nothing, so
            # no retries/spills replay; budgetPeak reads the arbiter's
            # serve-time peak like healthState reads serve-time health
            "oomRetries": 0,
            "splitRetries": 0,
            "spillBytes": 0,
            "unspills": 0,
            "budgetPeak": _mem_budget_peak(),
            # v11 streaming fields: a cached serve runs no micro-batch
            # and refreshes no view, so every delta is 0; mvEpoch stays
            # the filling run's — it describes the DATA being served,
            # which a valid cache entry still reflects
            "microBatches": 0,
            "mvRefreshes": 0,
            "mvIncrementalRefreshes": 0,
            "mvFullRecomputes": 0,
            "sinkCommits": 0,
            "sinkReplays": 0,
        })
        handle.event_record = rec
        try:
            s._write_event_record(rec)
        except OSError as exc:  # best-effort, like the session's writer
            print(f"spark_rapids_tpu: cache-hit event emission failed: "
                  f"{exc}")

    # -- lifecycle -----------------------------------------------------------
    def shutdown(self, wait: bool = True) -> None:
        """Stop accepting and stop workers after their current query.
        Still-queued handles are CANCELLED so their waiters unblock."""
        with self._cond:
            self._shutdown = True
            for (pool, _t), dq in self._queues.items():
                while dq:
                    h = dq.popleft()
                    self._queued_per_pool[pool] -= 1
                    if h._transition(QueryState.CANCELLED,
                                     error=QueryCancelledError(
                                         "service shut down")):
                        self.counters["cancelled"] += 1
            self._cond.notify_all()
            workers = list(self._workers)
        if wait:
            for w in workers:
                w.thread.join(timeout=30)
            self._sweeper.join(timeout=5)
            self._watchdog.join(timeout=5)
        if self.introspect is not None:
            self.introspect.shutdown()
            self.introspect = None
        # stop recurring streams + detach the MV registry's epoch
        # listener so neither outlives the service
        with self._streams_lock:
            streams, mvs = list(self._streams.values()), self._mvs
            self._streams.clear()
            self._mvs = None
        for s in streams:
            try:
                s.stop(wait=wait)
            except Exception:
                pass
        if mvs is not None:
            mvs.close()

    def __enter__(self) -> "QueryService":
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # -- introspection -------------------------------------------------------

    #: FINISHED handles retained per (pool, tenant) for the rolling
    #: SLO percentiles (a window, not a conf: the introspection
    #: surface is an operator tool, not a tuning target)
    _SLO_WINDOW = 512

    def _note_finished(self, handle: QueryHandle) -> None:
        """Record a FINISHED handle's latency/run wall into the rolling
        SLO window (the /slo endpoint's source)."""
        lat, run = handle.latency_s, handle.run_s
        with self._cond:
            dq = self._finished_lat.setdefault(
                (handle.pool, handle.tenant),
                deque(maxlen=self._SLO_WINDOW))
            dq.append((lat or 0.0, run or 0.0))

    @staticmethod
    def _pcts(vals: List[float]) -> Dict[str, float]:
        ordered = sorted(vals)
        n = len(ordered)

        def pct(q: float) -> float:
            return ordered[min(n - 1, int(q * n))]

        return {"p50S": round(pct(0.50), 6), "p95S": round(pct(0.95), 6)}

    def slo_snapshot(self) -> dict:
        """Rolling per-pool and per-tenant p50/p95 over recently
        FINISHED handles: ``latency`` = submit->finish (queue wait
        included — what a caller experiences), ``run`` = running wall
        only. Empty dicts before any query finishes."""
        with self._cond:
            windows = [((p, t), list(dq))
                       for (p, t), dq in self._finished_lat.items() if dq]
        pools: Dict[str, dict] = {}
        tenants: Dict[str, dict] = {}
        by_pool: Dict[str, list] = {}
        for (pool, tenant), samples in windows:
            by_pool.setdefault(pool, []).extend(samples)
            tenants[f"{pool}/{tenant}"] = {
                "count": len(samples),
                "latency": self._pcts([s[0] for s in samples]),
                "run": self._pcts([s[1] for s in samples]),
            }
        for pool, samples in by_pool.items():
            pools[pool] = {
                "count": len(samples),
                "latency": self._pcts([s[0] for s in samples]),
                "run": self._pcts([s[1] for s in samples]),
            }
        return {"window": self._SLO_WINDOW, "pools": pools,
                "tenants": dict(sorted(tenants.items()))}

    def query_table(self, blocking: bool = True) -> Optional[List[dict]]:
        """The live query table: RUNNING handles (from the workers)
        plus QUEUED handles in pick order context. ``blocking=False``
        is the flight recorder's no-wait contract: the recorder must
        never stall behind a busy scheduler, so a contended condition
        lock yields None ("table unavailable") instead of queueing the
        bundle write on it. (Condition wraps an RLock, so a same-
        thread caller re-enters successfully either way.)"""
        if not self._cond.acquire(blocking=blocking):
            return None
        try:
            now = time.monotonic()
            out: List[dict] = []
            for w in self._workers:
                h = w.handle
                if h is None:
                    continue
                out.append({
                    "id": h.query_id, "state": h.state,
                    "tenant": h.tenant, "pool": h.pool, "tag": h.tag,
                    "worker": w.name,
                    "runningS": (round(now - h.start_t, 3)
                                 if h.start_t is not None else None),
                })
            for (pool, tenant), dq in self._queues.items():
                for h in dq:
                    out.append({
                        "id": h.query_id, "state": "QUEUED",
                        "tenant": tenant, "pool": pool, "tag": h.tag,
                        "queuedS": round(now - h.submit_t, 3),
                    })
        finally:
            self._cond.release()
        return out

    def _fleet_degraded_reason(self) -> Optional[str]:
        """The driver/service unification's shedding input: live host
        strength and arbiter occupancy, read from the same singletons
        the degradation ladders mutate. Legal under the condition lock
        — cluster.runtime(300) and memory.arbiter(740) both rank above
        service.scheduler.cond(200), so these reads only ever acquire
        upward."""
        if self._degrade_on_host_loss:
            from spark_rapids_tpu.runtime.cluster import CLUSTER
            hosts = CLUSTER.health_snapshot()
            if hosts["enabled"]:
                if hosts["singleProcessReason"]:
                    return ("cluster latched single-process: "
                            f"{hosts['singleProcessReason']}")
                if hosts["lostHosts"] or hosts["excludedHosts"]:
                    return (
                        "cluster below declared strength: "
                        f"{len(hosts['liveHosts'])}/"
                        f"{hosts['declaredHosts']} live (lost="
                        f"{hosts['lostHosts']}, excluded="
                        f"{hosts['excludedHosts']})")
        frac = self._degrade_memory_fraction
        if frac > 0.0:
            from spark_rapids_tpu.runtime.memory import MEMORY
            budget = MEMORY.budget_bytes()
            occupancy = MEMORY.occupancy()
            if budget > 0 and occupancy > frac * budget:
                return (f"arbiter occupancy {occupancy}B over "
                        f"{frac:g} x budget {budget}B")
        return None

    def _health_state_locked(self) -> str:
        """HEALTHY → DEGRADED → CPU_ONLY. CPU_ONLY comes from the
        process-wide device latch; DEGRADED while the device is mid
        loss-streak, this service recently lost workers and has not
        yet completed _DEGRADE_CLEAR_SUCCESSES queries, OR the shared
        topology reports the fleet below strength (host loss, arbiter
        over occupancy) — the service IS the cluster driver, so its
        shedding decision consults the cluster's live state. Caller
        holds the condition lock (the degraded counter is mutated
        under it)."""
        device = HEALTH.state()
        if device == "CPU_ONLY":
            return "CPU_ONLY"
        if (device == "DEGRADED" or self._degraded_pending > 0
                or self._fleet_degraded_reason() is not None):
            return "DEGRADED"
        return "HEALTHY"

    def topology_snapshot(self) -> dict:
        """ONE coherent fleet-topology view (hosts + mesh + memory +
        ladders + quarantine) taken with every owning lock held — the
        shared-topology path (runtime/health.py); also served as the
        ``/topology`` introspection route."""
        from spark_rapids_tpu.runtime.health import (
            consistent_topology_snapshot,
        )
        return consistent_topology_snapshot()

    def health(self) -> dict:
        """The service health surface the ISSUE's states machine drives
        admission from (and ``tools loadtest`` reports). The hosts /
        mesh / memory sections come from ONE consistent topology
        snapshot — all owning locks held together — so the view cannot
        tear across a mid-query shrink (a host loss excludes mesh
        devices only after dropping the cluster lock; independent
        section reads could observe the gap)."""
        topo = self.topology_snapshot()
        with self._cond:
            out = {
                "state": self._health_state_locked(),
                "workersLost": self._workers_lost,
                "workersRespawned": self._workers_respawned,
                "workerCount": len(self._workers),
                "degradedPendingSuccesses": self._degraded_pending,
                "shedPool": self._shed_pool,
                "fleetDegradedReason": self._fleet_degraded_reason(),
            }
        out["cpuOnlyReason"] = topo["cpuOnlyReason"]
        out["device"] = topo["backend"]
        out["quarantine"] = topo["quarantine"]
        # the mesh fault domain: current topology (shrunken shape and
        # excluded devices after partial losses, with the degradation
        # reason) plus the ladder's counters — a degraded-but-serving
        # mesh is VISIBLE here, not silently smaller
        out["mesh"] = topo["mesh"]
        # the host fault domain above the mesh: current topology
        # (declared/live/lost/excluded hosts, the single-process latch)
        # plus the host ladder's counters — a cluster serving below
        # declared strength is VISIBLE here, not silently smaller
        out["hosts"] = topo["hosts"]
        # the memory fault domain: arbiter budget/occupancy/peak plus
        # the memory degradation ladder's counters — a query surviving
        # out-of-core is VISIBLE here, not silently slower
        out["memory"] = topo["memory"]
        out["topologyGeneration"] = topo["generation"]
        return out

    def stats(self) -> dict:
        # snapshot EVERYTHING mutated under _cond while holding it —
        # including the survivability fields — so a concurrent worker
        # can never hand back a torn view (pinned by the stats
        # concurrency test)
        with self._cond:
            out = {
                **self.counters,
                "running": self._running,
                "queued": {p: n for p, n in self._queued_per_pool.items()
                           if n},
                "heldForMemory": self._held_for_memory,
                "healthState": self._health_state_locked(),
                "workersLost": self._workers_lost,
                "workersRespawned": self._workers_respawned,
                "poolClocks": {p: round(c, 6)
                               for p, c in self._pool_clock.items()},
                "tenantClocks": {f"{p}/{t}": round(c, 6)
                                 for (p, t), c in
                                 self._tenant_clock.items()},
            }
        out["quarantine"] = QUARANTINE.snapshot()
        if self.result_cache is not None:
            out["resultCache"] = self.result_cache.stats()
        return out

    # -- recurring streams ---------------------------------------------------
    def register_stream(self, stream) -> None:
        """Register a recurring tenant (a StreamingQuery) for the
        introspection surfaces; latest registration wins a name."""
        with self._streams_lock:
            self._streams[stream.name] = stream

    def unregister_stream(self, name: str) -> None:
        with self._streams_lock:
            self._streams.pop(name, None)

    def streams(self) -> List[dict]:
        """Descriptors of every registered recurring stream (name,
        source kind, pool/tenant, batch/offset progress, state) —
        rendered by ``tools top`` and served on /top."""
        with self._streams_lock:
            items = sorted(self._streams.items())
        out = []
        for _, s in items:
            try:
                out.append(s.describe())
            except Exception:
                pass  # a dying stream must not break introspection
        return out

    def mv_registry(self):
        """The service's MaterializedViewRegistry (streaming/mv.py),
        created on first use over the shared session and torn down with
        the service (its epoch listener must not outlive it)."""
        with self._streams_lock:
            if self._mvs is None:
                from spark_rapids_tpu.streaming.mv import (
                    MaterializedViewRegistry,
                )
                self._mvs = MaterializedViewRegistry(self.session)
            return self._mvs
