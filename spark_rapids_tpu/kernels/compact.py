"""Pallas row compaction: mask -> one gather kernel over every column.

Row compaction — in every filter, join output, aggregate output pack
and split — is THE cost PERF.md's round-4 measurement pinned: the HLO
path scatters every column, and every 64-bit column scatters as 2-3
32-bit passes plus a recombine chain (ops/scatter32.py), so an
8-column table pays ~20 scatter passes over HBM.

This kernel inverts the data movement: ONE i32 scatter builds the
gather map (``sel[j]`` = source row of output slot j — the scatter's
payload is row indices, never column data), and a single fused kernel
then gathers every column's 32-bit limb streams through ``sel`` in one
pass, zeroing the dead tail exactly like the scatter path's zero-init
does. Scatter passes no longer scale with column count or width.

The limb policy matches ops/scatter32.py: 64-bit streams split on
backends where 64-bit scatter/gather serializes (non-CPU), and ride
natively on the CPU backend — where splitting f64 would be lossy and
the native gather is free. Either way the result is bit-identical to
the scatter_pair loop (pinned by tests/test_kernels.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from spark_rapids_tpu.kernels import KernelIneligible, config, interpret_mode
from spark_rapids_tpu.runtime.faults import fault_point


def _split_streams(datas, valids):
    """Flatten (data, validity) pairs into ≤32-bit gather streams plus
    a recombine recipe. Streams for one column: its validity plus
    either the raw array (narrow dtypes / CPU backend) or the two limb
    halves."""
    from spark_rapids_tpu.ops.limbs import split_f64_hi_lo, split_i64_hi_lo
    from spark_rapids_tpu.ops.scatter32 import _split_worthwhile
    streams = []
    recipe = []  # (kind, dtype) per column, kinds: raw | f64 | i64
    for d, v in zip(datas, valids):
        if not _split_worthwhile(d.dtype):
            streams.append(d)
            recipe.append(("raw", d.dtype))
        elif d.dtype == jnp.float64:
            hi, lo = split_f64_hi_lo(d)
            streams.extend((hi, lo))
            recipe.append(("f64", d.dtype))
        else:
            hi, lo = split_i64_hi_lo(d)
            streams.extend((hi, lo))
            recipe.append(("i64", d.dtype))
        streams.append(v)
    return streams, recipe


def _recombine(outs, recipe):
    from spark_rapids_tpu.ops.limbs import combine_f64, combine_i64
    pairs = []
    i = 0
    for kind, dtype in recipe:
        if kind == "raw":
            data = outs[i]
            i += 1
        elif kind == "f64":
            data = combine_f64(outs[i], outs[i + 1])
            i += 2
        else:
            data = combine_i64(outs[i], outs[i + 1]).astype(dtype)
            i += 2
        pairs.append((data, outs[i]))
        i += 1
    return pairs


def gather_compact(datas, valids, keep, pos, new_n, capacity: int):
    """[(data, validity)...] compacted to the row prefix — bit-identical
    to the per-column scatter_pair loop. ``pos`` is the exclusive-style
    cumsum position (cumsum(keep)-1) the caller already computed; the
    gather map inverts it with ONE i32 scatter."""
    fault_point("kernels.compact")
    nbytes = 0
    for d in datas:
        nbytes += d.dtype.itemsize * d.size + capacity  # data + validity
    if 3 * nbytes > config().vmem_budget:
        raise KernelIneligible("compaction working set exceeds the VMEM "
                               "budget")
    tgt = jnp.where(keep, pos, capacity)
    sel = jnp.zeros((capacity,), jnp.int32).at[tgt].set(
        jnp.arange(capacity, dtype=jnp.int32), mode="drop")
    out_live = jnp.arange(capacity, dtype=jnp.int32) < new_n

    streams, recipe = _split_streams(datas, valids)
    shapes = tuple((s.shape, str(s.dtype)) for s in streams)

    from spark_rapids_tpu.dispatch import pallas_program
    key = ("compact", capacity, shapes)

    def build():
        def kernel(*refs):
            n_in = len(streams)
            sel_v = refs[0][:]
            live_v = refs[1][:]
            for i in range(n_in):
                x = refs[2 + i][:]
                g = jnp.take(x, sel_v, axis=0)
                mask = live_v if x.ndim == 1 else live_v[:, None]
                refs[2 + n_in + i][:] = jnp.where(mask, g,
                                                  jnp.zeros_like(g))

        return pl.pallas_call(
            kernel,
            out_shape=[jax.ShapeDtypeStruct(s.shape, s.dtype)
                       for s in streams],
            interpret=interpret_mode())

    fn = pallas_program(key, build)
    outs = fn(sel, out_live, *streams)
    return _recombine(list(outs), recipe)
