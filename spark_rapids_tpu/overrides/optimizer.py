"""Cost-based optimizer (reference: CostBasedOptimizer.scala — SURVEY.md
§2.2 / VERDICT r1 missing #8).

The reference's CBO estimates each operator's GPU cost vs CPU cost from
row counts and conf-tunable per-op factors, and reverts plan SECTIONS to
CPU when the accelerator isn't worth the transfer+dispatch overhead (small
inputs are the classic case). Same shape here, adapted to the tunneled-TPU
cost model measured in PERF.md: a device query pays a fixed ~0.1s-class
dispatch/sync overhead plus per-row work that is far cheaper than CPU
per-row work.

Model (all conf-tunable):
  device_cost(plan) = execOverhead * n_execs + gpuRowCost * sum(rows)
  cpu_cost(plan)    = cpuRowCost * sum(rows)
When ``cpu_cost < device_cost`` for the WHOLE eligible plan, every node is
tagged with a CBO reason so conversion falls back — mirroring the
reference's "avoid transitions that don't pay for themselves" behavior.
Nodes without row estimates (no stats) leave the plan untouched, like the
reference treating unknown stats as not-optimizable.
"""

from __future__ import annotations

from typing import Optional

from spark_rapids_tpu.conf import bool_conf, float_conf

OPTIMIZER_ENABLED = bool_conf(
    "spark.rapids.sql.optimizer.enabled", False,
    "Cost-based optimizer: estimate device vs CPU cost from row counts and "
    "fall back plan sections that don't pay for the transfer/dispatch "
    "overhead (CostBasedOptimizer analog; off by default like the "
    "reference).")

OPTIMIZER_EXEC_OVERHEAD = float_conf(
    "spark.rapids.sql.optimizer.gpu.execOverhead", 0.05,
    "Estimated fixed cost (arbitrary units ~seconds) per device operator "
    "dispatch — the tunnel's per-sync latency class.")

OPTIMIZER_GPU_ROW_COST = float_conf(
    "spark.rapids.sql.optimizer.gpu.rowCost", 2e-9,
    "Estimated device cost per input row.")

OPTIMIZER_CPU_ROW_COST = float_conf(
    "spark.rapids.sql.optimizer.cpu.rowCost", 3e-7,
    "Estimated CPU cost per input row.")


def estimate_rows(node) -> Optional[int]:
    """Row-count estimate (the stats Spark's CBO reads from the logical
    plan). Leaf scans know; row-preserving unaries propagate; unknown
    stays None."""
    from spark_rapids_tpu.plan import nodes as P

    if isinstance(node, P.LocalScan):
        return sum(b.num_rows for b in node.batches)
    if isinstance(node, P.CachedRelation):
        if node._table is not None:
            return node._table.num_rows
        return estimate_rows(node.children[0])
    row_preserving = [P.Project, P.Filter, P.Sort, P.Sample]
    if hasattr(P, "WindowNode"):
        row_preserving.append(P.WindowNode)
    if isinstance(node, tuple(row_preserving)):
        return estimate_rows(node.children[0])
    if isinstance(node, (P.Limit, P.CollectLimit)):
        child = estimate_rows(node.children[0])
        return min(child, node.limit) if child is not None else node.limit
    if isinstance(node, P.TakeOrderedAndProject):
        return node.limit
    if isinstance(node, P.Exchange):
        return estimate_rows(node.children[0])
    return None


def apply_cbo(meta, conf) -> None:
    """Tag the whole plan for CPU when the device estimate loses."""
    if not conf.get_entry(OPTIMIZER_ENABLED):
        return
    if not meta.can_run_on_tpu:
        return  # already (partially) falling back; don't double-decide

    total_rows = 0
    n_execs = 0
    stack = [meta]
    while stack:
        m = stack.pop()
        n_execs += 1
        r = estimate_rows(m.node)
        if r is None:
            return  # unknown stats: leave the plan alone (reference rule)
        total_rows += r
        stack.extend(m.children)

    overhead = conf.get_entry(OPTIMIZER_EXEC_OVERHEAD)
    gpu_row = conf.get_entry(OPTIMIZER_GPU_ROW_COST)
    cpu_row = conf.get_entry(OPTIMIZER_CPU_ROW_COST)
    device_cost = overhead * n_execs + gpu_row * total_rows
    cpu_cost = cpu_row * total_rows
    if cpu_cost < device_cost:
        reason = (f"CBO: est. CPU cost {cpu_cost:.4g} < device cost "
                  f"{device_cost:.4g} ({total_rows} rows, {n_execs} ops)")
        stack = [meta]
        while stack:
            m = stack.pop()
            m.reasons.append(reason)
            stack.extend(m.children)
