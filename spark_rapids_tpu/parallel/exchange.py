"""ICI shuffle exchange: hash-partition rows across a device mesh with ONE
all-to-all collective.

Reference mapping (SURVEY.md §2.6): GpuShuffleExchangeExec's UCX fast path
becomes `jax.lax.all_to_all` over the mesh axis — each device bucketizes its
row shard by Spark-exact murmur3 target, pads buckets to the static shard
size, and the collective delivers every device its partition. All shapes are
static (bucket = local shard capacity, the worst case); validity masks carry
the live counts. This is the building block the distributed engine uses when
all partitions live on one slice; host-file shuffle covers the general case.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.shuffle.hashing import SPARK_SEED, murmur3_hash_device


def _shard_map():
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    return sm


def _bucketize(pid, live, ndev: int, cap: int):
    """Per-row scatter target into a (ndev*cap) padded send buffer:
    pid*cap + rank-within-bucket; dead rows drop."""
    spid = jnp.where(live, pid, ndev)
    order = jnp.argsort(spid, stable=True)
    sorted_pid = spid[order]
    idx = jnp.arange(cap, dtype=jnp.int32)
    is_first = jnp.concatenate([jnp.ones(1, jnp.bool_),
                                sorted_pid[1:] != sorted_pid[:-1]])
    run_start = jnp.where(is_first, idx, 0)
    run_start = jax.lax.associative_scan(jnp.maximum, run_start)
    slot_sorted = idx - run_start
    slot = jnp.zeros(cap, jnp.int32).at[order].set(slot_sorted)
    return jnp.where(live, pid * cap + slot, ndev * cap)


def mesh_hash_exchange(mesh,
                       dtypes: Sequence[T.DataType],
                       key_idx: Sequence[int],
                       axis_name: str = "data"):
    """Build a jitted exchange: global arrays sharded on axis 0 are
    re-partitioned so device d holds exactly the rows with
    pmod(murmur3(keys), ndev) == d.

    Returns run(datas, valids) -> (out_datas, out_valids, out_live); output
    shards are padded to ndev * local_cap with out_live marking real rows.
    (String keys need dictionary byte-matrix plumbing — non-string keys for
    now; the host-shuffle path covers strings.)"""
    from jax.sharding import NamedSharding, PartitionSpec as P_

    ndev = mesh.shape[axis_name]
    dts = list(dtypes)
    kset = list(key_idx)
    ncols = len(dts)

    def shard_fn(*flat):
        datas = flat[:ncols]
        valids = flat[ncols:]
        cap = datas[0].shape[0]
        live = jnp.ones(cap, jnp.bool_)

        keys = [(datas[i], valids[i], dts[i]) for i in kset]
        h = murmur3_hash_device(keys, SPARK_SEED)
        pid = h % jnp.int32(ndev)
        pid = jnp.where(pid < 0, pid + ndev, pid)
        tgt = _bucketize(pid, live, ndev, cap)

        send_live = jnp.zeros((ndev * cap,), jnp.bool_).at[tgt].set(
            True, mode="drop").reshape(ndev, cap)
        recv_live = jax.lax.all_to_all(send_live, axis_name, 0, 0)

        out_datas, out_valids = [], []
        for d, v in zip(datas, valids):
            send = jnp.zeros((ndev * cap,), d.dtype).at[tgt].set(
                d, mode="drop").reshape(ndev, cap)
            send_v = jnp.zeros((ndev * cap,), jnp.bool_).at[tgt].set(
                v, mode="drop").reshape(ndev, cap)
            out_datas.append(
                jax.lax.all_to_all(send, axis_name, 0, 0).reshape(ndev * cap))
            out_valids.append(
                jax.lax.all_to_all(send_v, axis_name, 0, 0).reshape(ndev * cap))
        return tuple(out_datas) + tuple(out_valids) + (recv_live.reshape(ndev * cap),)

    sm = _shard_map()
    fn = jax.jit(sm(shard_fn, mesh=mesh,
                    in_specs=tuple(P_(axis_name) for _ in range(2 * ncols)),
                    out_specs=tuple(P_(axis_name) for _ in range(2 * ncols + 1))))

    def run(datas: List[jax.Array], valids: List[jax.Array]):
        sharding = NamedSharding(mesh, P_(axis_name))
        flat = [jax.device_put(x, sharding) for x in list(datas) + list(valids)]
        out = fn(*flat)
        return list(out[:ncols]), list(out[ncols:2 * ncols]), out[2 * ncols]

    return run


def mesh_partial_then_merge(mesh, axis_name: str = "data"):
    """Partial-aggregate-per-shard + psum merge (the distributed two-phase
    GpuHashAggregate shape); used by the multichip dry run."""
    from jax.sharding import PartitionSpec as P_

    def build(local_fn):
        def wrapper(*args):
            partial_out = local_fn(*args)
            return jax.tree.map(lambda x: jax.lax.psum(x, axis_name),
                                partial_out)

        sm = _shard_map()
        return jax.jit(sm(wrapper, mesh=mesh,
                          in_specs=P_(axis_name), out_specs=P_()))
    return build
