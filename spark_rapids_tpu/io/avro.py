"""Avro object-container-file scan.

Reference (SURVEY.md §2.4): ``GpuAvroScan.scala`` / ``AvroDataFileReader
.scala`` (~1,500 LoC) — header/schema parse on the CPU in Scala, block
decode on the GPU, with the shared three reader modes. The TPU build
decodes on host (pure-Python binary decoder — no Avro library is baked
into the image) into columnar numpy and uploads through the standard scan
machinery; PERFILE/COALESCING/MULTITHREADED prefetch semantics come from
FileScanNode (io/common.py), exactly as the reference inherits them from
GpuMultiFileReader.

Supported schema surface (mirrors the engine's device types, with the
reference's tag-or-reject contract): records of null/boolean/int/long/
float/double/string, nullable unions ``["null", T]``, and the logical
types date (int), timestamp-millis/micros (long). Unsupported branches
(bytes/fixed/enum/map/nested records/arrays, multi-branch unions) raise
with a reason instead of decoding wrongly. Codecs: null, deflate, zstd
(when the zstandard module is present); snappy is rejected."""

from __future__ import annotations

import json
import struct
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.conf import RapidsConf, str_conf
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.io.common import FileScanNode
from spark_rapids_tpu.plan.nodes import Schema

AVRO_READER_TYPE = str_conf(
    "spark.rapids.sql.format.avro.reader.type", "AUTO",
    "PERFILE, COALESCING, MULTITHREADED or AUTO.")

MAGIC = b"Obj\x01"

_F32 = struct.Struct("<f")
_F64 = struct.Struct("<d")


class ByteReader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise ColumnarProcessingError("truncated avro data")
        self.pos += n
        return b

    def read_long(self) -> int:
        """Zigzag varint (avro int and long share the encoding)."""
        buf, pos = self.buf, self.pos
        shift = 0
        acc = 0
        while True:
            if pos >= len(buf):
                raise ColumnarProcessingError("truncated avro varint")
            b = buf[pos]
            pos += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        self.pos = pos
        return (acc >> 1) ^ -(acc & 1)

    def read_bytes(self) -> bytes:
        return self.read(self.read_long())

    def at_end(self) -> bool:
        return self.pos >= len(self.buf)


# -- schema mapping ----------------------------------------------------------

def _spark_type_of(field_schema: Any) -> Tuple[T.DataType, bool]:
    """(spark type, nullable) for one avro field schema; raises on
    unsupported shapes (the reference's willNotWorkOnGpu analog)."""
    if isinstance(field_schema, list):  # union
        branches = [b for b in field_schema if b != "null"]
        if len(branches) != 1 or len(field_schema) > 2:
            raise ColumnarProcessingError(
                f"unsupported avro union {field_schema} (only "
                "[\"null\", T] unions are supported)")
        dt, _ = _spark_type_of(branches[0])
        return dt, True
    if isinstance(field_schema, dict):
        logical = field_schema.get("logicalType")
        base = field_schema.get("type")
        if logical == "date" and base == "int":
            return T.DATE, False
        if logical == "timestamp-micros" and base == "long":
            return T.TIMESTAMP, False
        if logical == "timestamp-millis" and base == "long":
            return T.TIMESTAMP, False
        if logical is None and isinstance(base, str):
            return _spark_type_of(base)
        raise ColumnarProcessingError(
            f"unsupported avro logical type {field_schema}")
    mapping = {"boolean": T.BOOLEAN, "int": T.INT, "long": T.LONG,
               "float": T.FLOAT, "double": T.DOUBLE, "string": T.STRING}
    if field_schema in mapping:
        return mapping[field_schema], False
    raise ColumnarProcessingError(
        f"unsupported avro type {field_schema!r} (bytes/fixed/enum/map/"
        "array/nested records are not supported)")


def _decoder_of(field_schema: Any) -> Callable[[ByteReader], Any]:
    """Value decoder for one (non-null-branch) schema; None return means
    the null branch was taken."""
    if isinstance(field_schema, list):
        branches = list(field_schema)
        inner = _decoder_of([b for b in branches if b != "null"][0])
        null_index = branches.index("null")

        def dec_union(r: ByteReader):
            idx = r.read_long()
            if idx == null_index:
                return None
            return inner(r)
        return dec_union
    if isinstance(field_schema, dict):
        logical = field_schema.get("logicalType")
        if logical == "timestamp-millis":
            return lambda r: r.read_long() * 1000  # -> micros
        return _decoder_of(field_schema["type"])
    if field_schema in ("int", "long"):
        return ByteReader.read_long
    if field_schema == "boolean":
        return lambda r: r.read(1) == b"\x01"
    if field_schema == "float":
        return lambda r: _F32.unpack(r.read(4))[0]
    if field_schema == "double":
        return lambda r: _F64.unpack(r.read(8))[0]
    if field_schema == "string":
        return lambda r: r.read_bytes().decode("utf-8")
    raise ColumnarProcessingError(f"unsupported avro type {field_schema!r}")


# -- container file ----------------------------------------------------------

class AvroFileInfo:
    def __init__(self, schema_json: dict, codec: str, sync: bytes,
                 blocks_offset: int):
        self.schema_json = schema_json
        self.codec = codec
        self.sync = sync
        self.blocks_offset = blocks_offset


def read_header(buf: bytes) -> AvroFileInfo:
    """Parse the container header: magic, metadata map, sync marker
    (AvroDataFileReader header parse analog)."""
    if buf[:4] != MAGIC:
        raise ColumnarProcessingError("not an avro object container file")
    r = ByteReader(buf, 4)
    meta: Dict[str, bytes] = {}
    while True:
        n = r.read_long()
        if n == 0:
            break
        if n < 0:  # negative count: abs count + byte size follows
            n = -n
            r.read_long()
        for _ in range(n):
            key = r.read_bytes().decode("utf-8")
            meta[key] = r.read_bytes()
    sync = r.read(16)
    schema_json = json.loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    return AvroFileInfo(schema_json, codec, sync, r.pos)


def _decompress_block(codec: str, data: bytes) -> bytes:
    if codec == "null":
        return data
    if codec == "deflate":
        return zlib.decompress(data, wbits=-15)  # raw DEFLATE per spec
    if codec == "zstandard":
        try:
            import zstandard
        except ImportError:
            raise ColumnarProcessingError(
                "avro zstandard codec needs the zstandard module")
        return zstandard.ZstdDecompressor().decompress(data)
    raise ColumnarProcessingError(f"unsupported avro codec {codec!r}")


def decode_file(buf: bytes) -> HostTable:
    """Decode a whole container file to a HostTable."""
    info = read_header(buf)
    schema = info.schema_json
    if schema.get("type") != "record":
        raise ColumnarProcessingError("avro top-level schema must be a record")
    fields = schema["fields"]
    names = [f["name"] for f in fields]
    spark_types = []
    decoders = []
    for f in fields:
        dt, _nullable = _spark_type_of(f["type"])
        spark_types.append(dt)
        decoders.append(_decoder_of(f["type"]))

    values: List[List[Any]] = [[] for _ in fields]
    r = ByteReader(buf, info.blocks_offset)
    while not r.at_end():
        count = r.read_long()
        size = r.read_long()
        block = ByteReader(_decompress_block(info.codec, r.read(size)))
        if r.read(16) != info.sync:
            raise ColumnarProcessingError("avro sync marker mismatch")
        for _ in range(count):
            for dec, out in zip(decoders, values):
                out.append(dec(block))

    cols = []
    for dt, vals in zip(spark_types, values):
        validity = np.array([v is not None for v in vals], dtype=np.bool_)
        if isinstance(dt, T.StringType):
            data = np.array(vals, dtype=object)
        else:
            fill = [v if v is not None else 0 for v in vals]
            data = np.asarray(fill, dtype=dt.np_dtype)
        cols.append(HostColumn(dt, data, validity))
    return HostTable(names, cols)


class AvroScanNode(FileScanNode):
    format_name = "avro"

    def _conf_reader_type(self) -> str:
        return self.conf.get_entry(AVRO_READER_TYPE)

    def file_schema(self, path: str) -> Schema:
        with open(path, "rb") as f:
            head = f.read(1 << 16)
        try:
            info = read_header(head)
        except ColumnarProcessingError:
            with open(path, "rb") as f:  # header larger than probe window
                info = read_header(f.read())
        return [(f["name"], _spark_type_of(f["type"])[0])
                for f in info.schema_json["fields"]]

    def read_file(self, path: str) -> HostTable:
        with open(path, "rb") as f:
            buf = f.read()
        table = decode_file(buf)
        if self.columns is not None:
            data_names = [n for n, _ in self.data_schema]
            idx = {n: i for i, n in enumerate(table.names)}
            table = HostTable([n for n in data_names],
                              [table.columns[idx[n]] for n in data_names])
        return table


# -- generic (nested) record decoding ----------------------------------------
# The COLUMNAR decode above intentionally stays flat (device types); this
# generic decoder handles full Avro recursion (nested records, arrays,
# maps, enums, fixed, multi-branch unions) into Python dicts — what the
# Iceberg connector needs for manifest-list/manifest files
# (AvroDataFileReader's generic datum path).

def _generic_decoder(schema: Any, named: Optional[dict] = None):
    named = {} if named is None else named
    if isinstance(schema, str):
        prim = {"null": lambda r: None,
                "boolean": lambda r: r.read(1) == b"\x01",
                "int": ByteReader.read_long,
                "long": ByteReader.read_long,
                "float": lambda r: _F32.unpack(r.read(4))[0],
                "double": lambda r: _F64.unpack(r.read(8))[0],
                "bytes": ByteReader.read_bytes,
                "string": lambda r: r.read_bytes().decode("utf-8")}
        if schema in prim:
            return prim[schema]
        if schema in named:
            return lambda r: named[schema](r)
        raise ColumnarProcessingError(f"unknown avro type {schema!r}")
    if isinstance(schema, list):
        branches = [_generic_decoder(b, named) for b in schema]

        def dec_union(r: ByteReader):
            return branches[r.read_long()](r)
        return dec_union
    t = schema["type"]
    if t == "record":
        field_decs = []
        names = []
        placeholder = [None]
        if "name" in schema:
            named[schema["name"]] = lambda r: placeholder[0](r)
        for f in schema["fields"]:
            names.append(f["name"])
            field_decs.append(_generic_decoder(f["type"], named))

        def dec_record(r: ByteReader):
            return {n: d(r) for n, d in zip(names, field_decs)}
        placeholder[0] = dec_record
        return dec_record
    if t == "array":
        item = _generic_decoder(schema["items"], named)

        def dec_array(r: ByteReader):
            out = []
            while True:
                n = r.read_long()
                if n == 0:
                    return out
                if n < 0:
                    n = -n
                    r.read_long()  # block byte size
                for _ in range(n):
                    out.append(item(r))
        return dec_array
    if t == "map":
        val = _generic_decoder(schema["values"], named)

        def dec_map(r: ByteReader):
            out = {}
            while True:
                n = r.read_long()
                if n == 0:
                    return out
                if n < 0:
                    n = -n
                    r.read_long()
                for _ in range(n):
                    k = r.read_bytes().decode("utf-8")
                    out[k] = val(r)
        return dec_map
    if t == "enum":
        symbols = schema["symbols"]
        return lambda r: symbols[r.read_long()]
    if t == "fixed":
        size = schema["size"]
        return lambda r: r.read(size)
    # logical types / wrapped primitives
    return _generic_decoder(t, named)


def decode_records(buf: bytes) -> List[dict]:
    """Decode a container file of arbitrary (possibly nested) records to a
    list of Python dicts."""
    info = read_header(buf)
    dec = _generic_decoder(info.schema_json)
    out: List[dict] = []
    r = ByteReader(buf, info.blocks_offset)
    while not r.at_end():
        count = r.read_long()
        size = r.read_long()
        block = ByteReader(_decompress_block(info.codec, r.read(size)))
        if r.read(16) != info.sync:
            raise ColumnarProcessingError("avro sync marker mismatch")
        for _ in range(count):
            out.append(dec(block))
    return out
