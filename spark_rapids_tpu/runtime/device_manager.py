"""Device acquisition & memory setup (reference: GpuDeviceManager.scala —
picks the GPU, initializes the RMM pool, pinned pool, off-heap limits;
SURVEY.md §2.5).

TPU analog: discover devices/topology through JAX/PJRT, record HBM budget
from the conf fraction, and expose the live-arrays accounting XLA gives us.
XLA's allocator already pools HBM (BFC) — the engine's job is budget
tracking + spill/retry on top (runtime/catalog.py, runtime/retry.py)."""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import List, Optional

import jax

from spark_rapids_tpu.conf import (
    CONCURRENT_TPU_TASKS,
    HBM_POOL_FRACTION,
    HBM_RESERVE_BYTES,
    RapidsConf,
)
from spark_rapids_tpu.lockorder import ordered_lock

_DEFAULT_HBM_BYTES = 16 << 30  # v5e has 16 GiB per chip


@dataclass
class DeviceInfo:
    device: object
    platform: str
    hbm_limit_bytes: int
    #: PJRT topology facts (GpuDeviceManager resource-discovery analog)
    device_ordinal: int = 0
    process_index: int = 0
    num_processes: int = 1
    local_device_count: int = 1
    global_device_count: int = 1
    coords: Optional[tuple] = None
    core_on_chip: Optional[int] = None


class TpuDeviceManager:
    """Singleton-ish per-process device state."""

    _instance: Optional["TpuDeviceManager"] = None
    _instance_lock = ordered_lock("device.manager.instance")

    def __init__(self, conf: RapidsConf):
        self.conf = conf
        self.devices: List[object] = []
        self.info: Optional[DeviceInfo] = None
        self.initialized = False

    def _select_device(self, local: List[object]) -> int:
        """Device selection (reference: GpuDeviceManager.scala:243-251 —
        explicit resource address, else round-robin by executor id).
        TPU analog: explicit conf ordinal, else round-robin by process
        index across multi-process launches."""
        from spark_rapids_tpu.conf import DEVICE_ORDINAL
        want = self.conf.get_entry(DEVICE_ORDINAL)
        if want >= 0:
            if want >= len(local):
                from spark_rapids_tpu.errors import ColumnarProcessingError
                raise ColumnarProcessingError(
                    f"spark.rapids.tpu.deviceOrdinal={want} but only "
                    f"{len(local)} local devices exist")
            return want
        try:
            pi = jax.process_index()
        except Exception:
            pi = 0
        return pi % len(local) if len(local) else 0

    def initialize(self):
        if self.initialized:
            return
        # the backend is being initialized anyway; auto-detected TPU
        # hosts (unset JAX_PLATFORMS) pick up the persistent compile
        # cache here rather than silently running uncached (ADVICE r5)
        import spark_rapids_tpu
        spark_rapids_tpu.ensure_compile_cache()
        self.devices = list(jax.devices())
        local = list(jax.local_devices())
        ordinal = self._select_device(local)
        dev = local[ordinal]
        total = _DEFAULT_HBM_BYTES
        stats = None
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if stats and "bytes_limit" in stats:
            total = int(stats["bytes_limit"])
        frac = self.conf.get_entry(HBM_POOL_FRACTION)
        reserve = self.conf.get_entry(HBM_RESERVE_BYTES)
        limit = max(int(total * frac) - reserve, 256 << 20)
        try:
            nproc = jax.process_count()
            pidx = jax.process_index()
        except Exception:
            nproc, pidx = 1, 0
        self.info = DeviceInfo(
            device=dev, platform=dev.platform, hbm_limit_bytes=limit,
            device_ordinal=ordinal, process_index=pidx,
            num_processes=nproc, local_device_count=len(local),
            global_device_count=len(self.devices),
            coords=getattr(dev, "coords", None),
            core_on_chip=getattr(dev, "core_on_chip", None))
        from spark_rapids_tpu.conf import (
            HOST_MEMORY_LIMIT,
            HOST_SPILL_STORAGE_SIZE,
            PINNED_POOL_SIZE,
        )
        from spark_rapids_tpu.runtime.host_alloc import (
            HostMemoryArbiter,
            PinnedMemoryPool,
        )
        from spark_rapids_tpu.runtime.spill import BufferCatalog
        BufferCatalog.get().host_limit_bytes = \
            self.conf.get_entry(HOST_SPILL_STORAGE_SIZE)
        HostMemoryArbiter.reset(self.conf.get_entry(HOST_MEMORY_LIMIT))
        PinnedMemoryPool.initialize(self.conf.get_entry(PINNED_POOL_SIZE))
        with TpuDeviceManager._instance_lock:
            TpuDeviceManager._instance = self
        self.initialized = True

    @classmethod
    def current(cls) -> Optional["TpuDeviceManager"]:
        return cls._instance

    @property
    def mesh_runtime(self):
        """The process-wide mesh runtime (parallel/mesh.py) — device
        topology is process state like the manager itself; the
        placement layer configures it from the session conf per query."""
        from spark_rapids_tpu.parallel.mesh import MESH
        return MESH

    def bytes_in_use(self) -> int:
        try:
            stats = self.info.device.memory_stats()
            return int(stats.get("bytes_in_use", 0))
        except Exception:
            return 0

    @property
    def concurrent_tasks(self) -> int:
        return self.conf.get_entry(CONCURRENT_TPU_TASKS)

    def topology(self) -> dict:
        """Discovery summary (logged at session init; the reference logs
        the chosen GPU + memory configuration the same way)."""
        i = self.info
        from spark_rapids_tpu.parallel.mesh import MESH
        return {
            "mesh_shape": MESH.shape_str(),
            "platform": i.platform,
            "device_ordinal": i.device_ordinal,
            "local_devices": i.local_device_count,
            "global_devices": i.global_device_count,
            "process_index": i.process_index,
            "num_processes": i.num_processes,
            "coords": i.coords,
            "core_on_chip": i.core_on_chip,
            "hbm_limit_bytes": i.hbm_limit_bytes,
        }
