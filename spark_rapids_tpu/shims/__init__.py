"""Version shim layer (reference: ShimLoader.scala + build/shimplify.py —
SURVEY.md §2.12).

The reference compiles one source tree against many Spark versions and
selects a binary shim at runtime by inspecting the Spark version string
(ShimLoader.getShimVersion). The TPU engine's moving dependency is JAX,
not Spark: public APIs the engine relies on have historically migrated
(``jax.experimental.shard_map`` -> ``jax.shard_map``,
``jax.tree_util.tree_map`` -> ``jax.tree.map``, pallas module layout), so
the same problem — one engine tree, many runtime versions — gets the same
shape of answer, adapted to Python:

- every version-variant API goes through a ``Shim`` provider object;
- provider classes declare the half-open version range they serve
  (``MIN_VERSION <= jax < MAX_VERSION``), the shimplify "which shim owns
  this file" tag turned into data;
- the loader resolves the running JAX version against the registry ONCE,
  lazily, and fails with an explicit supported-range message for versions
  outside every range (ShimLoader's UnsupportedOperationException analog);
- because Python resolves at runtime, ONE wheel ships all shims — the
  reference needs its multi-jar ``dist/`` assembly only because the JVM
  must pick a binary per Spark version (see pyproject.toml).

The env var ``SPARK_RAPIDS_TPU_JAX_SHIM_OVERRIDE`` forces a specific
version (the hook the reference exposes via the
spark.rapids.shims-provider-override SYSTEM PROPERTY — an env-style
process-global, deliberately NOT a session conf: shims resolve at module
import, before any session can exist).
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple, Type

from spark_rapids_tpu.shims.base import BaseShim
from spark_rapids_tpu.shims.jax_legacy import JaxLegacyShim
from spark_rapids_tpu.shims.jax_current import JaxCurrentShim

#: ordered registry of provider classes; ranges must not overlap and are
#: checked by tests/test_shims.py (the shimplify "shims must be disjoint"
#: invariant)
SHIM_PROVIDERS: List[Type[BaseShim]] = [JaxLegacyShim, JaxCurrentShim]

_active: Optional[BaseShim] = None


def parse_version(v: str) -> Tuple[int, int, int]:
    """'0.4.35' / '0.9.0rc1' / '0.9' -> (major, minor, patch); tolerant of
    suffixes the way ShimLoader tolerates vendor version strings like
    '3.4.1-databricks'."""
    parts = []
    for piece in v.split(".")[:3]:
        m = re.match(r"\d+", piece)
        parts.append(int(m.group()) if m else 0)
    while len(parts) < 3:
        parts.append(0)
    return tuple(parts)


def resolve_provider(version: Tuple[int, int, int]) -> Type[BaseShim]:
    for cls in SHIM_PROVIDERS:
        if cls.MIN_VERSION <= version < cls.MAX_VERSION:
            return cls
    ranges = ", ".join(
        f"{cls.__name__} [{'.'.join(map(str, cls.MIN_VERSION))}, "
        f"{'.'.join(map(str, cls.MAX_VERSION))})"
        for cls in SHIM_PROVIDERS)
    raise RuntimeError(
        f"No shim provider for jax {'.'.join(map(str, version))}; "
        f"supported ranges: {ranges}. Set the env var "
        f"SPARK_RAPIDS_TPU_JAX_SHIM_OVERRIDE to force a version "
        f"(at your own risk).")


def get_shim() -> BaseShim:
    """The active shim, resolved once per process (ShimLoader caches its
    SparkShims instance the same way). The override rides an env var,
    NOT a session conf: shims resolve at module import, before any
    session exists — exactly why the reference uses a system property
    for spark.rapids.shims-provider-override."""
    global _active
    if _active is None:
        import os

        import jax
        override = os.environ.get("SPARK_RAPIDS_TPU_JAX_SHIM_OVERRIDE", "")
        version = parse_version(override or jax.__version__)
        _active = resolve_provider(version)()
    return _active


def _reset_for_tests() -> None:
    global _active
    _active = None
