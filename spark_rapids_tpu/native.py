"""Native (C++) runtime components, loaded via ctypes with a pure-Python
fallback when the toolchain or prebuilt library is unavailable.

The compute path is JAX/XLA; these are the HOST runtime hot spots the
reference also keeps native (cuDF/JNI): currently the order-preserving
string dictionary encoder (native/strcodec.cpp). The shared library builds
lazily with g++ on first use and is cached next to the source; every
caller must tolerate ``None`` (fallback to numpy)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libstrcodec.so")
_SRC_PATH = os.path.join(_NATIVE_DIR, "strcodec.cpp")

_lock = threading.Lock()
_lib = None
_lib_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            if not os.path.exists(_SO_PATH) or (
                    os.path.exists(_SRC_PATH)
                    and os.path.getmtime(_SRC_PATH) > os.path.getmtime(_SO_PATH)):
                subprocess.run(
                    ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                     _SRC_PATH, "-o", _SO_PATH],
                    check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(_SO_PATH)
            lib.encode_sorted_dict_u32.restype = ctypes.c_int64
            lib.encode_sorted_dict_u32.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_void_p]
            _lib = lib
        except Exception:
            _lib_failed = True
            _lib = None
    return _lib


def native_available() -> bool:
    return _load() is not None


def _sort_keys_native(keys: np.ndarray):
    """Sort an object array of DISTINCT strings by code-point order with
    the native codec (numpy UTF-32 conversion + C++ index sort); None when
    the library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    k = len(keys)
    u = keys.astype(str).astype("U")
    width = max(u.dtype.itemsize // 4, 1)
    chars = np.ascontiguousarray(u).view(np.uint32).reshape(k, width)
    codes = np.empty(k, dtype=np.int32)
    dict_row = np.empty(k, dtype=np.int64)
    ndict = lib.encode_sorted_dict_u32(
        chars.ctypes.data_as(ctypes.c_void_p), k, width,
        codes.ctypes.data_as(ctypes.c_void_p),
        dict_row.ctypes.data_as(ctypes.c_void_p))
    if ndict != k:
        # numpy 'U' padding cannot represent trailing NULs: distinct keys
        # like "a" and "a\x00" collapse to one row — fall back to the
        # python comparator which distinguishes them
        return None
    return codes  # rank of each key in sorted order (keys are distinct)


#: above this many distinct keys, Python-object argsort comparisons lose
#: to the native UTF-32 index sort
_NATIVE_SORT_MIN_KEYS = 4096


def encode_sorted_dict(values: np.ndarray):
    """Order-preserving dictionary encode of an object array of str:
    hash-dedupe at C-dict speed, then rank the DISTINCT keys — natively
    (UTF-32 code-point sort) at high cardinality, via numpy otherwise.
    Returns (codes int32, dictionary object array); 5-6x the old
    np.unique-over-objects path at typical cardinalities."""
    n = len(values)
    if n == 0:
        return (np.zeros(0, dtype=np.int32), np.array([], dtype=object))
    table: dict = {}
    setd = table.setdefault
    raw = np.fromiter((setd(s, len(table)) for s in values),
                      dtype=np.int32, count=n)
    keys = np.fromiter(table.keys(), dtype=object, count=len(table))
    k = len(keys)
    rank = None
    if k >= _NATIVE_SORT_MIN_KEYS:
        rank = _sort_keys_native(keys)
    if rank is None:
        order = np.argsort(keys)
        rank = np.empty(k, dtype=np.int32)
        rank[order] = np.arange(k, dtype=np.int32)
    codes = rank[raw]
    dictionary = np.empty(k, dtype=object)
    dictionary[rank] = keys
    return codes, dictionary
