"""Window exec tests vs the CPU oracle (reference: window_function_test.py
matrix — SURVEY.md §4)."""

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.ops.window import Window
from tests.asserts import assert_runs_on_tpu, assert_tpu_and_cpu_are_equal
from tests.data_gen import DoubleGen, IntGen, LongGen, StringGen, gen_table


def _t(n=400, seed=0):
    return gen_table({"k": IntGen(min_val=0, max_val=8, null_prob=0.05),
                      "o": LongGen(min_val=-100, max_val=100),
                      "v": LongGen(),
                      "d": DoubleGen(),
                      "s": StringGen(cardinality=12)}, n, seed=seed)


W_KO = lambda: Window.partition_by("k").order_by("o")  # noqa: E731


@pytest.mark.parametrize("fn", [
    lambda: F.row_number(), lambda: F.rank(), lambda: F.dense_rank(),
], ids=["row_number", "rank", "dense_rank"])
def test_ranking_functions(session, cpu_session, fn):
    host = _t()
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            r=fn().over(W_KO())), session, cpu_session)


def test_rank_with_ties(session, cpu_session):
    host = HostTable.from_pydict({
        "k": [1, 1, 1, 1, 2, 2], "o": [5, 5, 7, 9, 1, 1]})
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            rn=F.row_number().over(W_KO()),
            rk=F.rank().over(W_KO()),
            dr=F.dense_rank().over(W_KO())), session, cpu_session)


@pytest.mark.parametrize("off,default", [(1, None), (2, None), (1, -99)],
                         ids=["lag1", "lag2", "lag1_default"])
def test_lag_lead(session, cpu_session, off, default):
    host = _t(300, seed=2)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            lg=F.lag("v", off, default).over(W_KO()),
            ld=F.lead("v", off, default).over(W_KO())),
        session, cpu_session)


def test_lag_string(session, cpu_session):
    host = _t(200, seed=3)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            p=F.lag("s").over(W_KO())), session, cpu_session)


@pytest.mark.parametrize("make_agg", [
    lambda: F.sum("v"), lambda: F.count("v"), lambda: F.min("v"),
    lambda: F.max("v"), lambda: F.avg("d"),
], ids=["sum", "count", "min", "max", "avg"])
def test_whole_partition_aggs(session, cpu_session, make_agg):
    host = _t(350, seed=4)
    w = Window.partition_by("k")  # no order -> whole partition frame
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            a=make_agg().over(w)), session, cpu_session,
        approximate_float=True)


@pytest.mark.parametrize("make_agg", [
    lambda: F.sum("v"), lambda: F.count("v"), lambda: F.min("v"),
    lambda: F.max("v"), lambda: F.avg("d"),
], ids=["sum", "count", "min", "max", "avg"])
def test_running_aggs_default_range_frame(session, cpu_session, make_agg):
    """ORDER BY default frame = RANGE UNBOUNDED..CURRENT (peers included)."""
    host = _t(300, seed=5)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            a=make_agg().over(W_KO())), session, cpu_session,
        approximate_float=True)


def test_running_rows_frame(session, cpu_session):
    host = _t(300, seed=6)
    w = W_KO().rows_between(None, 0)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            rsum=F.sum("v").over(w), rmin=F.min("v").over(w)),
        session, cpu_session)


@pytest.mark.parametrize("lo,hi", [(-2, 2), (-3, 0), (0, 3), (None, 1)],
                         ids=["pm2", "m3_0", "0_p3", "unb_p1"])
def test_bounded_rows_frames(session, cpu_session, lo, hi):
    host = _t(250, seed=7)
    w = W_KO().rows_between(lo, hi)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            bs=F.sum("v").over(w), bc=F.count("v").over(w),
            ba=F.avg("d").over(w)),
        session, cpu_session, approximate_float=True)


def test_window_runs_on_tpu(session):
    host = _t(100)
    assert_runs_on_tpu(
        lambda s: s.create_dataframe(host).with_windows(
            rn=F.row_number().over(W_KO()),
            sm=F.sum("v").over(W_KO())), session)


def test_bounded_min_falls_back(session):
    from spark_rapids_tpu.overrides import wrap_plan
    host = _t(50)
    df = session.create_dataframe(host).with_windows(
        bm=F.min("v").over(W_KO().rows_between(-2, 2)))
    meta = wrap_plan(df.plan, session.conf)
    assert not meta.can_run_on_tpu
    assert any("bounded rows min/max" in r for r in meta.reasons)
    # CPU fallback still answers
    assert df.count() == 50


def test_mixed_specs_stay_aligned(session, cpu_session):
    """Two window exprs with DIFFERENT partition/order specs in one node."""
    host = _t(200, seed=9)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            by_k=F.sum("v").over(Window.partition_by("k")),
            by_s=F.count("v").over(Window.partition_by("s"))),
        session, cpu_session)


def test_window_no_partition(session, cpu_session):
    """Global window (single partition)."""
    host = _t(150, seed=10)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            rn=F.row_number().over(Window.order_by("o")),
            tot=F.sum("v").over(Window.partition_by())),
        session, cpu_session)


def test_window_then_filter_pipeline(session, cpu_session):
    """Classic top-N per group: window + filter + project."""
    from spark_rapids_tpu.ops.expr import col
    host = _t(400, seed=11)

    def build(s):
        return (s.create_dataframe(host)
                .with_windows(rn=F.row_number().over(W_KO()))
                .filter(col("rn") <= 3)
                .select("k", "o", "rn"))
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)
