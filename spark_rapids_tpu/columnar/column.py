"""Host and device column representations."""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.errors import ColumnarProcessingError

_MISSING = object()

# Lane width on TPU is 128; keep every device buffer a multiple of it so XLA
# tiles cleanly onto the VPU/MXU.
MIN_BUCKET = 128


class BucketPolicy:
    """A BOUNDED, declared set of capacity buckets.

    Every device buffer's leading dimension is drawn from this set, so
    the number of distinct compiled programs per (schema, expression)
    is bounded by the set's size — the XLA analog of cuDF's precompiled
    kernels (SURVEY.md §7 hard parts). ``spark.rapids.sql.shapeBuckets``
    picks the policy:

    * ``pow2`` — powers of two from ``minBucket`` (the historical
      default: log2(max_rows) buckets);
    * ``pow4`` — powers of four from ``minBucket``: half the compiled
      shapes for at most 4x pad waste (mask-aware execs never touch the
      dead tail rows, they only cost bandwidth);
    * an explicit ascending comma-separated list (``'1024,16384,...'``)
      — the exact bucket set, continuing pow2 above its largest entry
      (a capacity must always exist for any row count).

    Buckets must be multiples of 128 (the TPU lane width) and strictly
    ascending; a bad spec raises at conf-apply time, never mid-kernel.
    """

    __slots__ = ("spec", "min_bucket", "_explicit", "_ratio")

    def __init__(self, spec: str = "pow2", min_bucket: int = MIN_BUCKET):
        self.spec = str(spec).strip().lower() or "pow2"
        self.min_bucket = int(min_bucket)
        if self.min_bucket < 1 or self.min_bucket % MIN_BUCKET:
            raise ColumnarProcessingError(
                f"spark.rapids.sql.shapeBuckets.minBucket must be a "
                f"positive multiple of {MIN_BUCKET}, got {min_bucket}")
        self._explicit = None
        if self.spec == "pow2":
            self._ratio = 2
        elif self.spec == "pow4":
            self._ratio = 4
        else:
            self._ratio = 2
            try:
                buckets = tuple(int(b) for b in self.spec.split(","))
            except ValueError:
                raise ColumnarProcessingError(
                    f"spark.rapids.sql.shapeBuckets must be 'pow2', "
                    f"'pow4' or an ascending comma-separated int list, "
                    f"got {spec!r}")
            if not buckets or any(b < 1 or b % self.min_bucket
                                  for b in buckets):
                # multiples of minBucket (itself a lane-width multiple):
                # the operator's minBucket contract applies to explicit
                # lists too, not just the geometric policies
                raise ColumnarProcessingError(
                    f"spark.rapids.sql.shapeBuckets entries must be "
                    f"positive multiples of "
                    f"spark.rapids.sql.shapeBuckets.minBucket "
                    f"({self.min_bucket}), got {spec!r}")
            if any(a >= b for a, b in zip(buckets, buckets[1:])):
                raise ColumnarProcessingError(
                    f"spark.rapids.sql.shapeBuckets entries must be "
                    f"strictly ascending, got {spec!r}")
            self._explicit = buckets

    def bucket_for(self, n: int) -> int:
        """Smallest declared bucket >= n (and >= the min bucket)."""
        if self._explicit is not None:
            for b in self._explicit:
                if b >= n:
                    return b
            b = self._explicit[-1]
        else:
            b = self.min_bucket
        while b < n:
            b *= self._ratio if self._explicit is None else 2
        return b

    def buckets_up_to(self, cap: int) -> tuple:
        """The declared bucket set covering capacities <= ``cap`` —
        the bound on distinct compiled shapes for a workload whose
        largest batch fits ``cap``."""
        out = []
        if self._explicit is not None:
            out.extend(b for b in self._explicit if b <= cap)
            b = self._explicit[-1] * 2
        else:
            b = self.min_bucket
        while b <= cap:
            out.append(b)
            b *= self._ratio if self._explicit is None else 2
        if not out or out[-1] < cap:
            out.append(self.bucket_for(cap))
        return tuple(sorted(set(out)))


_POLICY = BucketPolicy()
_POLICY_KEY = ("pow2", MIN_BUCKET)


def set_bucket_policy(spec: str, min_bucket: int = MIN_BUCKET) -> None:
    """Install the process-wide bucket policy (pushed from the session's
    conf per query, the DeviceTable.EMBED_* tuning pattern). No-op when
    unchanged; validates eagerly so a typo'd spec fails the query at
    plan time."""
    global _POLICY, _POLICY_KEY
    key = (str(spec).strip().lower() or "pow2", int(min_bucket))
    if key == _POLICY_KEY:
        return
    _POLICY = BucketPolicy(spec, min_bucket)
    _POLICY_KEY = key


def bucket_policy() -> BucketPolicy:
    return _POLICY


def bucket_for(n: int) -> int:
    """Smallest declared capacity bucket >= n (see BucketPolicy)."""
    return _POLICY.bucket_for(n)


class HostColumn:
    """A column on the host: numpy values + validity mask.

    For STRING, ``data`` is a numpy object array of Python str (None allowed
    at invalid slots). For everything else ``data`` is the Spark internal
    representation (see types.py).

    ``_cache`` memoizes derived per-column artifacts (dictionary encoding,
    all-valid flag) so repeated uploads of the same host column — re-collects,
    multi-query reuse of an in-memory table — don't redo O(n) host work."""

    __slots__ = ("dtype", "data", "validity", "_cache")

    def __init__(self, dtype: T.DataType, data: np.ndarray, validity: Optional[np.ndarray] = None):
        self.dtype = dtype
        self.data = data
        if validity is None:
            validity = np.ones(len(data), dtype=np.bool_)
        self.validity = validity
        self._cache = {}
        if len(data) != len(validity):
            raise ColumnarProcessingError("data/validity length mismatch")

    @property
    def all_valid(self) -> bool:
        got = self._cache.get("all_valid")
        if got is None:
            got = bool(self.validity.all())
            self._cache["all_valid"] = got
        return got

    def __len__(self) -> int:
        return len(self.data)

    @property
    def null_count(self) -> int:
        return int(len(self.validity) - self.validity.sum())

    def int_domain(self) -> Optional[Tuple[int, int]]:
        """(min, max) over VALID rows for integer-family columns, else None.

        Cheap host-side column statistics (one numpy min/max per upload,
        cached) in the spirit of the reference's use of parquet/ORC
        column statistics — consumed by the aggregation fast path, which
        turns a group-by on a bounded-domain integer key into a direct
        segment reduction with no sort (see TpuHashAggregateExec
        _fast_layout). The result is a conservative SUPERSET contract:
        every valid value lies in [min, max]."""
        got = self._cache.get("int_domain", _MISSING)
        if got is not _MISSING:
            return got
        dom = None
        if (isinstance(self.dtype, (T.ByteType, T.ShortType, T.IntegerType,
                                    T.LongType, T.DateType, T.TimestampType))
                and isinstance(self.data, np.ndarray)
                and self.data.dtype.kind in "iu"):
            vals = self.data[self.validity] if not self.all_valid else self.data
            if len(vals):
                dom = (int(vals.min()), int(vals.max()))
        self._cache["int_domain"] = dom
        return dom

    @staticmethod
    def from_pylist(values, dtype: Optional[T.DataType] = None) -> "HostColumn":
        import datetime as _dt
        if dtype is None:
            sample = next((v for v in values if v is not None), None)
            dtype = T.python_to_spark_type(sample) if sample is not None else T.NULL
        validity = np.array([v is not None for v in values], dtype=np.bool_)
        if isinstance(dtype, (T.StructType, T.MapType)):
            data = np.empty(len(values), dtype=object)
            data[:] = list(values)
        elif isinstance(dtype, T.ArrayType):
            ec = HostColumn._element_conv(dtype.element_type)
            data = np.empty(len(values), dtype=object)
            data[:] = [[ec(x) if x is not None else None for x in v]
                       if v is not None else None for v in values]
        elif isinstance(dtype, T.StringType):
            data = np.empty(len(values), dtype=object)
            data[:] = [v if v is not None else None for v in values]
        elif T.is_dec128(dtype):
            # unscaled values beyond int64: python-int object storage
            data = np.empty(len(values), dtype=object)
            data[:] = [int(v) if v is not None else 0 for v in values]
        else:
            np_dtype = dtype.np_dtype
            fill = np.zeros((), dtype=np_dtype).item()
            conv = lambda v: v  # noqa: E731
            if isinstance(dtype, T.DateType):
                epoch = _dt.date(1970, 1, 1)

                def conv(v):
                    if isinstance(v, _dt.datetime):  # datetime subclasses date
                        v = v.date()
                    return (v - epoch).days if isinstance(v, _dt.date) else v
            elif isinstance(dtype, T.TimestampType):
                epoch_ts = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)

                def conv(v):  # noqa: E731
                    if isinstance(v, _dt.datetime):
                        if v.tzinfo is None:
                            v = v.replace(tzinfo=_dt.timezone.utc)
                        delta = v - epoch_ts
                        return delta.days * 86_400_000_000 + delta.seconds * 1_000_000 + delta.microseconds
                    return v
            data = np.array([conv(v) if v is not None else fill for v in values],
                            dtype=np_dtype)
        return HostColumn(dtype, data, validity)

    @staticmethod
    def _element_conv(dtype: T.DataType):
        """Python value -> internal representation for ARRAY elements
        (dates to epoch days, timestamps to epoch micros)."""
        import datetime as _dt
        if isinstance(dtype, T.DateType):
            epoch = _dt.date(1970, 1, 1)

            def conv(v):
                if isinstance(v, _dt.datetime):
                    v = v.date()
                return (v - epoch).days if isinstance(v, _dt.date) else v
            return conv
        if isinstance(dtype, T.TimestampType):
            epoch_ts = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)

            def conv(v):
                if isinstance(v, _dt.datetime):
                    if v.tzinfo is None:
                        v = v.replace(tzinfo=_dt.timezone.utc)
                    d = v - epoch_ts
                    return (d.days * 86_400_000_000 + d.seconds * 1_000_000
                            + d.microseconds)
                return v
            return conv
        return lambda v: v

    @staticmethod
    def from_numpy(values: np.ndarray, validity: Optional[np.ndarray] = None,
                   dtype: Optional[T.DataType] = None) -> "HostColumn":
        if dtype is None:
            dtype = T.from_numpy(values.dtype)
        return HostColumn(dtype, values, validity)

    def to_pylist(self):
        import datetime as _dt
        conv = None
        if isinstance(self.dtype, T.ArrayType):
            edt = self.dtype.element_type
            if isinstance(edt, T.DateType):
                epoch = _dt.date(1970, 1, 1)
                conv = lambda lst: [  # noqa: E731
                    epoch + _dt.timedelta(days=int(x)) if x is not None
                    else None for x in lst]
            elif isinstance(edt, T.TimestampType):
                epoch_ts = _dt.datetime(1970, 1, 1)
                conv = lambda lst: [  # noqa: E731
                    epoch_ts + _dt.timedelta(microseconds=int(x))
                    if x is not None else None for x in lst]
        elif isinstance(self.dtype, T.DateType):
            epoch = _dt.date(1970, 1, 1)
            conv = lambda v: epoch + _dt.timedelta(days=int(v))  # noqa: E731
        elif isinstance(self.dtype, T.TimestampType):
            epoch_ts = _dt.datetime(1970, 1, 1)
            conv = lambda v: epoch_ts + _dt.timedelta(microseconds=int(v))  # noqa: E731
        out = []
        for i in range(len(self)):
            if not self.validity[i]:
                out.append(None)
            else:
                v = self.data[i]
                if conv is not None:
                    out.append(conv(v))
                else:
                    out.append(v.item() if isinstance(v, np.generic) else v)
        return out

    def slice(self, start: int, length: int) -> "HostColumn":
        return HostColumn(self.dtype, self.data[start:start + length],
                          self.validity[start:start + length])

    def nbytes(self) -> int:
        if isinstance(self.dtype, T.StringType):
            return int(sum(len(s.encode("utf-8")) for s, v in zip(self.data, self.validity) if v)) + len(self)
        if isinstance(self.dtype, T.ArrayType):
            elem = np.dtype(self.dtype.element_type.np_dtype).itemsize
            total = sum(len(x) for x, v in zip(self.data, self.validity) if v)
            return int(total * elem + 4 * (len(self) + 1) + len(self))
        return int(self.data.nbytes + self.validity.nbytes)


class DeviceColumn:
    """A column resident on device as XLA buffers.

    ``data``     : jnp array of length ``capacity`` (padded bucket)
    ``validity`` : jnp bool array, True = valid; padding region is False at
                   upload time; operators maintain correctness on [0, n).
    ``dictionary``: for STRING columns, host numpy object array such that the
                   logical value of row i is dictionary[data[i]]. When
                   ``dict_sorted`` is True the dictionary is sorted+unique so
                   code order == Spark UTF-8 byte order (order-preserving).
    """

    __slots__ = ("dtype", "data", "validity", "dictionary", "dict_sorted",
                 "domain")

    def __init__(self, dtype: T.DataType, data, validity,
                 dictionary: Optional[np.ndarray] = None, dict_sorted: bool = True,
                 domain: Optional[Tuple[int, int]] = None):
        self.dtype = dtype
        self.data = data
        self.validity = validity
        self.dictionary = dictionary
        self.dict_sorted = dict_sorted
        #: host-known (min, max) bound on VALID values of integer-family
        #: columns (None = unknown). Contract: a conservative SUPERSET —
        #: set at upload from column stats, carried only through
        #: structural ops (with_arrays: gather/slice/permute/pad, same
        #: logical value space as the dictionary it already carries).
        #: Consumed by the aggregation no-sort fast path.
        self.domain = domain

    @property
    def is_array(self) -> bool:
        return isinstance(self.data, tuple)

    @property
    def is_struct(self) -> bool:
        from spark_rapids_tpu.columnar.nested import StructData
        return isinstance(self.data, StructData)

    @property
    def is_map(self) -> bool:
        from spark_rapids_tpu.columnar.nested import MapData
        return isinstance(self.data, MapData)

    @property
    def is_nested(self) -> bool:
        return self.is_array or self.is_struct or self.is_map

    @property
    def capacity(self) -> int:
        # array columns store data as (offsets, elem_data, elem_validity);
        # row capacity always equals the validity length
        return int(self.validity.shape[0])

    def device_nbytes(self) -> int:
        if self.is_array:
            off, ed, ev = self.data
            return int(off.size * 4 + ed.size * ed.dtype.itemsize
                       + ev.size + self.validity.size)
        if self.is_struct or self.is_map:
            from spark_rapids_tpu.columnar.nested import nested_nbytes
            return nested_nbytes(self.data) + int(self.validity.size)
        return int(self.data.size * self.data.dtype.itemsize + self.validity.size)

    @staticmethod
    def _encode_strings(host: HostColumn) -> Tuple[np.ndarray, np.ndarray]:
        """Order-preserving dictionary encode. Returns (codes int32, dict).

        Python str comparison is by code point, which equals UTF-8 byte order
        — the order Spark's UTF8String.compareTo uses — so a sorted-unique
        dictionary makes code comparisons match Spark string comparisons."""
        got = host._cache.get("encode")
        if got is not None:
            return got
        vals = np.where(host.validity, host.data, "")
        # hash-dedupe + (native UTF-32 sort | numpy argsort) — 5-6x the old
        # np.unique-over-objects; order is code-point order == UTF-8 byte
        # order either way (spark_rapids_tpu/native.py)
        from spark_rapids_tpu.native import encode_sorted_dict
        got = encode_sorted_dict(np.asarray(vals, dtype=object))
        host._cache["encode"] = got
        return got

    @staticmethod
    def _array_parts(host: HostColumn, cap: int):
        """Flatten host lists to (offsets[cap+1] i32, elem_data, elem_valid);
        null/padding rows get ZERO length (the engine invariant: only live
        valid rows own elements)."""
        n = len(host)
        lengths = np.zeros(cap + 1, dtype=np.int64)
        for i in range(n):
            if host.validity[i]:
                lengths[i + 1] = len(host.data[i])
        offsets = np.cumsum(lengths).astype(np.int32)
        total = int(offsets[cap])
        ecap = bucket_for(max(total, 1))
        edt = host.dtype.element_type.np_dtype
        elems = np.zeros(ecap, dtype=edt)
        evalid = np.zeros(ecap, dtype=np.bool_)
        pos = 0
        for i in range(n):
            if host.validity[i]:
                for v in host.data[i]:
                    if v is not None:
                        elems[pos] = v
                        evalid[pos] = True
                    pos += 1
        return offsets, elems, evalid

    @staticmethod
    def from_host(host: HostColumn, capacity: Optional[int] = None) -> "DeviceColumn":
        n = len(host)
        cap = capacity or bucket_for(n)
        if cap < n:
            raise ColumnarProcessingError(f"capacity {cap} < rows {n}")
        if isinstance(host.dtype, T.StructType):
            from spark_rapids_tpu.columnar.nested import struct_from_host
            sd, validity = struct_from_host(host, cap)
            return DeviceColumn(host.dtype, sd, validity)
        if isinstance(host.dtype, T.MapType):
            from spark_rapids_tpu.columnar.nested import map_from_host
            md, validity = map_from_host(host, cap)
            return DeviceColumn(host.dtype, md, validity)
        if isinstance(host.dtype, T.ArrayType):
            offsets, elems, evalid = DeviceColumn._array_parts(host, cap)
            validity = np.zeros(cap, dtype=np.bool_)
            validity[:n] = host.validity
            return DeviceColumn(host.dtype,
                                (jnp.asarray(offsets), jnp.asarray(elems),
                                 jnp.asarray(evalid)),
                                jnp.asarray(validity))
        validity = np.zeros(cap, dtype=np.bool_)
        validity[:n] = host.validity
        if T.is_dec128(host.dtype):
            limbs = dec128_limbs(host.data, host.validity, cap)
            return DeviceColumn(host.dtype, jnp.asarray(limbs),
                                jnp.asarray(validity))
        if isinstance(host.dtype, T.StringType):
            codes, dictionary = DeviceColumn._encode_strings(host)
            data = np.zeros(cap, dtype=np.int32)
            data[:n] = codes
            return DeviceColumn(host.dtype, jnp.asarray(data), jnp.asarray(validity),
                                dictionary=dictionary, dict_sorted=True)
        np_dtype = host.dtype.np_dtype
        data = np.zeros(cap, dtype=np_dtype)
        data[:n] = host.data
        return DeviceColumn(host.dtype, jnp.asarray(data), jnp.asarray(validity),
                            domain=host.int_domain())

    def to_host(self, num_rows: int) -> HostColumn:
        if self.is_array:
            return self._array_to_host(num_rows)
        if self.is_struct:
            from spark_rapids_tpu.columnar.nested import struct_to_host
            return struct_to_host(self.dtype, self.data, self.validity,
                                  num_rows)
        if self.is_map:
            from spark_rapids_tpu.columnar.nested import map_to_host
            return map_to_host(self.dtype, self.data, self.validity,
                               num_rows)
        # device-slice down to the live bucket BEFORE the transfer: results
        # are often tiny (an aggregate's groups) while capacity is the input
        # bucket, and D2H bandwidth is the scarcest resource on a tunneled
        # TPU — never ship padding.
        k = bucket_for(max(num_rows, 1))
        dev_data = self.data[:k] if k < self.capacity else self.data
        dev_valid = self.validity[:k] if k < self.capacity else self.validity
        data = np.asarray(dev_data)[:num_rows]
        validity = np.ascontiguousarray(np.asarray(dev_valid)[:num_rows])
        return self.decode_host(data, validity)

    def _array_to_host(self, num_rows: int) -> HostColumn:
        off = np.asarray(self.data[0])
        elems = np.asarray(self.data[1])
        evalid = np.asarray(self.data[2])
        validity = np.ascontiguousarray(np.asarray(self.validity)[:num_rows])
        out = np.empty(num_rows, dtype=object)
        for i in range(num_rows):
            if validity[i]:
                s, e = int(off[i]), int(off[i + 1])
                out[i] = [elems[j].item() if evalid[j] else None
                          for j in range(s, e)]
        return HostColumn(self.dtype, out, validity)

    def decode_host(self, data: np.ndarray, validity: np.ndarray) -> HostColumn:
        """Build the logical HostColumn from downloaded raw arrays (shared
        by the per-column path above and DeviceTable's packed to_host)."""
        if T.is_dec128(self.dtype):
            return HostColumn(self.dtype,
                              dec128_unscaled(np.asarray(data), validity),
                              validity)
        if isinstance(self.dtype, T.StringType):
            if self.dictionary is None:
                raise ColumnarProcessingError("string column missing dictionary")
            # Clip: padding/invalid slots may hold arbitrary codes.
            codes = np.clip(data, 0, max(len(self.dictionary) - 1, 0))
            vals = np.empty(len(data), dtype=object)
            if len(self.dictionary):
                vals[:] = self.dictionary[codes]
            vals[~validity] = None
            return HostColumn(self.dtype, vals, validity)
        arr = np.ascontiguousarray(data)
        if arr.dtype != self.dtype.np_dtype:
            arr = arr.astype(self.dtype.np_dtype)
        return HostColumn(self.dtype, arr, validity)

    def with_arrays(self, data, validity) -> "DeviceColumn":
        return DeviceColumn(self.dtype, data, validity, self.dictionary,
                            self.dict_sorted, domain=self.domain)

    def sliced_rows(self, k: int) -> "DeviceColumn":
        """First k row slots (array/map columns keep their element buffers
        and slice only the offsets — the shape every row-slicer must use)."""
        if self.is_array:
            off, ed, ev = self.data
            return self.with_arrays((off[:k + 1], ed, ev), self.validity[:k])
        if self.is_struct:
            from spark_rapids_tpu.columnar.nested import StructData
            sd = StructData(tuple((d[:k], v[:k])
                                  for d, v in self.data.fields))
            return self.with_arrays(sd, self.validity[:k])
        if self.is_map:
            from spark_rapids_tpu.columnar.nested import MapData
            md = self.data
            return self.with_arrays(
                MapData(md.offsets[:k + 1], md.kdata, md.kvalid,
                        md.vdata, md.vvalid), self.validity[:k])
        return self.with_arrays(self.data[:k], self.validity[:k])


_MASK64 = (1 << 64) - 1


def dec128_limbs(values, validity, cap: int) -> np.ndarray:
    """Python-int unscaled values -> (cap, 2) int64 two-limb storage:
    [:, 0] = signed high 64 bits, [:, 1] = unsigned low 64 bits
    reinterpreted as int64 (the DECIMAL128 device layout). Vectorized
    over object arrays — this runs per upload AND per shuffle batch."""
    n = len(values)
    out = np.zeros((cap, 2), dtype=np.int64)
    if n == 0:
        return out
    v = np.where(np.asarray(validity[:n], dtype=bool),
                 np.asarray(values[:n], dtype=object), 0)
    lo = v & _MASK64
    lo = np.where(lo >= (1 << 63), lo - (1 << 64), lo)
    out[:n, 0] = (v >> 64).astype(np.int64)
    out[:n, 1] = lo.astype(np.int64)
    return out


def dec128_unscaled(limbs: np.ndarray, validity) -> np.ndarray:
    """(n, 2) int64 limbs -> python-int unscaled object array."""
    n = len(limbs)
    out = np.empty(n, dtype=object)
    if n == 0:
        return out
    vals = ((limbs[:, 0].astype(object) << 64)
            | (limbs[:, 1].astype(object) & _MASK64))
    out[:] = np.where(np.asarray(validity[:n], dtype=bool), vals, 0)
    return out


def null_data_array(dt: T.DataType, capacity: int):
    """All-null device data of the right SHAPE for ``dt`` — dec128
    columns are (capacity, 2) limb matrices (outer-join null sides)."""
    if T.is_dec128(dt):
        return jnp.zeros((capacity, 2), dtype=jnp.int64)
    return jnp.zeros(capacity, dtype=dt.np_dtype)


def stage_upload(host: HostColumn, cap: int, split_f64: bool):
    """Host side of the fast H2D path: turn one column into (recipe, staged
    numpy arrays, dictionary). The tunneled TPU transfers raw f32/i64/u32/i8
    at full bandwidth but converts f64 (its on-device form is an f32 pair),
    i32, and bool slowly on the host — so stage every column as a
    fast-transferring dtype and let the jitted assemble kernel (table.py)
    rebuild the logical dtype on device:

      f64   -> (hi, lo) f32 pair with hi = f32(x), lo = f32(x - hi); the
               device sum hi+lo is bit-identical to what the native f64
               transfer produces on TPU (verified), and exact f64 rides
               unchanged on CPU backends (split_f64=False there);
      i32   -> u32 view (astype back is value-exact mod 2^32 = bit-exact);
      bool  -> i8 (compare != 0 on device);
      rest  -> direct (i8/i16/i64/f32 transfer fast natively);
      validity -> omitted when all-valid (device row mask), else i8.
    """
    n = len(host)
    if isinstance(host.dtype, T.StringType):
        codes, dictionary = DeviceColumn._encode_strings(host)
        # narrow the code transfer to the dictionary's width: low-cardinality
        # string columns (the common case) ship 1 byte/row instead of 4
        if len(dictionary) <= 0xFF:
            padded = np.zeros(cap, dtype=np.uint8)
            padded[:n] = codes
            kind, arrays = "u8codes", [padded]
        elif len(dictionary) <= 0xFFFF:
            padded = np.zeros(cap, dtype=np.uint16)
            padded[:n] = codes
            kind, arrays = "u16codes", [padded]
        else:
            padded = np.zeros(cap, dtype=np.int32)
            padded[:n] = codes
            kind, arrays = "u32", [padded.view(np.uint32)]
    elif T.is_dec128(host.dtype):
        limbs = dec128_limbs(host.data, host.validity, cap)
        dictionary = None
        kind, arrays = "dec128", [np.ascontiguousarray(limbs[:, 0]),
                                  np.ascontiguousarray(limbs[:, 1])]
    else:
        np_dtype = host.dtype.np_dtype
        dictionary = None
        padded = np.zeros(cap, dtype=np_dtype)
        padded[:n] = host.data
        if np_dtype == np.float64 and split_f64:
            hi = padded.astype(np.float32)
            # inf/overflowed values: hi is +/-inf and x - hi would be NaN;
            # lo=0 keeps hi+lo == +/-inf on device (NaN hi propagates fine)
            with np.errstate(invalid="ignore", over="ignore"):
                lo = np.where(np.isfinite(hi),
                              padded - hi.astype(np.float64),
                              0.0).astype(np.float32)
                # keep -0.0: lo carries the signed zero so hi+lo preserves it
                lo = np.where(padded == 0.0, hi, lo)
            kind, arrays = "f64split", [hi, lo]
        elif np_dtype == np.int32:
            kind, arrays = "u32", [padded.view(np.uint32)]
        elif np_dtype == np.bool_:
            kind, arrays = "bool8", [padded.astype(np.int8)]
        else:
            kind, arrays = "direct", [padded]
    if host.all_valid:
        vkind = "ones"
    else:
        vpad = np.zeros(cap, dtype=np.int8)
        vpad[:n] = host.validity
        vkind = "i8"
        arrays.append(vpad)
    recipe = (kind, vkind, str(host.dtype))
    return recipe, arrays, dictionary
