"""Device memory arbiter: out-of-core execution under a hard HBM budget.

Reference (SURVEY.md §2.5): the reference enforces its device budget at
the allocator — RMM's pool is sized to ``spark.rapids.memory.gpu.
allocFraction`` and an allocation past it triggers
``DeviceMemoryEventHandler`` spills, then the RmmSpark OOM state machine
(RetryOOM / SplitAndRetryOOM). On TPU XLA owns the real allocator, so
budget enforcement moves UP a layer: this module is the engine-side
ledger that accounts every device LANDING (``DeviceTable.from_host``)
against a hard conf-driven byte budget (default: the backend-reported
HBM limit), synchronously spills idle BufferCatalog entries when a
reservation would exceed it, and raises :class:`RetryOOM` into the
existing retry framework when spilling cannot make room — which is how
ROADMAP item 2's "query whose working set exceeds HBM" survives instead
of dying at the first oversized batch:

* **reserve → land → account**: a landing reserves its ESTIMATED device
  bytes first (``mem.reserve`` fault point — the budget-squeeze
  injection site), spilling idle spillables / evicting cached scan
  images when the reservation would cross the budget; the landed table
  is then accounted at its ACTUAL device bytes for as long as the
  object lives (weakref-finalized — a spilled or dropped table releases
  its bytes the moment the last reference goes).
* **chunked scans**: :func:`scan_chunks` bounds one scan batch to
  ``spark.rapids.memory.device.scanChunkFraction`` of the budget —
  a host batch that would exceed its budget share lands as several
  bounded partitions instead of one resident table (the out-of-core
  scan half of ROADMAP item 2). The memory degradation ladder
  (runtime/health.py ``on_memory_pressure``) can force a smaller chunk
  target for a whole replay attempt via :func:`forced_chunking`.
* **zero-violation contract**: accounting an actual landing that still
  exceeds the budget after a synchronous spill pass counts a
  ``budgetViolations`` — the chaos closure (scale_test.py
  ``--device-budget``) asserts it stays 0.

Counters live in the unified registry's ``memory`` scope so the event
log (schema v10) diffs them per query like spill/recovery/mesh.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import weakref
from typing import Dict, Optional

from spark_rapids_tpu.conf import float_conf, int_conf
from spark_rapids_tpu.errors import RetryOOM
from spark_rapids_tpu.obs.metrics import metric_scope, register_metric
from spark_rapids_tpu.lockorder import ordered_lock

DEVICE_BUDGET_BYTES = int_conf(
    "spark.rapids.memory.device.budgetBytes", 0,
    "Hard device-memory budget the memory arbiter (runtime/memory.py) "
    "enforces on every device landing: a reservation that would exceed "
    "it synchronously spills idle BufferCatalog entries and, when "
    "spilling cannot make room, raises RetryOOM into the retry "
    "framework (spill-replay, then split-and-retry, then the memory "
    "degradation ladder: chunked re-execution and per-op CPU "
    "demotion). 0 = the backend-reported HBM limit "
    "(spark.rapids.memory.gpu.allocFraction applied), overridable for "
    "tests and out-of-core scale runs.", commonly_used=True)

DEVICE_SCAN_CHUNK_FRACTION = float_conf(
    "spark.rapids.memory.device.scanChunkFraction", 0.25,
    "Largest share of the device budget one scan batch may occupy: a "
    "host batch whose estimated device bytes exceed "
    "budgetBytes * fraction lands as several bounded partitions "
    "(chunked out-of-core scan) instead of one resident table. The "
    "memory degradation ladder halves the effective chunk target when "
    "it replays a query under the 'chunk' rung.")

register_metric("oomRetries", "count", "ESSENTIAL",
                "spill-and-replay retries the OOM retry framework "
                "performed (RetryOOM survived — injected or real)")
register_metric("splitRetries", "count", "ESSENTIAL",
                "split-and-retry escalations: an input batch halved by "
                "rows and both halves replayed after same-size retries "
                "stopped helping")
register_metric("spillBytes", "bytes", "ESSENTIAL",
                "device bytes freed by spill demotions (the memory "
                "scope's mirror of the spill scope's device counter — "
                "the out-of-core work a budgeted query paid)")
register_metric("unspills", "count", "ESSENTIAL",
                "spilled batches brought back to the device "
                "(host or disk tier re-landed)")
register_metric("spillCorruptions", "count", "ESSENTIAL",
                "disk-tier spill frames whose CRC footer failed on "
                "unspill — caught and re-landed from the scan cache "
                "via query replay instead of serving wrong bytes")
register_metric("scanChunks", "count", "MODERATE",
                "bounded partitions chunked scans landed in place of "
                "over-budget single batches")
register_metric("arbiterSpills", "count", "MODERATE",
                "synchronous spill passes the memory arbiter ran to "
                "fit a reservation under the device budget")
register_metric("budgetRaises", "count", "MODERATE",
                "reservations the arbiter refused with RetryOOM after "
                "spilling could not make room")
register_metric("budgetViolations", "count", "ESSENTIAL",
                "actual landings that exceeded the device budget even "
                "after a synchronous spill pass (the chaos closure "
                "asserts this stays 0)")

#: the process-wide ``memory`` scope (shared with retry.py's
#: oomRetries/splitRetries bumps and spill.py's spillBytes mirror)
MEM_SCOPE = metric_scope("memory")

#: per-attempt chunk-target override installed by the memory
#: degradation ladder's 'chunk' rung (runtime/health.py) — like
#: parallel.mesh.suppressed_mesh, per-THREAD so concurrent service
#: workers replay independently
_FORCED_CHUNK_BYTES: contextvars.ContextVar[Optional[int]] = \
    contextvars.ContextVar("rapids_forced_chunk_bytes", default=None)


@contextlib.contextmanager
def forced_chunking(nbytes: int):
    """Force every scan in this thread/attempt to chunk its batches to
    at most ``nbytes`` of estimated device memory — the ladder's
    chunked re-execution rung."""
    token = _FORCED_CHUNK_BYTES.set(max(1, int(nbytes)))
    try:
        yield
    finally:
        _FORCED_CHUNK_BYTES.reset(token)


def forced_chunk_bytes() -> Optional[int]:
    return _FORCED_CHUNK_BYTES.get()


#: approximate per-row DEVICE bytes by logical type (data word +
#: validity byte): strings land as i32 dictionary codes, decimal128 as
#: two i64 limbs, small ints natively. Estimation only — the ledger
#: re-accounts the ACTUAL device bytes after the landing.
def _device_row_bytes(dtype) -> int:
    from spark_rapids_tpu import types as T
    if isinstance(dtype, T.StringType):
        return 4 + 1
    if isinstance(dtype, T.DecimalType) and dtype.precision > 18:
        return 16 + 1
    if isinstance(dtype, (T.ByteType, T.BooleanType)):
        return 1 + 1
    if isinstance(dtype, T.ShortType):
        return 2 + 1
    if isinstance(dtype, (T.IntegerType, T.FloatType, T.DateType)):
        return 4 + 1
    # LONG / DOUBLE / TIMESTAMP / small decimals / unknown: 8B words
    return 8 + 1


def estimate_device_nbytes(host, capacity: Optional[int] = None) -> int:
    """Estimated device bytes a HostTable lands as (padded to its
    capacity bucket)."""
    if not host.columns:
        return 0
    if capacity is None:
        from spark_rapids_tpu.columnar.column import bucket_for
        capacity = bucket_for(max(host.num_rows, 1))
    return sum(_device_row_bytes(c.dtype) for c in host.columns) * capacity


class MemoryReservation:
    """Short-lived grant covering one landing: ``MEMORY.account(table,
    reservation)`` converts it into ledger bytes; ``release()`` returns
    the estimate (upload failed). Usable as a context manager."""

    __slots__ = ("arbiter", "nbytes", "_done")

    def __init__(self, arbiter: "MemoryArbiter", nbytes: int):
        self.arbiter = arbiter
        self.nbytes = int(nbytes)
        self._done = False

    def release(self) -> None:
        if not self._done:
            self._done = True
            self.arbiter._release_reserved(self.nbytes)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class MemoryArbiter:
    """Process-wide device-byte budget + landing ledger.

    The ledger maps a monotonically increasing token to the device
    bytes of one live accounted DeviceTable; a ``weakref.finalize`` on
    the table returns the bytes the instant the last reference drops
    (a spill demotion drops the device reference, so spilling IS the
    release path). Occupancy = reserved + ledger bytes. Reads are
    bounded dict work — safe from the passive telemetry sampler."""

    def __init__(self):
        self._lock = ordered_lock("memory.arbiter")
        self._cfg = None
        #: resolved hard budget; <=0 means "not yet configured" and
        #: enforcement resolves the backend HBM limit lazily
        self._budget = 0
        self._chunk_fraction = float(DEVICE_SCAN_CHUNK_FRACTION.default)
        self._reserved = 0
        self._ledger: Dict[int, int] = {}
        #: running sum of the ledger — occupancy reads are O(1) so the
        #: hot reserve/account paths (and the passive telemetry
        #: sampler's snapshot) never walk the live-table dict under
        #: the lock
        self._ledger_total = 0
        self._by_table_id: Dict[int, int] = {}
        self._next_token = 0
        self._peak = 0
        self._violations = 0
        self._metrics = MEM_SCOPE

    # -- configuration -------------------------------------------------------
    def configure(self, conf) -> None:
        """Cheap when unchanged (the FAULTS.arm contract) — called per
        query by the session and at QueryService construction."""
        budget = int(conf.get_entry(DEVICE_BUDGET_BYTES))
        fraction = float(conf.get_entry(DEVICE_SCAN_CHUNK_FRACTION))
        key = (budget, fraction)
        with self._lock:
            if key == self._cfg:
                return
            self._cfg = key
            self._budget = budget if budget > 0 else self._backend_budget()
            self._chunk_fraction = min(max(fraction, 0.001), 1.0)

    @staticmethod
    def _backend_budget() -> int:
        """The backend-reported HBM limit (allocFraction applied); the
        v5e per-chip default when no manager has initialized yet."""
        try:
            from spark_rapids_tpu.runtime.device_manager import (
                TpuDeviceManager,
                _DEFAULT_HBM_BYTES,
            )
            mgr = TpuDeviceManager.current()
            if mgr is not None and mgr.info is not None:
                return int(mgr.info.hbm_limit_bytes)
            return int(_DEFAULT_HBM_BYTES)
        except Exception:
            return 16 << 30

    def budget_bytes(self) -> int:
        with self._lock:
            if self._budget <= 0:
                self._budget = self._backend_budget()
            return self._budget

    def scan_chunk_bytes(self) -> int:
        """The largest estimated device size one scan batch may land
        as — the attempt-scoped forced override (degradation ladder),
        else budget * scanChunkFraction."""
        forced = _FORCED_CHUNK_BYTES.get()
        if forced is not None:
            return forced
        budget = self.budget_bytes()
        with self._lock:
            return max(1, int(budget * self._chunk_fraction))

    # -- accounting ----------------------------------------------------------
    def occupancy(self) -> int:
        with self._lock:
            return self._reserved + self._ledger_total

    def _note_peak_locked(self) -> None:
        occ = self._reserved + self._ledger_total
        if occ > self._peak:
            self._peak = occ

    def _release_reserved(self, nbytes: int) -> None:
        with self._lock:
            self._reserved -= nbytes

    def _drop(self, token: int, table_id: int) -> None:
        with self._lock:
            self._ledger_total -= self._ledger.pop(token, 0)
            if self._by_table_id.get(table_id) == token:
                self._by_table_id.pop(table_id, None)

    def _spill_for(self, need: int) -> int:
        """One synchronous make-room pass: cached scan images first
        (lowest priority, weakly dropped), then idle spillables through
        the catalog tiers. Returns catalog bytes freed (cache evictions
        release through their finalizers)."""
        from spark_rapids_tpu.columnar.table import evict_device_caches
        from spark_rapids_tpu.runtime.spill import BufferCatalog
        self._metrics.add("arbiterSpills", 1)
        evict_device_caches()
        return BufferCatalog.get().synchronous_spill(max(need, 1))

    def reserve(self, nbytes: int, label: str = "") -> MemoryReservation:
        """Grant ``nbytes`` of device budget for an imminent landing.
        Over budget: spill idle catalog entries; still over: raise
        RetryOOM (the retry framework spills more and replays, then
        splits, then the memory ladder takes the attempt)."""
        from spark_rapids_tpu.runtime.faults import fault_point
        fault_point("mem.reserve", op=label or None)
        nbytes = max(0, int(nbytes))
        budget = self.budget_bytes()
        with self._lock:
            occ = self._reserved + self._ledger_total
            if occ + nbytes <= budget:
                self._reserved += nbytes
                self._note_peak_locked()
                return MemoryReservation(self, nbytes)
        self._spill_for(occ + nbytes - budget)
        with self._lock:
            occ = self._reserved + self._ledger_total
            if occ + nbytes <= budget:
                self._reserved += nbytes
                self._note_peak_locked()
                return MemoryReservation(self, nbytes)
        self._metrics.add("budgetRaises", 1)
        raise RetryOOM(
            f"device budget exhausted: want {nbytes}B"
            + (f" for {label}" if label else "")
            + f", {occ}/{budget}B accounted — spilling freed no room")

    def account(self, table,
                reservation: Optional[MemoryReservation] = None):
        """Record one live DeviceTable against the budget (actual
        device bytes; released by weakref finalizer when the table
        dies). Consumes ``reservation``. An actual landing that still
        exceeds the budget after a spill pass counts a violation —
        enforcement failed, and the chaos closure asserts it never
        does. Returns the table for call-through use."""
        if reservation is not None:
            reservation.release()
        try:
            nbytes = int(table.device_nbytes())
        except Exception:
            return table
        with self._lock:
            if id(table) in self._by_table_id:
                return table  # already accounted (cache re-serve)
            self._next_token += 1
            token = self._next_token
            self._ledger[token] = nbytes
            self._ledger_total += nbytes
            self._by_table_id[id(table)] = token
            weakref.finalize(table, self._drop, token, id(table))
            self._note_peak_locked()
            budget = self._budget if self._budget > 0 else None
            occ = self._reserved + self._ledger_total
        if budget is not None and occ > budget:
            self._spill_for(occ - budget)
            with self._lock:
                occ = self._reserved + self._ledger_total
                if occ > budget:
                    self._violations += 1
                    self._metrics.add("budgetViolations", 1)
        return table

    # -- introspection -------------------------------------------------------
    def snapshot(self) -> dict:
        budget = self.budget_bytes()
        with self._lock:
            return self._snapshot_locked(budget)

    def _snapshot_locked(self, budget: int) -> dict:
        """Snapshot body for callers already holding ``self._lock``.
        ``budget`` must be computed BEFORE entering the lock
        (budget_bytes() self-acquires, and ordered locks are
        non-reentrant by contract)."""
        ledger = self._ledger_total
        return {
            "budgetBytes": budget,
            "occupancyBytes": self._reserved + ledger,
            "ledgerBytes": ledger,
            "reservedBytes": self._reserved,
            "peakBytes": self._peak,
            "accountedTables": len(self._ledger),
            "budgetViolations": self._violations,
        }

    def peak_bytes(self) -> int:
        with self._lock:
            return self._peak

    def reset(self) -> None:
        """Test support: drop the ledger/peak and force reconfigure.
        Live finalizers keep working (their _drop pops by token)."""
        with self._lock:
            self._cfg = None
            self._budget = 0
            self._reserved = 0
            self._ledger = {}
            self._ledger_total = 0
            self._by_table_id = {}
            self._peak = 0
            self._violations = 0


MEMORY = MemoryArbiter()


def scan_chunks(host) -> list:
    """Split one scan host batch into bounded partitions so no single
    landing exceeds its device-budget share — the chunked out-of-core
    scan. Returns ``[host]`` unchanged when the batch fits (the common
    case is one cheap estimate)."""
    limit = MEMORY.scan_chunk_bytes()
    n = host.num_rows
    from spark_rapids_tpu.columnar.column import MIN_BUCKET, bucket_for
    if n <= MIN_BUCKET or not host.columns:
        return [host]
    cap = bucket_for(n)
    est = estimate_device_nbytes(host, cap)
    if est <= limit:
        return [host]
    per_row = max(est / cap, 1e-9)
    rows = max(MIN_BUCKET, int(limit / per_row))
    # chunk rows align DOWN to a full capacity bucket: every chunk's
    # landed capacity equals its row count exactly, so a downstream
    # concat of the chunks re-buckets to (about) the UNCHUNKED upload's
    # capacity instead of inflating it (bucket_for over a sum of
    # already-rounded chunk capacities can double twice)
    bucket = MIN_BUCKET
    while bucket * 2 <= rows:
        bucket *= 2
    rows = bucket
    chunks = [host.slice(i, min(rows, n - i)) for i in range(0, n, rows)]
    MEM_SCOPE.add("scanChunks", len(chunks))
    return chunks
