"""CLI: ``python -m spark_rapids_tpu.tools``.

Subcommands:

* ``profile <eventlog>`` — profiling report over a .jsonl event log (or
  a directory of them): top operators by self time, compute/transfer/
  shuffle/spill breakdown, per-exchange summary, fallback inventory,
  span attribution with the untracked remainder.
* ``compare <A> <B>`` — per-query/per-operator diff of two runs.
* ``loadtest`` — TPC-H corpus through the concurrent QueryService
  across simulated tenants; reports throughput, p50/p95 latency, queue
  wait, result-cache hit rate, cold/warm latency percentiles and the
  per-phase compile breakdown (new traces, executable-cache hit rate)
  vs the serial baseline, asserting bit-identical results (exit 1 on
  any divergence). ``--warmup-from DIR`` AOT-warms from an event-log
  corpus first, so the "cold" pass measures warmed-cold latency.
* ``warmup`` — replay an event-log corpus's distinct plan templates to
  populate the kernel/executable caches (and the persistent XLA
  compile cache on device backends) before traffic arrives; reports
  programs compiled vs skipped.
* ``vacuum <dir>`` — find un-referenced/staged output files: Delta
  orphans vs the latest snapshot, committed-write-dir orphans vs the
  _SUCCESS manifest, and _temporary/ staging debris of jobs that died
  mid-write. DRY RUN by default; ``--delete`` removes.
* ``top`` — live view of a running QueryService: polls the loopback
  introspection endpoint (spark.rapids.service.introspect.enabled)
  and renders health/topology, rolling per-pool/tenant p50/p95 SLOs,
  the live query table, and the telemetry ring's latest deltas.
* ``incident`` — render flight-recorder bundles (spark.rapids.obs.
  flightRecorder.dir): the triggering fault point and ladder action,
  topology at the instant of the incident, recovery counters, the
  telemetry tail, and recent/live query context.

``--json`` emits the raw report dict for machines; exit status 2 when a
profile's span coverage falls below ``--coverage-floor`` (default 0.95)
so CI can gate on attribution quality.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.tools",
        description="offline profiling / qualification tools over query "
                    "event logs (spark.rapids.sql.eventLog.*)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("profile", help="profiling report over one run")
    p.add_argument("eventlog", help=".jsonl event log file or directory")
    p.add_argument("--json", action="store_true",
                   help="emit the raw report JSON")
    p.add_argument("--top", type=int, default=10,
                   help="operators to show per ranking (default 10)")
    p.add_argument("--coverage-floor", type=float, default=0.95,
                   help="minimum span attribution per query; below it "
                        "the command exits 2 (default 0.95)")

    c = sub.add_parser("compare", help="diff two runs per-query/per-op")
    c.add_argument("a", help="baseline event log file or directory")
    c.add_argument("b", help="candidate event log file or directory")
    c.add_argument("--json", action="store_true",
                   help="emit the raw comparison JSON")
    c.add_argument("--top", type=int, default=5,
                   help="op diffs to show per query (default 5)")

    lt = sub.add_parser(
        "loadtest",
        help="concurrent multi-tenant corpus run through the "
             "QueryService, verified bit-identical to serial")
    lt.add_argument("--sf", type=float, default=0.05,
                    help="datagen scale factor (default 0.05)")
    lt.add_argument("--seed", type=int, default=0)
    lt.add_argument("--queries", type=str, default="",
                    help="comma-separated subset (default q1-q22)")
    lt.add_argument("--concurrency", type=int, default=4,
                    help="service worker threads (default 4)")
    lt.add_argument("--tenants", type=int, default=2,
                    help="simulated tenants, each submitting every "
                         "query (default 2)")
    lt.add_argument("--sql", action="store_true",
                    help="submit the SQL-text forms instead of DSL")
    lt.add_argument("--eventlog-dir", type=str, default="",
                    help="also write per-query event logs here")
    lt.add_argument("--json", action="store_true",
                    help="emit the raw report JSON")
    lt.add_argument("--out", type=str, default="",
                    help="write the report JSON to this file")
    lt.add_argument("--warmup-from", type=str, default="",
                    help="AOT-warm from this event-log dir before the "
                         "serial baseline (tools warmup, in-process)")
    lt.add_argument("--chaos", action="store_true",
                    help="arm the seeded service-level fault schedule "
                         "(worker crashes, device losses, a wedged "
                         "dispatch) on the service session; asserts "
                         "every submission terminal, FINISHED results "
                         "bit-identical, failures typed, recovery "
                         "bounded, and health back to HEALTHY")

    w = sub.add_parser(
        "warmup",
        help="AOT precompile: replay an event-log corpus's plan "
             "templates to populate the compile + executable caches")
    w.add_argument("--eventlog-dir", type=str, required=True,
                   help="event-log .jsonl file or directory to replay")
    w.add_argument("--sf", type=float, default=0.05,
                   help="datagen scale factor for the replay warehouse "
                        "(default 0.05)")
    w.add_argument("--seed", type=int, default=0)
    w.add_argument("--sql", action="store_true",
                   help="replay corpus queries in their SQL-text forms")
    w.add_argument("--json", action="store_true",
                   help="emit the raw report JSON")
    w.add_argument("--out", type=str, default="",
                   help="write the report JSON to this file")

    v = sub.add_parser(
        "vacuum",
        help="find (and with --delete, remove) un-referenced or "
             "staged output files under a table/write directory; "
             "dry-run by default")
    v.add_argument("path", help="delta table or write output directory")
    v.add_argument("--delete", action="store_true",
                   help="actually remove the orphans (default: report "
                        "only)")
    v.add_argument("--retention-hours", type=float, default=None,
                   help="keep orphans younger than this (delta mode; "
                        "default: spark.rapids.delta.vacuum."
                        "retentionHours)")
    v.add_argument("--json", action="store_true",
                   help="emit the raw report JSON")

    t = sub.add_parser(
        "top",
        help="live service view over the loopback introspection "
             "endpoint (health, SLOs, query table, telemetry)")
    t.add_argument("--url", type=str, default="",
                   help="endpoint URL (default "
                        "http://127.0.0.1:<port>/top from --port)")
    t.add_argument("--port", type=int, default=0,
                   help="introspection port (QueryService."
                        "introspect_port)")
    t.add_argument("--watch", type=float, default=0.0, metavar="SEC",
                   help="poll every SEC seconds instead of one-shot")
    t.add_argument("--iterations", type=int, default=0,
                   help="with --watch: stop after N polls (0 = forever)")
    t.add_argument("--json", action="store_true",
                   help="emit the raw /top JSON per poll")

    inc = sub.add_parser(
        "incident",
        help="render flight-recorder incident bundles "
             "(spark.rapids.obs.flightRecorder.dir)")
    inc.add_argument("path", nargs="?", default="",
                     help="bundle .json file or flight-recorder dir "
                          "(default: the conf default dir)")
    inc.add_argument("--last", type=int, default=0,
                     help="render only the newest N bundles")
    inc.add_argument("--json", action="store_true",
                     help="emit the raw bundle list JSON")

    args = ap.parse_args(argv)

    if args.cmd == "top":
        from spark_rapids_tpu.tools.top import run_top
        return run_top(url=args.url or None,
                       port=args.port or None,
                       watch_s=args.watch,
                       iterations=args.iterations or None,
                       as_json=args.json)

    if args.cmd == "incident":
        from spark_rapids_tpu.obs.telemetry import FLIGHT_RECORDER_DIR
        from spark_rapids_tpu.tools.incident import (
            load_bundles,
            render_incident,
        )
        path = args.path or str(FLIGHT_RECORDER_DIR.default)
        try:
            bundles = load_bundles(path)
        except FileNotFoundError as exc:
            print(str(exc), file=sys.stderr)
            return 1
        print(json.dumps(bundles) if args.json
              else render_incident(bundles, last=args.last))
        return 0

    if args.cmd == "vacuum":
        from spark_rapids_tpu.tools.vacuum import render_vacuum, run_vacuum
        report = run_vacuum(args.path, delete=args.delete,
                            retention_hours=args.retention_hours)
        print(json.dumps(report) if args.json else render_vacuum(report))
        return 0

    if args.cmd == "warmup":
        from spark_rapids_tpu.tools.warmup import render_warmup, run_warmup
        report = run_warmup(args.eventlog_dir, sf=args.sf,
                            seed=args.seed, use_sql=args.sql)
        print(json.dumps(report) if args.json else render_warmup(report))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(report, f, indent=1)
        return 0 if report["ok"] else 1

    if args.cmd == "loadtest":
        from spark_rapids_tpu.tools.loadtest import (
            render_loadtest,
            run_loadtest,
        )
        wanted = [q.strip() for q in args.queries.split(",") if q.strip()]
        report = run_loadtest(
            sf=args.sf, seed=args.seed, queries=wanted or None,
            use_sql=args.sql, concurrency=args.concurrency,
            tenants=args.tenants,
            eventlog_dir=args.eventlog_dir or None,
            warmup_from=args.warmup_from or None,
            chaos=args.chaos)
        print(json.dumps(report) if args.json
              else render_loadtest(report))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(report, f, indent=1)
        return 0 if report["ok"] else 1

    if args.cmd == "profile":
        from spark_rapids_tpu.tools.report import (
            build_profile,
            load_events,
            render_profile,
        )
        report = build_profile(load_events(args.eventlog), top_n=args.top,
                               coverage_floor=args.coverage_floor)
        print(json.dumps(report) if args.json else render_profile(report))
        return 2 if report["queriesBelowCoverageFloor"] else 0

    from spark_rapids_tpu.tools.compare import build_compare, render_compare
    cmp = build_compare(args.a, args.b)
    print(json.dumps(cmp) if args.json
          else render_compare(cmp, top_n=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
