"""Observability subsystem tests: unified metric registry + levels,
exec observation boundary (ESSENTIAL metrics), host span tracing +
Chrome trace export, the query event log (golden schema), and the
offline tools (profile report, A/B compare, CLI smoke)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.session import TpuSession


def _table_data(n=200):
    return {"k": np.array(["a", "b", "a", "c"] * (n // 4), dtype=object),
            "v": np.arange(n, dtype=np.int64)}


def _agg_df(s, n=200):
    df = s.create_dataframe(_table_data(n))
    return (df.filter(col("v") > lit(10))
            .group_by("k").agg(F.sum("v").alias("sv")))


def _exec_tree(session):
    from spark_rapids_tpu.lore import _iter_tree
    return list(_iter_tree(session._last_executable))


# ---------------------------------------------------------------------------
# metric registry
# ---------------------------------------------------------------------------


def test_metric_spec_conflict_raises():
    from spark_rapids_tpu.obs.metrics import register_metric
    register_metric("obsTestMetricA", "count", "MODERATE")
    register_metric("obsTestMetricA", "count", "MODERATE")  # idempotent
    with pytest.raises(ValueError):
        register_metric("obsTestMetricA", "timing", "MODERATE")
    with pytest.raises(ValueError):
        register_metric("obsTestMetricB", "weird", "MODERATE")


def test_metric_set_spec_level_and_typed():
    from spark_rapids_tpu.obs.metrics import (
        MetricSet,
        set_metrics_level,
        spec_for,
    )
    m = MetricSet()
    try:
        set_metrics_level("ESSENTIAL")
        m.add("opTime", 0.5)          # ESSENTIAL spec -> kept
        m.add("somethingTime", 1.0)   # inferred MODERATE -> dropped
        assert dict(m) == {"opTime": 0.5}
        set_metrics_level("MODERATE")
        m.add("somethingTime", 1.0)
        m.add("fooBytesRead", 3)
        t = m.typed()
        assert t["opTime"] == {"value": 0.5, "kind": "timing",
                               "level": "ESSENTIAL"}
        assert t["somethingTime"]["kind"] == "timing"
        assert t["fooBytesRead"]["kind"] == "bytes"
        assert spec_for("randomCounter").kind == "count"
    finally:
        set_metrics_level("MODERATE")


def test_metrics_level_applies_to_transitions():
    """DeviceToHost routes through the same level machinery as execs:
    at ESSENTIAL, its ESSENTIAL metrics survive and MODERATE exec
    metrics (scanCacheMiss) are dropped; at DEBUG everything records."""
    s = TpuSession({"spark.rapids.sql.metrics.level": "ESSENTIAL"})
    _agg_df(s).collect_table()
    tree = _exec_tree(s)
    d2h = tree[0]
    assert "d2hTime" in d2h.metrics
    assert "numOutputRows" in d2h.metrics
    all_metrics = set().union(*(t.metrics for t in tree))
    assert "scanCacheMiss" not in all_metrics  # MODERATE, dropped

    s2 = TpuSession({"spark.rapids.sql.metrics.level": "DEBUG"})
    _agg_df(s2).collect_table()
    all2 = set().union(*(t.metrics for t in _exec_tree(s2)))
    assert "scanCacheMiss" in all2


def test_every_exec_emits_essential_metrics():
    from spark_rapids_tpu.execs.base import DeviceToHost, TpuExec
    from spark_rapids_tpu.lint.registry_audit import audit_exec_metrics_tree
    from spark_rapids_tpu.obs.metrics import ESSENTIAL_EXEC_METRICS
    from spark_rapids_tpu.obs.spans import finalize_observation
    s = TpuSession()
    out = _agg_df(s).collect_table()
    assert out.num_rows == 3
    finalize_observation(s._last_executable)
    tree = _exec_tree(s)
    execs = [e for e in tree if isinstance(e, (TpuExec, DeviceToHost))]
    assert len(execs) >= 3
    for e in execs:
        for k in ESSENTIAL_EXEC_METRICS:
            assert k in e.metrics, (type(e).__name__, k, dict(e.metrics))
    # the positive side of the RA-ESSENTIAL-METRICS audit
    diags = []
    audit_exec_metrics_tree(s._last_executable, diags)
    assert diags == []
    # row counts are real, not placeholders: the scan saw all 200 rows
    scan = [e for e in execs if type(e).__name__ == "TpuScanExec"
            or "Scan" in type(e).__name__]
    assert scan and scan[0].metrics["numOutputRows"] == 200


def test_subsystem_scopes_record():
    from spark_rapids_tpu.obs.metrics import metric_scope
    before = dict(metric_scope("shuffle"))
    s = TpuSession({
        "spark.rapids.shuffle.localDeviceSplit.enabled": "false"})
    df = s.create_dataframe(_table_data(80), num_batches=2)
    df.repartition(4, "k").group_by("k").agg(
        F.count("v").alias("c")).collect_table()
    after = dict(metric_scope("shuffle"))
    assert after.get("shuffleBytesWritten", 0) > before.get(
        "shuffleBytesWritten", 0)
    assert after.get("shuffleBytesRead", 0) > before.get(
        "shuffleBytesRead", 0)


# ---------------------------------------------------------------------------
# spans + chrome trace
# ---------------------------------------------------------------------------


def test_chrome_trace_export_schema(tmp_path):
    s = TpuSession({"spark.rapids.trace.enabled": "true",
                    "spark.rapids.trace.dir": str(tmp_path)})
    _agg_df(s).collect_table()
    path = tmp_path / "query_0.trace.json"
    assert path.exists()
    trace = json.loads(path.read_text())
    events = trace["traceEvents"]
    assert events, "empty trace"
    names = set()
    for ev in events:
        assert ev["ph"] in ("X", "M")
        assert isinstance(ev["pid"], int)
        assert isinstance(ev["tid"], int)
        if ev["ph"] == "X":
            assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
            assert isinstance(ev["name"], str) and ev["name"]
            assert isinstance(ev["cat"], str)
            names.add(ev["name"])
        else:
            assert ev["name"] == "thread_name"
    # exec boundaries, phases and the d2h transfer all show up
    assert "TpuHashAggregateExec" in names or any(
        "Aggregate" in n for n in names)
    assert {"plan", "execute", "collect"} <= names
    assert "DeviceToHost" in names


def test_tracer_disabled_is_default_and_cheap():
    from spark_rapids_tpu.obs.spans import TRACER, span
    assert TRACER.enabled is False
    with span("nothing", cat="op"):
        pass  # no-op context manager when disabled
    s = TpuSession()
    _agg_df(s).collect_table()
    assert TRACER.enabled is False


def test_span_union_seconds():
    from spark_rapids_tpu.obs.spans import union_seconds
    assert union_seconds([]) == 0.0
    assert union_seconds([(0, 1), (0.5, 2), (3, 4)]) == pytest.approx(3.0)
    assert union_seconds([(0, 5), (1, 2)]) == pytest.approx(5.0)


# ---------------------------------------------------------------------------
# event log
# ---------------------------------------------------------------------------


#: budgetPeak is the memory arbiter's PROCESS-WIDE peak — earlier tests
#: in the same process move it, so the golden pins presence, not value
_VOLATILE_INT_KEYS = {"dispatches", "spanCount", "tid", "budgetPeak"}

#: scopes whose per-query delta depends on PROCESS WARMTH, not the
#: query (the compile scope reports kernelTraces on a cold process and
#: kernelTraceCacheHits on a warm one — both correct, neither golden)
_VOLATILE_SCOPES = {"compile"}


def _normalize(obj, key=None):
    """Normalize volatile values (timings, counters that shift with the
    engine's dispatch strategy) so the golden pins SCHEMA + stable
    semantics, not wall-clock noise."""
    if isinstance(obj, dict):
        if key == "scopes":
            obj = {k: v for k, v in obj.items()
                   if k not in _VOLATILE_SCOPES}
        return {k: _normalize(v, k) for k, v in sorted(obj.items())}
    if isinstance(obj, list):
        return [_normalize(v) for v in obj]
    if isinstance(obj, bool):
        return obj
    if isinstance(obj, float):
        return 0.0
    if isinstance(obj, int) and key in _VOLATILE_INT_KEYS:
        return 0
    return obj


def _run_eventlog_query(tmp_path, tag="golden"):
    s = TpuSession({"spark.rapids.sql.eventLog.enabled": "true",
                    "spark.rapids.sql.eventLog.dir": str(tmp_path)})
    s.next_query_tag = tag
    _agg_df(s).collect_table()
    return s


def test_event_log_written_and_valid(tmp_path):
    s = _run_eventlog_query(tmp_path)
    assert s.last_event_path and os.path.exists(s.last_event_path)
    lines = open(s.last_event_path).read().strip().splitlines()
    assert len(lines) == 1
    rec = json.loads(lines[0])
    # schema v11: the streaming PR added the streaming-scope deltas
    # (microBatches / mvRefreshes / mvIncrementalRefreshes /
    # mvFullRecomputes / sinkCommits / sinkReplays — all 0 on a
    # stream-free process) and mvEpoch (null unless the record serves
    # a materialized view) on top of v10's out-of-core fields, v9's
    # hostScans, v8's multi-host fault-domain fields, v7's mesh
    # fault-domain fields, v6's mesh-native fields, v5's
    # transactional-write fields and v4's survivability fields — see
    # obs/events.py
    assert rec["schema"] == 11
    assert rec["healthState"] == "HEALTHY"
    assert rec["quarantined"] is False
    assert rec["deviceReinits"] == 0 and rec["workerRestarts"] == 0
    assert rec["filesWritten"] == 0 and rec["bytesWritten"] == 0
    assert rec["commitRetries"] == 0
    assert rec["meshShape"] is None
    assert rec["iciBytes"] == 0 and rec["shardSkew"] == 0.0
    assert rec["meshDegradations"] == 0
    assert rec["shardRetries"] == 0 and rec["gatherChecksFailed"] == 0
    assert rec["hostTopology"] is None
    assert rec["hostsLost"] == 0 and rec["hostRelands"] == 0
    assert rec["dcnExchanges"] == 0
    assert rec["hostScans"] == {}
    assert rec["oomRetries"] == 0 and rec["splitRetries"] == 0
    assert rec["spillBytes"] == 0 and rec["unspills"] == 0
    assert isinstance(rec["budgetPeak"], int) and rec["budgetPeak"] >= 0
    assert rec["microBatches"] == 0 and rec["mvRefreshes"] == 0
    assert rec["mvIncrementalRefreshes"] == 0
    assert rec["mvFullRecomputes"] == 0
    assert rec["sinkCommits"] == 0 and rec["sinkReplays"] == 0
    assert rec["mvEpoch"] is None
    assert rec["event"] == "queryCompleted"
    assert rec["queryTag"] == "golden"
    assert rec["wallS"] > 0
    assert rec["spans"]["attributedS"] > 0
    assert rec["tenant"] is None and rec["pool"] is None
    assert rec["queueWaitS"] is None and rec["cacheHit"] is False
    # a fresh session over a fresh table: no cached executable to hit,
    # compileMs/padWasteRows present and typed
    assert rec["executableCacheHit"] is False
    assert isinstance(rec["compileMs"], float) and rec["compileMs"] >= 0
    assert isinstance(rec["padWasteRows"], int) and rec["padWasteRows"] >= 0
    # per-op metrics are typed in the plan tree
    agg = rec["plan"]["children"][0]
    assert agg["metrics"]["opTime"]["kind"] == "timing"
    assert agg["metrics"]["numOutputRows"]["value"] == 3


def test_event_log_golden_schema(tmp_path):
    """Golden record: normalized timings, byte-stable schema. A failure
    here means the event-log record shape changed — bump
    EVENT_SCHEMA_VERSION, regenerate tests/golden_eventlog.json (this
    test prints the new normalized record on mismatch) and check the
    offline tools still read it.

    Schema history: v1 = the PR-4 record; v2 = query-service fields
    (tenant, pool, queueWaitS, cacheHit — null/false when the query ran
    outside the service; a cache-hit serve replays the filling run's
    record with cacheHit=true and its own queueWaitS/wallS); v3 =
    serving-latency fields (compileMs — wall spent on new XLA traces,
    0.0 fully warm; executableCacheHit — the query checked out a cached
    converted executable; padWasteRows — dead rows padding batches to
    their capacity buckets; result-cache serves carry 0.0/false/0);
    v4 = survivability fields (healthState — HEALTHY/DEGRADED/CPU_ONLY
    at record time; quarantined — the template carries poison strikes;
    deviceReinits/workerRestarts — per-record deltas of the health
    scope's recovery counters, 0 on a quiet process);
    v5 = transactional-write fields (filesWritten/bytesWritten — data
    files the committer promoted during this query's wall and their
    bytes; commitRetries — Delta optimistic commits rebased after
    losing the version race; per-record deltas of the write scope,
    all 0 for read-only queries and result-cache serves);
    v6 = mesh-native execution fields (meshShape — the active device
    mesh topology, null off-mesh; iciBytes — payload bytes through ICI
    all-to-all collectives, a per-record delta of the mesh scope;
    shardSkew — max per-shard map-output max/median over the query's
    collective exchanges, measured from real shard live counts;
    result-cache serves carry serve-time meshShape and 0/0.0);
    v7 = mesh fault-domain fields (meshDegradations — degradation-
    ladder demotions during this query's wall, a health-scope delta;
    shardRetries / gatherChecksFailed — local re-gathers paid and
    checksum validations tripped at mesh gather boundaries, mesh-scope
    deltas; all 0 on a healthy mesh and for result-cache serves);
    v8 = multi-host fault-domain fields (hostTopology — the active
    cluster host topology at record time, '2' full / '1/2' degraded /
    '0/2' latched single-process, null off-cluster; hostsLost /
    hostRelands / dcnExchanges — executor hosts declared lost, lost
    hosts' shards re-landed onto survivors, and collectives that
    crossed the DCN axis during this query's wall — per-record deltas
    of the cluster scope; all 0/null off-cluster and for result-cache
    serves);
    v9 = flight-recorder fields (hostScans — per-executor-host scan
    attribution merged from cluster scan replies: {host: {scans,
    files, bytes, wallS, execWallS, crcRetries}}; {} off-cluster, for
    local-fallback scans and for result-cache serves — a cached serve
    dispatches nothing);
    v10 = out-of-core fields (oomRetries / splitRetries / spillBytes /
    unspills — per-record deltas of the memory scope: spill-and-replay
    retries survived, split-and-retry escalations, device bytes freed
    by spill demotions, spilled batches re-landed; all 0 on an
    unbudgeted quiet process and for result-cache serves; budgetPeak —
    the memory arbiter's peak accounted device bytes, absolute and
    process-wide, normalized in the golden);
    v11 = streaming fields (microBatches / mvRefreshes /
    mvIncrementalRefreshes / mvFullRecomputes / sinkCommits /
    sinkReplays — per-record deltas of the streaming scope: micro-batch
    executions, materialized-view refreshes split by maintenance
    strategy, and the exactly-once sink's commits and deduped replays;
    all 0 on a stream-free process and zeroed on result-cache serves;
    mvEpoch — the Delta version a served materialized view reflects,
    null for everything that is not an MV serve)."""
    s = _run_eventlog_query(tmp_path)
    got = _normalize(s.last_event_record)
    golden_path = os.path.join(os.path.dirname(__file__),
                               "golden_eventlog.json")
    golden = json.load(open(golden_path))
    assert got == golden, (
        "event-log record drifted from the golden schema; new normalized "
        "record:\n" + json.dumps(got, indent=1, sort_keys=True))


def test_event_log_disabled_writes_nothing(tmp_path):
    s = TpuSession({"spark.rapids.sql.eventLog.dir": str(tmp_path)})
    _agg_df(s).collect_table()
    assert s.last_event_path is None
    assert list(tmp_path.iterdir()) == []


def test_sql_text_recorded(tmp_path):
    s = TpuSession({"spark.rapids.sql.eventLog.enabled": "true",
                    "spark.rapids.sql.eventLog.dir": str(tmp_path)})
    s.create_dataframe(_table_data()).create_or_replace_temp_view("t")
    s.sql("SELECT k, SUM(v) AS sv FROM t GROUP BY k").collect_table()
    rec = s.last_event_record
    assert "SUM(v)" in rec["sqlText"]


def test_worker_thread_attribution_meets_floor(tmp_path):
    """A query executed from a NON-main thread must attribute its wall
    time against the EXECUTING thread's spans (PR 4 unioned main-thread
    intervals, under-attributing every off-main-thread query — the
    query service runs all queries off-main)."""
    import threading

    s = TpuSession({"spark.rapids.sql.eventLog.enabled": "true",
                    "spark.rapids.sql.eventLog.dir": str(tmp_path)})
    _agg_df(s).collect_table()  # warm: compile noise off the floor
    box = {"covs": []}

    def run():
        # best of three: the attribution BUG this pins (main-thread
        # interval union -> ~0 coverage off-main) fails every run; a
        # millisecond scheduler hiccup on a ~15ms query only fails one
        for _ in range(3):
            _agg_df(s).collect_table()
            rec = s.last_event_record  # thread-local, not a mirror
            box["covs"].append(rec["spans"]["attributedS"]
                               / rec["wallS"])

    t = threading.Thread(target=run, name="obs-worker")
    t.start()
    t.join(timeout=120)
    assert len(box["covs"]) == 3
    cov = max(box["covs"])
    assert cov >= 0.95, f"off-main-thread span coverage {cov:.3f} < 0.95"


def test_concurrent_queries_write_distinct_records(tmp_path):
    """Two sessions' queries executing CONCURRENTLY from worker threads
    must produce self-consistent records (no cross-thread span or
    envelope bleed): every record attributes >= 95% of its own wall."""
    import threading

    sessions = [
        TpuSession({"spark.rapids.sql.eventLog.enabled": "true",
                    "spark.rapids.sql.eventLog.dir": str(tmp_path)})
        for _ in range(2)]
    for s in sessions:  # warm: measure attribution, not XLA compiles
        _agg_df(s, n=400).collect_table()
    covs = {0: [], 1: []}

    def run(i):
        # best of three per session (see the off-main-thread test: the
        # pinned bug fails every run, scheduler noise only one)
        for _ in range(3):
            _agg_df(sessions[i], n=400).collect_table()
            rec = sessions[i].last_event_record
            covs[i].append(rec["spans"]["attributedS"] / rec["wallS"])

    threads = [threading.Thread(target=run, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for i in (0, 1):
        assert len(covs[i]) == 3
        cov = max(covs[i])
        assert cov >= 0.95, f"session {i} coverage {cov:.3f} < 0.95"


def test_nested_query_rides_outer_envelope(tmp_path):
    """A broadcast-join query materializes its build side through a
    nested execute; only ONE record per top-level query is written."""
    s = TpuSession({"spark.rapids.sql.eventLog.enabled": "true",
                    "spark.rapids.sql.eventLog.dir": str(tmp_path),
                    "spark.rapids.sql.broadcastSizeBytes": str(1 << 20)})
    left = s.create_dataframe(_table_data(100))
    right = s.create_dataframe({"k": np.array(["a", "b"], dtype=object),
                                "w": np.array([1, 2], dtype=np.int64)})
    left.join(right, on=["k"], how="inner").collect_table()
    lines = open(s.last_event_path).read().strip().splitlines()
    assert len(lines) == 1
    assert json.loads(lines[0])["queryIndex"] == 0


# ---------------------------------------------------------------------------
# offline tools
# ---------------------------------------------------------------------------


def _two_runs(tmp_path):
    dir_a, dir_b = tmp_path / "a", tmp_path / "b"
    for d in (dir_a, dir_b):
        s = TpuSession({"spark.rapids.sql.eventLog.enabled": "true",
                        "spark.rapids.sql.eventLog.dir": str(d)})
        s.next_query_tag = "q"
        _agg_df(s).collect_table()
    return str(dir_a), str(dir_b)


def test_tools_profile_report(tmp_path):
    from spark_rapids_tpu.tools import (
        build_profile,
        load_events,
        render_profile,
    )
    s = _run_eventlog_query(tmp_path, tag="q1")
    report = build_profile(load_events(str(tmp_path)))
    assert report["queryCount"] == 1
    q = report["queries"][0]
    assert q["query"] == "q1"
    att = q["attribution"]
    assert 0.0 < att["coverage"] <= 1.0
    assert att["attributedS"] + att["untrackedS"] == pytest.approx(
        q["wallS"], rel=0.01)
    b = q["breakdown"]
    assert b["wallS"] == pytest.approx(
        b["computeS"] + b["transferS"] + b["shuffleS"] + b["spillS"]
        + b["untrackedS"], rel=0.01)
    tops = q["topOpsBySelfTime"]
    assert tops and all(t["selfTimeS"] >= 0 for t in tops)
    # self times nest under total: sum of self <= wall-ish envelope
    assert sum(t["selfTimeS"] for t in tops) <= q["wallS"] * 1.05
    text = render_profile(report)
    assert "Top operators by self time" in text
    assert "q1" in text
    del s


def test_tools_compare(tmp_path):
    from spark_rapids_tpu.tools import build_compare, render_compare
    dir_a, dir_b = _two_runs(tmp_path)
    cmp = build_compare(dir_a, dir_b)
    assert cmp["matchedQueries"] == 1
    q = cmp["queries"][0]
    assert q["query"] == "q"
    assert q["aWallS"] > 0 and q["bWallS"] > 0
    common = [e for e in q["ops"] if e["status"] == "common"]
    assert common, "no matched ops"
    assert all("deltaOpTimeS" in e for e in common)
    assert q["newFallbacks"] == [] and q["resolvedFallbacks"] == []
    text = render_compare(cmp)
    assert "Matched queries: 1" in text


def test_tools_schema_mismatch_rejected(tmp_path):
    from spark_rapids_tpu.tools import load_events
    p = tmp_path / "bad.jsonl"
    p.write_text(json.dumps({"schema": 99, "event": "queryCompleted"})
                 + "\n")
    with pytest.raises(ValueError, match="schema"):
        load_events(str(p))


def test_tools_cli_smoke(tmp_path):
    """The acceptance smoke: run q1 (golden corpus), analyze its event
    log through the real CLI."""
    import scale_test
    from spark_rapids_tpu.lint.golden import golden_tables
    tables = golden_tables(0.005)
    s = TpuSession({"spark.rapids.sql.eventLog.enabled": "true",
                    "spark.rapids.sql.eventLog.dir": str(tmp_path)})
    queries = scale_test.build_queries(s, tables)
    s.next_query_tag = "q1"
    queries["q1"]().collect_table()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "profile",
         str(tmp_path)],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode in (0, 2), out.stderr
    assert "Queries: 1" in out.stdout
    assert "q1" in out.stdout
    out_json = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "profile",
         "--json", str(tmp_path)],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    report = json.loads(out_json.stdout)
    assert report["queryCount"] == 1


def test_lore_stripped_exec_keeps_metricset():
    """A LORE-dumped exec must round-trip with a usable MetricSet —
    add_metric on the replayed exec would crash on a plain dict."""
    import pickle

    from spark_rapids_tpu.execs.basic import TpuScanExec
    from spark_rapids_tpu.lore import _strip_for_pickle
    from spark_rapids_tpu.obs.metrics import MetricSet
    s = TpuSession()
    _agg_df(s).collect_table()
    scan = [e for e in _exec_tree(s)
            if isinstance(e, TpuScanExec)][0]
    clone = pickle.loads(pickle.dumps(_strip_for_pickle(scan)))
    assert isinstance(clone.metrics, MetricSet)
    clone.add_metric("scanRows", 5)
    assert clone.metrics["scanRows"] == 5


def test_tools_compare_aggregates_duplicate_tags(tmp_path):
    """Three warm runs per tag compare as medians, not last-run-wins."""
    from spark_rapids_tpu.tools import build_compare
    dirs = []
    for sub in ("a", "b"):
        d = tmp_path / sub
        s = TpuSession({"spark.rapids.sql.eventLog.enabled": "true",
                        "spark.rapids.sql.eventLog.dir": str(d)})
        for _ in range(3):
            s.next_query_tag = "q"
            _agg_df(s).collect_table()
        dirs.append(str(d))
    cmp = build_compare(*dirs)
    q = cmp["queries"][0]
    assert q["aRuns"] == 3 and q["bRuns"] == 3
    assert q["aWallMinS"] <= q["aWallS"]


# ---------------------------------------------------------------------------
# overhead guard
# ---------------------------------------------------------------------------


def test_disabled_observability_leaves_no_span_state():
    """With event log and tracing off (the default), executing queries
    must not accumulate span state or enable the tracer."""
    from spark_rapids_tpu.obs.spans import TRACER
    s = TpuSession()
    for _ in range(3):
        _agg_df(s).collect_table()
    assert TRACER.enabled is False
    assert TRACER._spans == []
