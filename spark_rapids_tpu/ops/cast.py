"""Cast (reference: GpuCast.scala, 1,809 LoC + JNI CastStrings; SURVEY.md
§2.3/§2.9). This round covers the numeric/boolean/temporal core with Java
narrowing semantics; string<->numeric and string<->temporal casts follow the
reference's staged approach (some off by default) and are added as they gain
CPU-exact implementations.

Java narrowing rules implemented:
* int -> smaller int: wrap (low bits);
* float/double -> integral: truncate toward zero, saturate at MIN/MAX,
  NaN -> 0;
* numeric -> boolean: v != 0; boolean -> numeric: 1/0;
* date -> timestamp: midnight UTC micros; timestamp -> date: floor to day.
"""

from __future__ import annotations

import datetime
import math
import re

import numpy as np
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.ops.common import UnaryExpression
from spark_rapids_tpu.ops.expr import DevVal, Expression, NodePrep

_INT_BOUNDS = {
    np.dtype(np.int8): (-(1 << 7), (1 << 7) - 1),
    np.dtype(np.int16): (-(1 << 15), (1 << 15) - 1),
    np.dtype(np.int32): (-(1 << 31), (1 << 31) - 1),
    np.dtype(np.int64): (-(1 << 63), (1 << 63) - 1),
}

MICROS_PER_DAY = 86_400_000_000


def _cast_data_np(data: np.ndarray, src: T.DataType, dst: T.DataType) -> np.ndarray:
    sd, dd = src.np_dtype, dst.np_dtype
    if isinstance(dst, T.BooleanType):
        return data != 0
    if isinstance(src, T.BooleanType):
        return data.astype(dd)
    if isinstance(src, (T.FloatType, T.DoubleType)) and isinstance(dst, T.IntegralType):
        lo, hi = _INT_BOUNDS[dd]
        with np.errstate(invalid="ignore"):
            t = np.trunc(data)
            t = np.where(np.isnan(data), 0.0, t)
            t = np.clip(t, float(lo), float(hi))
        # float64 cannot represent 2^63-1 exactly; rely on clip + cast with
        # saturation applied before conversion.
        out = np.empty(data.shape, dtype=dd)
        big = t >= float(hi)
        small = t <= float(lo)
        mid = ~(big | small)
        out[big] = hi
        out[small] = lo
        out[mid] = t[mid].astype(dd)
        return out
    if isinstance(src, T.DateType) and isinstance(dst, T.TimestampType):
        return data.astype(np.int64) * MICROS_PER_DAY
    if isinstance(src, T.TimestampType) and isinstance(dst, T.DateType):
        return np.floor_divide(data, MICROS_PER_DAY).astype(np.int32)
    with np.errstate(over="ignore", invalid="ignore"):
        return data.astype(dd)


def _cast_data_jnp(data, src: T.DataType, dst: T.DataType):
    dd = dst.np_dtype
    if isinstance(dst, T.BooleanType):
        return data != 0
    if isinstance(src, T.BooleanType):
        return data.astype(dd)
    if isinstance(src, (T.FloatType, T.DoubleType)) and isinstance(dst, T.IntegralType):
        lo, hi = _INT_BOUNDS[np.dtype(dd)]
        t = jnp.trunc(data)
        t = jnp.where(jnp.isnan(data), 0.0, t)
        t = jnp.clip(t, float(lo), float(hi))
        out = t.astype(dd)
        out = jnp.where(t >= float(hi), hi, out)
        out = jnp.where(t <= float(lo), lo, out)
        return out
    if isinstance(src, T.DateType) and isinstance(dst, T.TimestampType):
        return data.astype(jnp.int64) * MICROS_PER_DAY
    if isinstance(src, T.TimestampType) and isinstance(dst, T.DateType):
        return jnp.floor_divide(data, MICROS_PER_DAY).astype(jnp.int32)
    return data.astype(dd)


_SUPPORTED_SIMPLE = (T.BooleanType, T.ByteType, T.ShortType, T.IntegerType,
                     T.LongType, T.FloatType, T.DoubleType, T.DateType,
                     T.TimestampType)

# ---------------------------------------------------------------------------
# String <-> X casts (reference: GpuCast.scala castStringToInts/Floats/Bool/
# Date + JNI CastStrings; Spark CPU: Cast.scala with UTF8String.trimAll +
# strict toInt/toLong, processFloatingPointSpecialLiterals)
# ---------------------------------------------------------------------------

#: Java's trimAll strips every char <= U+0020
_JAVA_WS = "".join(chr(i) for i in range(0x21))

#: Hive-compatible integral string: optional fraction is TRUNCATED
#: (UTF8String.toLong accepts trailing .digits for Hive back-compat)
_INT_RE = re.compile(r"([+-]?)(\d*)(?:\.(\d*))?")
_DATE_RE = re.compile(r"(\d{4,5})(?:-(\d{1,2})(?:-(\d{1,2})(?:[T ].*)?)?)?")

_TRUE_STRINGS = frozenset(("t", "true", "y", "yes", "1"))
_FALSE_STRINGS = frozenset(("f", "false", "n", "no", "0"))

_FLOAT_SPECIALS = {"inf": np.inf, "+inf": np.inf, "infinity": np.inf,
                   "+infinity": np.inf, "-inf": -np.inf,
                   "-infinity": -np.inf, "nan": np.nan}


def parse_string_cast(s: str, dst: T.DataType):
    """Spark-exact string -> value parse; None = cast yields null."""
    t = s.strip(_JAVA_WS)
    if isinstance(dst, T.IntegralType):
        m = _INT_RE.fullmatch(t)
        if not m or (not m.group(2) and not m.group(3)):
            return None  # needs at least one digit somewhere
        v = int(m.group(2) or "0")
        if m.group(1) == "-":
            v = -v
        lo, hi = _INT_BOUNDS[np.dtype(dst.np_dtype)]
        return v if lo <= v <= hi else None  # overflow -> null (toInt fails)
    if isinstance(dst, (T.FloatType, T.DoubleType)):
        low = t.lower()
        if low in _FLOAT_SPECIALS:
            v = _FLOAT_SPECIALS[low]
        else:
            body = t
            # Java parseDouble accepts a trailing f/F/d/D suffix
            if body and body[-1] in "fFdD" and any(c.isdigit() for c in body[:-1]):
                body = body[:-1]
            if not body or "_" in body or body.lower() in ("", "+", "-"):
                return None
            try:
                v = float(body)
            except ValueError:
                return None
        if isinstance(dst, T.FloatType):
            v = float(np.float32(v))
        return v
    if isinstance(dst, T.BooleanType):
        low = t.lower()
        if low in _TRUE_STRINGS:
            return True
        if low in _FALSE_STRINGS:
            return False
        return None
    if isinstance(dst, T.DateType):
        m = _DATE_RE.fullmatch(t)
        if not m:
            return None
        y = int(m.group(1))
        mo = int(m.group(2)) if m.group(2) else 1
        d = int(m.group(3)) if m.group(3) else 1
        try:
            return (datetime.date(y, mo, d) - datetime.date(1970, 1, 1)).days
        except ValueError:
            return None
    return None


def _java_float_str(x: float, is_float: bool) -> str:
    """Java Float/Double.toString formatting (what Spark emits for
    float -> string casts): positional for 1e-3 <= |x| < 1e7, otherwise
    'd.dddE[-]e' scientific; NaN/Infinity spelled out; >=1 fractional
    digit always present."""
    if math.isnan(x):
        return "NaN"
    if math.isinf(x):
        return "Infinity" if x > 0 else "-Infinity"
    if x == 0.0:
        return "-0.0" if math.copysign(1.0, x) < 0 else "0.0"
    ax = abs(x)
    if 1e-3 <= ax < 1e7:
        if is_float:
            s = np.format_float_positional(np.float32(x), unique=True,
                                           trim="0")
        else:
            s = np.format_float_positional(x, unique=True, trim="0")
        if "." not in s:
            s += ".0"
        if s.endswith("."):
            s += "0"
        return s
    if is_float:
        s = np.format_float_scientific(np.float32(x), unique=True, trim="0")
    else:
        s = np.format_float_scientific(x, unique=True, trim="0")
    mant, _, exp = s.partition("e")
    if "." not in mant:
        mant += ".0"
    if mant.endswith("."):
        mant += "0"
    e = int(exp)
    return f"{mant}E{e}"


def format_value_as_string(v, src: T.DataType):
    """Spark-exact X -> string rendering."""
    if isinstance(src, T.BooleanType):
        return "true" if v else "false"
    if isinstance(src, (T.FloatType, T.DoubleType)):
        return _java_float_str(float(v), isinstance(src, T.FloatType))
    if isinstance(src, T.DateType):
        return (datetime.date(1970, 1, 1)
                + datetime.timedelta(days=int(v))).isoformat()
    return str(int(v))


def cast_supported(src: T.DataType, dst: T.DataType) -> bool:
    if src == dst:
        return True
    dec_max = T.DecimalType.MAX_LONG_DIGITS
    if isinstance(src, T.DecimalType) or isinstance(dst, T.DecimalType):
        # decimal64 device tier (GpuCast decimal branches + DecimalUtils)
        if isinstance(src, T.DecimalType) and isinstance(dst, T.DecimalType):
            return (src.precision <= dec_max and dst.precision <= dec_max
                    and abs(src.scale - dst.scale) <= 18)
        if isinstance(dst, T.DecimalType):
            return (dst.precision <= dec_max
                    and isinstance(src, T.IntegralType))
        # decimal -> double/float works at ANY precision (two-limb f64
        # combine, same precision loss as Spark's Decimal.toDouble); the
        # exact integral truncation stays decimal64-only
        if isinstance(dst, (T.DoubleType, T.FloatType)):
            return True
        return (src.precision <= dec_max
                and isinstance(dst, T.IntegralType))
    if isinstance(src, T.StringType):
        # device path: dictionary-transform (host parse of dict entries +
        # device gather); timestamps stay off like the reference default
        return isinstance(dst, (T.IntegralType, T.FloatType, T.DoubleType,
                                T.BooleanType, T.DateType))
    if isinstance(dst, T.StringType):
        # X -> string builds an unbounded output dictionary; CPU fallback
        return False
    if isinstance(src, _SUPPORTED_SIMPLE) and isinstance(dst, _SUPPORTED_SIMPLE):
        # temporal <-> non-temporal numeric casts not yet implemented except
        # the date/timestamp pair handled above.
        temporal = (T.DateType, T.TimestampType)
        s_t, d_t = isinstance(src, temporal), isinstance(dst, temporal)
        if s_t != d_t:
            return False
        return True
    return False


class Cast(UnaryExpression):
    def __init__(self, child: Expression, dtype: T.DataType):
        super().__init__(child)
        self._dtype = dtype

    @property
    def data_type(self):
        return self._dtype

    def with_children(self, children):
        return Cast(children[0], self._dtype)

    def key(self):
        return ("cast", str(self._dtype), self.children[0].key())

    @property
    def device_supported(self):
        return cast_supported(self.child.data_type, self._dtype)

    def _ansi_bad_np(self, c: HostColumn):
        """Rows whose ANSI cast would error (numeric range / NaN)."""
        dst = self._dtype
        if not isinstance(dst, T.IntegralType):
            return None
        if isinstance(c.dtype, (T.FloatType, T.DoubleType)):
            info = np.iinfo(dst.np_dtype)
            with np.errstate(invalid="ignore"):
                return c.validity & (np.isnan(c.data)
                                     | (c.data < float(info.min))
                                     | (c.data > float(info.max)))
        if isinstance(c.dtype, T.IntegralType) and \
                np.dtype(dst.np_dtype).itemsize < c.data.dtype.itemsize:
            info = np.iinfo(dst.np_dtype)
            return c.validity & ((c.data < info.min) | (c.data > info.max))
        return None

    def eval_cpu(self, table: HostTable) -> HostColumn:
        from spark_rapids_tpu.dispatch import ANSI_MODE
        from spark_rapids_tpu.errors import AnsiViolation
        c = self.child.eval_cpu(table)
        if c.dtype == self._dtype:
            return c
        if isinstance(c.dtype, T.DecimalType) or \
                isinstance(self._dtype, T.DecimalType):
            return _cpu_decimal_cast(c, self._dtype)
        if isinstance(c.dtype, T.StringType):
            out = self._cpu_from_string(c)
            if ANSI_MODE.get() and (c.validity & ~out.validity).any():
                raise AnsiViolation(
                    f"invalid input for cast to "
                    f"{self._dtype.simple_string()} "
                    "(spark.sql.ansi.enabled)")
            return out
        if isinstance(self._dtype, T.StringType):
            return self._cpu_to_string(c)
        if ANSI_MODE.get():
            bad = self._ansi_bad_np(c)
            if bad is not None and bad.any():
                raise AnsiViolation(
                    f"cast overflow to {self._dtype.simple_string()} "
                    "(spark.sql.ansi.enabled)")
        data = _cast_data_np(c.data, c.dtype, self._dtype)
        zero = np.zeros((), dtype=self._dtype.np_dtype).item()
        return HostColumn(self._dtype, np.where(c.validity, data, zero).astype(self._dtype.np_dtype),
                          c.validity.copy())

    def _cpu_from_string(self, c: HostColumn) -> HostColumn:
        n = len(c)
        out = np.zeros(n, dtype=self._dtype.np_dtype)
        validity = c.validity.copy()
        for i in range(n):
            if validity[i]:
                v = parse_string_cast(c.data[i], self._dtype)
                if v is None:
                    validity[i] = False
                else:
                    out[i] = v
        return HostColumn(self._dtype, out, validity)

    def _cpu_to_string(self, c: HostColumn) -> HostColumn:
        n = len(c)
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = (format_value_as_string(c.data[i], c.dtype)
                      if c.validity[i] else None)
        return HostColumn(T.STRING, out, c.validity.copy())

    def prep(self, pctx, child_preps):
        if isinstance(self.child.data_type, T.StringType) and \
                not isinstance(self._dtype, T.StringType):
            # dictionary transform: parse each dict entry ONCE on host, the
            # device gathers parsed values/validity by code (O(dict) host
            # work — the string-cast analog of DictStringToValue)
            d = child_preps[0].out_dict
            if d is None:
                d = np.array([], dtype=object)
            vals = np.zeros(max(len(d), 1), dtype=self._dtype.np_dtype)
            ok = np.ones(max(len(d), 1), dtype=np.bool_)
            for i, s in enumerate(d):
                v = parse_string_cast(s, self._dtype)
                if v is None:
                    ok[i] = False
                else:
                    vals[i] = v
            return NodePrep(aux_slots=(pctx.add_aux(vals),
                                       pctx.add_aux(ok)))
        return NodePrep()

    def eval_dev(self, ctx, child_vals, prep):
        (c,) = child_vals
        src, dst = self.child.data_type, self._dtype
        if src == dst:
            return c
        if isinstance(src, T.DecimalType) or isinstance(dst, T.DecimalType):
            return _dev_decimal_cast(c, src, dst)
        if prep.aux_slots:
            vals = ctx.aux[prep.aux_slots[0]]
            ok = ctx.aux[prep.aux_slots[1]]
            codes = jnp.clip(c.data, 0, vals.shape[0] - 1)
            data = vals[codes]
            validity = c.validity & ok[codes]
            if ctx.ansi:
                ctx.ansi_check(
                    f"invalid input for cast to {dst.simple_string()}",
                    c.validity & ~ok[codes])
            return DevVal(jnp.where(validity, data, jnp.zeros_like(data)),
                          validity)
        if ctx.ansi and isinstance(dst, T.IntegralType):
            if isinstance(src, (T.FloatType, T.DoubleType)):
                info = np.iinfo(np.dtype(dst.np_dtype))
                ctx.ansi_check(
                    f"cast overflow to {dst.simple_string()}",
                    c.validity & (jnp.isnan(c.data)
                                  | (c.data < float(info.min))
                                  | (c.data > float(info.max))))
            elif isinstance(src, T.IntegralType) and \
                    np.dtype(dst.np_dtype).itemsize < \
                    np.dtype(src.np_dtype).itemsize:
                info = np.iinfo(np.dtype(dst.np_dtype))
                ctx.ansi_check(
                    f"cast overflow to {dst.simple_string()}",
                    c.validity & ((c.data < info.min)
                                  | (c.data > info.max)))
        data = _cast_data_jnp(c.data, src, dst)
        return DevVal(jnp.where(c.validity, data, jnp.zeros_like(data)), c.validity)

    def __repr__(self):
        return f"cast({self.children[0]!r} as {self._dtype})"


# ---------------------------------------------------------------------------
# decimal casts (GpuCast decimal branches; exact host path at any
# precision, decimal64 device tier)
# ---------------------------------------------------------------------------

def _cpu_decimal_cast(c: HostColumn, dst: T.DataType) -> HostColumn:
    from decimal import Decimal, InvalidOperation

    from spark_rapids_tpu.ops.decimal import (
        _POW10,
        host_store,
        host_unscaled,
        rescale_int,
    )
    src = c.dtype
    n = len(c.data)
    validity = c.validity.copy()
    if isinstance(src, T.DecimalType) and isinstance(dst, T.DecimalType):
        vals = host_unscaled(c)
        out = [0] * n
        bound = _POW10[dst.precision]
        for i in range(n):
            if validity[i]:
                v = rescale_int(int(vals[i]), src.scale, dst.scale)
                if abs(v) >= bound:
                    validity[i] = False
                else:
                    out[i] = v
        return host_store(out, validity, dst)
    if isinstance(dst, T.DecimalType):
        if isinstance(src, T.StringType):
            out = [0] * n
            bound = _POW10[dst.precision]
            for i in range(n):
                if validity[i]:
                    try:
                        d = Decimal(str(c.data[i]).strip())
                        v = int(d.scaleb(dst.scale).to_integral_value(
                            rounding="ROUND_HALF_UP"))
                    except (InvalidOperation, ValueError):
                        validity[i] = False
                        continue
                    if abs(v) >= bound:
                        validity[i] = False
                    else:
                        out[i] = v
            return host_store(out, validity, dst)
        if isinstance(src, (T.FloatType, T.DoubleType)):
            # Spark: BigDecimal.valueOf(double) then HALF_UP to scale
            out = [0] * n
            bound = _POW10[dst.precision]
            for i in range(n):
                if validity[i]:
                    f = float(c.data[i])
                    if not np.isfinite(f):
                        validity[i] = False
                        continue
                    d = Decimal(repr(f))
                    v = int(d.scaleb(dst.scale).to_integral_value(
                        rounding="ROUND_HALF_UP"))
                    if abs(v) >= bound:
                        validity[i] = False
                    else:
                        out[i] = v
            return host_store(out, validity, dst)
        # integral -> decimal
        out = [0] * n
        bound = _POW10[dst.precision]
        scale = _POW10[dst.scale]
        for i in range(n):
            if validity[i]:
                v = int(c.data[i]) * scale
                if abs(v) >= bound:
                    validity[i] = False
                else:
                    out[i] = v
        return host_store(out, validity, dst)
    # decimal -> other
    vals = host_unscaled(c)
    scale = _POW10[src.scale]
    if isinstance(dst, (T.DoubleType, T.FloatType)):
        data = np.zeros(n, dtype=dst.np_dtype)
        for i in range(n):
            if validity[i]:
                data[i] = int(vals[i]) / scale
        return HostColumn(dst, data, validity)
    if isinstance(dst, T.StringType):
        out = np.empty(n, dtype=object)
        for i in range(n):
            if not validity[i]:
                out[i] = None
                continue
            v = int(vals[i])
            if src.scale == 0:
                out[i] = str(v)
            else:
                sign = "-" if v < 0 else ""
                a = abs(v)
                out[i] = f"{sign}{a // scale}." \
                         f"{a % scale:0{src.scale}d}"
        return HostColumn(T.STRING, out, validity)
    if isinstance(dst, T.IntegralType):
        data = np.zeros(n, dtype=dst.np_dtype)
        info = np.iinfo(dst.np_dtype)
        for i in range(n):
            if validity[i]:
                v = int(vals[i])
                q = abs(v) // scale  # truncate toward zero
                q = -q if v < 0 else q
                if not (info.min <= q <= info.max):
                    validity[i] = False  # overflow -> null (non-ANSI)
                else:
                    data[i] = q
        return HostColumn(dst, data, validity)
    raise ColumnarProcessingError(
        f"cast {src.simple_string()} -> {dst.simple_string()} not supported")


def _dev_decimal_cast(c, src: T.DataType, dst: T.DataType):
    from spark_rapids_tpu.ops.decimal import (
        _POW10,
        dev_rescale_checked,
        i128_abs_fits_pow10,
        i128_fits_int64,
        i128_mul_pow10,
        i128_to_i64,
    )
    if isinstance(src, T.DecimalType) and isinstance(dst, T.DecimalType):
        return dev_rescale_checked(c.data, c.validity, src.scale,
                                   dst.scale, dst.precision)
    if isinstance(dst, T.DecimalType):
        # integral -> decimal: value * 10^s, bound check
        v = c.data.astype(jnp.int64)
        hi = jnp.where(v < 0, jnp.int64(-1), jnp.int64(0))
        hi, lo = i128_mul_pow10(hi, v.astype(jnp.uint64), dst.scale)
        validity = c.validity & i128_fits_int64(hi, lo) & \
            i128_abs_fits_pow10(hi, lo, dst.precision)
        return DevVal(jnp.where(validity, i128_to_i64(hi, lo),
                                jnp.int64(0)), validity)
    # decimal -> double/float/integral
    scale = _POW10[src.scale]
    if isinstance(dst, (T.DoubleType, T.FloatType)):
        if T.is_dec128(src):
            # (n, 2) two-limb storage: [:,0] signed hi, [:,1] low 64 bits
            # reinterpreted int64 — combine in f64 (Decimal.toDouble-class
            # precision loss; the streaming decimal-average merge casts
            # its dec128 partial sums through here)
            hi = c.data[:, 0].astype(jnp.float64)
            lo_i = c.data[:, 1]
            lo = lo_i.astype(jnp.float64) + jnp.where(
                lo_i < 0, jnp.float64(2.0 ** 64), jnp.float64(0.0))
            data = (hi * jnp.float64(2.0 ** 64) + lo) / jnp.float64(scale)
        else:
            data = c.data.astype(jnp.float64) / jnp.float64(scale)
        return DevVal(jnp.where(c.validity, data.astype(dst.np_dtype),
                                jnp.zeros((), dst.np_dtype)), c.validity)
    # integral: truncate toward zero, overflow -> null
    mag = jnp.abs(c.data) // jnp.int64(scale)
    q = jnp.where(c.data < 0, -mag, mag)
    info = np.iinfo(dst.np_dtype)
    validity = c.validity & (q >= info.min) & (q <= info.max)
    return DevVal(jnp.where(validity, q.astype(dst.np_dtype),
                            jnp.zeros((), dst.np_dtype)), validity)
