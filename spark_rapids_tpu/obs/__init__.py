"""Observability subsystem.

Three layers over the same execution machinery (reference: GpuMetric +
NvtxWithMetrics + profiler.scala + the spark-rapids-tools event-log
analyzer — SURVEY.md §5):

* :mod:`spark_rapids_tpu.obs.metrics` — the unified MetricRegistry:
  typed metric specs (timing/count/bytes at ESSENTIAL/MODERATE/DEBUG
  levels), the per-operator :class:`MetricSet` every exec carries, and
  process-wide scopes for the subsystems that are not operators
  (spill, recovery, shuffle).
* :mod:`spark_rapids_tpu.obs.spans` — a thread-aware host-side span
  tracer (enter/exit wall times with query/op attribution) exportable
  as Chrome trace-event JSON, plus the per-query exec-boundary
  instrumentation that feeds both spans and the ESSENTIAL
  opTime/numOutputRows metrics.
* :mod:`spark_rapids_tpu.obs.events` — the per-query structured event
  log (JSONL) that `python -m spark_rapids_tpu.tools` analyzes
  offline.
* :mod:`spark_rapids_tpu.obs.telemetry` — the BETWEEN-queries layer:
  a passive background telemetry ring (per-scope metric deltas +
  topology at a conf-driven interval) and the flight recorder that
  dumps bounded incident bundles on every ladder action, quarantine
  strike, and kernel demotion (`tools incident` renders them).
"""

from spark_rapids_tpu.obs.metrics import (  # noqa: F401
    MetricSet,
    metric_scope,
    register_metric,
    set_metrics_level,
)
