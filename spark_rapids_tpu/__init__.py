"""spark_rapids_tpu — a TPU-native SQL/columnar execution engine.

A from-scratch framework with the capabilities of the RAPIDS Accelerator for
Apache Spark (reference: /root/reference, NVIDIA spark-rapids): a plan-rewrite
engine that converts SQL physical plans into columnar operators executing on
TPUs via JAX/XLA (Pallas for custom kernels), with per-operator CPU fallback,
bit-for-bit Spark-compatible semantics, an HBM buffer catalog with host/disk
spill and OOM split-and-retry, TPU-aware shuffle (host path + ICI collectives),
and accelerated Parquet/ORC/CSV/JSON/Avro IO.

Architecture mirrors the reference's proven shape (see SURVEY.md):
  plan -> meta/tag/convert (overrides/) -> columnar execs (execs/)
       -> runtime (semaphore, spill catalog, retry) -> shuffle (parallel/)
but the substrate is XLA: expression trees are fused into single jitted
computations over statically-bucketed device columns, strings ride an
order-preserving dictionary encoding so the device only touches fixed-width
data, and distributed exchange uses jax.sharding collectives over ICI/DCN.
"""

import os as _os

import jax

# Spark semantics are 64-bit (LongType, TimestampType micros, DoubleType).
# Bit-for-bit parity requires x64 mode; TPU emulates i64/f64 (slower but
# exact), and opt-in 32-bit fast paths can be layered on later.
jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: TPU backend compiles of sort-bearing kernels
# run ~50s each; caching them on disk amortizes across processes (the
# reference's CUDA kernels are precompiled — this is the XLA counterpart,
# SURVEY.md §7 "XLA compile-time amortization").
def _configured_platform() -> str:
    """The PRIMARY jax platform from explicit config ('' when the host
    relies on JAX auto-detection). The axon TPU config is 'axon,cpu',
    so only the first entry counts."""
    cfg = getattr(jax.config, "jax_platforms", None) or \
        _os.environ.get("JAX_PLATFORMS", "")
    return cfg.split(",")[0].strip().lower()


_compile_cache_enabled = False


def _enable_persistent_cache() -> None:
    global _compile_cache_enabled
    try:
        _cache_dir = _os.environ.get(
            "SPARK_RAPIDS_TPU_CACHE",
            _os.path.join(_os.path.dirname(__file__), "..", ".jax_cache"))
        # XLA:CPU AOT artifacts are compiled for the BUILD host's exact
        # CPU features and SEGFAULT when loaded on a host missing one
        # (jax's cache key does not cover host CPU flags) — namespace the
        # cache by a machine fingerprint so entries never cross hosts
        import hashlib as _hashlib
        import platform as _platform
        _fp_src = _platform.machine() + ":" + _platform.processor()
        try:
            with open("/proc/cpuinfo") as _f:
                for _line in _f:
                    if _line.startswith("flags"):
                        _fp_src += ":" + _line.strip()
                        break
        except OSError:
            pass
        _fp = _hashlib.sha256(_fp_src.encode()).hexdigest()[:12]
        jax.config.update("jax_compilation_cache_dir",
                          _os.path.join(_os.path.abspath(_cache_dir), _fp))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _compile_cache_enabled = True
    except Exception:  # cache is best-effort; older jax may lack the knobs
        pass


def ensure_compile_cache() -> bool:
    """Enable the persistent compile cache once the effective backend is
    known to be non-CPU. Import time only trusts an EXPLICIT platform
    config; hosts relying on JAX auto-detection (unset JAX_PLATFORMS on
    a stock TPU VM) get the cache here, called on runtime init, via
    jax.default_backend() — which initializes the backend, so it cannot
    run at import (ADVICE r5). CPU stays uncached: XLA:CPU compiles are
    fast AND this jax's CPU AOT (de)serialization can abort/segfault on
    some programs and on feature-mismatched hosts — both observed in
    this repo's test runs. Returns whether the cache is enabled."""
    if _compile_cache_enabled:
        return True
    if _configured_platform() == "cpu":
        return False
    try:
        backend = jax.default_backend()
    except Exception:
        return False
    if backend == "cpu":
        return False
    _enable_persistent_cache()
    return _compile_cache_enabled


if _configured_platform() not in ("", "cpu"):
    # explicit non-cpu primary: safe to enable before backend init
    _enable_persistent_cache()

__version__ = "0.1.0"

from spark_rapids_tpu.conf import RapidsConf  # noqa: E402,F401
from spark_rapids_tpu import types  # noqa: E402,F401


def __getattr__(name):
    # lazy heavy imports so `import spark_rapids_tpu` stays light
    import importlib
    if name == "TpuSession":
        return importlib.import_module("spark_rapids_tpu.session").TpuSession
    if name == "functions":
        return importlib.import_module("spark_rapids_tpu.functions")
    raise AttributeError(name)
