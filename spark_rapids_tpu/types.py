"""Spark SQL type system for the TPU engine.

Mirrors the reference's type support surface (TypeChecks.scala / TypeSig --
see SURVEY.md §2.2): every operator declares which of these types it supports,
and unsupported combinations fall back to CPU with a reason.

Device mapping (how each Spark type lives in HBM as an XLA buffer):
  BooleanType            -> bool_
  ByteType               -> int8
  ShortType              -> int16
  IntegerType            -> int32
  LongType               -> int64
  FloatType              -> float32
  DoubleType             -> float64
  DateType               -> int32   (days since epoch, Spark-compatible)
  TimestampType          -> int64   (microseconds since epoch, UTC)
  StringType             -> int32 dictionary codes (order-preserving, per
                            batch) + host-side dictionary; see columnar/
  DecimalType(p<=18, s)  -> int64 unscaled value
  NullType               -> int8 (all-null)
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass

import numpy as np


class DataType:
    """Base of the Spark SQL type hierarchy."""

    #: numpy dtype used for the device representation of this type.
    np_dtype: np.dtype = None  # type: ignore[assignment]

    def simple_string(self) -> str:
        return type(self).__name__.replace("Type", "").lower()

    def __repr__(self) -> str:
        return self.simple_string()

    def __eq__(self, other) -> bool:
        return type(self) is type(other)

    def __hash__(self) -> int:
        return hash(type(self).__name__)


class NumericType(DataType):
    pass


class IntegralType(NumericType):
    pass


class FractionalType(NumericType):
    pass


class BooleanType(DataType):
    np_dtype = np.dtype(np.bool_)


class ByteType(IntegralType):
    np_dtype = np.dtype(np.int8)

    def simple_string(self):
        return "tinyint"


class ShortType(IntegralType):
    np_dtype = np.dtype(np.int16)

    def simple_string(self):
        return "smallint"


class IntegerType(IntegralType):
    np_dtype = np.dtype(np.int32)

    def simple_string(self):
        return "int"


class LongType(IntegralType):
    np_dtype = np.dtype(np.int64)

    def simple_string(self):
        return "bigint"


class FloatType(FractionalType):
    np_dtype = np.dtype(np.float32)


class DoubleType(FractionalType):
    np_dtype = np.dtype(np.float64)


class StringType(DataType):
    # device representation is int32 dictionary codes; the logical type has
    # no fixed-width numpy dtype of its own.
    np_dtype = np.dtype(object)


class DateType(DataType):
    """Days since 1970-01-01 as int32 (Spark internal representation)."""

    np_dtype = np.dtype(np.int32)


class TimestampType(DataType):
    """Microseconds since epoch (UTC) as int64 (Spark internal repr)."""

    np_dtype = np.dtype(np.int64)


class NullType(DataType):
    np_dtype = np.dtype(np.int8)

    def simple_string(self):
        return "void"


@dataclass(frozen=True)
class DecimalType(FractionalType):
    """Decimal with precision/scale. p<=18 fits an int64 unscaled value.

    The reference uses 128-bit decimals via JNI DecimalUtils for p>18
    (SURVEY.md §2.9); we represent p<=18 natively and 19..38 as a
    (hi int64, lo uint64-as-int64) pair on device (phase: later).
    """

    precision: int = 10
    scale: int = 0

    MAX_PRECISION = 38
    MAX_LONG_DIGITS = 18

    def __post_init__(self):
        if not (0 < self.precision <= self.MAX_PRECISION):
            raise ValueError(f"bad decimal precision {self.precision}")
        if not (0 <= self.scale <= self.precision):
            raise ValueError(f"bad decimal scale {self.scale}")

    @property
    def np_dtype(self):  # type: ignore[override]
        return np.dtype(np.int64)

    def simple_string(self):
        return f"decimal({self.precision},{self.scale})"

    def __eq__(self, other):
        return (
            isinstance(other, DecimalType)
            and other.precision == self.precision
            and other.scale == self.scale
        )

    def __hash__(self):
        return hash(("decimal", self.precision, self.scale))


@dataclass(frozen=True)
class ArrayType(DataType):
    element_type: DataType = None  # type: ignore[assignment]
    contains_null: bool = True

    def simple_string(self):
        return f"array<{self.element_type.simple_string()}>"

    def __eq__(self, other):
        return (
            isinstance(other, ArrayType)
            and other.element_type == self.element_type
        )

    def __hash__(self):
        return hash(("array", self.element_type))


@dataclass(frozen=True)
class StructField:
    name: str
    data_type: DataType
    nullable: bool = True


class StructType(DataType):
    def __init__(self, fields):
        # accept (name, dtype) pairs as a convenience — pyspark users write
        # StructType([("a", LongType()), ...]) shapes constantly
        self.fields = tuple(
            f if isinstance(f, StructField) else StructField(*f)
            for f in fields)

    def simple_string(self):
        inner = ",".join(
            f"{f.name}:{f.data_type.simple_string()}" for f in self.fields
        )
        return f"struct<{inner}>"

    def __eq__(self, other):
        return isinstance(other, StructType) and other.fields == self.fields

    def __hash__(self):
        return hash(("struct", self.fields))


@dataclass(frozen=True)
class MapType(DataType):
    key_type: DataType = None  # type: ignore[assignment]
    value_type: DataType = None  # type: ignore[assignment]
    value_contains_null: bool = True

    def simple_string(self):
        return (
            f"map<{self.key_type.simple_string()},"
            f"{self.value_type.simple_string()}>"
        )

    def __eq__(self, other):
        return (
            isinstance(other, MapType)
            and other.key_type == self.key_type
            and other.value_type == self.value_type
        )

    def __hash__(self):
        return hash(("map", self.key_type, self.value_type))


# Singletons, Spark-style.
BOOLEAN = BooleanType()
BYTE = ByteType()
SHORT = ShortType()
INT = IntegerType()
LONG = LongType()
FLOAT = FloatType()
DOUBLE = DoubleType()
STRING = StringType()
DATE = DateType()
TIMESTAMP = TimestampType()
NULL = NullType()

ALL_INTEGRAL = (BYTE, SHORT, INT, LONG)
ALL_NUMERIC = ALL_INTEGRAL + (FLOAT, DOUBLE)
ALL_ORDERABLE = ALL_NUMERIC + (BOOLEAN, STRING, DATE, TIMESTAMP)

_NUMPY_TO_SPARK = {
    np.dtype(np.bool_): BOOLEAN,
    np.dtype(np.int8): BYTE,
    np.dtype(np.int16): SHORT,
    np.dtype(np.int32): INT,
    np.dtype(np.int64): LONG,
    np.dtype(np.float32): FLOAT,
    np.dtype(np.float64): DOUBLE,
}


def from_numpy(dtype) -> DataType:
    dt = _NUMPY_TO_SPARK.get(np.dtype(dtype))
    if dt is None:
        raise TypeError(f"no Spark type for numpy dtype {dtype}")
    return dt


def is_dec128(dt: DataType) -> bool:
    """p>18 decimals: two-limb (hi i64, lo u64-bits-in-i64) device storage
    as a (capacity, 2) int64 array (the reference's DECIMAL128 tier —
    TypeChecks.scala:613)."""
    return (isinstance(dt, DecimalType)
            and dt.precision > DecimalType.MAX_LONG_DIGITS)


def is_string(dt: DataType) -> bool:
    return isinstance(dt, StringType)


def is_integral(dt: DataType) -> bool:
    return isinstance(dt, IntegralType)


def is_numeric(dt: DataType) -> bool:
    return isinstance(dt, NumericType)


def is_floating(dt: DataType) -> bool:
    return isinstance(dt, (FloatType, DoubleType))


def is_nested(dt: DataType) -> bool:
    return isinstance(dt, (ArrayType, StructType, MapType))


_NAME_TO_TYPE = None


def parse_type(name: str) -> DataType:
    """Spark simple-string type names -> DataType (cast('bigint') etc.)."""
    global _NAME_TO_TYPE
    if _NAME_TO_TYPE is None:
        _NAME_TO_TYPE = {
            "boolean": BOOLEAN, "bool": BOOLEAN,
            "tinyint": BYTE, "byte": BYTE,
            "smallint": SHORT, "short": SHORT,
            "int": INT, "integer": INT,
            "bigint": LONG, "long": LONG,
            "float": FLOAT, "real": FLOAT,
            "double": DOUBLE,
            "string": STRING,
            "date": DATE,
            "timestamp": TIMESTAMP,
        }
    key = name.strip().lower()
    if key in _NAME_TO_TYPE:
        return _NAME_TO_TYPE[key]
    if key.startswith("decimal"):
        import re
        m = re.match(r"decimal\((\d+),\s*(\d+)\)", key)
        if m:
            return DecimalType(int(m.group(1)), int(m.group(2)))
        return DecimalType(10, 0)
    raise TypeError(f"cannot parse type name {name!r}")


def python_to_spark_type(value) -> DataType:
    """Infer the Spark type of a Python literal (Spark Literal.apply analog)."""
    if value is None:
        return NULL
    if isinstance(value, bool):
        return BOOLEAN
    if isinstance(value, int):
        return INT if np.iinfo(np.int32).min <= value <= np.iinfo(np.int32).max else LONG
    if isinstance(value, float):
        return DOUBLE
    if isinstance(value, str):
        return STRING
    if isinstance(value, _dt.datetime):
        return TIMESTAMP
    if isinstance(value, _dt.date):
        return DATE
    if isinstance(value, np.generic):
        return from_numpy(value.dtype)
    raise TypeError(f"cannot infer Spark type for {value!r}")


# Numeric widening lattice for implicit binary-op promotion (Spark
# TypeCoercion findTightestCommonType subset).
_PROMOTE_ORDER = {BYTE: 0, SHORT: 1, INT: 2, LONG: 3, FLOAT: 4, DOUBLE: 5}


def promote(a: DataType, b: DataType) -> DataType:
    if a == b:
        return a
    if isinstance(a, DecimalType) or isinstance(b, DecimalType):
        return _promote_decimal(a, b)
    if a in _PROMOTE_ORDER and b in _PROMOTE_ORDER:
        return a if _PROMOTE_ORDER[a] >= _PROMOTE_ORDER[b] else b
    if isinstance(a, NullType):
        return b
    if isinstance(b, NullType):
        return a
    raise TypeError(f"cannot promote {a} with {b}")


_INT_DECIMAL = {ByteType: (3, 0), ShortType: (5, 0), IntegerType: (10, 0),
                LongType: (20, 0)}


def _promote_decimal(a: DataType, b: DataType) -> DataType:
    """Spark decimal coercion: decimal+decimal widens to cover both;
    decimal+integral widens over the integral's decimal form;
    decimal+float/double promotes to double."""
    if isinstance(a, (FloatType, DoubleType)) or \
            isinstance(b, (FloatType, DoubleType)):
        return DOUBLE
    def as_dec(t):
        if isinstance(t, DecimalType):
            return t
        ps = _INT_DECIMAL.get(type(t))
        return DecimalType(*ps) if ps else None
    da, db = as_dec(a), as_dec(b)
    if da is None or db is None:
        raise TypeError(f"cannot promote {a} with {b}")
    scale = max(da.scale, db.scale)
    int_digits = max(da.precision - da.scale, db.precision - db.scale)
    p = min(int_digits + scale, DecimalType.MAX_PRECISION)
    return DecimalType(p, scale)
