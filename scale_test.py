"""Scale Test harness (reference: integration_tests/ScaleTest.md +
datagen scaletest — SURVEY.md §2.11/§6): a parameterized join/agg/window
query set over generated tables, emitting a JSON timing report.

Usage: python scale_test.py [--sf 0.1] [--queries q1,q5] [--cpu-baseline]
       python scale_test.py --chaos [--seed 7]
       python scale_test.py --mesh 8 [--chaos] [--seed 7]
       python scale_test.py --streaming [--chaos] [--seed 7]

``--chaos`` runs the corpus twice — fault-free, then under a
randomized-but-SEEDED fault schedule (fetch errors, transport
disconnects, corrupt frames, kernel crashes injected through
``spark.rapids.test.faults`` — runtime/faults.py) — asserting
bit-identical results and bounded recovery work, with per-query
retry/recompute/demotion counts in the JSON report. It also runs the
WRITE corpus (run_write_chaos): seeded kill-mid-write scenarios
asserting the exactly-once transactional-write contract — no torn
file ever reader-visible, rerun-after-kill bit-identical, Delta
concurrent commits converge through the rebase-and-retry loop, and
vacuum reports zero orphans afterwards.

``--streaming`` runs the micro-batch streaming + materialized-view
harness (run_streaming, STREAM_r01.json): rate, file-watch and Delta
CDF-tail streams over corpus-derived tables into exactly-once Delta
sinks, plus two incrementally-maintained MVs, asserting sink row sets
bit-identical to a fault-free twin and every MV read bit-identical to a
from-scratch recompute at the same epoch; with ``--chaos`` each stream
is killed once mid-micro-batch (after its offsets are durably logged,
before the commit) under the seeded streaming fault schedule and must
resume exactly-once from its checkpoint.

``--mesh N --chaos`` composes both modes (run_mesh_chaos): the corpus
runs MESH-NATIVE under a seeded mesh-fault schedule firing every
``mesh.*`` point — shard-put crashes, checksummed-fetch corruption,
partial device losses walking the degradation ladder down to a mesh
shrink — asserting bit-identity to fault-free single-chip, bounded
recovery counters, and the mesh back at full strength at the end
(MULTICHIP_r07.json). Unsupported flag combinations fail fast
(validate_flags) instead of silently ignoring a mode."""

from __future__ import annotations

import argparse
import json
import time


def build_queries(s, tables, paths=None):
    """q1-q22: the TPC-H-flavored golden corpus (scan/filter/agg/join/
    window mix; the lint plan verifier and test_lint run over every one
    of these in both DSL and SQL form). With ``paths`` (the --hosts
    harness), each table comes from its parquet directory through the
    file-scan path instead of an in-memory HostTable — same queries,
    but scans can partition their source files BY HOST."""
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.ops.expr import col, lit
    from spark_rapids_tpu.plan import from_host_table

    if paths is not None:
        cust = lambda: s.read_parquet(paths["customer"])   # noqa: E731
        orders = lambda: s.read_parquet(paths["orders"])   # noqa: E731
        li = lambda: s.read_parquet(paths["lineitem"])     # noqa: E731
    else:
        cust = lambda: from_host_table(tables["customer"], s)  # noqa: E731
        orders = lambda: from_host_table(tables["orders"], s)  # noqa: E731
        li = lambda: from_host_table(tables["lineitem"], s)    # noqa: E731

    def q1():  # pricing summary (TPC-H q1 shape)
        import datetime as _dt
        cutoff = _dt.date(1970, 1, 1) + _dt.timedelta(days=10500)
        return (li().filter(col("l_shipdate") <= lit(cutoff))
                .group_by("l_returnflag", "l_linestatus")
                .agg(F.sum("l_quantity").alias("sum_qty"),
                     F.sum("l_extendedprice").alias("sum_base"),
                     F.avg("l_discount").alias("avg_disc"),
                     F.count("l_quantity").alias("cnt")))

    def q2():  # filter + project arithmetic
        return (li().filter((col("l_discount") > lit(0.05))
                            & (col("l_quantity") < lit(25)))
                .select((col("l_extendedprice") * col("l_discount"))
                        .alias("revenue"))
                .agg(F.sum("revenue").alias("total")))

    def q3():  # join orders->lineitem + agg
        oj = orders().select("o_orderkey", "o_custkey", "o_orderdate")
        j = li().join(oj.with_column("l_orderkey", col("o_orderkey")),
                      on=["l_orderkey"], how="inner")
        return (j.group_by("o_custkey")
                .agg(F.sum("l_extendedprice").alias("spend"),
                     F.count("l_quantity").alias("items")))

    def q4():  # two-level join: customer -> orders -> lineitem
        oj = orders().select("o_orderkey", "o_custkey")
        cj = cust().select("c_custkey", "c_nationkey")
        j1 = (li().select("l_orderkey", "l_extendedprice")
              .join(oj.with_column("l_orderkey", col("o_orderkey")),
                    on=["l_orderkey"], how="inner"))
        j2 = j1.with_column("c_custkey", col("o_custkey")).join(
            cj, on=["c_custkey"], how="inner")
        return (j2.group_by("c_nationkey")
                .agg(F.sum("l_extendedprice").alias("rev")))

    def q5():  # sort + limit (TakeOrderedAndProject)
        return (orders().sort("o_totalprice", ascending=False).limit(100))

    def q6():  # window: rank orders per customer by price
        from spark_rapids_tpu.functions import row_number
        from spark_rapids_tpu.ops.window import Window as W
        return orders().with_windows(
            rn=row_number().over(
                W.partition_by("o_custkey").order_by("o_totalprice")))\
            .filter(col("rn") <= lit(3))

    def q7():  # repartition + agg (shuffle exercise)
        return (li().repartition(8, "l_returnflag")
                .group_by("l_returnflag")
                .agg(F.count("l_quantity").alias("c"),
                     F.sum("l_quantity").alias("s")))

    def q8():  # distinct-ish: group by high-cardinality key
        return (orders().group_by("o_custkey")
                .agg(F.max("o_totalprice").alias("m"))
                .agg(F.count("m").alias("n_custs")))

    def q9():  # TPC-H q5-like: 2-level join + filters + group + topk
        import datetime as _dt
        cut = _dt.date(1970, 1, 1) + _dt.timedelta(days=9000)
        cj = cust().select("c_custkey", "c_nationkey")
        oj = (orders().filter(col("o_orderdate") >= lit(cut))
              .select("o_orderkey", "o_custkey"))
        j1 = (li().select("l_orderkey", "l_extendedprice", "l_discount")
              .join(oj.with_column("l_orderkey", col("o_orderkey")),
                    on=["l_orderkey"], how="inner"))
        j2 = j1.with_column("c_custkey", col("o_custkey")).join(
            cj, on=["c_custkey"], how="inner")
        return (j2.select(col("c_nationkey"),
                          (col("l_extendedprice")
                           * (lit(1.0) - col("l_discount"))).alias("rev"))
                .group_by("c_nationkey")
                .agg(F.sum("rev").alias("revenue"))
                .sort("revenue", ascending=False).limit(10))

    def q10():  # TPC-H q17-like: join against an aggregated subquery
        avg_q = (li().group_by("l_orderkey")
                 .agg(F.avg("l_quantity").alias("avg_qty")))
        j = li().select("l_orderkey", "l_quantity", "l_extendedprice")\
            .join(avg_q, on=["l_orderkey"], how="inner")
        return (j.filter(col("l_quantity").cast("double")
                         < lit(0.6) * col("avg_qty"))
                .agg(F.sum("l_extendedprice").alias("total")))

    def q11():  # TPC-H q11-like: per-nation balance totals over a floor
        agged = (cust().group_by("c_nationkey")
                 .agg(F.sum("c_acctbal").alias("total_bal"),
                      F.count("c_custkey").alias("n")))
        return (agged.filter(col("n") > lit(5))
                .sort("total_bal", ascending=False))

    def q12():  # TPC-H q12-like: date-window join + per-flag counts
        import datetime as _dt
        lo = _dt.date(1970, 1, 1) + _dt.timedelta(days=9000)
        hi = _dt.date(1970, 1, 1) + _dt.timedelta(days=10000)
        lj = (li().filter((col("l_shipdate") >= lit(lo))
                          & (col("l_shipdate") < lit(hi)))
              .select("l_orderkey", "l_returnflag"))
        oj = orders().select("o_orderkey", "o_totalprice")
        j = lj.join(oj.with_column("l_orderkey", col("o_orderkey")),
                    on=["l_orderkey"], how="inner")
        return (j.group_by("l_returnflag")
                .agg(F.count("l_orderkey").alias("n"),
                     F.avg("o_totalprice").alias("avg_price")))

    def q13():  # TPC-H q13-like: customer order-count distribution
        per_cust = (orders().group_by("o_custkey")
                    .agg(F.count("o_orderkey").alias("c_orders")))
        return (per_cust.group_by("c_orders")
                .agg(F.count("o_custkey").alias("n_custs"))
                .sort("c_orders"))

    def q14():  # TPC-H q14-like: windowed revenue ratio
        import datetime as _dt
        lo = _dt.date(1970, 1, 1) + _dt.timedelta(days=9500)
        hi = _dt.date(1970, 1, 1) + _dt.timedelta(days=9700)
        f = (li().filter((col("l_shipdate") >= lit(lo))
                         & (col("l_shipdate") < lit(hi)))
             .select((col("l_extendedprice")
                      * (lit(1.0) - col("l_discount"))).alias("rev")))
        agged = f.agg(F.sum("rev").alias("total_rev"),
                      F.count("rev").alias("n"))
        return agged.select((col("total_rev") / col("n")).alias("avg_rev"),
                            col("total_rev"))

    def q15():  # TPC-H q15-like: top revenue customers
        oj = orders().select("o_orderkey", "o_custkey")
        j = (li().select("l_orderkey", "l_extendedprice", "l_discount")
             .join(oj.with_column("l_orderkey", col("o_orderkey")),
                   on=["l_orderkey"], how="inner"))
        return (j.select(col("o_custkey"),
                         (col("l_extendedprice")
                          * (lit(1.0) - col("l_discount"))).alias("rev"))
                .group_by("o_custkey").agg(F.sum("rev").alias("revenue"))
                .sort("revenue", ascending=False).limit(5))

    def q16():  # TPC-H q16-like: active customers per nation
        oc = (orders().select("o_custkey").group_by("o_custkey")
              .agg(F.count("o_custkey").alias("x")))
        j = oc.with_column("c_custkey", col("o_custkey")).join(
            cust().select("c_custkey", "c_nationkey"),
            on=["c_custkey"], how="inner")
        return (j.group_by("c_nationkey")
                .agg(F.count("c_custkey").alias("active_custs"))
                .sort("c_nationkey"))

    def q17():  # TPC-H q17-like: below-average-quantity revenue
        avg_q = (li().group_by("l_orderkey")
                 .agg(F.avg("l_quantity").alias("aq")))
        j = (li().select("l_orderkey", "l_quantity", "l_extendedprice")
             .join(avg_q, on=["l_orderkey"], how="inner"))
        return (j.filter(col("l_quantity").cast("double")
                         < lit(0.5) * col("aq"))
                .agg(F.sum("l_extendedprice").alias("s"))
                .select((col("s") / lit(7.0)).alias("avg_yearly")))

    def q18():  # TPC-H q18-like: large-volume orders
        big = (li().group_by("l_orderkey")
               .agg(F.sum("l_quantity").alias("sum_qty"))
               .filter(col("sum_qty") > lit(150)))
        j = big.with_column("o_orderkey", col("l_orderkey")).join(
            orders().select("o_orderkey", "o_custkey", "o_totalprice"),
            on=["o_orderkey"], how="inner")
        return (j.select("l_orderkey", "sum_qty", "o_custkey",
                         "o_totalprice")
                .sort("o_totalprice", ascending=False).limit(20))

    def q19():  # TPC-H q19-like: disjunctive predicate revenue
        f = li().filter(
            ((col("l_quantity") >= lit(1)) & (col("l_quantity") <= lit(11))
             & (col("l_discount") > lit(0.02)))
            | ((col("l_quantity") >= lit(10))
               & (col("l_quantity") <= lit(20))
               & (col("l_discount") < lit(0.06)))
            | (col("l_returnflag") == lit("R00000001")))
        return (f.select((col("l_extendedprice")
                          * (lit(1.0) - col("l_discount"))).alias("rev"))
                .agg(F.sum("rev").alias("revenue")))

    def q20():  # TPC-H q20-like: customers with big orders
        per = (orders().filter(col("o_totalprice") > lit(400000.0))
               .select("o_custkey").group_by("o_custkey")
               .agg(F.count("o_custkey").alias("nbig")))
        j = per.with_column("c_custkey", col("o_custkey")).join(
            cust().select("c_custkey", "c_name", "c_acctbal"),
            on=["c_custkey"], how="inner")
        return (j.select("c_custkey", "nbig", "c_name", "c_acctbal")
                .sort("nbig", ascending=False).limit(10))

    def q21():  # TPC-H q21-like: per-nation top accounts via window rank
        from spark_rapids_tpu.functions import row_number
        from spark_rapids_tpu.ops.window import Window as W
        return (cust().with_windows(
            rn=row_number().over(
                W.partition_by("c_nationkey").order_by("c_custkey")))
            .filter(col("rn") <= lit(2))
            .select("c_nationkey", "c_custkey", "rn"))

    def q22():  # TPC-H q22-like: accounts above the global average
        avg_t = (cust().select(col("c_acctbal"))
                 .agg(F.avg("c_acctbal").alias("ab"))
                 .with_column("k", lit(1)))
        c = (cust().select("c_custkey", "c_nationkey", "c_acctbal")
             .with_column("k", lit(1)))
        j = c.join(avg_t, on=["k"], how="inner")
        return (j.filter(col("c_acctbal").cast("double") > col("ab"))
                .group_by("c_nationkey")
                .agg(F.count("c_custkey").alias("numcust"),
                     F.sum("c_acctbal").alias("totacctbal"))
                .sort("c_nationkey"))

    return {"q1": q1, "q2": q2, "q3": q3, "q4": q4, "q5": q5,
            "q6": q6, "q7": q7, "q8": q8, "q9": q9, "q10": q10,
            "q11": q11, "q12": q12, "q13": q13, "q14": q14, "q15": q15,
            "q16": q16, "q17": q17, "q18": q18, "q19": q19, "q20": q20,
            "q21": q21, "q22": q22}


def sql_texts():
    """q1-q22 re-expressed as SQL text. Each query is written so the
    analyzer lowers it onto the SAME plan shape as its build_queries DSL
    form (nested selects mirror select/with_column chains; USING joins
    mirror on=[key] joins) — test_sql_frontend.py asserts result AND
    device-dispatch-count equality between the two forms."""
    import datetime as _dt

    def _iso(days):
        return (_dt.date(1970, 1, 1) + _dt.timedelta(days=days)).isoformat()

    cutoff = _iso(10500)
    cut9 = _iso(9000)
    return {
        "q1": f"""
            SELECT l_returnflag, l_linestatus,
                   SUM(l_quantity) AS sum_qty,
                   SUM(l_extendedprice) AS sum_base,
                   AVG(l_discount) AS avg_disc,
                   COUNT(l_quantity) AS cnt
            FROM lineitem
            WHERE l_shipdate <= DATE '{cutoff}'
            GROUP BY l_returnflag, l_linestatus""",
        "q2": """
            SELECT SUM(revenue) AS total FROM (
                SELECT l_extendedprice * l_discount AS revenue
                FROM lineitem
                WHERE l_discount > 0.05 AND l_quantity < 25)""",
        "q3": """
            SELECT o_custkey, SUM(l_extendedprice) AS spend,
                   COUNT(l_quantity) AS items
            FROM lineitem
            JOIN (SELECT o_orderkey, o_custkey, o_orderdate,
                         o_orderkey AS l_orderkey
                  FROM (SELECT o_orderkey, o_custkey, o_orderdate
                        FROM orders))
              USING (l_orderkey)
            GROUP BY o_custkey""",
        "q4": """
            SELECT c_nationkey, SUM(l_extendedprice) AS rev
            FROM (SELECT *, o_custkey AS c_custkey
                  FROM (SELECT l_orderkey, l_extendedprice FROM lineitem)
                  JOIN (SELECT o_orderkey, o_custkey,
                               o_orderkey AS l_orderkey
                        FROM (SELECT o_orderkey, o_custkey FROM orders))
                    USING (l_orderkey))
            JOIN (SELECT c_custkey, c_nationkey FROM customer)
              USING (c_custkey)
            GROUP BY c_nationkey""",
        "q5": """
            SELECT * FROM orders ORDER BY o_totalprice DESC LIMIT 100""",
        "q6": """
            SELECT * FROM (
                SELECT *, ROW_NUMBER() OVER (PARTITION BY o_custkey
                                             ORDER BY o_totalprice) AS rn
                FROM orders)
            WHERE rn <= 3""",
        "q7": """
            SELECT /*+ REPARTITION(8, l_returnflag) */
                   l_returnflag, COUNT(l_quantity) AS c,
                   SUM(l_quantity) AS s
            FROM lineitem GROUP BY l_returnflag""",
        "q8": """
            SELECT COUNT(m) AS n_custs FROM (
                SELECT o_custkey, MAX(o_totalprice) AS m
                FROM orders GROUP BY o_custkey)""",
        "q9": f"""
            SELECT c_nationkey, SUM(rev) AS revenue FROM (
                SELECT c_nationkey,
                       l_extendedprice * (1.0 - l_discount) AS rev
                FROM (SELECT *, o_custkey AS c_custkey
                      FROM (SELECT l_orderkey, l_extendedprice, l_discount
                            FROM lineitem)
                      JOIN (SELECT o_orderkey, o_custkey,
                                   o_orderkey AS l_orderkey
                            FROM (SELECT o_orderkey, o_custkey FROM orders
                                  WHERE o_orderdate >= DATE '{cut9}'))
                        USING (l_orderkey))
                JOIN (SELECT c_custkey, c_nationkey FROM customer)
                  USING (c_custkey))
            GROUP BY c_nationkey
            ORDER BY revenue DESC LIMIT 10""",
        "q10": """
            SELECT SUM(l_extendedprice) AS total
            FROM (SELECT l_orderkey, l_quantity, l_extendedprice
                  FROM lineitem)
            JOIN (SELECT l_orderkey, AVG(l_quantity) AS avg_qty
                  FROM lineitem GROUP BY l_orderkey)
              USING (l_orderkey)
            WHERE CAST(l_quantity AS double) < 0.6 * avg_qty""",
        "q11": """
            SELECT * FROM (
                SELECT c_nationkey, SUM(c_acctbal) AS total_bal,
                       COUNT(c_custkey) AS n
                FROM customer GROUP BY c_nationkey)
            WHERE n > 5
            ORDER BY total_bal DESC""",
        "q12": f"""
            SELECT l_returnflag, COUNT(l_orderkey) AS n,
                   AVG(o_totalprice) AS avg_price
            FROM (SELECT l_orderkey, l_returnflag FROM lineitem
                  WHERE l_shipdate >= DATE '{_iso(9000)}'
                    AND l_shipdate < DATE '{_iso(10000)}')
            JOIN (SELECT o_orderkey, o_totalprice,
                         o_orderkey AS l_orderkey
                  FROM (SELECT o_orderkey, o_totalprice FROM orders))
              USING (l_orderkey)
            GROUP BY l_returnflag""",
        "q13": """
            SELECT c_orders, COUNT(o_custkey) AS n_custs FROM (
                SELECT o_custkey, COUNT(o_orderkey) AS c_orders
                FROM orders GROUP BY o_custkey)
            GROUP BY c_orders ORDER BY c_orders""",
        "q14": f"""
            SELECT total_rev / n AS avg_rev, total_rev FROM (
                SELECT SUM(rev) AS total_rev, COUNT(rev) AS n FROM (
                    SELECT l_extendedprice * (1.0 - l_discount) AS rev
                    FROM lineitem
                    WHERE l_shipdate >= DATE '{_iso(9500)}'
                      AND l_shipdate < DATE '{_iso(9700)}'))""",
        "q15": """
            SELECT o_custkey, SUM(rev) AS revenue FROM (
                SELECT o_custkey,
                       l_extendedprice * (1.0 - l_discount) AS rev
                FROM (SELECT l_orderkey, l_extendedprice, l_discount
                      FROM lineitem)
                JOIN (SELECT o_orderkey, o_custkey,
                             o_orderkey AS l_orderkey
                      FROM (SELECT o_orderkey, o_custkey FROM orders))
                  USING (l_orderkey))
            GROUP BY o_custkey ORDER BY revenue DESC LIMIT 5""",
        "q16": """
            SELECT c_nationkey, COUNT(c_custkey) AS active_custs
            FROM (SELECT *, o_custkey AS c_custkey FROM (
                    SELECT o_custkey, COUNT(o_custkey) AS x
                    FROM (SELECT o_custkey FROM orders)
                    GROUP BY o_custkey))
            JOIN (SELECT c_custkey, c_nationkey FROM customer)
              USING (c_custkey)
            GROUP BY c_nationkey ORDER BY c_nationkey""",
        "q17": """
            SELECT s / 7.0 AS avg_yearly FROM (
                SELECT SUM(l_extendedprice) AS s
                FROM (SELECT l_orderkey, l_quantity, l_extendedprice
                      FROM lineitem)
                JOIN (SELECT l_orderkey, AVG(l_quantity) AS aq
                      FROM lineitem GROUP BY l_orderkey)
                  USING (l_orderkey)
                WHERE CAST(l_quantity AS double) < 0.5 * aq)""",
        "q18": """
            SELECT l_orderkey, sum_qty, o_custkey, o_totalprice FROM (
                SELECT *, l_orderkey AS o_orderkey FROM (
                    SELECT l_orderkey, SUM(l_quantity) AS sum_qty
                    FROM lineitem GROUP BY l_orderkey)
                WHERE sum_qty > 150)
            JOIN (SELECT o_orderkey, o_custkey, o_totalprice FROM orders)
              USING (o_orderkey)
            ORDER BY o_totalprice DESC LIMIT 20""",
        "q19": """
            SELECT SUM(rev) AS revenue FROM (
                SELECT l_extendedprice * (1.0 - l_discount) AS rev
                FROM lineitem
                WHERE (l_quantity >= 1 AND l_quantity <= 11
                       AND l_discount > 0.02)
                   OR (l_quantity >= 10 AND l_quantity <= 20
                       AND l_discount < 0.06)
                   OR l_returnflag = 'R00000001')""",
        "q20": """
            SELECT c_custkey, nbig, c_name, c_acctbal FROM (
                SELECT *, o_custkey AS c_custkey FROM (
                    SELECT o_custkey, COUNT(o_custkey) AS nbig
                    FROM (SELECT o_custkey FROM orders
                          WHERE o_totalprice > 400000.0)
                    GROUP BY o_custkey))
            JOIN (SELECT c_custkey, c_name, c_acctbal FROM customer)
              USING (c_custkey)
            ORDER BY nbig DESC LIMIT 10""",
        "q21": """
            SELECT c_nationkey, c_custkey, rn FROM (
                SELECT *, ROW_NUMBER() OVER (PARTITION BY c_nationkey
                                             ORDER BY c_custkey) AS rn
                FROM customer)
            WHERE rn <= 2""",
        "q22": """
            SELECT c_nationkey, COUNT(c_custkey) AS numcust,
                   SUM(c_acctbal) AS totacctbal
            FROM (SELECT *, 1 AS k
                  FROM (SELECT c_custkey, c_nationkey, c_acctbal
                        FROM customer))
            JOIN (SELECT *, 1 AS k
                  FROM (SELECT AVG(c_acctbal) AS ab FROM customer))
              USING (k)
            WHERE CAST(c_acctbal AS double) > ab
            GROUP BY c_nationkey ORDER BY c_nationkey""",
    }


def build_sql_queries(s, tables, paths=None):
    """q1-q22 from SQL text via session.sql() over temp views (--sql
    mode): same queries as build_queries, entering through the parser ->
    analyzer -> plan layer instead of the DataFrame DSL. With ``paths``
    the views sit over parquet scans (the --hosts harness) instead of
    in-memory tables."""
    from spark_rapids_tpu.plan import from_host_table
    if paths is not None:
        for name, tdir in paths.items():
            s.read_parquet(tdir).create_or_replace_temp_view(name)
    else:
        for name, table in tables.items():
            from_host_table(table, s).create_or_replace_temp_view(name)
    return {name: (lambda text=text: s.sql(text))
            for name, text in sql_texts().items()}


def time_query(fn, runs=3, session=None, tag=None):
    """Cold run + `runs` warm trials; returns (cold, min, median).

    >=3 warm trials with a median bound so tunnel-latency variance is
    distinguishable from real regressions (the reference ScaleTest
    harness reports per-iteration times for the same reason —
    ref: integration_tests/ScaleTest.md). With a session+tag, every run
    is tagged in the query event log (cold runs as <tag>_cold) so the
    offline tools can match runs per query across reports."""

    def _tag(suffix=""):
        if session is not None and tag is not None:
            session.next_query_tag = tag + suffix

    _tag("_cold")
    t0 = time.perf_counter()
    fn().collect_table()
    cold = time.perf_counter() - t0
    warms = []
    for _ in range(runs):
        _tag()
        t0 = time.perf_counter()
        fn().collect_table()
        warms.append(time.perf_counter() - t0)
    warms.sort()
    return cold, warms[0], warms[len(warms) // 2]


# ---------------------------------------------------------------------------
# Chaos mode
# ---------------------------------------------------------------------------


def chaos_fault_spec(seed: int) -> str:
    """The seeded fault schedule: every recoverable fault class fires
    with a small per-hit probability (deterministic per seed). Kernel
    crashes stay rare — each one costs a whole-query replay."""
    return ";".join([
        f"shuffle.fetch.metadata:fetch:0.15:{seed * 10 + 1}",
        f"shuffle.fetch.stream:fetch:0.1:{seed * 10 + 2}",
        f"shuffle.fetch.stream:corrupt:0.1:{seed * 10 + 3}",
        f"shuffle.transport.request:disconnect:0.25:{seed * 10 + 4}",
        f"exec.execute:crash:0.01:{seed * 10 + 5}",
        f"dispatch.kernel:crash:0.001:{seed * 10 + 6}",
    ])


def service_fault_spec(seed: int) -> str:
    """Service-level survivability faults (PR 7) — THE schedule both
    chaos harnesses share (tools/loadtest.py owns it; drift between the
    two would mean they test different contracts)."""
    from spark_rapids_tpu.tools.loadtest import service_chaos_spec
    return service_chaos_spec(seed)


def chaos_conf(seed: int, faults: bool, service_faults: bool = False,
               concurrency: int = 4):
    """Session conf for a chaos (or its fault-free twin) run: the P2P
    shuffle so the full client/server/transport wire path is exercised,
    fast retry backoff, and the circuit breaker armed. The twin differs
    ONLY in the fault schedule so results are comparable bit-for-bit.
    ``service_faults`` extends the schedule with the service-level
    points (worker crash / device loss / wedge) plus the shared
    survivability settings (watchdog hard limit, slots == workers,
    strike budget — loadtest.service_chaos_settings)."""
    conf = {
        "spark.rapids.shuffle.mode": "P2P",
        "spark.rapids.shuffle.localDeviceSplit.enabled": "false",
        "spark.rapids.shuffle.fetch.retryWaitMs": "1",
        "spark.rapids.shuffle.fetch.maxRetries": "3",
        "spark.rapids.sql.runtimeFallback.enabled": "true",
        # every chaos closure runs with the lock witness armed: a rank
        # inversion under fault pressure fails the run (the committed
        # artifact records the violation count in-band)
        "spark.rapids.lint.lockWitness": "true",
    }
    if faults:
        spec = chaos_fault_spec(seed)
        if service_faults:
            from spark_rapids_tpu.tools.loadtest import (
                service_chaos_settings,
            )
            spec = spec + ";" + service_fault_spec(seed)
            conf.update(service_chaos_settings(concurrency))
        conf["spark.rapids.test.faults"] = spec
    return conf


def _record_lock_witness(report: dict, failures: list) -> None:
    """Record the runtime lock witness verdict in-band in a chaos
    artifact. Every chaos closure arms ``spark.rapids.lint.lockWitness``
    in its session conf, so locks constructed for the run are
    rank-checked at every blocking acquire; a nonzero count here is a
    rank inversion OBSERVED under fault pressure — a run failure the
    committed artifact must carry as evidence, not a warning."""
    from spark_rapids_tpu import lockorder
    n = int(lockorder.witness_violations())
    report["lockWitnessViolations"] = n
    report["lockWitnessArmed"] = lockorder.witness_armed()
    if n:
        report["lockWitnessRecords"] = (
            lockorder.witness_violation_records())
        failures.append(
            f"lock witness observed {n} rank inversion(s) during the run")


def tables_differ(a, b):
    """Bit-identity check between two HostTables; returns None when
    identical, else a description of the first divergence."""
    import numpy as np
    if list(a.names) != list(b.names):
        return f"column names differ: {a.names} vs {b.names}"
    if a.num_rows != b.num_rows:
        return f"row counts differ: {a.num_rows} vs {b.num_rows}"
    for name, ca, cb in zip(a.names, a.columns, b.columns):
        if type(ca.dtype) is not type(cb.dtype):
            return f"column {name}: dtypes differ ({ca.dtype} vs {cb.dtype})"
        va = np.asarray(ca.validity, dtype=bool)
        vb = np.asarray(cb.validity, dtype=bool)
        if not np.array_equal(va, vb):
            return f"column {name}: validity differs"
        da, db = np.asarray(ca.data), np.asarray(cb.data)
        if da.dtype == object or db.dtype == object:
            for i in range(a.num_rows):
                if va[i] and da[i] != db[i]:
                    return (f"column {name} row {i}: "
                            f"{da[i]!r} != {db[i]!r}")
        else:
            # bit identity over VALID rows only: raw bytes so NaN
            # payloads and signed zeros count (float equality would mask
            # them); boolean row indexing also masks multi-dim layouts
            # (decimal128 limb pairs), whose null slots are garbage
            if da[va].tobytes() != db[vb].tobytes():
                return f"column {name}: valid values differ bitwise"
    return None


#: per-query recovery-work ceilings the chaos run asserts (a runaway
#: retry loop must fail the run, not grind through it)
CHAOS_BOUNDS = {"fetch_retries": 500, "recomputed_maps": 200,
                "query_replays": 12}


# ---------------------------------------------------------------------------
# Write chaos: the exactly-once contract under kill-mid-write
# ---------------------------------------------------------------------------


def run_write_chaos(seed: int = 7, base_dir=None) -> dict:
    """Seeded kill-mid-write corpus asserting the transactional write
    contract (io/committer.py + delta conflict retry):

    * **no torn files** — a write killed at the file write or at a
      task-commit rename leaves the destination exactly as it was
      (old data fully intact, zero new ``part-*`` visible, staging
      swept by abort);
    * **rerun converges** — re-running the SAME WriteFiles plan after
      the injected kill produces output bit-identical to a fault-free
      write;
    * **transparent replay** — with the runtime-fallback replay armed,
      a crash mid-write auto-replays and the query COMPLETES with
      exactly-once output (no doubled files);
    * **Delta concurrency** — concurrent disjoint appends from one
      snapshot both land via the rebase-and-retry loop; an injected
      ``delta.commit.race`` is absorbed with commitRetries counted;
    * **zero orphans** — after every scenario ``tools vacuum`` reports
      a clean directory (dry-run first, then delete, then dry-run
      again must be empty)."""
    import os
    import tempfile
    import threading

    from spark_rapids_tpu.io.committer import TEMP_DIR, WRITE_METRICS
    from spark_rapids_tpu.plan import nodes as P
    from spark_rapids_tpu.runtime.faults import FAULTS
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.tools.vacuum import run_vacuum

    base = base_dir or tempfile.mkdtemp(prefix="rapids_write_chaos_")
    failures = []
    report = {"seed": seed, "dir": base, "backend": _resolved_backend(),
              "scenarios": {}}

    def _frame(s, n=200):
        import numpy as np
        rng = np.random.default_rng(seed)
        return s.create_dataframe({
            "k": [f"k{i % 5}" for i in range(n)],
            "v": np.arange(n, dtype=np.int64),
            "x": rng.standard_normal(n)})

    def _visible(path):
        """part-* files a scan would see (what expand_paths lists)."""
        out = []
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if not d.startswith(("_", "."))]
            out.extend(os.path.join(root, f) for f in files
                       if not f.startswith(("_", ".")))
        return sorted(out)

    def _assert_clean_vacuum(path, entry):
        rep = run_vacuum(path)
        entry["orphansAfter"] = len(rep["orphans"])
        if rep["orphans"]:
            failures.append(
                f"{entry['name']}: vacuum found orphans {rep['orphans']}")

    def _read_back(s, path, fmt):
        if fmt == "parquet":
            df = s.read_parquet(path)
        else:
            df = s.read_csv(path, header=True)
        return sorted(df.collect(), key=repr)

    # -- scenario: kill at the file write / at the commit rename, both
    # formats, partitioned and not; typed failure then rerun converges
    kill_specs = [
        ("parquet", None, "io.write.file:crash:1:%d" % (seed * 10 + 1)),
        ("parquet", ["k"], "io.write.file:crash:1:%d" % (seed * 10 + 2)),
        ("parquet", ["k"], "io.write.commit:crash:1:%d" % (seed * 10 + 3)),
        ("csv", None, "io.write.commit:crash:1:%d" % (seed * 10 + 4)),
    ]
    for i, (fmt, part_by, spec) in enumerate(kill_specs):
        name = f"kill_{fmt}_{'part' if part_by else 'flat'}_{i}"
        entry = {"name": name, "spec": spec}
        clean_dir = os.path.join(base, name, "clean")
        dest = os.path.join(base, name, "out")
        s_clean = TpuSession()
        writer = getattr(_frame(s_clean), f"write_{fmt}")
        writer(clean_dir, partition_by=part_by)
        expected = _read_back(s_clean, clean_dir, fmt)

        # v1 of the destination: old data a killed overwrite must keep
        # (written FAULT-FREE by the clean session — the kill is for
        # the overwrite attempt, not the setup)
        old = s_clean.create_dataframe({"k": ["old"], "v": [0],
                                        "x": [0.0]})
        getattr(old, f"write_{fmt}")(dest, partition_by=part_by)
        before = _visible(dest)

        s_kill = TpuSession({"spark.rapids.test.faults": spec,
                             "spark.rapids.sql.runtimeFallback.enabled":
                                 "false"})
        df = _frame(s_kill)
        node = P.WriteFiles(df.plan, fmt, dest, part_by, {})
        try:
            s_kill.execute(node)
            failures.append(f"{name}: injected kill did not fire")
        except Exception as exc:
            entry["killed"] = type(exc).__name__
        entry["oldDataIntact"] = _visible(dest) == before
        if not entry["oldDataIntact"]:
            failures.append(f"{name}: reader-visible files changed "
                            "under a killed write")
        if os.path.isdir(os.path.join(dest, TEMP_DIR)):
            failures.append(f"{name}: staging not swept by abort")
        # rerun the SAME plan: the armed count is spent, the job id is
        # the same — then vacuum drops the files the new manifest no
        # longer references (the old data's superseded partitions) and
        # the readable output must converge bit-identically
        s_kill.execute(node)
        run_vacuum(dest, delete=True)
        got = _read_back(s_kill, dest, fmt)
        entry["rerunIdentical"] = got == expected
        if got != expected:
            failures.append(f"{name}: rerun-after-kill diverged")
        _assert_clean_vacuum(dest, entry)
        report["scenarios"][name] = entry

    # -- scenario: transparent replay — crash mid-write with the
    # runtime-fallback replay armed completes exactly-once
    name = "replay_parquet_part"
    spec = "io.write.file:crash:1:%d" % (seed * 10 + 5)
    s_rep = TpuSession({"spark.rapids.test.faults": spec})
    dest = os.path.join(base, name, "out")
    clean_dir = os.path.join(base, name, "clean")
    _frame(TpuSession()).write_parquet(clean_dir, partition_by=["k"])
    stats = _frame(s_rep).write_parquet(dest, partition_by=["k"])
    # capture BEFORE the read-backs: each later execute on this
    # session overwrites the last-query mirror with its own 0
    replays = int(s_rep.last_fault_replays or 0)
    got = _read_back(s_rep, dest, "parquet")
    expected = _read_back(s_rep, clean_dir, "parquet")
    entry = {"name": name, "spec": spec, "replays": replays,
             "identical": got == expected,
             "numFiles": int(stats.to_pydict()["numFiles"][0])}
    if not entry["replays"]:
        failures.append(f"{name}: crash did not trigger a replay")
    if not entry["identical"]:
        failures.append(f"{name}: replayed write not exactly-once")
    _assert_clean_vacuum(dest, entry)
    report["scenarios"][name] = entry

    # -- scenario: Delta — injected commit race + two real concurrent
    # disjoint appends through the rebase-and-retry loop
    name = "delta_concurrent"
    from spark_rapids_tpu.delta.log import DeltaLog
    from spark_rapids_tpu.delta.table import (
        OptimisticTransaction,
        _write_data_file,
        write_delta,
    )
    table_dir = os.path.join(base, name)
    spec = "delta.commit.race:race:1:%d" % (seed * 10 + 6)
    s_d = TpuSession({"spark.rapids.test.faults": spec})
    retries0 = WRITE_METRICS["commitRetries"]
    write_delta(_frame(s_d, 50).plan, s_d, table_dir, mode="error")
    entry = {"name": name, "spec": spec,
             "raceRetries": WRITE_METRICS["commitRetries"] - retries0}
    if entry["raceRetries"] < 1:
        failures.append(f"{name}: injected race was not retried")
    log = DeltaLog(table_dir)
    snap_v = log.latest_version()
    errs = []
    barrier = threading.Barrier(2)

    def _append(tag):
        from spark_rapids_tpu.columnar import HostTable
        txn = OptimisticTransaction(log, s_d.conf, read_version=snap_v)
        txn.stage(_write_data_file(
            table_dir, HostTable.from_pydict({
                "k": [tag], "v": [999], "x": [0.0]}), {}))
        barrier.wait()
        try:
            txn.commit("WRITE (append)")
        except Exception as exc:  # noqa: BLE001 - report, don't hang
            errs.append(f"{tag}: {type(exc).__name__}: {exc}")

    ts = [threading.Thread(target=_append, args=(t,)) for t in ("a", "b")]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    entry["concurrentAppendErrors"] = errs
    if errs:
        failures.append(f"{name}: concurrent appends failed: {errs}")
    rows = s_d.read_delta(table_dir).count()
    entry["rows"] = rows
    if rows != 52:
        failures.append(f"{name}: expected 52 rows after two appends, "
                        f"got {rows}")
    _assert_clean_vacuum(table_dir, entry)
    report["scenarios"][name] = entry

    FAULTS.disarm()
    report["ok"] = not failures
    report["failures"] = failures
    return report


def run_chaos(sf: float = 0.02, seed: int = 7, queries=None,
              use_sql: bool = False, concurrency: int = 0,
              service_faults: bool = False):
    """Fault-free run, then the seeded-fault run, per query; returns the
    chaos report dict (and raises AssertionError on any divergence or
    bound violation — callers in CI want the failure loud).

    ``concurrency > 1`` runs the CHAOTIC side through a QueryService
    worker pool instead of serially — recovery (fetch retry, map
    recompute, crash replay/demotion) and the concurrent scheduler are
    then exercised TOGETHER, still asserting bit-identity against the
    fault-free serial baseline. Recovery bounds apply to the whole run
    (per-query attribution is meaningless across interleaved workers)."""
    from spark_rapids_tpu.datagen import scale_test_specs
    from spark_rapids_tpu.runtime.faults import (
        CIRCUIT_BREAKER,
        FAULTS,
        RECOVERY,
    )
    from spark_rapids_tpu.session import TpuSession

    # argument sanity BEFORE the (expensive) datagen
    if service_faults and (not concurrency or concurrency <= 1):
        raise SystemExit(
            "--service-faults needs --concurrency > 1 (the service "
            "points live in the worker/watchdog machinery)")
    # write corpus FIRST, self-contained (own sessions, own fault
    # specs, disarms at the end): the read corpus's seeded schedule
    # must then advance uninterrupted across q1-q22
    write_report = run_write_chaos(seed)

    specs = scale_test_specs(sf)
    tables = {name: spec.generate_table(sf, seed=seed)
              for name, spec in specs.items()}
    build = build_sql_queries if use_sql else build_queries

    baseline = TpuSession(chaos_conf(seed, faults=False))
    chaotic = TpuSession(chaos_conf(seed, faults=True,
                                    service_faults=service_faults,
                                    concurrency=concurrency))
    base_queries = build(baseline, tables)
    chaos_queries = build(chaotic, tables)
    wanted = queries or list(base_queries)

    report = {"mode": "chaos", "seed": seed, "scale_factor": sf,
              "backend": _resolved_backend(),
              # the spec ACTUALLY armed (chaos_conf composed it) — not
              # a rebuilt copy that could drift from it
              "fault_spec": chaotic.conf.to_dict()[
                  "spark.rapids.test.faults"],
              "service_faults": service_faults,
              "writes": write_report,
              "queries": {}}
    failures = list(write_report["failures"])
    # ALL fault-free runs first: each execute() re-arms the registry from
    # its session's conf, and interleaving arm("")/arm(spec) would reset
    # the seeded schedule every query — the RNG must advance ACROSS the
    # corpus for the schedule to be randomized rather than cyclic
    expected_tables = {name: base_queries[name]().collect_table()
                       for name in wanted}
    if concurrency and concurrency > 1:
        return _run_chaos_concurrent(
            report, failures, wanted, expected_tables, base_queries,
            chaos_queries, chaotic, concurrency,
            service_faults=service_faults)
    for name in wanted:
        expected = expected_tables[name]
        before = RECOVERY.snapshot()
        fires_before = FAULTS.counters()
        demoted_before = set(CIRCUIT_BREAKER.demoted_ops())
        t0 = time.perf_counter()
        got = chaos_queries[name]().collect_table()
        elapsed = time.perf_counter() - t0
        recovery = {k: v - before[k] for k, v in RECOVERY.snapshot().items()}
        entry = {
            "chaos_s": round(elapsed, 4),
            "identical": None,
            **recovery,
            "demotions_total": len(CIRCUIT_BREAKER.demoted_ops()),
            "newly_demoted": sorted(
                set(CIRCUIT_BREAKER.demoted_ops()) - demoted_before),
            # per-query delta, like every other field in this entry
            "fault_fires": {
                k: v - fires_before.get(k, 0)
                for k, v in FAULTS.counters().items()
                if v - fires_before.get(k, 0)},
        }
        diff = tables_differ(expected, got)
        if diff is not None and CIRCUIT_BREAKER.demoted_ops():
            # ANY active demotion (this query's or an earlier one's) can
            # change float reduction order vs the pre-demotion device
            # baseline (conf: variableFloatAgg). The breaker is
            # process-wide, so re-collecting the BASELINE now runs it
            # through the same demoted (CPU) plan — results must be
            # bit-identical to THAT fault-free run of the same plan.
            # suspended(): the baseline session's arm("") must not reset
            # the seeded schedule mid-corpus (see the comment above).
            with FAULTS.suspended():
                redo = base_queries[name]().collect_table()
            diff = tables_differ(redo, got)
            entry["compared_vs_demoted_baseline"] = True
        entry["identical"] = diff is None
        if diff is not None:
            failures.append(f"{name}: {diff}")
        for field, bound in CHAOS_BOUNDS.items():
            if recovery.get(field, 0) > bound:
                failures.append(
                    f"{name}: {field}={recovery[field]} exceeds the "
                    f"chaos bound {bound}")
        report["queries"][name] = entry
        print(json.dumps({"query": name, **entry}))
    report["demoted_ops"] = CIRCUIT_BREAKER.demoted_ops()
    _record_lock_witness(report, failures)
    report["ok"] = not failures
    report["failures"] = failures
    FAULTS.disarm()
    if failures:
        raise AssertionError("chaos run failed:\n" + "\n".join(failures))
    return report


def _run_chaos_concurrent(report, failures, wanted, expected_tables,
                          base_queries, chaos_queries, chaotic_session,
                          concurrency, service_faults=False):
    """Concurrent half of run_chaos: submit the chaotic corpus to a
    QueryService at the requested concurrency across two simulated
    tenants, then verify each result bit-identical to the fault-free
    serial baseline (re-collected through the demoted plan when the
    circuit breaker fired mid-run, exactly like the serial path).

    With ``service_faults`` the schedule also kills workers, loses the
    device, and wedges a dispatch: the bar becomes the survivability
    contract — every submission terminal (no hangs), FINISHED results
    still bit-identical, non-FINISHED outcomes typed, recovery bounded,
    and the service back at HEALTHY."""
    from contextlib import ExitStack

    from spark_rapids_tpu.runtime.faults import (
        CIRCUIT_BREAKER,
        FAULTS,
        RECOVERY,
    )
    from spark_rapids_tpu.runtime.health import HEALTH, QUARANTINE
    from spark_rapids_tpu.service import QueryService
    from spark_rapids_tpu.tools.loadtest import (
        _CHAOS_TYPED_ERRORS as typed_ok,
        drive_health_probes,
        wedge_stall_env,
    )

    report["concurrency"] = concurrency
    before = RECOVERY.snapshot()
    fires_before = FAULTS.counters()
    health_before = HEALTH.snapshot()
    chaos_env = ExitStack()
    if service_faults:
        # stall longer than the hard limit so the watchdog provably
        # fires; the abandoned thread exits on its own afterwards
        chaos_env.enter_context(wedge_stall_env())
    svc = QueryService(session=chaotic_session,
                       max_concurrent=concurrency,
                       queue_depth=max(len(wanted), 64))
    t0 = time.perf_counter()
    handles = {}
    health_probes = 0
    svc_health = None
    try:
        with svc:
            hung = False
            for i, name in enumerate(wanted):
                handles[name] = svc.submit(chaos_queries[name](),
                                           tenant=f"t{i % 2}", tag=name)
            for name, h in handles.items():
                if not h.wait(timeout=600):
                    hung = True
                    failures.append(f"{name}: still {h.state} after 600s")
            # a hung run already failed — waiting out probe timeouts
            # would only delay the verdict (loadtest guards likewise)
            if service_faults and not hung:
                health_probes = drive_health_probes(
                    svc, chaos_queries[wanted[0]], timeout_s=600)
            svc_health = svc.health()
    finally:
        chaos_env.close()
    report["wall_s"] = round(time.perf_counter() - t0, 4)
    recovery = {k: v - before[k] for k, v in RECOVERY.snapshot().items()}
    report["recovery"] = recovery
    report["fault_fires"] = {
        k: v - fires_before.get(k, 0) for k, v in FAULTS.counters().items()
        if v - fires_before.get(k, 0)}
    report["service"] = svc.stats()
    for name, h in handles.items():
        got = h.result_table
        if got is None:
            if (service_faults
                    and type(h.error).__name__ in typed_ok):
                # survivable typed outcome under service faults: the
                # contract is TERMINAL + typed, not all-finished
                report["queries"][name] = {
                    "state": h.state, "identical": None,
                    "typed_error": f"{type(h.error).__name__}: "
                                   f"{h.error}",
                    "requeues": h.requeues}
                continue
            failures.append(f"{name}: no result ({h.state}: {h.error})")
            report["queries"][name] = {"state": h.state,
                                       "identical": False}
            continue
        diff = tables_differ(expected_tables[name], got)
        if diff is not None and CIRCUIT_BREAKER.demoted_ops():
            with FAULTS.suspended():
                redo = base_queries[name]().collect_table()
            diff = tables_differ(redo, got)
        entry = {"state": h.state, "identical": diff is None,
                 "latency_s": round(h.latency_s or 0.0, 4),
                 "queue_wait_s": round(h.queue_wait_s or 0.0, 4),
                 "requeues": h.requeues}
        if diff is not None:
            failures.append(f"{name}: {diff}")
        if h.state != "FINISHED":
            failures.append(f"{name}: unexpected terminal state "
                            f"{h.state} ({h.error})")
        report["queries"][name] = entry
    # whole-run recovery bounds: the per-query ceilings summed
    for field, bound in CHAOS_BOUNDS.items():
        total_bound = bound * len(wanted)
        if recovery.get(field, 0) > total_bound:
            failures.append(f"{field}={recovery[field]} exceeds the "
                            f"whole-run chaos bound {total_bound}")
    stats = report["service"]
    if service_faults:
        health_after = HEALTH.snapshot()
        if svc_health is None:
            svc_health = svc.health()
        report["survivability"] = {
            "deviceReinits": health_after["deviceReinits"]
            - health_before["deviceReinits"],
            "workersLost": stats["workersLost"],
            "workersRespawned": stats["workersRespawned"],
            "requeued": stats["requeued"],
            "hardTimeouts": stats["hardTimeouts"],
            "quarantine": QUARANTINE.snapshot(),
            "healthAtEnd": svc_health,
            "healthProbes": health_probes,
        }
        if svc_health["state"] != "HEALTHY":
            failures.append(
                f"service did not return to HEALTHY: {svc_health}")
        # the watchdog's hard timeouts are EXPECTED under the wedge
        # fault; cancellations and rejections still are not
        if stats["cancelled"] or stats["rejected"]:
            failures.append(f"spurious lifecycle events: {stats}")
    elif stats["cancelled"] or stats["timed_out"] or stats["rejected"]:
        failures.append(f"spurious lifecycle events: {stats}")
    report["demoted_ops"] = CIRCUIT_BREAKER.demoted_ops()
    _record_lock_witness(report, failures)
    report["ok"] = not failures
    report["failures"] = failures
    FAULTS.disarm()
    if failures:
        raise AssertionError("concurrent chaos run failed:\n"
                             + "\n".join(failures))
    return report


# ---------------------------------------------------------------------------
# Mesh chaos: the distributed path under a seeded mesh-fault schedule
# ---------------------------------------------------------------------------


def memory_chaos_fault_spec(seed: int) -> str:
    """The seeded memory-fault schedule: every ``mem.*`` point fires at
    least once (asserted by run_memory_chaos) — a budget squeeze
    mid-query (the retry framework spills and replays), a spill
    FAILURE (the demotion path dies; circuit-breaker/replay recovers),
    and an unspill CORRUPTION (the disk frame's CRC footer trips;
    typed SpillCorruptionError re-lands from the scan cache via query
    replay). COUNT-based entries only, so the schedule is
    deterministic and the post-corpus phases run fault-free."""
    return ";".join([
        f"mem.reserve:oom:2:{seed * 10 + 1}",
        f"mem.spill:crash:1:{seed * 10 + 2}",
        f"mem.unspill:corrupt:1:{seed * 10 + 3}",
    ])


#: whole-run recovery-work ceilings for the memory chaos closure (a
#: runaway spill/retry loop must fail the run, not grind through it)
MEMORY_CHAOS_BOUNDS = {"query_replays": 30, "oomRetries": 4000,
                       "splitRetries": 200, "spillCorruptions": 4,
                       "budgetRaises": 2000}


def tables_differ_unordered(a, b):
    """Bitwise row-MULTISET comparison: chunked/budgeted execution
    legitimately changes the ROW ORDER of unsorted output (group-by
    emission order follows batching), but every row must still exist
    bitwise-identically on both sides. repr() round-trips floats
    exactly (and distinguishes -0.0), so sorting the repr'd rows
    compares value bits, not approximations."""
    if a.names != b.names:
        return f"column names differ: {a.names} vs {b.names}"
    if a.num_rows != b.num_rows:
        return f"row counts differ: {a.num_rows} vs {b.num_rows}"
    rows_a = sorted(map(repr, zip(*[c.to_pylist() for c in a.columns])))
    rows_b = sorted(map(repr, zip(*[c.to_pylist() for c in b.columns])))
    if rows_a != rows_b:
        for i, (ra, rb) in enumerate(zip(rows_a, rows_b)):
            if ra != rb:
                return f"row multiset differs (first at sorted #{i}: " \
                       f"{ra} vs {rb})"
    return None


def tables_close(a, b, rtol=1e-9):
    """Order-insensitive SEMANTIC comparison: non-float values exact,
    floats within rtol. Used to pin that chunked execution computes
    the same ANSWER as unchunked — f64 partial merges over different
    batch structures legitimately differ in final ulps (addition is
    not associative), which is exactly why the bitwise contract runs
    against the same-shape baseline instead."""
    if a.names != b.names:
        return f"column names differ: {a.names} vs {b.names}"
    if a.num_rows != b.num_rows:
        return f"row counts differ: {a.num_rows} vs {b.num_rows}"

    def key(row):
        return tuple(f"{v:.6g}" if isinstance(v, float) else repr(v)
                     for v in row)

    rows_a = sorted(zip(*[c.to_pylist() for c in a.columns]), key=key)
    rows_b = sorted(zip(*[c.to_pylist() for c in b.columns]), key=key)
    for i, (ra, rb) in enumerate(zip(rows_a, rows_b)):
        for va, vb in zip(ra, rb):
            if isinstance(va, float) and isinstance(vb, float):
                if va != vb and not (
                        abs(va - vb) <= rtol * max(abs(va), abs(vb))):
                    return f"row {i}: {va!r} !~ {vb!r}"
            elif va != vb:
                return f"row {i}: {va!r} != {vb!r}"
    return None


def run_memory_chaos(sf: float, seed: int, budget: int, queries=None,
                     use_sql: bool = False, chaos: bool = True):
    """``--device-budget BYTES [--chaos]``: q1-q22 under a hard device
    budget well below the working set — every landing accounted by the
    MemoryArbiter, scans chunked, intermediates spilled through the
    device->host->disk tiers (host tier squeezed so the DISK tier and
    its CRC footers see traffic) — asserting every query bit-identical
    to unbudgeted execution, spillBytes > 0, zero budget violations,
    recovery within MEMORY_CHAOS_BOUNDS and (with --chaos) every
    ``mem.*`` fault point fired, a full memory-ladder walk with one
    incident bundle per action, and a QueryService ending HEALTHY.
    This is the OOC_r01 acceptance harness — ROADMAP item 2's
    out-of-core half exercised end to end."""
    from spark_rapids_tpu.datagen import scale_test_specs
    from spark_rapids_tpu.obs.metrics import scopes_snapshot
    from spark_rapids_tpu.runtime.faults import (
        CIRCUIT_BREAKER,
        FAULTS,
        RECOVERY,
    )
    from spark_rapids_tpu.runtime.health import HEALTH
    from spark_rapids_tpu.runtime.memory import MEMORY
    from spark_rapids_tpu.runtime.spill import BufferCatalog
    from spark_rapids_tpu.session import TpuSession

    specs = scale_test_specs(sf)
    tables = {name: spec.generate_table(sf, seed=seed)
              for name, spec in specs.items()}
    build = build_sql_queries if use_sql else build_queries

    # 16KB host tier: device spills overflow to DISK almost instantly,
    # so the CRC-footed frames and the mem.unspill point see traffic
    BufferCatalog.reset(host_limit_bytes=16 * 1024)
    MEMORY.reset()

    import os
    import tempfile
    flight_dir = tempfile.mkdtemp(prefix="rapids_mem_flightrec_")
    spec = memory_chaos_fault_spec(seed) if chaos else ""
    plain = TpuSession()
    # chunk share at a TENTH of the budget: the join/agg pipeline's
    # irreducible live set (current probe chunk + join output + build
    # + coalesce pending) is a few chunk shares wide — keeping it well
    # under the budget is what makes ZERO violations achievable while
    # spill pressure still builds across the query
    chunk_fraction = 0.1
    budgeted = TpuSession({
        "spark.rapids.memory.device.budgetBytes": str(int(budget)),
        "spark.rapids.memory.device.scanChunkFraction":
            str(chunk_fraction),
        "spark.rapids.sql.runtimeFallback.enabled": "true",
        "spark.rapids.lint.lockWitness": "true",
        "spark.rapids.test.faults": spec,
        "spark.rapids.obs.telemetry.enabled": "true",
        "spark.rapids.obs.telemetry.intervalMs": "200",
        "spark.rapids.obs.flightRecorder.dir": flight_dir,
    })
    plain_queries = build(plain, tables)
    budget_queries = build(budgeted, tables)
    wanted = queries or list(plain_queries)

    report = {"mode": "memory-chaos", "backend": _resolved_backend(),
              "scale_factor": sf, "seed": seed, "sql": use_sql,
              "device_budget_bytes": int(budget),
              "chaos": bool(chaos),
              "fault_spec": spec, "queries": {}}
    failures = []

    # ALL baselines first (run_mesh_chaos's discipline: the baseline
    # session's arm('') must not reset the seeded schedule). TWO
    # baselines per query:
    #
    # * UNBUDGETED (plain): measures the working set — the arbiter's
    #   peak accounted bytes over the whole corpus is what the budget
    #   must sit well below for the run to prove anything.
    # * SHAPE baseline (plain session under forced_chunking at the
    #   budget's chunk share, NO enforcement): executes the exact
    #   batching structure the budgeted run will take — chunked scans,
    #   capped coalesce flushes, sub-partitioned builds — with zero
    #   spills/retries. The budgeted run must be BITWISE IDENTICAL to
    #   it: multi-batch f64 partial merges are only reproducible
    #   against the same batch structure (the MeshReland bit-identity
    #   argument), so this is the comparison that isolates what the
    #   PR adds — enforcement, spill round trips, retries — and
    #   proves it corrupts nothing.
    from spark_rapids_tpu.runtime.memory import forced_chunking
    expected_plain = {name: plain_queries[name]().collect_table()
                      for name in wanted}
    working_set = MEMORY.snapshot()["peakBytes"]
    report["working_set_peak_bytes"] = int(working_set)
    if budget >= working_set:
        failures.append(
            f"--device-budget {budget} is not below the measured "
            f"unbudgeted working-set peak {working_set} — the run "
            "would prove nothing")
    chunk_share = max(1, int(budget * chunk_fraction))
    report["chunk_share_bytes"] = chunk_share
    expected_chunked = {}
    with forced_chunking(chunk_share):
        for name in wanted:
            expected_chunked[name] = plain_queries[name]().collect_table()
    # chunking must not change the ANSWER (row multiset, float ulps
    # aside the values are the same computation): pin the shape
    # baseline against the plain one order-insensitively before
    # trusting it as the identity reference
    for name in wanted:
        sem = tables_close(expected_plain[name], expected_chunked[name])
        if sem is not None:
            failures.append(f"{name}: chunked execution changed the "
                            f"answer vs unchunked: {sem}")
    # a fresh ledger + clean caches for the budgeted phase (the
    # baseline scans' cached unchunked device images would otherwise
    # start the budgeted run already over budget)
    from spark_rapids_tpu.columnar.table import evict_device_caches
    evict_device_caches()
    MEMORY.reset()

    def _mem():
        return dict(scopes_snapshot().get("memory", {}))

    recovery_before = RECOVERY.snapshot()
    mem_before_all = _mem()

    # -- spill round-trip closure ---------------------------------------------
    # The full demotion chain on the REAL corpus data, bitwise: every
    # lineitem chunk lands (budget-enforced, OOM-retried), registers as
    # a SpillableDeviceTable, is forced through device->host->disk
    # (the 16KB host tier overflows to CRC-footed disk frames
    # immediately), and re-lands via get() — the armed mem.unspill
    # corruption fires at the first disk read here, and the phase
    # demonstrates the documented recovery: typed SpillCorruptionError,
    # frame dropped, data re-landed from the source chunk, still
    # bitwise identical. The armed mem.reserve squeezes fire at these
    # landings too (survived by the retry framework).
    from spark_rapids_tpu.errors import (
        KernelCrashError,
        SpillCorruptionError,
    )
    from spark_rapids_tpu.runtime.memory import scan_chunks
    from spark_rapids_tpu.runtime.retry import retry_block
    from spark_rapids_tpu.runtime.spill import SpillableDeviceTable
    from spark_rapids_tpu.columnar import DeviceTable

    def _spill_all_tolerant(counter: dict) -> None:
        """One forced demotion pass, surviving the armed mem.spill
        CRASH (the spill path itself dying leaves the buffer resident
        — the documented failure mode); the immediate retry drains
        the rest of the demotion."""
        try:
            catalog.spill_all_device()
        except KernelCrashError:
            counter["spillCrashesSurvived"] = counter.get(
                "spillCrashesSurvived", 0) + 1
            catalog.spill_all_device()
    if chaos:
        FAULTS.arm(spec)
    catalog = BufferCatalog.get()
    roundtrip = {"chunks": 0, "unspillsBitIdentical": 0,
                 "corruptionsRelanded": 0}
    budgeted.set_conf("spark.rapids.memory.device.budgetBytes",
                      str(int(budget)))
    MEMORY.configure(budgeted.conf)
    with forced_chunking(chunk_share):
        li_chunks = scan_chunks(tables["lineitem"])
    sbs = []
    try:
        for ch in li_chunks:
            dt = retry_block(lambda c=ch: DeviceTable.from_host(c))
            sbs.append((ch, SpillableDeviceTable(dt, catalog)))
            del dt
        _spill_all_tolerant(roundtrip)  # host tier overflows to disk
        for ch, sb in sbs:
            roundtrip["chunks"] += 1
            try:
                got_dt = sb.get()
            except SpillCorruptionError:
                # the corrupt frame was dropped, never served: re-land
                # from the source chunk (the scan-cache re-land path)
                got_dt = retry_block(
                    lambda c=ch: DeviceTable.from_host(c))
                roundtrip["corruptionsRelanded"] += 1
            rt_diff = tables_differ(ch, got_dt.to_host())
            if rt_diff is not None:
                failures.append(
                    f"spill round trip chunk {roundtrip['chunks']} not "
                    f"bit-identical: {rt_diff}")
            else:
                roundtrip["unspillsBitIdentical"] += 1
            del got_dt
            _spill_all_tolerant(roundtrip)
    finally:
        for _, sb in sbs:
            sb.release()
    report["spill_roundtrip"] = roundtrip
    if chaos and roundtrip["corruptionsRelanded"] != 1:
        failures.append(
            f"expected exactly 1 corrupt unspill re-landed in the "
            f"round-trip phase, got {roundtrip['corruptionsRelanded']}")

    for name in wanted:
        before = _mem()
        fires_before = FAULTS.counters()
        t0 = time.perf_counter()
        got = budget_queries[name]().collect_table()
        wall = time.perf_counter() - t0
        after = _mem()
        # BITWISE identity against the same-shape baseline: the
        # budgeted run's spills/unspills/retries must not change one
        # bit of what the identical batch structure computes clean
        diff = tables_differ(expected_chunked[name], got)
        compare_mode = "bitwise"
        if diff is not None and (CIRCUIT_BREAKER.demoted_ops()
                                 or HEALTH.state() != "HEALTHY"):
            # an active demotion changes float accumulation order vs
            # the pre-demotion baseline (process-wide): re-collect the
            # baseline through the same demoted plan (run_chaos
            # pattern; suspended() keeps the schedule from resetting)
            with FAULTS.suspended(), forced_chunking(chunk_share):
                redo = plain_queries[name]().collect_table()
            diff = tables_differ(redo, got)
            compare_mode = "bitwise_vs_demoted"
        if diff is not None:
            # a mid-query split-and-retry legitimately changes the
            # batch structure (halved inputs re-accumulate): fall back
            # to the order-insensitive multiset view before declaring
            # divergence, and report which contract held
            if tables_differ_unordered(expected_chunked[name],
                                       got) is None:
                diff = None
                compare_mode = "multiset"
        entry = {
            "chaos_s": round(wall, 4),
            "identical": diff is None,
            "compare_mode": compare_mode,
            "memory": {k: int(after.get(k, 0) - before.get(k, 0))
                       for k in ("oomRetries", "splitRetries",
                                 "spillBytes", "unspills", "scanChunks",
                                 "arbiterSpills", "budgetRaises",
                                 "spillCorruptions", "budgetViolations")
                       if after.get(k, 0) != before.get(k, 0)},
            "fault_fires": {
                k: v - fires_before.get(k, 0)
                for k, v in FAULTS.counters().items()
                if v - fires_before.get(k, 0)},
            "budget_peak": MEMORY.snapshot()["peakBytes"],
        }
        if diff is not None:
            failures.append(f"{name}: {diff}")
        report["queries"][name] = entry
        print(json.dumps({"query": name, **entry}))

    # -- closure assertions ---------------------------------------------------
    mem_after_all = _mem()
    moved = {k: int(mem_after_all.get(k, 0) - mem_before_all.get(k, 0))
             for k in set(mem_after_all) | set(mem_before_all)}
    report["memory_totals"] = {k: v for k, v in sorted(moved.items())
                               if v}
    if moved.get("spillBytes", 0) <= 0:
        failures.append("spillBytes == 0: the budget never forced a "
                        "spill — it is not below the working set")
    if moved.get("unspills", 0) <= 0:
        failures.append("unspills == 0: spilled data never round-"
                        "tripped back to the device")
    if moved.get("scanChunks", 0) <= 0:
        failures.append("scanChunks == 0: no scan ever chunked")
    if moved.get("budgetViolations", 0) != 0:
        failures.append(
            f"budgetViolations={moved['budgetViolations']}: a landing "
            "exceeded the budget after spilling — enforcement leaked")
    arb = MEMORY.snapshot()
    report["arbiter"] = arb
    report["budgeted_peak_bytes"] = arb["peakBytes"]
    if arb["budgetViolations"] != 0:
        # (redundant with the scope delta above, but the snapshot is
        # the arbiter's own ground truth for the budgeted phase)
        failures.append(
            f"arbiter recorded {arb['budgetViolations']} budget "
            "violations in the budgeted phase")
    if chaos:
        fires = FAULTS.counters()
        for point in sorted(e.split(":")[0] for e in spec.split(";")):
            if not fires.get(point):
                failures.append(
                    f"armed memory fault point {point} never fired — "
                    "the schedule does not cover the out-of-core path")
        report["fault_fires_total"] = dict(fires)
    recovery = {k: v - recovery_before[k]
                for k, v in RECOVERY.snapshot().items()}
    for k in ("oomRetries", "splitRetries", "spillCorruptions",
              "budgetRaises"):
        recovery[k] = moved.get(k, 0)
    report["recovery"] = recovery
    for field, bound in MEMORY_CHAOS_BOUNDS.items():
        if recovery.get(field, 0) > bound:
            failures.append(f"{field}={recovery[field]} exceeds the "
                            f"memory chaos bound {bound}")

    # -- ladder closure: the full walk, one incident bundle per action -------
    if chaos:
        from spark_rapids_tpu.tools.incident import (
            load_bundles,
            render_incident,
        )
        FAULTS.disarm()
        ladder_before = HEALTH.memory_snapshot()["memoryPressureEvents"]
        # a sustained squeeze (every reservation refused for 10 grants)
        # walks retry -> chunk -> cpu_demote end to end and STILL
        # completes; compared against a baseline re-collected through
        # the same demoted plan
        ladder = TpuSession({
            "spark.rapids.memory.device.budgetBytes": str(int(budget)),
            "spark.rapids.memory.device.scanChunkFraction":
                str(chunk_fraction),
            "spark.rapids.sql.runtimeFallback.enabled": "true",
            "spark.rapids.lint.lockWitness": "true",
            "spark.rapids.test.faults":
                f"mem.reserve:oom:10:{seed * 10 + 9}",
            "spark.rapids.obs.flightRecorder.dir": flight_dir,
        })
        ladder_queries = build(ladder, tables)
        probe = wanted[0]
        # the WALK itself: sustained refusals drive retry -> chunk ->
        # cpu_demote; completion (not identity) is the contract here —
        # attempts mid-walk mix demotion states by design
        got = ladder_queries[probe]().collect_table()
        assert got is not None
        # the POST-WALK contract: with the demotions now in place and
        # the schedule spent, a clean re-run of the same query is
        # bitwise identical to a plain-session run through the same
        # demoted plan at the same chunk share
        FAULTS.disarm()
        got = ladder_queries[probe]().collect_table()
        with forced_chunking(chunk_share):
            redo = plain_queries[probe]().collect_table()
        ladder_snap = HEALTH.memory_snapshot()
        actions_taken = (ladder_snap["memoryPressureEvents"]
                         - ladder_before)
        bundles = load_bundles(flight_dir) if os.path.isdir(flight_dir) \
            and os.listdir(flight_dir) else []
        mem_bundles = [b for b in bundles
                       if b.get("kind") == "memory.ladder"]
        ladder_diff = tables_differ(redo, got)
        report["ladder_probe"] = {
            "query": probe,
            "identical": ladder_diff is None,
            "ladder": ladder_snap,
            "demoted_ops": CIRCUIT_BREAKER.demoted_ops(),
            "actions_taken": actions_taken,
            "memory_ladder_bundles": len(mem_bundles),
            "actions_seen": sorted({b.get("action")
                                    for b in mem_bundles}),
        }
        if ladder_diff is not None:
            failures.append(f"ladder probe {probe} diverged: "
                            f"{ladder_diff}")
        if ladder_snap["memoryChunkedReexecutions"] < 1:
            failures.append("ladder never reached the chunked "
                            "re-execution rung")
        if ladder_snap["memoryCpuDemotions"] < 1:
            failures.append("ladder never reached the per-op CPU "
                            "demotion rung")
        if len(mem_bundles) < actions_taken:
            failures.append(
                f"only {len(mem_bundles)} memory-ladder incident "
                f"bundles for {actions_taken} ladder actions")
        elif mem_bundles:
            rendered = render_incident(mem_bundles, last=1)
            for marker in ("trigger:", "ladder:"):
                if marker not in rendered:
                    failures.append(f"tools incident render missing "
                                    f"its {marker!r} section")
        # leave a clean process for the service phase: the ladder's
        # deliberate demotions are this probe's, not the service's
        FAULTS.disarm()
        CIRCUIT_BREAKER.reset()
        HEALTH.reset()
    report["incident_bundles_total"] = len(
        os.listdir(flight_dir)) if os.path.isdir(flight_dir) else 0
    report["flight_recorder_dir"] = flight_dir

    # -- service closure: budgeted serving ends HEALTHY ----------------------
    from spark_rapids_tpu.service.scheduler import QueryService
    svc = QueryService({
        "spark.rapids.memory.device.budgetBytes": str(int(budget)),
        "spark.rapids.memory.device.scanChunkFraction":
            str(chunk_fraction),
        "spark.rapids.service.maxConcurrentQueries": "2",
        "spark.rapids.lint.lockWitness": "true",
    })
    try:
        svc_probe = wanted[0]
        svc_queries = (build_sql_queries if use_sql
                       else build_queries)(svc.session, tables)
        # the corpus closures return DataFrames when called; submit
        # the plan through the service and compare to the baseline
        handle = svc.submit(svc_queries[svc_probe]())
        out = handle.result(timeout=120)
        health = svc.health()
        report["service"] = {
            "state": health["state"],
            "memory": health["memory"],
        }
        if health["state"] != "HEALTHY":
            failures.append(
                f"service ended {health['state']}, not HEALTHY")
        if "memory" not in health:
            failures.append("health() lacks the memory surface")
        # the service session runs the same budget -> same chunk share
        # -> the same-shape baseline applies bitwise here too
        diff = tables_differ(expected_chunked[svc_probe], out)
        if diff is not None:
            failures.append(f"service probe {svc_probe} diverged: "
                            f"{diff}")
    finally:
        svc.shutdown()

    report["demoted_ops"] = CIRCUIT_BREAKER.demoted_ops()
    report["health_state"] = HEALTH.state()
    _record_lock_witness(report, failures)
    report["ok"] = not failures
    report["failures"] = failures
    FAULTS.disarm()
    if failures:
        err = AssertionError("memory chaos run failed:\n"
                             + "\n".join(failures))
        err.report = report
        raise err
    return report


def mesh_chaos_fault_spec(seed: int) -> str:
    """The seeded mesh-fault schedule: every ``mesh.*`` point fires at
    least once (asserted by run_mesh_chaos), exercising all four
    recovery mechanisms — query replay (crash), checksum-validated
    refetch (corrupt at the ICI counts fetch and at the re-land
    gather), the partial-loss degradation ladder down to a mesh shrink
    (device_lost x4: retry -> single-device -> shrink -> retry), and
    plain slowness. COUNT-based entries only, so the seeded schedule is
    deterministic and the end-of-run restore probe runs fault-free."""
    return ";".join([
        f"mesh.shard.put:crash:1:{seed * 10 + 1}",
        f"mesh.shard.put:slow:2:{seed * 10 + 2}",
        f"mesh.ici.exchange:corrupt:2:{seed * 10 + 3}",
        f"mesh.ici.exchange:crash:1:{seed * 10 + 4}",
        f"mesh.gather:corrupt:2:{seed * 10 + 5}",
        f"mesh.gather:device_lost:4:{seed * 10 + 6}",
        f"mesh.dict.upload:slow:1:{seed * 10 + 7}",
    ])


#: whole-run recovery-work ceilings for the mesh chaos closure (a
#: runaway retry loop must fail the run, not grind through it)
MESH_CHAOS_BOUNDS = {"query_replays": 30, "shardRetries": 40,
                     "gatherChecksFailed": 40, "fetch_retries": 100}


def run_mesh_chaos(sf: float, seed: int, ndev: int, queries=None,
                   use_sql: bool = False, shape: str = ""):
    """``--mesh N --chaos``: q1-q22 MESH-NATIVE under the seeded
    mesh-fault schedule, asserting every query bit-identical to the
    fault-free single-chip baseline, every ``mesh.*`` fault point fired
    at least once, recovery counters within MESH_CHAOS_BOUNDS, and the
    mesh back at full strength at the end (a degraded end state is
    tolerated only EXPLAINED — shrink reason + excluded devices in the
    report). This is the MULTICHIP_r07 acceptance harness: the newest,
    most distributed layer of the engine under the same chaos contract
    the host shuffle has carried since PR 3."""
    _ensure_host_mesh(ndev)
    from spark_rapids_tpu.datagen import scale_test_specs
    from spark_rapids_tpu.obs.metrics import scopes_snapshot
    from spark_rapids_tpu.runtime.faults import (
        CIRCUIT_BREAKER,
        FAULTS,
        RECOVERY,
    )
    from spark_rapids_tpu.runtime.health import HEALTH, QUARANTINE
    from spark_rapids_tpu.parallel.mesh import MESH
    from spark_rapids_tpu.session import TpuSession

    specs = scale_test_specs(sf)
    tables = {name: spec.generate_table(sf, seed=seed)
              for name, spec in specs.items()}
    build = build_sql_queries if use_sql else build_queries

    spec = mesh_chaos_fault_spec(seed)
    # flight-recorder closure (ISSUE 14): every injected mesh ladder
    # action must dump an incident bundle into this run's fresh dir
    import os
    import tempfile
    flight_dir = tempfile.mkdtemp(prefix="rapids_mesh_flightrec_")
    chip = TpuSession()
    mesh = TpuSession({
        "spark.rapids.mesh.enabled": "true",
        "spark.rapids.mesh.shape": shape or str(ndev),
        "spark.rapids.sql.runtimeFallback.enabled": "true",
        "spark.rapids.lint.lockWitness": "true",
        "spark.rapids.test.faults": spec,
        "spark.rapids.obs.telemetry.enabled": "true",
        "spark.rapids.obs.telemetry.intervalMs": "200",
        "spark.rapids.obs.flightRecorder.dir": flight_dir,
    })
    chip_queries = build(chip, tables)
    mesh_queries = build(mesh, tables)
    wanted = queries or list(chip_queries)
    # the collective-bearing query (q7, the corpus's one explicit
    # repartition) runs FIRST: the seeded ladder may legitimately
    # shrink the mesh mid-corpus, and a shrunken mesh demotes the
    # 8-way exchange to the host shuffle — the ICI fault points must
    # see traffic before that can happen or the closure assertion
    # below ("every armed point fired") could never pass
    wanted = sorted(wanted, key=lambda n: (n != "q7", wanted.index(n)))

    report = {"mode": "mesh-chaos", "n_devices": ndev,
              "backend": _resolved_backend(),
              "mesh_shape": shape or str(ndev), "scale_factor": sf,
              "seed": seed, "sql": use_sql,
              "fault_spec": mesh.conf.to_dict()[
                  "spark.rapids.test.faults"],
              "queries": {}}
    failures = []
    # ALL fault-free baselines first: interleaving the baseline
    # session's arm("") with the chaotic arm(spec) would reset the
    # seeded schedule every query (run_chaos's discipline)
    expected_tables = {name: chip_queries[name]().collect_table()
                       for name in wanted}

    def _scopes():
        snap = scopes_snapshot()
        return dict(snap.get("mesh", {})), dict(snap.get("health", {}))

    recovery_before = RECOVERY.snapshot()
    mesh_before_all, health_before_all = _scopes()
    #: on_mesh_device_loss invocations == mesh ladder actions (each
    #: bumps the cumulative count) — the incident-bundle floor
    mesh_ladder_before = HEALTH.mesh_snapshot()["meshDeviceLost"]
    for name in wanted:
        before_m, before_h = _scopes()
        fires_before = FAULTS.counters()
        t0 = time.perf_counter()
        got = mesh_queries[name]().collect_table()
        wall = time.perf_counter() - t0
        after_m, after_h = _scopes()
        diff = tables_differ(expected_tables[name], got)
        recollected = False
        if diff is not None and (CIRCUIT_BREAKER.demoted_ops()
                                 or HEALTH.state() != "HEALTHY"):
            # an active demotion or the CPU-only latch changes float
            # accumulation order vs the pre-demotion baseline; both are
            # process-wide, so re-collecting the baseline NOW runs it
            # through the same demoted/latched plan (run_chaos pattern;
            # suspended() keeps the seeded schedule from resetting)
            with FAULTS.suspended():
                redo = chip_queries[name]().collect_table()
            diff = tables_differ(redo, got)
            recollected = True
        entry = {
            "chaos_s": round(wall, 4),
            "identical": diff is None,
            "mesh": {k: int(after_m.get(k, 0) - before_m.get(k, 0))
                     for k in ("shardsDispatched", "iciExchanges",
                               "hostShuffleFallbacks", "shardRetries",
                               "gatherChecksFailed", "meshRelandRows")
                     if after_m.get(k, 0) != before_m.get(k, 0)},
            "ladder": {k: int(after_h.get(k, 0) - before_h.get(k, 0))
                       for k in ("meshDeviceLost", "meshDegradations",
                                 "meshShrinks", "deviceReinits")
                       if after_h.get(k, 0) != before_h.get(k, 0)},
            "fault_fires": {
                k: v - fires_before.get(k, 0)
                for k, v in FAULTS.counters().items()
                if v - fires_before.get(k, 0)},
            "mesh_shape_now": MESH.shape_str(),
        }
        if recollected:
            entry["compared_vs_demoted_baseline"] = True
        if diff is not None:
            failures.append(f"{name}: {diff}")
        report["queries"][name] = entry
        print(json.dumps({"query": name, **entry}))

    # -- closure assertions ---------------------------------------------------
    fires = FAULTS.counters()
    armed_points = {e.split(":")[0] for e in spec.split(";")}
    for point in sorted(armed_points):
        if not fires.get(point):
            failures.append(
                f"armed mesh fault point {point} never fired — the "
                f"schedule does not cover the distributed path")
    report["fault_fires_total"] = dict(fires)
    recovery = {k: v - recovery_before[k]
                for k, v in RECOVERY.snapshot().items()}
    mesh_after_all, health_after_all = _scopes()
    recovery["shardRetries"] = int(
        mesh_after_all.get("shardRetries", 0)
        - mesh_before_all.get("shardRetries", 0))
    recovery["gatherChecksFailed"] = int(
        mesh_after_all.get("gatherChecksFailed", 0)
        - mesh_before_all.get("gatherChecksFailed", 0))
    report["recovery"] = recovery
    for field, bound in MESH_CHAOS_BOUNDS.items():
        if recovery.get(field, 0) > bound:
            failures.append(f"{field}={recovery[field]} exceeds the "
                            f"mesh chaos bound {bound}")
    report["ladder"] = HEALTH.mesh_snapshot()
    report["quarantine"] = QUARANTINE.snapshot()

    # -- end state: full strength, or an explained degraded state ------------
    end_state = MESH.health_snapshot()
    report["mesh_end_state"] = end_state
    if end_state["excludedDeviceIds"]:
        # the schedule is count-based and spent: restoring and probing
        # must succeed — a mesh that cannot return to full strength
        # after the faults stopped would be a real (reported) problem
        MESH.restore("mesh chaos run complete; probing full strength")
        probe = wanted[0]
        with FAULTS.suspended():
            redo = chip_queries[probe]().collect_table()
        got = mesh_queries[probe]().collect_table()
        restored = MESH.health_snapshot()
        report["restore_probe"] = {
            "query": probe,
            "identical": tables_differ(redo, got) is None,
            "mesh": restored,
        }
        if tables_differ(redo, got) is not None:
            failures.append(f"restore probe {probe} diverged")
        if restored["excludedDeviceIds"]:
            failures.append(
                "mesh did not return to full strength after restore: "
                f"{restored}")
    # -- flight-recorder closure (ISSUE 14) ----------------------------------
    from spark_rapids_tpu.tools.incident import (
        load_bundles,
        render_incident,
    )
    ladder_actions = (HEALTH.mesh_snapshot()["meshDeviceLost"]
                      - mesh_ladder_before)
    bundles = load_bundles(flight_dir) if os.path.isdir(flight_dir) \
        and os.listdir(flight_dir) else []
    mesh_bundles = [b for b in bundles if b.get("kind") == "mesh.ladder"]
    report["incident_bundles"] = len(bundles)
    report["mesh_ladder_bundles"] = len(mesh_bundles)
    report["mesh_ladder_actions"] = ladder_actions
    report["flight_recorder_dir"] = flight_dir
    if len(mesh_bundles) < ladder_actions:
        failures.append(
            f"only {len(mesh_bundles)} mesh-ladder incident bundles "
            f"for {ladder_actions} injected ladder actions")
    elif mesh_bundles:
        rendered = render_incident(mesh_bundles, last=1)
        for marker in ("trigger:", "ladder:", "telemetry tail:"):
            if marker not in rendered:
                failures.append(f"tools incident render missing its "
                                f"{marker!r} section")
        report["incident_actions"] = sorted(
            {b.get("action") for b in mesh_bundles})

    report["demoted_ops"] = CIRCUIT_BREAKER.demoted_ops()
    report["health_state"] = HEALTH.state()
    _record_lock_witness(report, failures)
    report["ok"] = not failures
    report["failures"] = failures
    FAULTS.disarm()
    if failures:
        err = AssertionError("mesh chaos run failed:\n"
                             + "\n".join(failures))
        err.report = report
        raise err
    return report


# ---------------------------------------------------------------------------
# Mesh mode: the corpus executed mesh-native, bit-identical to single-chip
# ---------------------------------------------------------------------------


def _ensure_host_mesh(n: int) -> None:
    """Force an n-device virtual host-platform mesh BEFORE the JAX
    backend initializes (shared with the dryrun_multichip entry): real
    multi-host pods bring their own devices; set
    SPARK_RAPIDS_TPU_DRYRUN_REAL=1 to use whatever the process has."""
    from spark_rapids_tpu.parallel.mesh import ensure_host_devices
    have = ensure_host_devices(n)
    if have < n:
        raise SystemExit(
            f"--mesh {n} needs {n} devices but only {have} are available "
            "(the JAX backend initialized before the host device-count "
            "flag could take effect)")


def run_mesh(sf: float, seed: int, ndev: int, queries=None,
             use_sql: bool = False, shape: str = ""):
    """Mesh-native corpus run: q1-q22 single-chip for the baseline, the
    SAME corpus with ``spark.rapids.mesh.enabled`` over an ndev-device
    mesh, asserting BIT-IDENTITY per query and reporting per-exchange
    ICI accounting (collective count, payload bytes, host-shuffle
    fallbacks with reasons, re-land rows) from the mesh metric scope
    and the per-exchange metrics. Raises AssertionError on any
    divergence — this is the MULTICHIP_r06 acceptance harness."""
    _ensure_host_mesh(ndev)
    from spark_rapids_tpu.datagen import scale_test_specs
    from spark_rapids_tpu.obs.events import collect_exchanges
    from spark_rapids_tpu.obs.metrics import scopes_snapshot
    from spark_rapids_tpu.session import TpuSession

    specs = scale_test_specs(sf)
    tables = {name: spec.generate_table(sf, seed=seed)
              for name, spec in specs.items()}
    build = build_sql_queries if use_sql else build_queries

    chip = TpuSession()
    mesh = TpuSession({
        "spark.rapids.mesh.enabled": "true",
        "spark.rapids.mesh.shape": shape or str(ndev),
    })
    chip_queries = build(chip, tables)
    mesh_queries = build(mesh, tables)
    wanted = queries or list(chip_queries)

    report = {"mode": "mesh", "n_devices": ndev,
              "backend": _resolved_backend(),
              "mesh_shape": shape or str(ndev), "scale_factor": sf,
              "seed": seed, "sql": use_sql, "queries": {}}
    failures = []
    for name in wanted:
        expected = chip_queries[name]().collect_table()
        before = dict(scopes_snapshot().get("mesh", {}))
        t0 = time.perf_counter()
        got = mesh_queries[name]().collect_table()
        wall = time.perf_counter() - t0
        after = dict(scopes_snapshot().get("mesh", {}))
        delta = {k: int(after.get(k, 0) - before.get(k, 0))
                 for k in ("shardsDispatched", "iciExchanges", "iciBytes",
                           "hostShuffleFallbacks", "meshHostUploads",
                           "meshRelandRows", "meshDictInterns",
                           "meshGatherRows")}
        diff = tables_differ(expected, got)
        exchanges = []
        for e in collect_exchanges(mesh._last_executable):
            exchanges.append({k: e[k] for k in
                              ("op", "loreId", "iciPartitions", "iciBytes",
                               "iciExchangeTime", "hostShuffleFallbacks",
                               "mapOutputBytesMax", "mapOutputBytesMedian",
                               "skewedPartitions")
                              if k in e})
        entry = {"identical": diff is None, "mesh_wall_s": round(wall, 4),
                 "mesh": delta, "exchanges": exchanges}
        if diff is not None:
            failures.append(f"{name}: {diff}")
        report["queries"][name] = entry
        print(json.dumps({"query": name, **entry}))
    report["totals"] = {
        k: sum(q["mesh"][k] for q in report["queries"].values())
        for k in ("iciExchanges", "iciBytes", "hostShuffleFallbacks",
                  "meshHostUploads", "shardsDispatched")}
    report["ok"] = not failures
    report["failures"] = failures
    if failures:
        # the report IS the diagnostic (per-query identical flags, mesh
        # deltas, exchange accounting) — carry it on the error so the
        # CLI can still write --out before exiting non-zero
        err = AssertionError("mesh run diverged from single-chip:\n"
                             + "\n".join(failures))
        err.report = report
        raise err
    return report


# ---------------------------------------------------------------------------
# Multi-host mode: the corpus over the driver/executor protocol,
# bit-identical to single-process (runtime/cluster.py)
# ---------------------------------------------------------------------------


def write_host_corpus(tables, base_dir, files_per_table: int) -> dict:
    """Write each generated table as ``files_per_table`` parquet files
    (contiguous row slices, one file per chunk subdir so the sorted
    file walk preserves row order) — the source-file layout the
    by-host scan partitioner distributes. Returns name -> table dir."""
    import os

    from spark_rapids_tpu.io.parquet import write_parquet
    paths = {}
    for name, table in tables.items():
        tdir = os.path.join(base_dir, name)
        n = table.num_rows
        chunk = max(1, (n + files_per_table - 1) // files_per_table)
        start = i = 0
        while start < n:
            write_parquet(table.slice(start, min(chunk, n - start)),
                          os.path.join(tdir, f"c{i:03d}"))
            start += chunk
            i += 1
        paths[name] = tdir
    return paths


def host_chaos_fault_spec(seed: int) -> str:
    """The seeded HOST-fault schedule: every ``host.*`` point fires at
    least once (asserted by run_hosts), exercising the full ladder
    surface — dispatch crash (query replay), corrupt shard landings
    (CRC-caught re-lands), injected host losses walking retry ->
    re-land-on-survivors, DCN-exchange faults, and dropped executor
    heartbeats. COUNT-based entries only, so the schedule is
    deterministic and the end-of-run restore probe runs fault-free.
    The scripted mid-corpus host KILL (a real SIGKILL of an executor
    process) rides on top of this schedule."""
    return ";".join([
        # raising kinds get their own points: co-located raising
        # entries mask each other (the first raise wins the call and
        # the other's schedule is consumed), so corrupt lives ALONE on
        # the landing point — its CRC-retry path must actually run
        f"host.dispatch:crash:1:{seed * 10 + 1}",
        f"host.dispatch:device_lost:3:{seed * 10 + 2}",
        f"host.shard.land:corrupt:2:{seed * 10 + 3}",
        f"host.dcn.exchange:slow:1:{seed * 10 + 4}",
        f"host.dcn.exchange:crash:1:{seed * 10 + 5}",
        f"host.heartbeat:crash:2:{seed * 10 + 6}",
    ])


#: whole-run recovery-work ceilings for the host chaos closure
HOST_CHAOS_BOUNDS = {"query_replays": 30, "hostShardRetries": 20,
                     "hostsLost": 10, "fetch_retries": 100}

#: harness heartbeat settings: a VERY generous missed-beat budget —
#: the driver shares its process with jax compilation, which can hold
#: the GIL for whole seconds at a time, and a spurious eviction would
#: walk the ladder for no reason. A real SIGKILL is still detected
#: promptly through the beat-connection EOF path, not this window.
_HOSTS_HEARTBEAT_MS = 250
_HOSTS_MISSED_BEATS = 120


def _boot_cluster(nhosts: int):
    """Driver + N subprocess executors, registered and attached."""
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.runtime.cluster import (
        CLUSTER,
        ClusterDriver,
        spawn_executor,
    )
    driver = ClusterDriver(nhosts, RapidsConf({
        "spark.rapids.cluster.heartbeatIntervalMs":
            str(_HOSTS_HEARTBEAT_MS),
        "spark.rapids.cluster.missedBeats": str(_HOSTS_MISSED_BEATS),
    }))
    executors = {
        f"h{i}": spawn_executor(driver.address, f"h{i}",
                                heartbeat_ms=_HOSTS_HEARTBEAT_MS,
                                mode="process")
        for i in range(nhosts)}
    driver.wait_ready(nhosts, timeout_s=120.0)
    CLUSTER.attach_driver(driver)
    return driver, executors


def _teardown_cluster(driver, executors) -> None:
    from spark_rapids_tpu.runtime.cluster import CLUSTER
    CLUSTER.attach_driver(None)
    driver.shutdown()
    for h in executors.values():
        try:
            h.terminate()
        except Exception:
            pass


def _wait_for(predicate, timeout_s: float) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.1)
    return predicate()


def run_hosts(sf: float, seed: int, nhosts: int, queries=None,
              use_sql: bool = False, chaos: bool = False):
    """``--hosts N [--chaos]``: q1-q22 through the multi-process
    simulation harness — N REAL executor subprocesses scanning their
    by-host file assignments and shipping shards back over the
    driver/executor socket protocol, the corpus running mesh-native on
    the hierarchical (hosts x devices-per-host) mesh so all-to-alls
    physically model ICI-within-a-host / DCN-across. Asserts every
    query bit-identical to a fault-free single-process run over the
    SAME files.

    With ``chaos``, the corpus additionally runs under the seeded
    ``host.*`` fault schedule PLUS a scripted mid-corpus host KILL
    (SIGKILL of one executor): the missed-beat sweep must declare the
    host lost, scans must re-land its shards onto survivors, the
    respawned executor must REJOIN through the heartbeat re-register
    path, and the end-of-run restore probe must return the topology to
    full strength — the MULTIHOST_r01 acceptance harness."""
    _ensure_host_mesh(8)
    import os
    import tempfile

    import jax

    from spark_rapids_tpu.datagen import scale_test_specs
    from spark_rapids_tpu.obs.metrics import scopes_snapshot
    from spark_rapids_tpu.runtime.cluster import CLUSTER
    from spark_rapids_tpu.runtime.faults import (
        CIRCUIT_BREAKER,
        FAULTS,
        RECOVERY,
    )
    from spark_rapids_tpu.runtime.health import HEALTH
    from spark_rapids_tpu.session import TpuSession

    ndev = len(jax.devices())
    if ndev % nhosts:
        raise SystemExit(
            f"--hosts {nhosts} must divide the {ndev}-device pool so "
            f"every host owns an equal dcn row")
    shape = f"{nhosts}x{ndev // nhosts}"

    specs = scale_test_specs(sf)
    tables = {name: spec.generate_table(sf, seed=seed)
              for name, spec in specs.items()}
    base = tempfile.mkdtemp(prefix="rapids_hosts_")
    paths = write_host_corpus(tables, base, files_per_table=2 * nhosts)

    spec = host_chaos_fault_spec(seed) if chaos else ""
    driver, executors = _boot_cluster(nhosts)
    # the observability closure (ISSUE 14): the cluster session runs
    # with event log + host tracing + the telemetry sampler on, and
    # the flight recorder pointed at a fresh dir — the run then
    # asserts executor-host spans per routed scan, the tools-profile
    # per-host breakdown over the 95% coverage floor, and (chaos) one
    # incident bundle per injected host ladder action
    obs_dir = tempfile.mkdtemp(prefix="rapids_hosts_obs_")
    eventlog_dir = os.path.join(obs_dir, "eventlog")
    trace_dir = os.path.join(obs_dir, "trace")
    flight_dir = os.path.join(obs_dir, "flightrec")
    report = {"mode": "hosts-chaos" if chaos else "hosts",
              "hosts": nhosts, "n_devices": ndev, "mesh_shape": shape,
              "backend": _resolved_backend(), "scale_factor": sf,
              "seed": seed, "sql": use_sql, "corpus_dir": base,
              "files_per_table": 2 * nhosts,
              "observability": {"eventlog_dir": eventlog_dir,
                                "trace_dir": trace_dir,
                                "flight_recorder_dir": flight_dir},
              "queries": {}}
    failures = []
    try:
        single = TpuSession()
        conf = {
            "spark.rapids.cluster.enabled": "true",
            "spark.rapids.cluster.hosts": str(nhosts),
            "spark.rapids.cluster.heartbeatIntervalMs":
                str(_HOSTS_HEARTBEAT_MS),
            "spark.rapids.cluster.missedBeats":
                str(_HOSTS_MISSED_BEATS),
            "spark.rapids.mesh.enabled": "true",
            "spark.rapids.mesh.shape": shape,
            "spark.rapids.sql.runtimeFallback.enabled": "true",
            "spark.rapids.sql.eventLog.enabled": "true",
            "spark.rapids.sql.eventLog.dir": eventlog_dir,
            "spark.rapids.trace.enabled": "true",
            "spark.rapids.trace.dir": trace_dir,
            "spark.rapids.obs.telemetry.enabled": "true",
            "spark.rapids.obs.telemetry.intervalMs": "200",
            "spark.rapids.obs.flightRecorder.dir": flight_dir,
        }
        if spec:
            conf["spark.rapids.test.faults"] = spec
            conf["spark.rapids.lint.lockWitness"] = "true"
            report["fault_spec"] = spec
        clus = TpuSession(conf)
        build = build_sql_queries if use_sql else build_queries
        single_queries = build(single, tables, paths=paths)
        clus_queries = build(clus, tables, paths=paths)
        wanted = queries or list(single_queries)
        # the collective-bearing query runs FIRST (run_mesh_chaos's
        # discipline): the dcn-exchange fault points must see traffic
        # before the ladder may legitimately degrade the topology
        wanted = sorted(wanted, key=lambda n: (n != "q7",
                                               wanted.index(n)))
        # ALL fault-free baselines first: the seeded schedule must
        # advance uninterrupted across the chaotic corpus
        expected_tables = {name: single_queries[name]().collect_table()
                           for name in wanted}

        recovery_before = RECOVERY.snapshot()
        cluster_before_all = dict(
            scopes_snapshot().get("cluster", {}))
        #: on_host_loss invocations == host ladder actions (each bumps
        #: the cumulative loss count) — the incident-bundle floor
        host_ladder_before = HEALTH.host_snapshot()["hostsLost"]
        # the kill lands mid-corpus and the rejoin ALWAYS fits before
        # the last query — a --queries subset too short for the script
        # must not leave the victim dead into the closure assertions
        kill_at = min(len(wanted) // 2,
                      len(wanted) - 2) if chaos else None
        rejoin_at = (min(kill_at + 2, len(wanted) - 1)
                     if chaos and kill_at is not None and kill_at >= 0
                     else None)
        if chaos and (kill_at is None or kill_at < 0
                      or rejoin_at <= kill_at):
            kill_at = rejoin_at = None  # corpus too short to script
        victim = f"h{nhosts - 1}"
        kill_info = {}
        for qi, name in enumerate(wanted):
            if chaos and qi == kill_at:
                # scripted mid-corpus HOST KILL: a real SIGKILL; the
                # missed-beat sweep must declare the host lost
                t0 = time.time()
                executors[victim].terminate()
                detected = _wait_for(
                    lambda: victim in CLUSTER.health_snapshot()[
                        "lostHosts"]
                    or victim in CLUSTER.health_snapshot()[
                        "excludedHosts"],
                    timeout_s=30.0)
                kill_info = {"host": victim, "atQuery": name,
                             "detected": detected,
                             "detectS": round(time.time() - t0, 3)}
                if not detected:
                    failures.append(
                        f"killed host {victim} never declared lost by "
                        f"the heartbeat sweep")
            if chaos and qi == rejoin_at:
                # respawn: the fresh registration is the rejoin path
                t0 = time.time()
                from spark_rapids_tpu.runtime.cluster import (
                    spawn_executor,
                )
                executors[victim] = spawn_executor(
                    driver.address, victim,
                    heartbeat_ms=_HOSTS_HEARTBEAT_MS, mode="process")
                rejoined = _wait_for(
                    lambda: victim not in CLUSTER.health_snapshot()[
                        "lostHosts"]
                    and victim not in CLUSTER.health_snapshot()[
                        "excludedHosts"],
                    timeout_s=60.0)
                kill_info["rejoined"] = rejoined
                kill_info["rejoinS"] = round(time.time() - t0, 3)
                if not rejoined:
                    failures.append(
                        f"respawned host {victim} never rejoined the "
                        f"topology")
            before_c = dict(scopes_snapshot().get("cluster", {}))
            before_h = HEALTH.host_snapshot()
            fires_before = FAULTS.counters()
            t0 = time.perf_counter()
            got = clus_queries[name]().collect_table()
            wall = time.perf_counter() - t0
            after_c = dict(scopes_snapshot().get("cluster", {}))
            after_h = HEALTH.host_snapshot()
            diff = tables_differ(expected_tables[name], got)
            recollected = False
            if diff is not None and (CIRCUIT_BREAKER.demoted_ops()
                                     or HEALTH.state() != "HEALTHY"):
                with FAULTS.suspended():
                    redo = single_queries[name]().collect_table()
                diff = tables_differ(redo, got)
                recollected = True
            rec = clus.last_event_record or {}
            entry = {
                "chaos_s" if chaos else "wall_s": round(wall, 4),
                "identical": diff is None,
                "cluster": {k: int(after_c.get(k, 0)
                                   - before_c.get(k, 0))
                            for k in ("hostShardsLanded", "hostsLost",
                                      "hostRelands", "hostShrinks",
                                      "hostRestores", "dcnExchanges",
                                      "hostShardRetries",
                                      "executorBeatsDropped",
                                      "clusterScanFallbacks")
                            if after_c.get(k, 0) != before_c.get(k, 0)},
                "ladder": {k: int(after_h[k] - before_h[k])
                           for k in after_h
                           if after_h[k] != before_h[k]},
                "host_topology": CLUSTER.topology_str(),
                "query_index": rec.get("queryIndex"),
                "host_scans": sorted(rec.get("hostScans") or {}),
            }
            if chaos:
                entry["fault_fires"] = {
                    k: v - fires_before.get(k, 0)
                    for k, v in FAULTS.counters().items()
                    if v - fires_before.get(k, 0)}
            if recollected:
                entry["compared_vs_demoted_baseline"] = True
            if diff is not None:
                failures.append(f"{name}: {diff}")
            report["queries"][name] = entry
            print(json.dumps({"query": name, **entry}))
        if chaos:
            report["kill"] = kill_info

        # -- closure assertions ----------------------------------------------
        fires = FAULTS.counters()
        if chaos:
            armed_points = {e.split(":")[0] for e in spec.split(";")}
            for point in sorted(armed_points):
                if not fires.get(point):
                    failures.append(
                        f"armed host fault point {point} never fired — "
                        f"the schedule does not cover the multi-host "
                        f"path")
            report["fault_fires_total"] = dict(fires)
        recovery = {k: v - recovery_before[k]
                    for k, v in RECOVERY.snapshot().items()}
        cluster_after_all = dict(scopes_snapshot().get("cluster", {}))
        for k in ("hostShardRetries", "hostsLost"):
            recovery[k] = int(cluster_after_all.get(k, 0)
                              - cluster_before_all.get(k, 0))
        report["recovery"] = recovery
        if chaos:
            for field, bound in HOST_CHAOS_BOUNDS.items():
                if recovery.get(field, 0) > bound:
                    failures.append(
                        f"{field}={recovery[field]} exceeds the host "
                        f"chaos bound {bound}")
        report["cluster_totals"] = {
            k: int(cluster_after_all.get(k, 0)
                   - cluster_before_all.get(k, 0))
            for k in sorted(cluster_after_all)}
        report["ladder"] = HEALTH.host_snapshot()

        # -- end state: full strength, or restore and prove it ---------------
        end_state = CLUSTER.health_snapshot()
        report["hosts_end_state"] = end_state
        if (end_state["lostHosts"] or end_state["excludedHosts"]
                or end_state["singleProcessReason"]):
            # the count-based schedule is spent: restore and probe —
            # a topology that cannot return to full strength after the
            # faults stopped is a real (reported) problem
            CLUSTER.restore()
            probe = wanted[0]
            with FAULTS.suspended():
                redo = single_queries[probe]().collect_table()
                got = clus_queries[probe]().collect_table()
            restored = CLUSTER.health_snapshot()
            report["restore_probe"] = {
                "query": probe,
                "identical": tables_differ(redo, got) is None,
                "hosts": restored,
            }
            if tables_differ(redo, got) is not None:
                failures.append(f"restore probe {probe} diverged")
            if (restored["lostHosts"] or restored["excludedHosts"]
                    or restored["singleProcessReason"]):
                failures.append(
                    "cluster did not return to full strength after "
                    f"restore: {restored}")
        # -- observability closure (ISSUE 14) --------------------------------
        # (a) the merged Chrome trace carries executor-host spans for
        # every cluster-routed scan: the driver's per-host cluster.scan
        # span AND the executor's own spans merged onto an
        # executor-<host> lane
        for name, entry in report["queries"].items():
            landed = entry["cluster"].get("hostShardsLanded", 0)
            qi = entry.get("query_index")
            if not landed or qi is None:
                continue
            tpath = os.path.join(trace_dir, f"query_{qi}.trace.json")
            if not os.path.exists(tpath):
                failures.append(f"{name}: cluster-routed scan has no "
                                f"Chrome trace at {tpath}")
                continue
            with open(tpath) as f:
                events = json.load(f)["traceEvents"]
            cluster_spans = [e for e in events
                             if e.get("name") == "cluster.scan"]
            exec_lanes = sorted(
                {str((e.get("args") or {}).get("name", ""))
                 for e in events if e.get("ph") == "M"
                 and str((e.get("args") or {}).get("name", ""))
                 .startswith("executor-")})
            exec_spans = [e for e in events
                          if e.get("cat") == "exec-scan"]
            if not cluster_spans:
                failures.append(f"{name}: no cluster.scan span in the "
                                f"merged trace")
            if not exec_lanes or not exec_spans:
                failures.append(f"{name}: no executor-host spans "
                                f"merged into the trace")
            entry["trace"] = {"clusterScanSpans": len(cluster_spans),
                              "executorLanes": exec_lanes,
                              "executorSpans": len(exec_spans)}

        # (b) tools profile over the run's event log: the per-host
        # breakdown exists and telemetry/trace overhead stays above
        # the existing 95% span-coverage floor
        from spark_rapids_tpu.tools.report import (
            build_profile,
            load_events,
        )
        profile = build_profile(load_events(eventlog_dir))
        report["profile"] = {
            "minCoverage": profile["minCoverage"],
            "queriesBelowCoverageFloor":
                profile["queriesBelowCoverageFloor"],
            "perHost": profile["hostResilience"]["perHost"],
        }
        if profile["queriesBelowCoverageFloor"]:
            failures.append(
                "span coverage fell below the 95% floor under "
                f"telemetry: {profile['queriesBelowCoverageFloor']}")
        if not profile["hostResilience"]["perHost"]:
            failures.append("tools profile has no per-host breakdown "
                            "(hostScans never recorded)")

        # (c) flight recorder: every injected host ladder action
        # produced an incident bundle, and tools incident renders them
        from spark_rapids_tpu.tools.incident import (
            load_bundles,
            render_incident,
        )
        ladder_actions = (HEALTH.host_snapshot()["hostsLost"]
                          - host_ladder_before)
        bundles = (load_bundles(flight_dir)
                   if os.path.isdir(flight_dir) else [])
        host_bundles = [b for b in bundles
                        if b.get("kind") == "host.ladder"]
        report["incident_bundles"] = len(bundles)
        report["host_ladder_bundles"] = len(host_bundles)
        report["host_ladder_actions"] = ladder_actions
        if chaos:
            if len(host_bundles) < ladder_actions:
                failures.append(
                    f"only {len(host_bundles)} host-ladder incident "
                    f"bundles for {ladder_actions} injected ladder "
                    f"actions")
            if host_bundles:
                rendered = render_incident(host_bundles, last=1)
                for marker in ("trigger:", "ladder:",
                               "telemetry tail:"):
                    if marker not in rendered:
                        failures.append(
                            f"tools incident render missing its "
                            f"{marker!r} section")
                report["incident_actions"] = sorted(
                    {b.get("action") for b in host_bundles})
            elif ladder_actions:
                failures.append("no host-ladder incident bundles were "
                                "recorded")

        report["demoted_ops"] = CIRCUIT_BREAKER.demoted_ops()
        report["health_state"] = HEALTH.state()
    finally:
        FAULTS.disarm()
        _teardown_cluster(driver, executors)
    _record_lock_witness(report, failures)
    report["ok"] = not failures
    report["failures"] = failures
    if failures:
        err = AssertionError("hosts run failed:\n" + "\n".join(failures))
        err.report = report
        raise err
    return report


# ---------------------------------------------------------------------------
# Fleet closure: composable chaos planes through the QueryService-as-
# cluster-driver — multi-host serving under combined fault domains
# ---------------------------------------------------------------------------


#: scheduling pools for the fleet run: two weights so DEGRADED-mode
#: shedding has a lowest-weight pool to push back on while the
#: interactive pool keeps serving (scheduler.py's shed contract)
FLEET_POOLS = "interactive:weight=2;batch:weight=1"

#: fault-POINT prefix -> fault domain, for the per-domain fleet
#: closure asserts. Distinct from obs.telemetry.fault_domain (which
#: classifies incident KINDS like "memory.ladder"): injection points
#: spell memory "mem.*", and the service plane's points spread over
#: the service./device./dispatch. prefixes (all -> "service").
_FLEET_POINT_DOMAINS = (("host.", "host"), ("mesh.", "mesh"),
                        ("mem.", "memory"), ("stream.", "stream"))


def _fleet_point_domain(point: str) -> str:
    for prefix, domain in _FLEET_POINT_DOMAINS:
        if point.startswith(prefix):
            return domain
    return "service"


def fleet_planes(seed: int) -> dict:
    """The composable chaos PLANES: each contributes fault points,
    recovery-work ceilings and the HEALTH ladder counter its injected
    losses bump, all merged into ONE seeded cross-domain schedule —
    planes COMPOSE instead of the older mutually-exclusive chaos
    modes. COUNT-based entries only (run_hosts's discipline): total
    disruption is deterministic regardless of corpus size, and the
    end-of-run restore probes run fault-free once the schedule is
    spent. Seed offsets are disjoint per plane so composing planes
    never aliases two RNG streams."""
    from spark_rapids_tpu.tools.loadtest import (
        SERVICE_CHAOS_BOUNDS,
        service_chaos_spec,
    )
    return {
        "host": {
            "spec": ";".join([
                f"host.dispatch:crash:1:{seed * 100 + 11}",
                f"host.shard.land:corrupt:1:{seed * 100 + 12}",
                f"host.dispatch:device_lost:2:{seed * 100 + 13}",
            ]),
            "bounds": {"query_replays": 30, "hostShardRetries": 20,
                       "hostsLost": 10, "fetch_retries": 100},
            "ladder_counter": "hostsLost",
            "description": "executor-host faults: dispatch crash "
                           "(query replay), corrupt shard landing "
                           "(CRC re-land), injected host losses "
                           "walking the host ladder; the scripted "
                           "SIGKILL + rejoin rides on top",
        },
        "mesh": {
            "spec": ";".join([
                f"mesh.gather:corrupt:1:{seed * 100 + 21}",
                f"mesh.gather:device_lost:2:{seed * 100 + 22}",
            ]),
            "bounds": {"query_replays": 30, "shardRetries": 40,
                       "gatherChecksFailed": 40, "fetch_retries": 100},
            "ladder_counter": "meshDeviceLost",
            "description": "mesh-device faults: checksummed-gather "
                           "corruption (re-fetch) and partial device "
                           "losses walking the mesh ladder",
        },
        "memory": {
            "spec": ";".join([
                f"mem.reserve:oom:12:{seed * 100 + 31}",
                f"mem.spill:crash:1:{seed * 100 + 32}",
            ]),
            "bounds": {"query_replays": 30, "oomRetries": 4000,
                       "splitRetries": 200, "budgetRaises": 2000},
            "ladder_counter": "memoryPressureEvents",
            "description": "arbiter pressure under the hard device "
                           "budget: sustained reservation refusals "
                           "(retry -> chunk -> cpu_demote) and a "
                           "spill-path crash",
        },
        "service": {
            "spec": service_chaos_spec(seed),
            "bounds": dict(SERVICE_CHAOS_BOUNDS),
            "ladder_counter": "deviceLost",
            "description": "service-level survivability: worker "
                           "crashes, device losses (backend ladder), "
                           "one wedged dispatch the watchdog must "
                           "hard-time-out",
        },
        "exec": {
            "spec": f"exec.execute:crash:1:{seed * 100 + 41}",
            "bounds": {"query_replays": 30},
            "ladder_counter": None,
            "description": "the seeded kernel/exec schedule: one "
                           "executor crash absorbed by query replay",
        },
    }


def fleet_fault_spec(seed: int) -> str:
    """The merged cross-domain schedule: every plane's points in one
    ``spark.rapids.test.faults`` string."""
    return ";".join(p["spec"] for p in fleet_planes(seed).values())


def fleet_bounds(planes: dict) -> dict:
    """Merged recovery-work ceilings: when two planes bound the same
    counter, the LOOSEST wins — each plane's bound was calibrated for
    its own schedule alone and the merged schedule fires them all."""
    merged = {}
    for plane in planes.values():
        for field, bound in plane["bounds"].items():
            merged[field] = max(bound, merged.get(field, 0))
    return merged


def fleet_plan(nhosts: int, seed: int, tenants: int = 2,
               concurrency: int = 2, budget: int = 0,
               sf: float = 0.02, queries=None) -> dict:
    """The --fleet run plan as a JSON document (what ``--dry-run``
    prints after validating the merged schedule parses): planes,
    merged spec + bounds, topology and tenancy — everything the run
    will arm, with no backend initialization."""
    planes = fleet_planes(seed)
    return {
        "mode": "fleet-plan",
        "hosts": nhosts,
        "tenants": tenants,
        "pools": FLEET_POOLS,
        "concurrency": concurrency,
        "scale_factor": sf,
        "seed": seed,
        "device_budget_bytes": (int(budget) if budget else
                                "auto: 0.6 x measured working-set "
                                "peak"),
        "queries": list(queries) if queries else "q1-q22",
        "planes": {name: {"fault_spec": p["spec"],
                          "bounds": p["bounds"],
                          "ladder_counter": p["ladder_counter"],
                          "description": p["description"]}
                   for name, p in planes.items()},
        "merged_fault_spec": fleet_fault_spec(seed),
        "merged_bounds": fleet_bounds(planes),
        "scripted": {
            "sigkill": "one executor host SIGKILLed mid-run, "
                       "respawned two submissions later; the missed-"
                       "beat sweep must declare it lost and the "
                       "rejoin must restore full strength",
            "wedge_stall_env": "SRT_WEDGE_SLEEP_S armed for the "
                               "service plane's wedged dispatch",
        },
    }


def run_fleet(sf: float, seed: int, nhosts: int, tenants: int = 2,
              concurrency: int = 2, budget: int = 0, queries=None,
              use_sql: bool = False, timeout_s: float = 300.0):
    """``--fleet``: the fleet closure (FLEET_r01) — N executor hosts x
    concurrent tenant pools x a hard device budget x the merged
    cross-domain fault schedule, served through a QueryService that IS
    the cluster driver (scheduler.py configures the shared topology;
    DEGRADED/shedding decisions consult live host strength and arbiter
    occupancy). One run, every plane: a scripted SIGKILL + rejoin,
    injected host/mesh device losses, sustained memory pressure under
    the budget, worker crash / device loss / wedged dispatch.

    Asserts: every submission reaches a terminal state (zero hangs),
    every FINISHED result bit-identical to the fault-free twin (the
    shape baseline at the budget's chunk share; demoted-baseline and
    row-multiset escalation recorded per query), at least one fault
    fired in each of the host/mesh/memory/service domains, per-tenant
    p95 SLOs served from the live ``/slo`` endpoint, one incident
    bundle per tripped ladder action (matched by seq id + faultDomain,
    seq ids unique), recovery within the merged bounds, ZERO lock
    witness violations, and the service back to HEALTHY at the end."""
    _ensure_host_mesh(8)
    import os
    import tempfile
    import urllib.request

    import jax

    from spark_rapids_tpu.columnar.table import evict_device_caches
    from spark_rapids_tpu.datagen import scale_test_specs
    from spark_rapids_tpu.errors import (
        QueryQuarantinedError,
        QueryRejectedError,
    )
    from spark_rapids_tpu.obs.metrics import scopes_snapshot
    from spark_rapids_tpu.parallel.mesh import MESH
    from spark_rapids_tpu.runtime.cluster import CLUSTER, spawn_executor
    from spark_rapids_tpu.runtime.faults import (
        CIRCUIT_BREAKER,
        FAULTS,
        RECOVERY,
    )
    from spark_rapids_tpu.runtime.health import HEALTH
    from spark_rapids_tpu.runtime.memory import MEMORY, forced_chunking
    from spark_rapids_tpu.runtime.spill import BufferCatalog
    from spark_rapids_tpu.service.scheduler import QueryService
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.tools.incident import load_bundles
    from spark_rapids_tpu.tools.loadtest import (
        _CHAOS_TYPED_ERRORS,
        drive_health_probes,
        service_chaos_settings,
        wedge_stall_env,
    )

    ndev = len(jax.devices())
    if ndev % nhosts:
        raise SystemExit(
            f"--fleet with --hosts {nhosts} must divide the "
            f"{ndev}-device pool so every host owns an equal dcn row")
    shape = f"{nhosts}x{ndev // nhosts}"

    planes = fleet_planes(seed)
    spec = fleet_fault_spec(seed)
    bounds = fleet_bounds(planes)

    specs = scale_test_specs(sf)
    tables = {name: s.generate_table(sf, seed=seed)
              for name, s in specs.items()}
    base = tempfile.mkdtemp(prefix="rapids_fleet_")
    paths = write_host_corpus(tables, base, files_per_table=2 * nhosts)
    flight_dir = tempfile.mkdtemp(prefix="rapids_fleet_flightrec_")

    build = build_sql_queries if use_sql else build_queries
    report = {"mode": "fleet", "backend": _resolved_backend(),
              "hosts": nhosts, "n_devices": ndev, "mesh_shape": shape,
              "tenants": tenants, "pools": FLEET_POOLS,
              "concurrency": concurrency,
              "scale_factor": sf, "seed": seed, "sql": use_sql,
              "fault_spec": spec,
              "planes": {name: {"fault_spec": p["spec"],
                                "bounds": p["bounds"]}
                         for name, p in planes.items()},
              "merged_bounds": bounds,
              "flight_recorder_dir": flight_dir,
              "queries": {}}
    failures = []

    driver, executors = _boot_cluster(nhosts)
    BufferCatalog.reset()
    MEMORY.reset()
    try:
        cluster_conf = {
            "spark.rapids.cluster.enabled": "true",
            "spark.rapids.cluster.hosts": str(nhosts),
            "spark.rapids.cluster.heartbeatIntervalMs":
                str(_HOSTS_HEARTBEAT_MS),
            "spark.rapids.cluster.missedBeats":
                str(_HOSTS_MISSED_BEATS),
            "spark.rapids.mesh.enabled": "true",
            "spark.rapids.mesh.shape": shape,
            "spark.rapids.sql.runtimeFallback.enabled": "true",
        }
        # -- fault-free twin (cluster+mesh, UNBUDGETED): the expected
        # results plus the measured working set the budget must sit
        # below for the memory plane to prove anything ------------------
        twin = TpuSession(dict(cluster_conf))
        twin_queries = build(twin, tables, paths=paths)
        wanted = queries or list(twin_queries)
        # the collective-bearing query first (run_mesh_chaos's
        # discipline): the mesh fault points must see gather traffic
        # before the ladder may legitimately shrink the topology
        wanted = sorted(wanted, key=lambda n: (n != "q7",
                                               wanted.index(n)))
        expected_plain = {name: twin_queries[name]().collect_table()
                          for name in wanted}
        working_set = MEMORY.snapshot()["peakBytes"]
        report["working_set_peak_bytes"] = int(working_set)
        if not budget:
            budget = max(4096, int(working_set * 0.6))
        report["device_budget_bytes"] = int(budget)
        if budget >= working_set:
            failures.append(
                f"device budget {budget} is not below the measured "
                f"unbudgeted working-set peak {working_set} — the "
                "fleet run would prove nothing about memory pressure")
        chunk_fraction = 0.1
        chunk_share = max(1, int(budget * chunk_fraction))
        report["chunk_share_bytes"] = chunk_share
        # the SHAPE baseline (run_memory_chaos's discipline): forced
        # chunking at the service's share, still unbudgeted — what a
        # CPU-demoted storm run reproduces (demoted ops bypass the
        # arbiter, so they never split)
        expected_chunked = {}
        with forced_chunking(chunk_share):
            for name in wanted:
                expected_chunked[name] = (
                    twin_queries[name]().collect_table())
        for name in wanted:
            sem = tables_close(expected_plain[name],
                               expected_chunked[name])
            if sem is not None:
                failures.append(f"{name}: chunked twin changed the "
                                f"answer vs unchunked: {sem}")
        evict_device_caches()
        MEMORY.reset()
        # the EXECUTION baseline: the service enforces this budget for
        # real — reserve refusals split batches and the memory ladder's
        # chunk rung may halve a share mid-collect, all deterministic
        # for a serial run but structurally unlike ANY unbudgeted twin.
        # Collect expected results through a session wearing the exact
        # service memory conf so the recovered-fleet wave has a
        # bit-identical reference (and a warm kernel cache: the wave's
        # first on-device query must not pay whole-pipeline compiles
        # inside its hard wall)
        budgeted_twin = TpuSession(dict(
            cluster_conf, **{
                "spark.rapids.memory.device.budgetBytes":
                    str(int(budget)),
                "spark.rapids.memory.device.scanChunkFraction":
                    str(chunk_fraction)}))
        btwin_queries = build(budgeted_twin, tables, paths=paths)
        expected_budgeted = {}
        for name in wanted:
            expected_budgeted[name] = (
                btwin_queries[name]().collect_table())
        for name in wanted:
            sem = tables_close(expected_plain[name],
                               expected_budgeted[name])
            if sem is not None:
                failures.append(f"{name}: budgeted twin changed the "
                                f"answer vs unbudgeted: {sem}")
        # walking the ladder during that collect is expected (the wave
        # walks the same rungs) — but its demotions are the TWIN's, not
        # the service's; record and clear them
        report["budgeted_twin_ladder"] = HEALTH.memory_snapshot()
        report["budgeted_twin_demoted_ops"] = (
            CIRCUIT_BREAKER.demoted_ops())
        CIRCUIT_BREAKER.reset()
        # a fresh ledger + clean caches for the budgeted service phase
        evict_device_caches()
        MEMORY.reset()

        # -- the service AS the cluster driver ---------------------------
        svc_conf = dict(cluster_conf)
        svc_conf.update({
            "spark.rapids.memory.device.budgetBytes": str(int(budget)),
            "spark.rapids.memory.device.scanChunkFraction":
                str(chunk_fraction),
            "spark.rapids.lint.lockWitness": "true",
            # the closure verifies EXECUTION identity: a fingerprint
            # cache hit would replay the storm's (possibly diverged)
            # table straight back to the recovery wave and mask it
            "spark.rapids.service.resultCache.enabled": "false",
            "spark.rapids.service.pools": FLEET_POOLS,
            "spark.rapids.service.maxConcurrentQueries":
                str(concurrency),
            "spark.rapids.service.queueDepth":
                str(max(64, 2 * len(wanted) * tenants)),
            "spark.rapids.service.introspect.enabled": "true",
            "spark.rapids.service.introspect.port": "0",
            "spark.rapids.obs.telemetry.enabled": "true",
            "spark.rapids.obs.telemetry.intervalMs": "200",
            "spark.rapids.obs.flightRecorder.dir": flight_dir,
            "spark.rapids.test.faults": spec,
        })
        svc_conf.update(service_chaos_settings(concurrency))

        recovery_before = RECOVERY.snapshot()
        health_before = HEALTH.snapshot()
        cluster_before = dict(scopes_snapshot().get("cluster", {}))
        mesh_before = dict(scopes_snapshot().get("mesh", {}))
        ladder_before = {
            "host": HEALTH.host_snapshot()["hostsLost"],
            "mesh": HEALTH.mesh_snapshot()["meshDeviceLost"],
            "memory": HEALTH.memory_snapshot()["memoryPressureEvents"],
            "service": health_before["deviceLost"],
        }

        pools_cycle = tuple(
            p.split(":")[0] for p in FLEET_POOLS.split(";"))
        subs = [(name, pools_cycle[(qi + ti) % len(pools_cycle)],
                 f"tenant{ti}")
                for ti in range(tenants)
                for qi, name in enumerate(wanted)]
        kill_at = len(subs) // 3 if len(subs) >= 6 else None
        rejoin_at = kill_at + 2 if kill_at is not None else None
        victim = f"h{nhosts - 1}"
        kill_info = {}
        shed_rejections = [0]
        typed_outcomes = []
        handles = []
        hung = []
        resubmit = []

        def _submit_retry(name, pool, tenant, label):
            """Submit with bounded retry across the DEGRADED shed
            window: a QueryRejectedError is the scheduler pushing back
            on the lowest-weight pool while the fleet is below
            strength — live traffic retries after the hinted delay.
            Quarantine refusals and a still-shed submission after the
            retry budget are TYPED terminal outcomes, not hangs."""
            for _ in range(20):
                try:
                    return svc.submit(svc_queries[name](),
                                      tenant=tenant, pool=pool,
                                      tag=label)
                except QueryRejectedError as exc:
                    shed_rejections[0] += 1
                    delay = (getattr(exc, "retry_after_ms", None)
                             or 250) / 1000.0
                    time.sleep(min(1.0, max(0.05, delay)))
                except QueryQuarantinedError as exc:
                    typed_outcomes.append({
                        "query": label, "state": "QUARANTINED",
                        "error": f"{type(exc).__name__}: {exc}"})
                    return None
            typed_outcomes.append({
                "query": label, "state": "REJECTED",
                "error": "QueryRejectedError: still shed after the "
                         "retry budget"})
            return None

        t0_run = time.perf_counter()
        with wedge_stall_env():
            svc = QueryService(svc_conf)
            try:
                svc_queries = build(svc.session, tables, paths=paths)
                # arm BEFORE the first submit (run_streaming's
                # discipline): per-query re-arms from the same conf
                # string are no-ops, so the one-shot counters survive
                FAULTS.arm(spec)
                for si, (name, pool, tenant) in enumerate(subs):
                    if si == kill_at:
                        # scripted mid-run HOST KILL: a real SIGKILL
                        # while the service is dispatching; the
                        # missed-beat sweep must declare the host lost
                        t0 = time.time()
                        executors[victim].terminate()
                        detected = _wait_for(
                            lambda: victim in CLUSTER.health_snapshot()[
                                "lostHosts"]
                            or victim in CLUSTER.health_snapshot()[
                                "excludedHosts"],
                            timeout_s=30.0)
                        kill_info = {"host": victim, "atSubmission": si,
                                     "detected": detected,
                                     "detectS": round(
                                         time.time() - t0, 3)}
                        if not detected:
                            failures.append(
                                f"SIGKILLed host {victim} never "
                                f"declared lost by the heartbeat sweep")
                    if si == rejoin_at:
                        t0 = time.time()
                        executors[victim] = spawn_executor(
                            driver.address, victim,
                            heartbeat_ms=_HOSTS_HEARTBEAT_MS,
                            mode="process")
                        rejoined = _wait_for(
                            lambda: victim not in
                            CLUSTER.health_snapshot()["lostHosts"]
                            and victim not in
                            CLUSTER.health_snapshot()["excludedHosts"],
                            timeout_s=60.0)
                        kill_info["rejoined"] = rejoined
                        kill_info["rejoinS"] = round(
                            time.time() - t0, 3)
                        if not rejoined:
                            failures.append(
                                f"respawned host {victim} never "
                                f"rejoined the topology")
                    label = f"{name}@{tenant}/{pool}"
                    h = _submit_retry(name, pool, tenant, label)
                    if h is not None:
                        handles.append((name, pool, tenant, label, h))
                    else:
                        # shed/quarantined to exhaustion mid-storm
                        # (recorded typed): owed a clean run on the
                        # recovered fleet below
                        resubmit.append((name, pool, tenant))
                for name, pool, tenant, label, h in handles:
                    if not h.wait(timeout=timeout_s):
                        hung.append(f"{label}: still {h.state} after "
                                    f"{timeout_s}s")
                        failures.append(hung[-1])
                # the count-based schedule is spent: return the
                # topology to full strength
                end_hosts = CLUSTER.health_snapshot()
                if (end_hosts["lostHosts"] or end_hosts["excludedHosts"]
                        or end_hosts["singleProcessReason"]):
                    CLUSTER.restore()
                if MESH.health_snapshot()["excludedDeviceIds"]:
                    MESH.restore("fleet schedule spent; probing full "
                                 "strength")

                # -- mid-storm verdicts (demotion state still live) --
                compare_modes = {}
                finished = 0
                for name, pool, tenant, label, h in handles:
                    if h.state != "FINISHED":
                        if (type(h.error).__name__
                                in _CHAOS_TYPED_ERRORS):
                            typed_outcomes.append({
                                "query": label, "state": h.state,
                                "error": f"{type(h.error).__name__}: "
                                         f"{h.error}",
                                "requeues": h.requeues})
                            resubmit.append((name, pool, tenant))
                            continue
                        failures.append(
                            f"{label}: {h.state} ({h.error})")
                        continue
                    finished += 1
                    got = h.result_table
                    # verdict ladder: the budgeted twin is THE
                    # reference (same memory conf, same splits); a
                    # CPU-demoted storm run bypasses the arbiter and
                    # reproduces the forced-chunk twin instead
                    mode = "bitwise-budgeted-twin"
                    diff = tables_differ(expected_budgeted[name], got)
                    if diff is not None:
                        if tables_differ(expected_chunked[name],
                                         got) is None:
                            diff, mode = None, "bitwise-chunked-twin"
                    if diff is not None and (
                            CIRCUIT_BREAKER.demoted_ops()
                            or HEALTH.state() != "HEALTHY"):
                        # an active demotion changes float reduction
                        # order vs the pre-demotion twin: re-collect
                        # the twin through the SAME demoted plan at
                        # the same chunk share
                        with FAULTS.suspended(), \
                                forced_chunking(chunk_share):
                            redo = twin_queries[name]().collect_table()
                        diff = tables_differ(redo, got)
                        mode = "bitwise-demoted-twin"
                    if diff is not None:
                        # concurrent budgeted execution may emit rows
                        # in a different ORDER (batching under
                        # pressure); every row must still exist
                        # bitwise on both sides
                        if tables_differ_unordered(
                                expected_plain[name], got) is None:
                            diff, mode = None, "row-multiset"
                    if diff is not None:
                        # a demotion that landed MID-query (the
                        # breaker moved while this ran concurrently)
                        # matches no static twin — record the storm
                        # divergence and require the post-recovery
                        # resubmission below to come back bitwise
                        mode = "diverged-mid-storm"
                        resubmit.append((name, pool, tenant))
                    compare_modes[mode] = (
                        compare_modes.get(mode, 0) + 1)
                    entry = report["queries"].setdefault(
                        name, {"runs": []})
                    entry["runs"].append({
                        "tenant": tenant, "pool": pool,
                        "identical": diff is None,
                        "compare_mode": mode,
                        "latencyS": round(h.latency_s, 4),
                        "queueWaitS": round(h.queue_wait_s or 0.0, 4),
                        "requeues": h.requeues})

                # the storm is over: record what it demoted, reset the
                # breaker (run_memory_chaos's discipline — the ladder's
                # deliberate demotions are the STORM's, not the
                # recovered fleet's), and pay the DEGRADED latch down
                # with live probes (what real traffic does)
                report["storm_demoted_ops"] = (
                    CIRCUIT_BREAKER.demoted_ops())
                CIRCUIT_BREAKER.reset()
                probes = 0
                if not hung:
                    probes = drive_health_probes(
                        svc, svc_queries[wanted[0]],
                        timeout_s=timeout_s)
                report["health_probes"] = probes

                # -- post-recovery wave: every shed-rejected or storm-
                # diverged query resubmits against the recovered fleet
                # and must come back FINISHED and bitwise — rejection
                # during the storm is backpressure, not data loss ----
                recovered = 0
                # the recovered-fleet verdict RE-EXECUTES (the result
                # cache is off): drop the storm's cached scan images —
                # built under ladder-forced chunk shares and OOM
                # splits, they would replay storm-era batch structures
                # into the re-scan and diverge the f64 merge order
                evict_device_caches()
                # the storm's schedule is spent and the breaker reset:
                # the recovered-fleet verdict must be about the FLEET,
                # not about a leftover one-shot fault landing on it
                recovery_retries = 0
                with FAULTS.suspended():
                    for name, pool, tenant in resubmit:
                        label = f"{name}@{tenant}/{pool}#recovery"
                        h = None
                        for attempt in range(2):
                            h = _submit_retry(name, pool, tenant, label)
                            if h is None:
                                break
                            if not h.wait(timeout=timeout_s):
                                hung.append(f"{label}: still {h.state} "
                                            f"after {timeout_s}s")
                                failures.append(hung[-1])
                                h = None
                                break
                            if h.state == "FINISHED":
                                break
                            if attempt == 0:
                                # the last storm wedge can still be
                                # sleeping inside an abandoned dispatch
                                # when the wave starts: its zombie
                                # thread drains through the launch gate
                                # and can push the FIRST wave execution
                                # over the hard wall. That is the
                                # watchdog doing its job — the verdict
                                # is whether the fleet serves the
                                # RETRY, not whether the first probe
                                # threads the drain.
                                recovery_retries += 1
                                continue
                            failures.append(f"{label}: {h.state} "
                                            f"({h.error}) on the "
                                            f"recovered fleet")
                            h = None
                        if h is None:
                            if not any(label in f for f in failures):
                                failures.append(
                                    f"{label}: still refused after "
                                    f"recovery")
                            continue
                        # bit-identity against the fault-free twin
                        # wearing the SAME memory conf; the forced-
                        # chunk twin stays a valid secondary identity
                        # (a query whose working set fits never splits)
                        mode = "bitwise-after-recovery"
                        diff = tables_differ(expected_budgeted[name],
                                             h.result_table)
                        if diff is not None and tables_differ(
                                expected_chunked[name],
                                h.result_table) is not None:
                            # the arbiter splits by LIVE occupancy, so
                            # a wave run late in the sequence can chunk
                            # where the pre-storm twin did not — a
                            # fault-free execution the static twins
                            # cannot represent. Re-collect the twin NOW
                            # (same process, same arbiter state): the
                            # service result must be bit-identical to a
                            # fault-free session execution at the same
                            # instant, or the fleet diverged.
                            live = btwin_queries[name]().collect_table()
                            diff = tables_differ(live, h.result_table)
                            mode = "bitwise-live-twin"
                        if diff is not None:
                            failures.append(f"{label}: {diff}")
                            continue
                        recovered += 1
                        compare_modes[mode] = (
                            compare_modes.get(mode, 0) + 1)
                        entry = report["queries"].setdefault(
                            name, {"runs": []})
                        entry["runs"].append({
                            "tenant": tenant, "pool": pool,
                            "identical": True,
                            "compare_mode": mode,
                            "latencyS": round(h.latency_s, 4),
                            "queueWaitS": round(h.queue_wait_s or 0.0, 4),
                            "requeues": h.requeues})
                report["recovery_retries"] = recovery_retries
                report["recovered_after_storm"] = recovered

                svc_health_live = svc.health()
                topo_live = svc.topology_snapshot()
                svc_stats = svc.stats()
                # live HTTP surfaces: the SLOs come from /slo, the
                # shared-topology snapshot from /topology
                url = f"http://127.0.0.1:{svc.introspect_port}"

                def _get(route):
                    with urllib.request.urlopen(url + route,
                                                timeout=30) as resp:
                        return json.loads(resp.read().decode("utf-8"))
                slo = _get("/slo")
                http_topology = _get("/topology")
                http_health = _get("/health")
            finally:
                fires = FAULTS.counters()
                FAULTS.disarm()
                svc.shutdown()
        report["wall_s"] = round(time.perf_counter() - t0_run, 3)

        report["finished"] = finished
        report["compare_modes"] = compare_modes
        report["typed_outcomes"] = typed_outcomes
        report["shed_rejections"] = shed_rejections[0]
        report["submissions"] = len(subs)
        report["hung"] = hung
        if not finished:
            failures.append("no submission FINISHED mid-storm — the "
                            "fleet run proves nothing")
        # every pool must end with served, verified traffic: a pool
        # that only ever shed proved admission control, not serving
        pool_cover = {}
        for entry in report["queries"].values():
            for run in entry["runs"]:
                if run["identical"]:
                    pool_cover[run["pool"]] = (
                        pool_cover.get(run["pool"], 0) + 1)
        report["pool_coverage"] = pool_cover
        for pool in pools_cycle:
            if not pool_cover.get(pool):
                failures.append(
                    f"pool {pool!r} ended with zero verified runs")
        if kill_at is not None:
            report["kill"] = kill_info

        # -- every plane's domain fired ----------------------------------
        domain_fires = {}
        for point, n in fires.items():
            if n:
                d = _fleet_point_domain(point)
                domain_fires[d] = domain_fires.get(d, 0) + n
        report["fault_fires_total"] = {k: v for k, v in
                                       sorted(fires.items()) if v}
        report["domain_fires"] = domain_fires
        for domain in ("host", "mesh", "memory", "service"):
            if not domain_fires.get(domain):
                failures.append(
                    f"no {domain}-domain fault fired — the merged "
                    f"schedule did not cover the {domain} plane")

        # -- recovery within the merged bounds ---------------------------
        recovery = {k: v - recovery_before.get(k, 0)
                    for k, v in RECOVERY.snapshot().items()}
        cluster_after = dict(scopes_snapshot().get("cluster", {}))
        mesh_after = dict(scopes_snapshot().get("mesh", {}))
        for k in ("hostShardRetries", "hostsLost"):
            recovery[k] = int(cluster_after.get(k, 0)
                              - cluster_before.get(k, 0))
        for k in ("shardRetries", "gatherChecksFailed"):
            recovery[k] = int(mesh_after.get(k, 0)
                              - mesh_before.get(k, 0))
        health_after = HEALTH.snapshot()
        recovery["deviceReinits"] = (health_after["deviceReinits"]
                                     - health_before["deviceReinits"])
        for k in ("workersLost", "workersRespawned", "requeued",
                  "hardTimeouts"):
            recovery[k] = svc_stats[k]
        report["recovery"] = {k: v for k, v in sorted(recovery.items())
                              if v}
        for field, bound in bounds.items():
            if recovery.get(field, 0) > bound:
                failures.append(f"{field}={recovery[field]} exceeds "
                                f"the merged fleet bound {bound}")

        # -- ladder actions <-> incident bundles (seq + faultDomain) -----
        ladder_after = {
            "host": HEALTH.host_snapshot()["hostsLost"],
            "mesh": HEALTH.mesh_snapshot()["meshDeviceLost"],
            "memory": HEALTH.memory_snapshot()["memoryPressureEvents"],
            "service": health_after["deviceLost"],
        }
        actions = {d: int(ladder_after[d] - ladder_before[d])
                   for d in ladder_after}
        bundles = (load_bundles(flight_dir)
                   if os.path.isdir(flight_dir) else [])
        seqs = [b["seq"] for b in bundles if "seq" in b]
        ladder_by_domain = {}
        for b in bundles:
            if str(b.get("kind", "")).endswith(".ladder"):
                d = b.get("faultDomain")
                ladder_by_domain[d] = ladder_by_domain.get(d, 0) + 1
        report["incident_bundles"] = {
            "total": len(bundles),
            "ladder_by_domain": ladder_by_domain,
            "ladder_actions": actions,
            "seq_ids_unique": len(seqs) == len(set(seqs)),
        }
        if len(seqs) != len(set(seqs)):
            failures.append("incident bundle seq ids are not unique")
        if len(seqs) != len(bundles):
            failures.append("incident bundle(s) missing the seq id "
                            "(schema 2)")
        for b in bundles:
            if "faultDomain" not in b:
                failures.append(
                    f"incident bundle kind={b.get('kind')} lacks "
                    f"faultDomain")
                break
        for domain, n_actions in actions.items():
            if n_actions and ladder_by_domain.get(domain,
                                                  0) < n_actions:
                failures.append(
                    f"{domain}: only "
                    f"{ladder_by_domain.get(domain, 0)} ladder "
                    f"bundles for {n_actions} ladder actions")
        report["ladders_tripped"] = sorted(
            d for d, n in actions.items() if n)

        # -- per-tenant SLOs from the live /slo endpoint -----------------
        report["slo"] = slo
        if not slo.get("tenants"):
            failures.append("/slo served no per-tenant percentiles")
        for key, tentry in (slo.get("tenants") or {}).items():
            p95 = tentry.get("latency", {}).get("p95S")
            if p95 is None:
                failures.append(f"/slo tenant {key} lacks p95 latency")
            elif p95 > timeout_s:
                failures.append(f"/slo tenant {key} p95 {p95}s "
                                f"exceeds the {timeout_s}s ceiling")
        # the shared-topology path: generation-stamped, served both
        # in-process and over HTTP, fleet reason wired into health()
        report["topology"] = {
            "generation": topo_live["generation"],
            "state": topo_live["state"],
            "hosts": topo_live["hosts"],
        }
        if http_topology.get("generation") is None:
            failures.append("/topology lacks the generation stamp")
        if "fleetDegradedReason" not in http_health:
            failures.append("health() lacks fleetDegradedReason — the "
                            "service is not consulting the fleet "
                            "topology")

        # -- end state: HEALTHY, full strength ---------------------------
        report["service_end"] = {
            "state": svc_health_live["state"],
            "fleetDegradedReason":
                svc_health_live.get("fleetDegradedReason"),
            "workerCount": svc_health_live.get("workerCount"),
        }
        if svc_health_live["state"] != "HEALTHY":
            failures.append(f"service ended "
                            f"{svc_health_live['state']}, not HEALTHY")
        end_hosts = CLUSTER.health_snapshot()
        report["hosts_end_state"] = end_hosts
        if (end_hosts["lostHosts"] or end_hosts["excludedHosts"]
                or end_hosts["singleProcessReason"]):
            failures.append(f"cluster not at full strength at the end: "
                            f"{end_hosts}")
        end_mesh = MESH.health_snapshot()
        if end_mesh["excludedDeviceIds"]:
            failures.append(f"mesh not at full strength at the end: "
                            f"{end_mesh}")
        report["demoted_ops"] = CIRCUIT_BREAKER.demoted_ops()
        report["health_state"] = HEALTH.state()
    finally:
        FAULTS.disarm()
        _teardown_cluster(driver, executors)
    _record_lock_witness(report, failures)
    report["ok"] = not failures
    report["failures"] = failures
    if failures:
        err = AssertionError("fleet run failed:\n"
                             + "\n".join(failures))
        err.report = report
        raise err
    return report


def run_concurrent(sf: float, seed: int, queries=None, use_sql=False,
                   concurrency: int = 4, tenants: int = 2,
                   eventlog_dir=None):
    """Throughput mode (--concurrency without --chaos): run the corpus
    serially for a baseline, then submit every (tenant, query) pair to a
    QueryService and report aggregate wall, speedup, p50/p95 latency,
    queue wait and result-cache hit rate — the same report shape the
    `tools loadtest` CLI emits (tools/loadtest.py does the work)."""
    from spark_rapids_tpu.tools.loadtest import run_loadtest
    return run_loadtest(sf=sf, seed=seed, queries=queries,
                        use_sql=use_sql, concurrency=concurrency,
                        tenants=tenants, eventlog_dir=eventlog_dir)


def streaming_fault_spec(seed: int) -> str:
    """The seeded streaming fault schedule: one scripted mid-micro-batch
    kill per stream — the rate and file-watch streams die after their
    offsets are durably logged but before the batch executes, the CDF
    tail dies inside the harder window (sink commit staged, marker not
    yet written) — plus the rare seeded kernel crash the retry framework
    absorbs transparently."""
    return ";".join([
        "stream.batch@rate:crash:1",
        "stream.batch@files:crash:1",
        "stream.sink.commit@cdf:crash:1",
        f"exec.execute:crash:0.02:{seed * 10 + 9}",
    ])


def _sink_rows(session, path):
    from spark_rapids_tpu.delta.commands import DeltaTable
    return DeltaTable(session, path).to_df().collect_table()


def run_streaming(sf: float = 0.02, seed: int = 7, chaos: bool = False):
    """``--streaming [--chaos]``: rate + file-watch + CDF-tail streams
    over corpus-derived tables, sinking through the exactly-once Delta
    txn protocol, plus two incrementally-maintained MVs (re-aggregate +
    append strategies) refreshed across every commit epoch.

    The fault-free twin runs FIRST (its own QueryService, no faults
    armed) to record the expected sink row sets; the measured side then
    runs under the seeded streaming schedule when ``chaos`` — each
    stream killed once mid-micro-batch and resumed from its checkpoint
    — asserting: every sink row set bit-identical to the twin, every MV
    read bit-identical to a from-scratch recompute at its epoch with
    >= 1 incremental refresh, the service ending HEALTHY, and the
    ``streaming`` metric scope populated (the STREAM_r01 closure)."""
    import os
    import shutil
    import tempfile

    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu.datagen import scale_test_specs
    from spark_rapids_tpu.delta.commands import DeltaTable
    from spark_rapids_tpu.delta.table import write_delta
    from spark_rapids_tpu.io.parquet import write_parquet
    from spark_rapids_tpu.obs.metrics import scopes_snapshot
    from spark_rapids_tpu.ops.expr import col, lit
    from spark_rapids_tpu.plan import nodes as P
    from spark_rapids_tpu.runtime.faults import FAULTS
    from spark_rapids_tpu.service.scheduler import QueryService
    from spark_rapids_tpu.streaming import (
        DeltaCDFSource,
        DeltaStreamSink,
        FileWatchSource,
        RateSource,
        StreamingQuery,
    )

    base = tempfile.mkdtemp(prefix="rapids_streaming_")
    specs = scale_test_specs(sf)
    orders = specs["orders"].generate_table(sf, seed=seed)
    lineitem = specs["lineitem"].generate_table(sf, seed=seed)

    # the file-watch corpus: three contiguous lineitem slices, staged
    # through the transactional parquet writer then renamed into the
    # watched directory (one file per micro-batch at maxFiles=1)
    watch_dir = os.path.join(base, "watch")
    os.makedirs(watch_dir)
    rows_per_file = max(1, min(1500, lineitem.num_rows // 3))
    for i in range(3):
        stage = os.path.join(base, f"stage{i}")
        written = write_parquet(
            lineitem.slice(i * rows_per_file, rows_per_file), stage)
        os.replace(written[0],
                   os.path.join(watch_dir, f"batch-{i:05d}.parquet"))
        shutil.rmtree(stage, ignore_errors=True)

    # the CDF corpus: an orders-derived events table created at version
    # 0, CDF enabled at 1, then two appends the tail consumes
    ev_head = orders.slice(0, max(1, min(1000, orders.num_rows // 2)))
    ev_tail = [orders.slice(1000, 500), orders.slice(1500, 500)] \
        if orders.num_rows >= 2000 else [orders.slice(0, 1)] * 2

    def make_events(session, path):
        write_delta(P.LocalScan([ev_head]), session, path, mode="error")
        DeltaTable(session, path).set_properties(
            {"delta.enableChangeDataFeed": "true"})

    def cdf_transform(df):
        # a projection transform: drop the CDF metadata + date columns
        return df.select(col("o_orderkey"), col("o_custkey"),
                         col("o_totalprice"))

    def drive_streams(svc, tag):
        """Run all three streams to completion on ``svc``; when a
        scripted kill fires, restart the stream from its checkpoint
        (fresh StreamingQuery, same offset log). Returns per-stream
        {killedBy, batches} plus the sink paths."""
        session = svc.session
        events = os.path.join(base, f"{tag}_events")
        make_events(session, events)
        sinks = {name: os.path.join(base, f"{tag}_{name}_sink")
                 for name in ("rate", "files", "cdf")}
        cks = {name: os.path.join(base, f"{tag}_{name}_ck")
               for name in ("rate", "files", "cdf")}

        def mk(name):
            src = {
                "rate": lambda: RateSource(rows_per_batch=500, seed=seed,
                                           total_rows=1500, num_keys=32),
                "files": lambda: FileWatchSource(watch_dir, session.conf,
                                                 max_files_per_trigger=1),
                "cdf": lambda: DeltaCDFSource(events, starting_version=1),
            }[name]()
            return StreamingQuery(
                svc, src, DeltaStreamSink(sinks[name], name), cks[name],
                name=name,
                transform=cdf_transform if name == "cdf" else None)

        last_q = {}

        def drain(name, out):
            q = mk(name)
            try:
                out["batches"] += q.process_available()
            except Exception as e:
                # the scripted mid-micro-batch kill: the batch is
                # pending (offsets logged, no commit marker) — a fresh
                # stream over the same checkpoint resumes exactly-once
                out["killedBy"] = type(e).__name__
                q = mk(name)
                out["batches"] += q.process_available()
            last_q[name] = q

        results = {n: {"killedBy": None, "batches": 0}
                   for n in ("rate", "files", "cdf")}
        drain("rate", results["rate"])
        drain("files", results["files"])
        # the CDF tail interleaves with commits to the events table
        for delta in ev_tail:
            write_delta(P.LocalScan([delta]), session, events,
                        mode="append")
            drain("cdf", results["cdf"])
        for q in last_q.values():
            svc.register_stream(q)
        return results, sinks, events

    report = {"mode": "streaming", "seed": seed, "scale_factor": sf,
              "backend": _resolved_backend(), "chaos": chaos,
              "fault_spec": streaming_fault_spec(seed) if chaos else "",
              "streams": {}, "mvs": {}}
    failures = []

    # -- fault-free twin: records the expected sink row sets -----------------
    FAULTS.disarm()
    twin = QueryService({"spark.rapids.service.maxConcurrentQueries": 2})
    try:
        _, twin_sinks, _ = drive_streams(twin, "twin")
        expected = {name: _sink_rows(twin.session, path)
                    for name, path in twin_sinks.items()}
    finally:
        twin.shutdown()

    # -- measured side: seeded kills (with --chaos), MVs across epochs -------
    conf = {"spark.rapids.service.maxConcurrentQueries": 2,
            # a 500-row orders append touches ~1 group per customer;
            # keep the re-aggregate path open at this corpus scale
            "spark.rapids.streaming.mv.maxTouchedGroups": 2048}
    if chaos:
        conf["spark.rapids.test.faults"] = report["fault_spec"]
        conf["spark.rapids.lint.lockWitness"] = "true"
    svc = QueryService(conf)
    try:
        session = svc.session
        if chaos:
            # arm BEFORE the first stream batch: fault_point fires ahead
            # of the batch's execute (which would otherwise arm from
            # conf too late); same spec string, so per-query re-arms
            # are no-ops and the one-shot kill counters survive
            FAULTS.arm(report["fault_spec"])
        events = os.path.join(base, "mv_events")
        make_events(session, events)
        reg = svc.mv_registry()
        ev_df = DeltaTable(session, events).to_df()
        mv_agg = reg.register(
            "rev_by_cust", ev_df.group_by(col("o_custkey")).agg(
                F.sum(col("o_totalprice")).alias("rev"),
                F.count(col("o_orderkey")).alias("n")))
        mv_proj = reg.register(
            "big_orders", ev_df.filter(
                col("o_totalprice") > lit(250_000.0)).select(
                    col("o_orderkey"), col("o_totalprice")))
        mv_epochs_ok = {m.name: 0 for m in (mv_agg, mv_proj)}

        results, sinks, _ = drive_streams(svc, "run")
        if chaos:
            for name, entry in results.items():
                if entry["killedBy"] is None:
                    failures.append(f"{name}: scripted kill never fired")

        # every commit epoch: each MV read must be bit-identical to a
        # from-scratch recompute of its registered plan at that epoch
        for delta in ev_tail:
            write_delta(P.LocalScan([delta]), session, events,
                        mode="append")
            for mv in (mv_agg, mv_proj):
                diff = tables_differ_unordered(mv.read(),
                                               mv.recompute_at_epoch())
                if diff is not None:
                    failures.append(
                        f"mv {mv.name} diverged at epoch {mv.epoch()}: "
                        f"{diff}")
                else:
                    mv_epochs_ok[mv.name] += 1

        for name, path in sinks.items():
            got = _sink_rows(session, path)
            diff = tables_differ_unordered(expected[name], got)
            entry = dict(results[name])
            entry["rows"] = got.num_rows
            entry["identical"] = diff is None
            if diff is not None:
                failures.append(f"{name}: sink diverged: {diff}")
            report["streams"][name] = entry
            print(json.dumps({"stream": name, **entry}))
        for mv in (mv_agg, mv_proj):
            entry = {"strategy": mv.strategy,
                     "epochsVerified": mv_epochs_ok[mv.name],
                     "incrementalRefreshes": mv.incremental_refreshes,
                     "fullRecomputes": mv.full_recomputes,
                     "lastRefreshMode": mv.last_refresh_mode,
                     "fallbackReason": mv.fallback_reason}
            if mv.incremental_refreshes < 1:
                failures.append(
                    f"mv {mv.name}: no refresh took the incremental "
                    f"path (strategy={mv.strategy})")
            report["mvs"][mv.name] = entry
            print(json.dumps({"mv": mv.name, **entry}))

        health = svc.health()
        report["service"] = {"health": health,
                             "streams": svc.streams()}
        if health["state"] != "HEALTHY":
            failures.append(
                f"service ended {health['state']}, not HEALTHY")
        scope = dict(scopes_snapshot().get("streaming", {}))
        report["streaming_scope"] = scope
        for key in ("microBatches", "sinkCommits", "mvRefreshes",
                    "mvIncrementalRefreshes"):
            if not scope.get(key):
                failures.append(
                    f"streaming scope not populated: {key}="
                    f"{scope.get(key, 0)}")
        if chaos:
            report["fault_fires"] = {
                k: v for k, v in FAULTS.counters().items() if v}
    finally:
        svc.shutdown()
        FAULTS.disarm()
        shutil.rmtree(base, ignore_errors=True)
    _record_lock_witness(report, failures)
    report["ok"] = not failures
    report["failures"] = failures
    if failures:
        err = AssertionError(
            "streaming run failed:\n" + "\n".join(failures))
        err.report = report
        raise err
    return report


#: the harness's supported mode combinations — named in every flag-
#: validation error so a bad invocation is a one-line fix, not an
#: archaeology session through silently-ignored flags
SUPPORTED_MODES = (
    "supported modes: (default timing run) | --cpu-baseline | "
    "--chaos [--concurrency N [--service-faults]] | --concurrency N | "
    "--mesh N [--mesh-shape DxI] [--chaos] | --hosts N [--chaos] | "
    "--streaming [--chaos] | --fleet [--hosts N] [--device-budget B] "
    "[--concurrency N] [--tenants N] [--dry-run]")


def _resolved_backend() -> str:
    """The JAX backend this run actually measured — stamped into every
    report artifact so a CPU-backend number can never masquerade as a
    TPU one (the BENCH_r06 lesson)."""
    import jax
    return jax.default_backend()


def validate_flags(args) -> None:
    """Fail fast on flag combinations the harness does not implement —
    a silently-ignored mode flag reads as a passing run of a contract
    that was never exercised.

    Fault PLANES compose: --fleet (or any two of --hosts /
    --device-budget / --concurrency together) routes to the fleet
    closure, where host, mesh-device, memory, service and exec faults
    merge into one seeded schedule. The single-plane modes keep their
    original harnesses (and their original rejections) — a lone
    --hosts run is still the serial bit-identity harness, not a fleet
    run that happens to have one plane."""
    def bad(msg):
        raise SystemExit(f"{msg} ({SUPPORTED_MODES})")

    fleet = getattr(args, "fleet", False)
    combo = sum(1 for v in (args.hosts, args.device_budget,
                            args.concurrency) if v)
    if fleet or combo >= 2:
        if args.mesh:
            bad("--fleet does not compose with --mesh: the fleet "
                "harness builds its own hierarchical (hosts x "
                "devices-per-host) mesh")
        if args.streaming:
            bad("--fleet does not compose with --streaming: recurring "
                "streams own their kill points; the fleet corpus is "
                "the one-shot q1-q22 set")
        if args.cpu_baseline:
            bad("--fleet does not compose with --cpu-baseline: the "
                "fleet baseline is its own fault-free twin over the "
                "same cluster topology, not the CPU path")
        if args.require_tpu:
            bad("--fleet does not compose with --require-tpu: the "
                "fleet harness pins virtual host-platform (cpu) "
                "devices, and the gate would initialize the backend "
                "before the device-count flag can take effect")
        if args.hosts and args.hosts < 2:
            bad(f"--hosts {args.hosts}: a cluster needs at least 2 "
                "executor hosts")
        if args.device_budget and args.device_budget < 4096:
            bad(f"--device-budget {args.device_budget}: below 4KB not "
                "even a MIN_BUCKET chunk of one column fits")
        return
    if getattr(args, "dry_run", False):
        bad("--dry-run only applies to --fleet: the single-plane "
            "harnesses have no plan document to print")
    if args.mesh:
        if args.mesh < 2:
            bad(f"--mesh {args.mesh}: a mesh needs at least 2 devices")
        if args.concurrency:
            bad("--mesh does not compose with --concurrency: the mesh "
                "harness asserts per-query bit-identity serially")
        if args.service_faults:
            bad("--mesh does not compose with --service-faults: "
                "service-level faults need --chaos --concurrency N")
        if args.cpu_baseline:
            bad("--mesh does not compose with --cpu-baseline: the mesh "
                "baseline is fault-free single-chip, not the CPU path")
        if args.require_tpu:
            bad("--mesh does not compose with --require-tpu: the mesh "
                "harness pins virtual host-platform (cpu) devices, and "
                "the gate would initialize the backend before the "
                "device-count flag can take effect")
    if args.hosts:
        if args.hosts < 2:
            bad(f"--hosts {args.hosts}: a cluster needs at least 2 "
                "executor hosts")
        if args.mesh:
            bad("--hosts does not compose with --mesh: the hosts "
                "harness builds its own hierarchical (hosts x "
                "devices-per-host) mesh")
        if args.concurrency:
            bad("--hosts does not compose with --concurrency: the "
                "hosts harness asserts per-query bit-identity "
                "serially")
        if args.service_faults:
            bad("--hosts does not compose with --service-faults: "
                "service-level faults need --chaos --concurrency N")
        if args.cpu_baseline:
            bad("--hosts does not compose with --cpu-baseline: the "
                "hosts baseline is fault-free single-process over the "
                "same files, not the CPU path")
        if args.require_tpu:
            bad("--hosts does not compose with --require-tpu: the "
                "hosts harness pins virtual host-platform (cpu) "
                "devices, and the gate would initialize the backend "
                "before the device-count flag can take effect")
    if args.device_budget:
        if args.device_budget < 4096:
            bad(f"--device-budget {args.device_budget}: below 4KB not "
                "even a MIN_BUCKET chunk of one column fits")
        if args.mesh or args.hosts:
            bad("--device-budget does not compose with --mesh/--hosts: "
                "the memory harness asserts single-process bit-"
                "identity against unbudgeted execution")
        if args.concurrency or args.service_faults:
            bad("--device-budget does not compose with --concurrency/"
                "--service-faults: the memory harness runs serially "
                "(its own service phase asserts HEALTHY)")
        if args.cpu_baseline:
            bad("--device-budget does not compose with --cpu-baseline: "
                "the memory baseline is unbudgeted device execution, "
                "not the CPU path")
        if args.require_tpu:
            bad("--device-budget does not compose with --require-tpu: "
                "the out-of-core contract is backend-independent and "
                "the artifact records the resolved backend in-band")
    if args.streaming:
        if args.mesh or args.hosts:
            bad("--streaming does not compose with --mesh/--hosts: the "
                "streaming harness drives its own recurring tenants "
                "through a single-process QueryService")
        if args.device_budget:
            bad("--streaming does not compose with --device-budget: "
                "the memory harness runs the one-shot corpus, not "
                "recurring streams")
        if args.concurrency or args.service_faults:
            bad("--streaming does not compose with --concurrency/"
                "--service-faults: streams ARE the concurrent tenants, "
                "and the streaming fault schedule owns the kill points")
        if args.cpu_baseline:
            bad("--streaming does not compose with --cpu-baseline: the "
                "streaming baseline is its own fault-free twin run")
    if args.service_faults and not (args.chaos and args.concurrency > 1):
        bad("--service-faults needs --chaos --concurrency > 1 (the "
            "service fault points live in the worker/watchdog "
            "machinery)")
    if args.cpu_baseline and (args.chaos or args.concurrency):
        bad("--cpu-baseline is a timing-run flag; it does not compose "
            "with --chaos or --concurrency")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=None,
                    help="scale factor (default 0.1; chaos mode defaults "
                         "to 0.02 — it exercises recovery paths, not "
                         "throughput)")
    ap.add_argument("--queries", type=str, default="")
    ap.add_argument("--cpu-baseline", action="store_true")
    ap.add_argument("--sql", action="store_true",
                    help="run the q1-q22 SQL-text forms through "
                         "session.sql() instead of the DataFrame DSL")
    ap.add_argument("--seed", type=int, default=None,
                    help="datagen / fault-schedule seed (default 0; "
                         "chaos mode defaults to 7)")
    ap.add_argument("--out", type=str, default="")
    ap.add_argument("--eventlog-dir", type=str,
                    default="/tmp/rapids_tpu_eventlog/scale",
                    help="directory for the per-query event log the "
                         "offline tools analyze (written by default; "
                         "--no-eventlog disables)")
    ap.add_argument("--no-eventlog", action="store_true",
                    help="disable query event logging")
    ap.add_argument("--chaos", action="store_true",
                    help="run the corpus fault-free and under a seeded "
                         "fault schedule, asserting bit-identical "
                         "results and bounded recovery work")
    ap.add_argument("--concurrency", type=int, default=0,
                    help="run through the QueryService at this worker "
                         "concurrency: with --chaos, the chaotic side "
                         "runs concurrently; alone, emits the loadtest "
                         "throughput/latency report vs the serial "
                         "baseline")
    ap.add_argument("--service-faults", action="store_true",
                    help="with --chaos --concurrency N: extend the "
                         "schedule with service-level faults (worker "
                         "crash, device loss, wedged dispatch) and "
                         "assert the survivability contract — all "
                         "terminal, typed failures only, bounded "
                         "recovery, health back to HEALTHY")
    ap.add_argument("--tenants", type=int, default=2,
                    help="simulated tenants for --concurrency runs")
    ap.add_argument("--mesh", type=int, default=0, metavar="N",
                    help="run the corpus MESH-NATIVE over an N-device "
                         "mesh (virtual host-platform devices unless "
                         "SPARK_RAPIDS_TPU_DRYRUN_REAL=1), asserting "
                         "bit-identity vs single-chip plus per-exchange "
                         "ICI accounting (the MULTICHIP_r06 harness); "
                         "with --chaos, the corpus runs under the "
                         "seeded MESH-fault schedule instead (the "
                         "MULTICHIP_r07 closure)")
    ap.add_argument("--mesh-shape", type=str, default="",
                    help="with --mesh: explicit spark.rapids.mesh.shape "
                         "('8' or '2x4'; default N on one flat axis)")
    ap.add_argument("--hosts", type=int, default=0, metavar="N",
                    help="run the corpus through the MULTI-HOST "
                         "simulation harness: N executor subprocesses "
                         "scan their by-host parquet assignments and "
                         "ship shards over the driver/executor socket "
                         "protocol, the corpus mesh-native on the "
                         "hierarchical (N x dev/N) mesh, asserting "
                         "bit-identity vs single-process over the same "
                         "files; with --chaos, adds the seeded host.* "
                         "fault schedule plus a scripted mid-corpus "
                         "host KILL + rejoin restore (MULTIHOST_r01)")
    ap.add_argument("--device-budget", type=int, default=0,
                    metavar="BYTES",
                    help="run q1-q22 under a hard device-memory budget "
                         "(runtime/memory.py MemoryArbiter) asserting "
                         "bit-identity to unbudgeted execution with "
                         "spillBytes > 0 and zero budget violations; "
                         "with --chaos, adds the seeded mem.* fault "
                         "schedule, the full memory-ladder walk with "
                         "incident bundles, and a HEALTHY service "
                         "closure (OOC_r01)")
    ap.add_argument("--streaming", action="store_true",
                    help="run the streaming + materialized-view harness "
                         "(rate / file-watch / Delta-CDF streams into "
                         "exactly-once Delta sinks, two incrementally-"
                         "maintained MVs verified bit-identical to a "
                         "from-scratch recompute at every epoch); with "
                         "--chaos, each stream is killed once mid-"
                         "micro-batch under the seeded schedule and "
                         "must resume exactly-once (STREAM_r01)")
    ap.add_argument("--fleet", action="store_true",
                    help="the FLEET closure: N executor hosts x "
                         "concurrent tenant pools x a hard device "
                         "budget x the merged cross-domain fault "
                         "schedule (host + mesh + memory + service + "
                         "exec planes COMPOSED), served through a "
                         "QueryService acting as the cluster driver; "
                         "asserts all-terminal, bit-identity vs the "
                         "fault-free twin, per-tenant /slo p95s, one "
                         "incident bundle per ladder action, zero "
                         "lock-witness violations, HEALTHY at the end "
                         "(FLEET_r01); any two of --hosts/"
                         "--device-budget/--concurrency also route "
                         "here")
    ap.add_argument("--dry-run", action="store_true",
                    help="with --fleet: build the run plan, validate "
                         "the merged fault schedule parses, print the "
                         "plan JSON and exit 0 — no backend "
                         "initialization, no cluster boot")
    ap.add_argument("--require-tpu", action="store_true",
                    help="exit non-zero when the resolved JAX backend is "
                         "'cpu' — a perf run that meant to hit the TPU "
                         "must fail loudly, not commit CPU numbers "
                         "(BENCH_r06 did exactly that)")
    args = ap.parse_args()
    validate_flags(args)

    # the require-tpu gate resolves the backend ONLY when asked: an
    # unconditional jax.default_backend() here would initialize the
    # backend before --mesh's _ensure_host_mesh can force the virtual
    # host-device count (the report dicts each stamp _resolved_backend()
    # themselves, after any mesh setup)
    if args.require_tpu:
        from spark_rapids_tpu.tools import require_tpu_backend
        require_tpu_backend()

    fleet_combo = sum(1 for v in (args.hosts, args.device_budget,
                                  args.concurrency) if v)
    if args.fleet or fleet_combo >= 2:
        nhosts = args.hosts or 2
        fleet_tenants = args.tenants or 2
        fleet_conc = args.concurrency or 2
        wanted = [q.strip() for q in args.queries.split(",")
                  if q.strip()]
        seed = args.seed if args.seed is not None else 7
        sf = args.sf if args.sf is not None else 0.02
        if args.dry_run:
            # plan + validate only: parse the merged cross-domain
            # schedule through the real spec parser (no arming, no
            # jax), print the plan, exit 0 — the under-5s smoke
            from spark_rapids_tpu.runtime.faults import parse_fault_spec
            plan = fleet_plan(nhosts, seed, tenants=fleet_tenants,
                              concurrency=fleet_conc,
                              budget=args.device_budget, sf=sf,
                              queries=wanted or None)
            plan["merged_fault_points"] = len(
                parse_fault_spec(plan["merged_fault_spec"]))
            print(json.dumps(plan))
            if args.out:
                with open(args.out, "w") as f:
                    json.dump(plan, f, indent=1)
            return

        def dump_fleet_report(report):
            print(json.dumps(report))
            if args.out:
                with open(args.out, "w") as f:
                    json.dump(report, f, indent=1)

        try:
            report = run_fleet(
                sf=sf, seed=seed, nhosts=nhosts,
                tenants=fleet_tenants, concurrency=fleet_conc,
                budget=args.device_budget, queries=wanted or None,
                use_sql=args.sql)
        except AssertionError as e:
            if getattr(e, "report", None) is not None:
                dump_fleet_report(e.report)
            raise SystemExit(f"FAILED: {e}")
        dump_fleet_report(report)
        return

    if args.streaming:
        def dump_stream_report(report):
            print(json.dumps(report))
            if args.out:
                with open(args.out, "w") as f:
                    json.dump(report, f, indent=1)

        try:
            report = run_streaming(
                sf=args.sf if args.sf is not None else 0.02,
                seed=args.seed if args.seed is not None else 7,
                chaos=args.chaos)
        except AssertionError as e:
            if getattr(e, "report", None) is not None:
                dump_stream_report(e.report)
            raise SystemExit(f"FAILED: {e}")
        dump_stream_report(report)
        return

    if args.device_budget:
        wanted = [q.strip() for q in args.queries.split(",") if q.strip()]

        def dump_memory_report(report):
            print(json.dumps(report))
            if args.out:
                with open(args.out, "w") as f:
                    json.dump(report, f, indent=1)

        try:
            report = run_memory_chaos(
                sf=args.sf if args.sf is not None else 0.02,
                seed=args.seed if args.seed is not None else 7,
                budget=args.device_budget, queries=wanted or None,
                use_sql=args.sql, chaos=args.chaos)
        except AssertionError as e:
            if getattr(e, "report", None) is not None:
                dump_memory_report(e.report)
            raise SystemExit(f"FAILED: {e}")
        dump_memory_report(report)
        return

    if args.hosts:
        wanted = [q.strip() for q in args.queries.split(",") if q.strip()]

        def dump_hosts_report(report):
            print(json.dumps(report))
            if args.out:
                with open(args.out, "w") as f:
                    json.dump(report, f, indent=1)

        try:
            report = run_hosts(
                sf=args.sf if args.sf is not None else (
                    0.02 if args.chaos else 0.05),
                seed=args.seed if args.seed is not None else (
                    7 if args.chaos else 0),
                nhosts=args.hosts, queries=wanted or None,
                use_sql=args.sql, chaos=args.chaos)
        except AssertionError as e:
            if getattr(e, "report", None) is not None:
                dump_hosts_report(e.report)
            raise SystemExit(f"FAILED: {e}")
        dump_hosts_report(report)
        return

    if args.mesh:
        wanted = [q.strip() for q in args.queries.split(",") if q.strip()]

        def dump_mesh_report(report):
            print(json.dumps(report))
            if args.out:
                with open(args.out, "w") as f:
                    json.dump(report, f, indent=1)

        try:
            if args.chaos:
                # mesh + chaos COMPOSED: the corpus mesh-native under
                # the seeded mesh-fault schedule (MULTICHIP_r07)
                report = run_mesh_chaos(
                    sf=args.sf if args.sf is not None else 0.02,
                    seed=args.seed if args.seed is not None else 7,
                    ndev=args.mesh, queries=wanted or None,
                    use_sql=args.sql, shape=args.mesh_shape)
            else:
                report = run_mesh(
                    sf=args.sf if args.sf is not None else 0.05,
                    seed=args.seed if args.seed is not None else 0,
                    ndev=args.mesh, queries=wanted or None,
                    use_sql=args.sql, shape=args.mesh_shape)
        except AssertionError as e:
            # divergence: the failure report carries exactly what we
            # need to debug it — write it before exiting non-zero
            if getattr(e, "report", None) is not None:
                dump_mesh_report(e.report)
            raise SystemExit(f"FAILED: {e}")
        dump_mesh_report(report)
        return

    if args.chaos:
        wanted = [q.strip() for q in args.queries.split(",") if q.strip()]
        report = run_chaos(sf=args.sf if args.sf is not None else 0.02,
                           seed=args.seed if args.seed is not None else 7,
                           queries=wanted or None, use_sql=args.sql,
                           concurrency=args.concurrency,
                           service_faults=args.service_faults)
        print(json.dumps(report))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(report, f, indent=1)
        return
    if args.concurrency and args.concurrency > 1:
        wanted = [q.strip() for q in args.queries.split(",") if q.strip()]
        report = run_concurrent(
            sf=args.sf if args.sf is not None else 0.1,
            seed=args.seed if args.seed is not None else 0,
            queries=wanted or None, use_sql=args.sql,
            concurrency=args.concurrency, tenants=args.tenants,
            eventlog_dir=(None if args.no_eventlog else args.eventlog_dir))
        print(json.dumps(report))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(report, f, indent=1)
        if not report["ok"]:
            raise SystemExit(1)
        return
    if args.sf is None:
        args.sf = 0.1
    if args.seed is None:
        args.seed = 0

    from spark_rapids_tpu.datagen import scale_test_specs
    from spark_rapids_tpu.session import TpuSession

    t0 = time.perf_counter()
    specs = scale_test_specs(args.sf)
    tables = {name: spec.generate_table(args.sf, seed=args.seed)
              for name, spec in specs.items()}
    gen_s = time.perf_counter() - t0

    build = build_sql_queries if args.sql else build_queries
    # event logs on by default so every SCALE artifact is analyzable by
    # `python -m spark_rapids_tpu.tools profile/compare`
    tpu_conf = {}
    if not args.no_eventlog:
        tpu_conf = {"spark.rapids.sql.eventLog.enabled": "true",
                    "spark.rapids.sql.eventLog.dir": args.eventlog_dir}
    tpu = TpuSession(tpu_conf)
    queries = build(tpu, tables)
    wanted = ([q.strip() for q in args.queries.split(",") if q.strip()]
              or list(queries))

    cpu_queries = None
    if args.cpu_baseline:
        cpu = TpuSession({"spark.rapids.sql.enabled": "false"})
        cpu_queries = build(cpu, tables)

    report = {"scale_factor": args.sf, "mode": "sql" if args.sql else "dsl",
              "backend": _resolved_backend(),
              "eventlog_dir": (args.eventlog_dir if not args.no_eventlog
                               else None),
              "datagen_s": round(gen_s, 3),
              "rows": {k: t.num_rows for k, t in tables.items()},
              "queries": {}}
    for name in wanted:
        cold, warm, warm_med = time_query(queries[name], session=tpu,
                                          tag=name)
        entry = {"cold_s": round(cold, 4), "warm_s": round(warm, 4),
                 "warm_med_s": round(warm_med, 4)}
        if cpu_queries is not None:
            _, cpu_warm, cpu_med = time_query(cpu_queries[name], runs=3)
            entry["cpu_warm_s"] = round(cpu_warm, 4)
            entry["cpu_warm_med_s"] = round(cpu_med, 4)
            entry["speedup"] = round(cpu_warm / warm, 3) if warm else None
            entry["speedup_med"] = (round(cpu_med / warm_med, 3)
                                    if warm_med else None)
        report["queries"][name] = entry
        print(json.dumps({"query": name, **entry}))
    import math

    def _geomean(vals):
        return round(math.exp(sum(math.log(x) for x in vals) / len(vals)), 3)

    speedups = [e["speedup"] for e in report["queries"].values()
                if e.get("speedup")]
    if speedups:
        report["geomean_speedup"] = _geomean(speedups)
    med_speedups = [e["speedup_med"] for e in report["queries"].values()
                    if e.get("speedup_med")]
    if med_speedups:
        report["geomean_speedup_med"] = _geomean(med_speedups)
    report["warm_total_s"] = round(
        sum(e["warm_s"] for e in report["queries"].values()), 4)
    report["cold_total_s"] = round(
        sum(e["cold_s"] for e in report["queries"].values()), 4)
    print(json.dumps(report))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)


if __name__ == "__main__":
    main()
