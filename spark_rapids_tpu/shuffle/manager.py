"""MULTITHREADED shuffle manager over local spill files.

Reference (SURVEY.md §2.6): RapidsShuffleInternalManagerBase — the
MULTITHREADED mode (RapidsShuffleThreadedWriterBase :238 /
ReaderBase :613) parallelizes serialization and IO over Spark's sort-shuffle
file layout: per map task ONE data file of concatenated per-partition
segments plus an index of offsets. This module keeps that exact layout
(data + index) with a thread pool for ser/deser, plus optional compression
(TableCompressionCodec analog via zlib/zstd when available).

A shuffle here is: N map outputs (one per input batch) x P reduce
partitions. The reader streams a reduce partition's segments from every map
output, deserializing in parallel, ordered by map id."""

from __future__ import annotations

import concurrent.futures as cf
import os
import tempfile
import threading
import zlib
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional

import numpy as np

from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.conf import (
    RapidsConf,
    SHUFFLE_COMPRESSION_CODEC,
    SHUFFLE_FETCH_BACKOFF_MULT,
    SHUFFLE_FETCH_MAX_RETRIES,
    SHUFFLE_FETCH_RETRY_WAIT_MS,
    SHUFFLE_MT_READER_THREADS,
    SHUFFLE_MT_WRITER_THREADS,
)
from spark_rapids_tpu.errors import (
    ColumnarProcessingError,
    CorruptFrameError,
    MapOutputLostError,
    ShuffleFetchError,
)
from spark_rapids_tpu.runtime.faults import backoff_retry, fault_point
from spark_rapids_tpu.shuffle.serializer import pack_table, unpack_table


def _zstd():
    try:
        import zstandard
        return zstandard
    except ImportError:
        return None


def resolve_codec(requested: str) -> str:
    """Map the requested codec conf to the codec that actually runs, so the
    wire metadata never lies about the on-disk format (ADVICE r1). lz4 runs
    the native C++ block codec (native/lz4codec.cpp), zstd the zstandard
    module; each degrades to zlib only when its backend is unavailable, and
    the RESOLVED name is what gets recorded and used for decompression."""
    if requested in ("none", "zlib"):
        return requested
    if requested == "lz4":
        from spark_rapids_tpu.native import lz4_available
        return "lz4" if lz4_available() else "zlib"
    if requested == "zstd":
        return "zstd" if _zstd() is not None else "zlib"
    raise ColumnarProcessingError(f"unknown shuffle codec {requested}")


def _compress(codec: str, data: bytes) -> bytes:
    if codec == "none":
        return data
    if codec == "zlib":
        return zlib.compress(data, level=1)
    if codec == "lz4":
        # raw LZ4 blocks don't carry the uncompressed size; frame it
        from spark_rapids_tpu.native import lz4_compress
        blob = lz4_compress(data)
        if blob is None:
            raise ColumnarProcessingError("native lz4 codec unavailable")
        return len(data).to_bytes(8, "little") + blob
    if codec == "zstd":
        return _zstd().ZstdCompressor(level=1).compress(data)
    raise ColumnarProcessingError(f"unresolved shuffle codec {codec}")


def _decompress(codec: str, data: bytes) -> bytes:
    if codec == "none":
        return data
    if codec == "zlib":
        return zlib.decompress(data)
    if codec == "lz4":
        from spark_rapids_tpu.native import lz4_decompress
        out = lz4_decompress(bytes(data[8:]),
                             int.from_bytes(bytes(data[:8]), "little"))
        if out is None:
            raise ColumnarProcessingError("native lz4 codec unavailable")
        return out
    if codec == "zstd":
        return _zstd().ZstdDecompressor().decompress(data)
    raise ColumnarProcessingError(f"unresolved shuffle codec {codec}")


def _codec_errors() -> tuple:
    """Exception types a codec raises on CORRUPT input (zlib.error for
    zlib/the degraded paths, ZstdError when zstandard is present,
    ValueError for malformed lz4 framing). Deliberately narrow: a
    programming bug (TypeError, AttributeError) must surface, not burn
    retries and a recompute storm masquerading as data corruption."""
    errors = (zlib.error, ValueError)
    z = _zstd()
    if z is not None:
        errors += (z.ZstdError,)
    return errors


def decode_blob(codec: str, blob) -> HostTable:
    """Decompress + unpack one shuffle blob, normalizing every CORRUPTION
    signal to the retryable CorruptFrameError. For compressed blobs the
    codec error is the ONLY corruption signal — the TPAK CRC sits under
    the compression — so it must not escape the fetch-retry loops as a
    query-fatal exception."""
    try:
        raw = _decompress(codec, blob)
    except _codec_errors() as e:
        raise CorruptFrameError(
            f"corrupt compressed shuffle blob (codec {codec}): {e}") from e
    table, _ = unpack_table(raw)  # CRC-checked; raises CorruptFrameError
    return table


@dataclass
class MapOutput:
    data_path: str
    #: offsets[p] .. offsets[p+1] = partition p's byte range
    offsets: List[int] = field(default_factory=list)


class ShuffleWriteHandle:
    """Writer for one shuffle: each written batch becomes one map output."""

    def __init__(self, shuffle_id: int, num_partitions: int, workdir: str,
                 codec: str, pool: cf.ThreadPoolExecutor):
        self.shuffle_id = shuffle_id
        self.num_partitions = num_partitions
        self.workdir = workdir
        self.codec = codec
        self.pool = pool
        self.map_outputs: List[MapOutput] = []
        self.bytes_written = 0

    def write_partitions(self, partitions: List[HostTable]) -> MapOutput:
        """Serialize per-partition tables (in parallel) and append one map
        output file (data + in-memory index). Serialized bytes are held
        under a host-memory grant until flushed (HostAlloc integration)."""
        if len(partitions) != self.num_partitions:
            raise ColumnarProcessingError("partition count mismatch")
        import time

        from spark_rapids_tpu.obs.metrics import metric_scope
        from spark_rapids_tpu.obs.spans import span
        from spark_rapids_tpu.runtime.host_alloc import HostMemoryArbiter
        codec = self.codec
        grant = HostMemoryArbiter.get().alloc(
            sum(t.nbytes() for t in partitions))
        try:
            t0 = time.perf_counter()
            with span("shuffle.serialize", cat="shuffle"):
                blobs = list(self.pool.map(
                    lambda t: _compress(codec, pack_table(t)), partitions))
            # recorded from the calling thread (worker adds would race)
            metric_scope("shuffle").add("serializeTime",
                                        time.perf_counter() - t0)
        except BaseException:
            grant.release()
            raise
        try:
            map_id = len(self.map_outputs)
            with span("shuffle.write.map", cat="shuffle", map=map_id):
                out = self._write_map_file(map_id, blobs)
            self.map_outputs.append(out)
            self.bytes_written += out.offsets[-1]
            metric_scope("shuffle").add("shuffleBytesWritten",
                                        out.offsets[-1])
            return out
        finally:
            grant.release()

    def _write_map_file(self, map_id: int, blobs, revision: int = 0
                        ) -> MapOutput:
        fault_point("shuffle.write.map")
        suffix = f"_r{revision}" if revision else ""
        path = os.path.join(
            self.workdir,
            f"shuffle_{self.shuffle_id}_{map_id}{suffix}.data")
        offsets = [0]
        with open(path, "wb") as f:
            for b in blobs:
                f.write(b)
                offsets.append(offsets[-1] + len(b))
        return MapOutput(path, offsets)

    def rewrite_map(self, map_id: int, partitions: List[HostTable]
                    ) -> MapOutput:
        """Recompute path: replace one LOST/CORRUPT map output with a
        freshly serialized copy (written to a new revisioned file so
        readers never see a half-rewritten file)."""
        if not 0 <= map_id < len(self.map_outputs):
            raise ColumnarProcessingError(
                f"cannot rewrite unknown map output {map_id}")
        if len(partitions) != self.num_partitions:
            raise ColumnarProcessingError("partition count mismatch")
        # same host-memory grant as write_partitions: recovery runs when
        # the system is already degraded, so it must not overcommit the
        # arbiter's budget either
        from spark_rapids_tpu.runtime.host_alloc import HostMemoryArbiter
        codec = self.codec
        grant = HostMemoryArbiter.get().alloc(
            sum(t.nbytes() for t in partitions))
        try:
            blobs = list(self.pool.map(
                lambda t: _compress(codec, pack_table(t)), partitions))
            old = self.map_outputs[map_id]
            revision = 1
            if "_r" in os.path.basename(old.data_path):
                revision = 1 + int(
                    os.path.basename(old.data_path).rsplit("_r", 1)[1]
                    .split(".")[0])
            out = self._write_map_file(map_id, blobs, revision)
        finally:
            grant.release()
        self.map_outputs[map_id] = out
        try:
            os.unlink(old.data_path)
        except OSError:
            pass
        return out


class ShuffleReadHandle:
    def __init__(self, handle: ShuffleWriteHandle, codec: str,
                 pool: cf.ThreadPoolExecutor,
                 max_retries: int = 3, retry_wait_s: float = 0.05,
                 backoff_mult: float = 2.0):
        self.write_handle = handle
        self.codec = codec
        self.pool = pool
        self.bytes_read = 0
        self.max_retries = max_retries
        self.retry_wait_s = retry_wait_s
        self.backoff_mult = backoff_mult
        self.retry_count = 0

    def _fetch_segment(self, mo: MapOutput, p: int):
        fault_point("shuffle.read.partition")
        start, end = mo.offsets[p], mo.offsets[p + 1]
        if end <= start:
            return None, 0
        size = end - start
        # pinned staging for the compressed read (PinnedMemoryPool):
        # safe only when a decompression copy follows — the codec
        # "none" path would alias the reusable buffer
        pinned = None
        if self.codec != "none":
            from spark_rapids_tpu.runtime.host_alloc import (
                PinnedMemoryPool,
            )
            pool = PinnedMemoryPool.get()
            pinned = pool.acquire(size) if pool is not None else None
        try:
            with open(mo.data_path, "rb") as f:
                f.seek(start)
                if pinned is not None:
                    blob = memoryview(pinned)[:size]
                    f.readinto(blob)
                else:
                    blob = f.read(size)
            # decode INSIDE the pinned scope (decompression copies out);
            # decode_blob normalizes codec errors + CRC mismatches to
            # the retryable CorruptFrameError
            table = decode_blob(self.codec, blob)
        finally:
            if pinned is not None:
                pool.release(pinned)
        return table, size

    def read_partition(self, p: int) -> Iterator[HostTable]:
        """All map outputs' segments for reduce partition p, deserialized in
        parallel, yielded in map order. A retryable failure (corrupt
        frame, torn read, injected fault) replays that map's read with
        exponential backoff; exhaustion raises MapOutputLostError naming
        the map so the exchange recomputes it from lineage."""

        def fetch(args):
            map_id, mo = args

            def note(_exc, _attempt):
                self.retry_count += 1

            try:
                return backoff_retry(
                    lambda: self._fetch_segment(mo, p),
                    max_retries=self.max_retries,
                    wait_s=self.retry_wait_s,
                    backoff_mult=self.backoff_mult,
                    retryable=(ShuffleFetchError, OSError),
                    on_failure=note)
            except (ShuffleFetchError, OSError) as e:
                raise MapOutputLostError(
                    f"map output {map_id} of shuffle "
                    f"{self.write_handle.shuffle_id} unreadable after "
                    f"retries: {e}", map_ids=[map_id]) from e

        from spark_rapids_tpu.obs.metrics import metric_scope
        from spark_rapids_tpu.obs.spans import span
        # materialize INSIDE the span (a span held open across yields
        # would absorb downstream consumer time and leak on
        # abandonment); the only caller buffers the partition anyway —
        # it is the recovery unit
        with span("shuffle.read.partition", cat="shuffle", partition=p):
            results = list(self.pool.map(
                fetch, enumerate(self.write_handle.map_outputs)))
        for t, nbytes in results:
            self.bytes_read += nbytes  # consumer thread only: no races
            if nbytes:
                metric_scope("shuffle").add("shuffleBytesRead", nbytes)
            if t is not None and t.num_rows > 0:
                yield t


class ShuffleManager:
    """Process-wide registry of shuffles (GpuShuffleEnv analog)."""

    def __init__(self, conf: RapidsConf):
        self.conf = conf
        self._lock = threading.Lock()
        self._next_id = 0
        self._shuffles: Dict[int, ShuffleWriteHandle] = {}
        self.workdir = tempfile.mkdtemp(prefix="rapids_tpu_shuffle_")
        self.codec = resolve_codec(
            str(conf.get_entry(SHUFFLE_COMPRESSION_CODEC)).lower())
        self._writer_pool = cf.ThreadPoolExecutor(
            max_workers=max(1, conf.get_entry(SHUFFLE_MT_WRITER_THREADS)),
            thread_name_prefix="shuffle-writer")
        self._reader_pool = cf.ThreadPoolExecutor(
            max_workers=max(1, conf.get_entry(SHUFFLE_MT_READER_THREADS)),
            thread_name_prefix="shuffle-reader")

    def new_shuffle(self, num_partitions: int) -> ShuffleWriteHandle:
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            h = ShuffleWriteHandle(sid, num_partitions, self.workdir,
                                   self.codec, self._writer_pool)
            self._shuffles[sid] = h
            return h

    def reader(self, handle: ShuffleWriteHandle) -> ShuffleReadHandle:
        return ShuffleReadHandle(
            handle, self.codec, self._reader_pool,
            max_retries=int(self.conf.get_entry(SHUFFLE_FETCH_MAX_RETRIES)),
            retry_wait_s=self.conf.get_entry(
                SHUFFLE_FETCH_RETRY_WAIT_MS) / 1000.0,
            backoff_mult=float(self.conf.get_entry(
                SHUFFLE_FETCH_BACKOFF_MULT)))

    def remove_shuffle(self, handle: ShuffleWriteHandle):
        with self._lock:
            self._shuffles.pop(handle.shuffle_id, None)
        for mo in handle.map_outputs:
            try:
                os.unlink(mo.data_path)
            except OSError:
                pass


_MANAGERS: Dict[tuple, ShuffleManager] = {}
_MANAGER_LOCK = threading.Lock()


def get_shuffle_manager(conf: RapidsConf) -> ShuffleManager:
    """One manager per distinct (codec, thread pools) configuration, so a
    session's shuffle settings always take effect."""
    key = (str(conf.get_entry(SHUFFLE_COMPRESSION_CODEC)).lower(),
           conf.get_entry(SHUFFLE_MT_WRITER_THREADS),
           conf.get_entry(SHUFFLE_MT_READER_THREADS),
           conf.get_entry(SHUFFLE_FETCH_MAX_RETRIES),
           conf.get_entry(SHUFFLE_FETCH_RETRY_WAIT_MS),
           conf.get_entry(SHUFFLE_FETCH_BACKOFF_MULT))
    with _MANAGER_LOCK:
        mgr = _MANAGERS.get(key)
        if mgr is None:
            mgr = ShuffleManager(conf)
            _MANAGERS[key] = mgr
        return mgr
