"""Session catalog: temp views, registered file-format tables, and
session-scoped SQL functions.

Reference: Spark's SessionCatalog slice the plugin sees — temp views
resolve before external tables, and ``CREATE TEMP VIEW ... USING fmt``
routes through the data-source API the way ``spark.read.format`` does.
Here file-format tables resolve lazily through the existing provider SPI
(``sources.create_scan``), so every registered connector is reachable
from SQL with no new wiring."""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from spark_rapids_tpu.errors import ColumnarProcessingError


def _invalidate_results(reason: str) -> None:
    """Catalog mutation: cached service results may now resolve names
    to different relations — drop them (service/result_cache.py)."""
    from spark_rapids_tpu.service.result_cache import (
        bump_invalidation_epoch,
    )
    bump_invalidation_epoch(reason)


class SessionCatalog:
    def __init__(self, session):
        self._session = session
        #: name -> PlanNode (shared subtree; plan nodes are not mutated)
        self._views: Dict[str, object] = {}
        #: name -> (fmt, paths, options) resolved lazily via sources SPI
        self._tables: Dict[str, tuple] = {}
        #: name -> expression builder (registered Python UDFs)
        self._functions: Dict[str, Callable] = {}

    # -- temp views ----------------------------------------------------------
    def create_or_replace_temp_view(self, name: str, df) -> None:
        plan = getattr(df, "plan", df)
        # views and registered tables share ONE name space (lookup checks
        # views first): replacing must evict a same-name table entry or
        # the old relation would survive a later DROP of the new one
        self._tables.pop(name.lower(), None)
        self._views[name.lower()] = plan
        _invalidate_results(f"temp view {name!r} (re)defined")

    def drop_temp_view(self, name: str) -> bool:
        dropped = self._views.pop(name.lower(), None) is not None
        if dropped:
            _invalidate_results(f"temp view {name!r} dropped")
        return dropped

    # -- file-format tables (sources SPI) -----------------------------------
    def register_table(self, name: str, fmt: str, *paths,
                       **options) -> None:
        """Register ``name`` as a lazy scan of ``paths`` through the
        external-source provider registry (ExternalSource analog)."""
        self._views.pop(name.lower(), None)
        self._tables[name.lower()] = (fmt, list(paths), dict(options))
        _invalidate_results(f"table {name!r} registered")

    def drop_table(self, name: str) -> bool:
        dropped = self._tables.pop(name.lower(), None) is not None
        if dropped:
            _invalidate_results(f"table {name!r} dropped")
        return dropped

    def list_tables(self) -> List[str]:
        return sorted(set(self._views) | set(self._tables))

    # -- functions -----------------------------------------------------------
    def register_function(self, name: str, builder: Callable) -> None:
        """Make ``builder(*arg_exprs) -> Expression`` callable from SQL
        as ``name(...)`` — e.g. a compiled Python UDF from
        ``spark_rapids_tpu.udf.udf`` or an F-style composition."""
        self._functions[name.lower()] = builder

    def unregister_function(self, name: str) -> bool:
        return self._functions.pop(name.lower(), None) is not None

    def lookup_function(self, name: str) -> Optional[Callable]:
        return self._functions.get(name.lower())

    # -- resolution ----------------------------------------------------------
    def lookup_relation(self, name: str):
        """DataFrame for a temp view or registered table, else None."""
        from spark_rapids_tpu.plan import DataFrame
        key = name.lower()
        plan = self._views.get(key)
        if plan is not None:
            return DataFrame(plan, self._session)
        entry = self._tables.get(key)
        if entry is not None:
            from spark_rapids_tpu.sources import create_scan
            fmt, paths, options = entry
            return DataFrame(
                create_scan(fmt, paths, self._session.conf, **options),
                self._session)
        return None

    def table(self, name: str):
        df = self.lookup_relation(name)
        if df is None:
            raise ColumnarProcessingError(
                f"table or view {name!r} not found "
                f"(known: {self.list_tables()})")
        return df
