"""HostAlloc arbiter + pinned pool + allocator event-handler tests
(reference: HostAlloc.scala, PinnedMemoryPool, DeviceMemoryEventHandler
— SURVEY.md §2.5)."""

import threading
import time

import numpy as np
import pytest

from spark_rapids_tpu.errors import CpuRetryOOM
from spark_rapids_tpu.runtime.host_alloc import (
    HostMemoryArbiter,
    PinnedMemoryPool,
)


def test_alloc_within_budget():
    arb = HostMemoryArbiter(1000)
    with arb.alloc(400):
        with arb.alloc(400):
            assert arb.used_bytes == 800
    assert arb.used_bytes == 0


def test_oversized_single_request_granted():
    arb = HostMemoryArbiter(100)
    g = arb.alloc(1000)  # must not deadlock
    assert arb.used_bytes == 1000
    g.release()
    assert arb.used_bytes == 0


def test_blocked_alloc_wakes_on_release():
    arb = HostMemoryArbiter(1000)
    g = arb.alloc(900)
    got = []

    def worker():
        with arb.alloc(500, timeout_s=5):
            got.append(True)

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.1)
    assert not got  # still blocked
    g.release()
    t.join(timeout=5)
    assert got and arb.blocked_count == 1


def test_exhaustion_raises_cpu_retry_oom():
    arb = HostMemoryArbiter(1000)
    g = arb.alloc(900)
    with pytest.raises(CpuRetryOOM, match="host memory exhausted"):
        arb.alloc(500, timeout_s=0.1)
    g.release()


def test_contention_spills_host_tier_to_disk():
    """Going over budget triggers a host->disk demotion of the spill
    framework's host tier before blocking."""
    import jax.numpy as jnp
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar import DeviceTable, HostTable
    from spark_rapids_tpu.runtime.spill import BufferCatalog, SpillableBatch

    catalog = BufferCatalog.reset(host_limit_bytes=1 << 30)
    host = HostTable.from_pydict({"x": np.arange(1000, dtype=np.int64)})
    sb = SpillableBatch(DeviceTable.from_host(host), catalog)
    sb.spill_to_host()
    assert sb.tier == "HOST"

    arb = HostMemoryArbiter(1000)
    g = arb.alloc(900)
    with pytest.raises(CpuRetryOOM):
        arb.alloc(200, timeout_s=0.05)
    assert arb.spill_triggered_count == 1
    assert sb.tier == "DISK"  # host tier was demoted
    sb.release()
    g.release()
    BufferCatalog.reset()


def test_pinned_pool_acquire_release_and_fallback():
    pool = PinnedMemoryPool(32 << 20, buffer_bytes=8 << 20)  # 4 buffers
    bufs = [pool.acquire(1 << 20) for _ in range(4)]
    assert all(b is not None for b in bufs)
    assert pool.acquire(1 << 20) is None       # exhausted -> fallback
    assert pool.acquire(100 << 20) is None     # oversized -> fallback
    for b in bufs:
        pool.release(b)
    assert pool.acquire(1) is not None
    assert pool.hits == 5 and pool.misses == 2


def test_device_event_handler_stops_after_fruitless_spills():
    from spark_rapids_tpu.runtime.retry import DeviceMemoryEventHandler
    from spark_rapids_tpu.runtime.spill import BufferCatalog
    h = DeviceMemoryEventHandler(BufferCatalog.reset())
    # empty catalog: nothing to spill; first fruitless pass still allows
    # one retry, the second does not
    assert h.on_alloc_failure() is True
    assert h.on_alloc_failure() is False
    assert h.alloc_failure_count == 2
    BufferCatalog.reset()


def test_device_event_handler_spills_and_allows_retry():
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar import DeviceTable, HostTable
    from spark_rapids_tpu.runtime.retry import DeviceMemoryEventHandler
    from spark_rapids_tpu.runtime.spill import BufferCatalog, SpillableBatch

    catalog = BufferCatalog.reset()
    host = HostTable.from_pydict({"x": np.arange(100, dtype=np.int64)})
    sb = SpillableBatch(DeviceTable.from_host(host), catalog)
    h = DeviceMemoryEventHandler(catalog)
    assert h.on_alloc_failure() is True
    assert h.spilled_bytes > 0
    assert sb.tier in ("HOST", "DISK")
    sb.release()
    BufferCatalog.reset()


def test_shuffle_write_uses_arbiter(session):
    from spark_rapids_tpu.columnar import DeviceTable, HostTable
    from spark_rapids_tpu.ops.expr import col
    from spark_rapids_tpu.shuffle.manager import ShuffleManager
    from spark_rapids_tpu.shuffle.partitioning import (
        HashPartitioner,
        split_by_partition,
    )

    arb = HostMemoryArbiter.reset(1 << 30)
    before = arb.alloc_count
    host = HostTable.from_pydict(
        {"k": np.arange(500, dtype=np.int64),
         "v": np.arange(500, dtype=np.int64)})
    dt = DeviceTable.from_host(host)
    mgr = ShuffleManager(session.conf)
    h = mgr.new_shuffle(3)
    h.write_partitions(split_by_partition(
        dt, HashPartitioner([col("k").bind(host.schema())], 3)))
    assert arb.alloc_count == before + 1
    assert arb.used_bytes == 0  # grant released after flush
    mgr.remove_shuffle(h)


def test_pinned_pool_used_by_shuffle_read(session):
    from spark_rapids_tpu.columnar import DeviceTable, HostTable
    from spark_rapids_tpu.ops.expr import col
    from spark_rapids_tpu.shuffle.manager import ShuffleManager
    from spark_rapids_tpu.shuffle.partitioning import (
        HashPartitioner,
        split_by_partition,
    )

    pool = PinnedMemoryPool.initialize(16 << 20, buffer_bytes=8 << 20)
    try:
        conf = session.conf.set(
            "spark.rapids.shuffle.compression.codec", "zstd")
        mgr = ShuffleManager(conf)
        host = HostTable.from_pydict(
            {"k": np.arange(800, dtype=np.int64),
             "v": np.arange(800, dtype=np.int64)})
        dt = DeviceTable.from_host(host)
        h = mgr.new_shuffle(2)
        h.write_partitions(split_by_partition(
            dt, HashPartitioner([col("k").bind(host.schema())], 2)))
        rows = sum(t.num_rows for p in range(2)
                   for t in mgr.reader(h).read_partition(p))
        assert rows == 800
        assert pool.hits > 0          # reads staged through pinned buffers
        assert len(pool._free) == pool.total_buffers  # all released
        mgr.remove_shuffle(h)
    finally:
        PinnedMemoryPool.initialize(0)


def test_pinned_pool_initialize_zero_clears():
    PinnedMemoryPool.initialize(16 << 20)
    assert PinnedMemoryPool.get() is not None
    PinnedMemoryPool.initialize(0)
    assert PinnedMemoryPool.get() is None
