"""Mesh re-land boundary: where sharded residency ends inside a plan.

Mesh-native execution (parallel/mesh.py) lands scan shards per-device
and lets the narrow pipeline — filter/project/masked ops, and the ICI
shuffle exchange — run on the resident shards (GSPMD partitions those
kernels; they are elementwise or pure data movement, so their results
are bitwise independent of the layout). Wide kernels are NOT layout-
independent: a float reduction partitioned over 8 shards accumulates in
a different order than the single-chip kernel, and the contract for
this engine is BIT-IDENTITY with single-chip results (scale_test
--mesh, MULTICHIP_r06). So every wide consumer (aggregate, sort, join,
window, ...) takes its input through a :class:`TpuMeshRelandExec`
boundary inserted at conversion time: one device-side gather (ICI on a
real pod — the host is never touched, pinned by RL-MESH-HOST and the
meshHostUploads counter) that re-lands the shards into the single-
device layout the wide kernel compiles against.

Post-exchange inputs are already per-device (the all-to-all emits each
partition on its owner device), so the boundary is a no-op there — the
distributed path through scan -> narrow ops -> ICI exchange ->
per-partition wide ops pays zero re-lands and zero host transfers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import DeviceTable
from spark_rapids_tpu.dispatch import tpu_jit
from spark_rapids_tpu.execs.base import (
    DeviceToHost,
    HostToDevice,
    InputAdapter,
    TpuExec,
)


def _table_digest(table: DeviceTable):
    """Device-side (row count + checksum) of one table — the TPAK-v2
    validation pair for the re-land gather: an order-independent uint32
    word-sum over every column's data and validity words, the live
    mask, and the row-count scalar. The gather (DeviceTable.unsharded)
    is pure data movement, so the digest of the landed copy must equal
    the digest of the sharded source EXACTLY; integer summation makes
    the GSPMD-partitioned evaluation bitwise equal to the single-device
    one, so one cached kernel (epoch-guarded in parallel/exchange.py —
    a device-loss reinit mid-build must not re-seed the cleared cache)
    serves both sides."""
    from spark_rapids_tpu.parallel.exchange import digest_kernel
    from spark_rapids_tpu.parallel.mesh import wordsum_u32

    key = ("reland-digest", table.schema_key()[0], table.capacity,
           table.live is not None)

    def build():
        def digest(datas, valids, live, nrows):
            acc = nrows.astype(jnp.uint32)
            for d in datas:
                acc = acc + wordsum_u32(d)
            for v in valids:
                acc = acc + wordsum_u32(v)
            if live is not None:
                acc = acc + wordsum_u32(live)
            return acc
        return tpu_jit(digest)

    fn = digest_kernel(key, build)
    return fn(tuple(c.data for c in table.columns),
              tuple(c.validity for c in table.columns),
              table.live, table.nrows_dev)


def _taint_landed(table: DeviceTable) -> DeviceTable:
    """Damage the LANDED copy the way an in-flight gather corruption
    would (validity of slot 0 flips: a row silently becomes null/non-
    null — exactly the class of wrong-results bug the digest exists to
    catch). Driven by the ``mesh.gather`` corrupt kind through a
    sentinel byte: the sharded source is untouched, so the bounded
    re-gather converges."""
    c0 = table.columns[0]
    flipped = c0.with_arrays(
        c0.data, c0.validity.at[0].set(~c0.validity[0]))
    out = DeviceTable(table.names, (flipped,) + tuple(table.columns[1:]),
                      table.nrows_dev, table.capacity, live=table.live)
    out._nrows_host = table._nrows_host
    return out


class TpuMeshRelandExec(TpuExec):
    """Schema-preserving residency boundary: re-lands physically
    sharded batches into the single-device layout (DeviceTable.
    unsharded) so the parent's kernels bitwise-match single-chip
    execution. Transparent to both batch protocols — masked batches
    stay masked (their live mask re-lands with the columns)."""

    def __init__(self, child: TpuExec):
        super().__init__()
        self.children = (child,)
        # mirror the child's protocol so mask-aware parents keep
        # consuming masked batches through the boundary
        self.produces_masked = bool(getattr(child, "produces_masked",
                                            False))

    def output_schema(self):
        return self.children[0].output_schema()

    def execute(self):
        for b in self.children[0].execute():
            yield self._reland(b)

    def execute_masked(self):
        for b in self.children[0].execute_masked():
            yield self._reland(b)

    def _reland(self, table: DeviceTable) -> DeviceTable:
        # count only PHYSICAL gathers: unsharded() also returns a new
        # object when it merely drops a shard_spec descriptor from
        # single-device buffers (1-device mesh) — no data moved there
        from spark_rapids_tpu.runtime.faults import fault_point
        if not (table.physically_sharded() and table.columns):
            return table.unsharded()
        from spark_rapids_tpu.parallel import mesh as PM
        from spark_rapids_tpu.parallel.mesh import MESH_SCOPE, mesh_gather
        self.add_metric("meshRelandRows", table.capacity)
        MESH_SCOPE.add("meshRelandRows", table.capacity)
        # crash / device_lost / slow fire here, BEFORE the gather (the
        # ladder's mesh.gather injection site); corrupt is consumed by
        # the sentinel inside the verified loop below
        fault_point("mesh.gather")
        if not PM.GATHER_VERIFY:
            return table.unsharded()
        # TPAK-v2 gather integrity: (row count + checksum) of the
        # sharded source vs the landed copy, compared in ONE tiny host
        # fetch through the sanctioned gather point. A mismatch is a
        # corrupted shard CAUGHT — re-land from the still-intact
        # sharded source instead of feeding the wide kernel above this
        # boundary silently wrong buffers.
        from spark_rapids_tpu.errors import MeshGatherError
        # the source digest evaluates GSPMD-partitioned on the shards
        # (replicated output); re-land the scalar once so the compare
        # pair below shares one committed device — device-to-device,
        # like the gather it validates
        pre = jax.device_put(_table_digest(table), jax.devices()[0])
        retries = 0
        while True:
            out = table.unsharded()
            if fault_point("mesh.gather", data=b"\x00") != b"\x00":
                out = _taint_landed(out)  # injected in-flight corruption
            post = _table_digest(out)
            # rows=0: a digest-pair compare is validation overhead,
            # not gathered table data — meshGatherRows must keep
            # meaning 'elements gathered'
            pair = mesh_gather(jax.lax.bitcast_convert_type(
                jnp.stack([pre, post]), jnp.int32), rows=0)
            if int(pair[0]) == int(pair[1]):
                return out
            MESH_SCOPE.add("gatherChecksFailed", 1)
            self.add_metric("gatherChecksFailed", 1)
            if retries >= PM.MAX_SHARD_RETRIES:
                raise MeshGatherError(
                    f"mesh re-land gather failed its row-count/checksum "
                    f"validation {retries + 1} times (source digest "
                    f"{int(pair[0])} vs landed {int(pair[1])})")
            retries += 1
            MESH_SCOPE.add("shardRetries", 1)
            self.add_metric("shardRetries", 1)

    def describe(self):
        return "MeshReland"


#: consumers that accept physically sharded input: elementwise /
#: data-movement execs whose results are bitwise layout-independent
#: (GSPMD partitions them across the resident shards), the ICI
#: exchange (it re-shards explicitly via shard_put), and the re-land
#: boundary itself. Everything else sees the single-device layout.
def _shard_safe_consumers() -> tuple:
    from spark_rapids_tpu.execs.basic import TpuFilterExec, TpuProjectExec
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    return (TpuFilterExec, TpuProjectExec, TpuShuffleExchangeExec,
            TpuMeshRelandExec)


def insert_mesh_relands(executable):
    """Conversion-time pass (applied by apply_overrides when mesh-
    native execution is on): wrap the TpuExec children of every
    non-shard-safe consumer in a re-land boundary, and stamp every scan
    with the mesh generation the boundaries were planned against
    (``_mesh_scan_gen`` — execs/basic._scan_sharding). Sharded
    placement is therefore BOUND to the converted tree: an unstamped
    tree (converted with the mesh off) never lands sharded batches even
    if a concurrent session flips the process mesh on mid-query — it
    has no boundaries, so sharded input would let GSPMD repartition a
    wide float kernel and break bit-identity. The boundary is a no-op
    on unsharded batches, so liberal insertion is correct — the
    whitelist only determines where sharded residency may FLOW, and
    default-deny means a new exec is bit-identical by construction
    until it is proven layout-independent."""
    from spark_rapids_tpu.execs.basic import TpuFileScanExec, TpuScanExec
    from spark_rapids_tpu.parallel.mesh import MESH

    safe = _shard_safe_consumers()
    gen = MESH.generation()

    def rec(node):
        if isinstance(node, (TpuScanExec, TpuFileScanExec)):
            node._mesh_scan_gen = gen
        if isinstance(node, DeviceToHost):
            # the root/mid-plan transition gathers to host anyway (the
            # sanctioned materialization point) — sharded input is fine
            rec(node.tpu_exec)
            return
        if isinstance(node, HostToDevice):
            rec(node.cpu_node)
            return
        if isinstance(node, InputAdapter):
            rec(node.source)
            return
        scan_node = getattr(node, "scan_node", None)
        if scan_node is not None:
            rec(scan_node)
        children = tuple(getattr(node, "children", ()) or ())
        if not children:
            return
        if isinstance(node, TpuExec) and not isinstance(node, safe):
            node.children = tuple(
                TpuMeshRelandExec(c)
                if isinstance(c, TpuExec)
                and not isinstance(c, TpuMeshRelandExec) else c
                for c in node.children)
            children = node.children
        for c in children:
            rec(c)

    rec(executable)
    return executable
