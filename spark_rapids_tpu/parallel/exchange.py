"""ICI shuffle exchange: hash-partition rows across a device mesh with ONE
all-to-all collective.

Reference mapping (SURVEY.md §2.6): GpuShuffleExchangeExec's UCX fast path
becomes ``jax.lax.all_to_all`` over the mesh axis — each device bucketizes
its row shard by Spark-exact murmur3 target, pads buckets to the static
shard size, and the collective delivers every device its partition. All
shapes are static (bucket = local shard capacity, the worst case); validity
masks carry the live counts. The plan-integrated entry point is
``MeshExchange`` (used by TpuShuffleExchangeExec when
spark.rapids.shuffle.mode=ICI and the partition count fits the mesh);
the host-file shuffle covers every other case.

String keys hash by their dictionary BYTE matrix (replicated across the
mesh — O(dict) bytes), so Spark-exact murmur3 applies to strings too.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
from spark_rapids_tpu.dispatch import tpu_jit
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.parallel.mesh import MESH_SCOPE, count_mesh_upload
from spark_rapids_tpu.shuffle.hashing import (
    SPARK_SEED,
    murmur3_hash_device,
    string_dict_bytes,
)


def _shard_map():
    from spark_rapids_tpu.shims import get_shim
    return get_shim().shard_map()


def _axis_size(mesh, axis) -> int:
    """Device count of ``axis`` — a single axis name or a tuple of them
    (the hierarchical (dcn, ici) mesh exchanges over both)."""
    if isinstance(axis, tuple):
        n = 1
        for a in axis:
            n *= mesh.shape[a]
        return n
    return mesh.shape[axis]


#: replicated string-dictionary byte matrices, interned by DICTIONARY
#: IDENTITY per device set (the dispatch.device_const pattern lifted to
#: the mesh): repeated exchanges over one dictionary pay the replication
#: upload once. ndarrays are not weakref-able, so the bounded LRU keys
#: on id() and pins the dictionary with a strong reference — the pin is
#: exactly what makes the id key sound (a live object's id can't be
#: reused), and the cap bounds the pinned host memory.
from collections import OrderedDict
from spark_rapids_tpu.lockorder import ordered_lock

_DICT_INTERN: "OrderedDict[int, tuple]" = OrderedDict()
_DICT_INTERN_LOCK = ordered_lock("mesh.dict_intern")
_DICT_INTERN_CAP = 256
#: jitted gather-digest kernels (the TPAK-v2 row-count/checksum
#: validation at mesh gather boundaries — execs/mesh.py and the
#: verified live-count fetch below). Device-referencing once traced,
#: so invalidation clears it with the other two mesh caches — and a
#: publish is epoch-guarded like theirs (a builder that started before
#: clear_mesh_caches ran must not re-seed the cleared cache)
_DIGEST_CACHE: Dict[tuple, object] = {}
#: (id(dict), dev_ids) -> Event while one thread replicates that entry:
#: concurrent first-exchangers over one dictionary wait for the winner
#: instead of each paying the upload (and each counting meshDictInterns/
#: meshHostUploads — the warm-path-zero contract must hold under a
#: concurrent QueryService too)
_DICT_INFLIGHT: dict = {}
#: bumped by clear_mesh_caches (under _DICT_INTERN_LOCK): a builder
#: that started against the pre-invalidation backend must not PUBLISH
#: its entry after the clear — device ids survive a reinit unchanged,
#: so a late insert would permanently re-seed the cache with the dead
#: backend's buffers (the executable cache's generation-stamp-at-
#: re-park contract, applied to these two caches)
_MESH_CACHE_EPOCH = 0


def clear_mesh_caches() -> int:
    """Drop every mesh-exchange cache that references device state: the
    interned replicated dictionary matrices ARE device arrays and a
    MeshExchange instance holds the mesh's Device objects plus a jitted
    program compiled against them. Both key on device IDS, which
    survive a device-loss backend reinit unchanged — without this hook
    a recovered backend would keep serving buffers of the dead one
    (runtime/health.py calls here alongside the exec/kernel/const/scan
    caches) — and the OOM eviction path (runtime/retry.py) frees the
    pinned replicated matrices like any other evictable device cache.
    Returns the number of entries dropped."""
    global _MESH_CACHE_EPOCH
    with _DICT_INTERN_LOCK:
        n = len(_DICT_INTERN)
        _DICT_INTERN.clear()
        n += len(MeshExchange._cache)
        MeshExchange._cache.clear()
        n += len(_DIGEST_CACHE)
        _DIGEST_CACHE.clear()
        # reject in-flight builders' late publishes (their device state
        # predates the invalidation)
        _MESH_CACHE_EPOCH += 1
    return n


def digest_kernel(key: tuple, build):
    """Epoch-guarded intern of one jitted gather-digest kernel: the
    check-then-build-then-publish window is closed the same way the
    other two mesh caches close it — a builder that started before
    clear_mesh_caches ran (a device-loss reinit racing an in-flight
    gather) serves its kernel to THIS caller only and never re-seeds
    the cleared cache with programs traced against the dead backend
    (pinned by a two-thread test)."""
    with _DICT_INTERN_LOCK:
        fn = _DIGEST_CACHE.get(key)
        if fn is not None:
            return fn
        epoch = _MESH_CACHE_EPOCH
    fn = build()
    with _DICT_INTERN_LOCK:
        if epoch == _MESH_CACHE_EPOCH:
            # a concurrent builder may have won; keep one canonical fn
            fn = _DIGEST_CACHE.setdefault(key, fn)
    return fn


def interned_dict_bytes(dictionary: np.ndarray, mesh) -> tuple:
    """(byte_matrix, lengths) of ``dictionary`` as device arrays
    replicated across ``mesh``, interned by dictionary identity. The
    replication happens OUTSIDE the lock (it is the slow part), so a
    per-(dictionary, device set) in-flight marker closes the
    check-then-act window: concurrent first-exchangers wait for the
    winner's entry instead of each paying — and counting — the upload.
    A winner that fails clears its marker in the finally, so a waiter
    loops back, misses, and becomes the uploader itself."""
    from jax.sharding import NamedSharding, PartitionSpec as P_
    dev_ids = tuple(d.id for d in mesh.devices.flat)
    key = id(dictionary)
    flight_key = (key, dev_ids)
    while True:
        with _DICT_INTERN_LOCK:
            entry = _DICT_INTERN.get(key)
            if entry is not None and entry[0] is dictionary:
                _DICT_INTERN.move_to_end(key)
                hit = entry[1].get(dev_ids)
                if hit is not None:
                    return hit
            ev = _DICT_INFLIGHT.get(flight_key)
            if ev is None:
                ev = threading.Event()
                _DICT_INFLIGHT[flight_key] = ev
                break  # this thread replicates
        ev.wait()
    try:
        with _DICT_INTERN_LOCK:
            epoch = _MESH_CACHE_EPOCH
        from spark_rapids_tpu.runtime.faults import fault_point
        fault_point("mesh.dict.upload")
        mat, lens = string_dict_bytes(dictionary)
        rep = NamedSharding(mesh, P_())
        out = (jax.device_put(mat, rep), jax.device_put(lens, rep))
        count_mesh_upload(2)
        MESH_SCOPE.add("meshDictInterns", 1)
        with _DICT_INTERN_LOCK:
            if epoch != _MESH_CACHE_EPOCH:
                # clear_mesh_caches ran mid-build (device-loss reinit):
                # this entry references the dead backend — serve it to
                # THIS caller only, never publish it
                return out
            entry = _DICT_INTERN.get(key)
            if entry is None or entry[0] is not dictionary:
                entry = (dictionary, {})
                _DICT_INTERN[key] = entry
                while len(_DICT_INTERN) > _DICT_INTERN_CAP:
                    _DICT_INTERN.popitem(last=False)
            entry[1][dev_ids] = out
        return out
    finally:
        with _DICT_INTERN_LOCK:
            _DICT_INFLIGHT.pop(flight_key, None)
        ev.set()


def _bucketize(pid, live, ndev: int, cap: int):
    """Per-row scatter target into a (ndev*cap) padded send buffer:
    pid*cap + rank-within-bucket; dead rows drop."""
    spid = jnp.where(live, pid, ndev)
    order = jnp.argsort(spid, stable=True)
    sorted_pid = spid[order]
    idx = jnp.arange(cap, dtype=jnp.int32)
    is_first = jnp.concatenate([jnp.ones(1, jnp.bool_),
                                sorted_pid[1:] != sorted_pid[:-1]])
    run_start = jnp.where(is_first, idx, 0)
    run_start = jax.lax.associative_scan(jnp.maximum, run_start)
    slot_sorted = idx - run_start
    slot = jnp.zeros(cap, jnp.int32).at[order].set(slot_sorted)
    return jnp.where(live, pid * cap + slot, ndev * cap)


class MeshExchange:
    """Plan-integrated all-to-all exchange over a device mesh.

    One instance is built per (mesh, column dtypes, key layout) — the
    jitted shard_map program is cached on the instance. ``run`` takes the
    coalesced input table's column arrays plus the live-row mask and
    returns, per partition, front-compacted output arrays + live counts.
    """

    _cache: Dict[tuple, "MeshExchange"] = {}

    @classmethod
    def get(cls, mesh, col_dtypes: Tuple[str, ...], key_cols: Tuple[int, ...],
            key_dtypes, string_key_shapes: tuple, cap: int,
            axis_name: str = "data"):
        dev_ids = tuple(d.id for d in mesh.devices.flat)
        key = (dev_ids, col_dtypes, key_cols, tuple(map(str, key_dtypes)),
               string_key_shapes, cap, axis_name)
        with _DICT_INTERN_LOCK:
            inst = cls._cache.get(key)
            epoch = _MESH_CACHE_EPOCH
        if inst is None:
            inst = cls(mesh, key_dtypes, axis_name)
            with _DICT_INTERN_LOCK:
                if epoch == _MESH_CACHE_EPOCH:
                    cls._cache[key] = inst
                # else: clear_mesh_caches ran mid-build (device-loss
                # reinit) — the instance holds the dead backend's mesh;
                # serve it to this caller only, never publish
        return inst

    def __init__(self, mesh, key_dtypes, axis_name="data"):
        self.mesh = mesh
        #: a single axis name, or a tuple of names for the hierarchical
        #: (dcn, ici) mesh — the all-to-all then rides the fast inner
        #: axis within each dcn group (one collective, two mesh dims)
        self.axis_name = axis_name
        self.ndev = _axis_size(mesh, axis_name)
        self.key_dtypes = list(key_dtypes)
        self._fn = None

    def _build(self, ncols: int, nkeys: int, has_sbytes: Tuple[bool, ...]):
        from jax.sharding import PartitionSpec as P_

        ndev = self.ndev
        axis = self.axis_name
        key_dts = self.key_dtypes

        def shard_fn(*flat):
            pos = 0
            datas = flat[pos:pos + ncols]; pos += ncols
            valids = flat[pos:pos + ncols]; pos += ncols
            kdatas = flat[pos:pos + nkeys]; pos += nkeys
            kvalids = flat[pos:pos + nkeys]; pos += nkeys
            live = flat[pos]; pos += 1
            sbytes = {}
            for i, has in enumerate(has_sbytes):
                if has:
                    sbytes[i] = (flat[pos], flat[pos + 1])
                    pos += 2
            cap = datas[0].shape[0] if datas else kdatas[0].shape[0]

            keys = [(kdatas[i], kvalids[i], key_dts[i]) for i in range(nkeys)]
            h = murmur3_hash_device(keys, SPARK_SEED, sbytes)
            pid = h % jnp.int32(ndev)
            pid = jnp.where(pid < 0, pid + ndev, pid)
            tgt = _bucketize(pid, live, ndev, cap)

            def exchange(arr):
                """Scatter into the (ndev, cap) send buffer and run the
                all-to-all — trailing dims (the decimal128 two-limb
                layout) ride along, indexed on the row axis only."""
                tail = arr.shape[1:]
                send = jnp.zeros((ndev * cap,) + tail, arr.dtype).at[
                    tgt].set(arr, mode="drop").reshape((ndev, cap) + tail)
                return jax.lax.all_to_all(send, axis, 0, 0).reshape(
                    (ndev * cap,) + tail)

            recv_live = jnp.zeros((ndev * cap,), jnp.bool_).at[tgt].set(
                True, mode="drop").reshape(ndev, cap)
            recv_live = jax.lax.all_to_all(recv_live, axis, 0, 0)

            out_datas, out_valids = [], []
            for d, v in zip(datas, valids):
                out_datas.append(exchange(d))
                out_valids.append(exchange(v))

            # per-shard compaction: received blocks are front-compacted per
            # source device but gapped between blocks; one scatter compacts
            # the whole shard and counts the live rows
            flat_live = recv_live.reshape(ndev * cap)
            cpos = jnp.cumsum(flat_live.astype(jnp.int32)) - 1
            ctgt = jnp.where(flat_live, cpos, ndev * cap)
            n_live = jnp.sum(flat_live.astype(jnp.int32))
            comp_d, comp_v = [], []
            for d, v in zip(out_datas, out_valids):
                comp_d.append(jnp.zeros_like(d).at[ctgt].set(d, mode="drop"))
                comp_v.append(jnp.zeros_like(v).at[ctgt].set(v, mode="drop"))
            return tuple(comp_d) + tuple(comp_v) + (n_live[None],)

        n_row_args = 2 * ncols + 2 * nkeys + 1
        in_specs = [P_(axis)] * n_row_args
        for has in has_sbytes:
            if has:
                in_specs += [P_(), P_()]  # replicated dictionary bytes
        out_specs = [P_(axis)] * (2 * ncols) + [P_(axis)]
        sm = _shard_map()
        return tpu_jit(sm(shard_fn, mesh=self.mesh,
                          in_specs=tuple(in_specs),
                          out_specs=tuple(out_specs)))

    def run(self, datas, valids, key_datas, key_valids, live,
            string_bytes: Optional[Dict[int, tuple]] = None):
        """All arrays are GLOBAL row arrays (length divisible by the mesh
        size). Returns (out_datas, out_valids, counts) where each output is
        global with per-device shards front-compacted and ``counts`` holds
        one live count per partition."""
        from jax.sharding import NamedSharding, PartitionSpec as P_

        from spark_rapids_tpu.parallel.mesh import shard_put

        string_bytes = string_bytes or {}
        has_sbytes = tuple(i in string_bytes for i in range(len(key_datas)))
        if self._fn is None:
            self._fn = self._build(len(datas), len(key_datas), has_sbytes)
        sharding = NamedSharding(self.mesh, P_(self.axis_name))
        rep = NamedSharding(self.mesh, P_())
        # shard_put counts host uploads: on a warm mesh query every
        # input is already device-resident (scans landed sharded, the
        # previous exchange's outputs never left the device), so the
        # puts below are device-side reshards only
        flat = [shard_put(x, sharding)
                for x in (*datas, *valids, *key_datas, *key_valids, live)]
        for i, has in enumerate(has_sbytes):
            if has:
                mat, lens = string_bytes[i]
                flat.append(shard_put(mat, rep))
                flat.append(shard_put(lens, rep))
        # the collective's fault site: crash exercises the replay path,
        # device_lost the partial-loss degradation ladder; corrupt is
        # consumed by the verified counts fetch below (it needs bytes)
        from spark_rapids_tpu.runtime.faults import fault_point
        fault_point("mesh.ici.exchange")
        # cross-HOST marker: when this exchange's mesh spans more than
        # one cluster host group the all-to-all crosses the DCN axis —
        # the host.dcn.exchange fault point fires there (device_lost
        # raises HostLostError into the host ladder) and dcnExchanges
        # counts (runtime/cluster.py; no-op without an active cluster)
        from spark_rapids_tpu.runtime.cluster import dcn_exchange_point
        dcn_exchange_point(self.mesh)
        out = self._fn(*flat)
        ncols = len(datas)
        return (list(out[:ncols]), list(out[ncols:2 * ncols]),
                self._verified_counts(out[2 * ncols]))

    def _verified_counts(self, counts_dev):
        """The ONE host materialization an ICI exchange pays — the
        per-partition live counts (they double as the AQE map-output
        statistic) — fetched CHECKSUMMED (TPAK-v2 pattern): a device-
        side uint32 word-sum digest rides the same fetch, the host
        recomputes it over the fetched bytes, and a mismatch (a
        corrupted wire fetch; the ``mesh.ici.exchange`` corrupt kind
        injects exactly this) refetches the intact device value —
        bounded by spark.rapids.mesh.maxShardRetries, counted in
        gatherChecksFailed/shardRetries — instead of feeding AQE and
        the batch slicer garbage counts."""
        import jax

        from spark_rapids_tpu.errors import MeshGatherError
        from spark_rapids_tpu.parallel import mesh as PM
        from spark_rapids_tpu.parallel.mesh import mesh_gather, wordsum_u32
        from spark_rapids_tpu.runtime.faults import fault_point

        if not PM.GATHER_VERIFY:
            return mesh_gather(counts_dev)
        counts_i32 = counts_dev.astype(jnp.int32).reshape(-1)
        digest = jax.lax.bitcast_convert_type(
            wordsum_u32(counts_i32), jnp.int32).reshape(1)
        packed = jnp.concatenate([counts_i32, digest])
        retries = 0
        while True:
            # count the COUNTS as gathered elements, not the digest
            # word riding along (meshGatherRows stays comparable with
            # pre-verification artifact rounds)
            arr = mesh_gather(packed,
                              rows=int(packed.shape[0]) - 1).astype(np.int32)
            raw = fault_point("mesh.ici.exchange", data=arr.tobytes())
            arr = np.frombuffer(raw, dtype=np.int32)
            counts, got = arr[:-1], arr[-1:].view(np.uint32)[0]
            want = np.uint32(
                counts.view(np.uint32).sum(dtype=np.uint64) & 0xFFFFFFFF)
            if got == want:
                return counts
            MESH_SCOPE.add("gatherChecksFailed", 1)
            if retries >= PM.MAX_SHARD_RETRIES:
                raise MeshGatherError(
                    f"ICI exchange live-count fetch failed its checksum "
                    f"{retries + 1} times (device digest {int(got)} vs "
                    f"recomputed {int(want)})")
            retries += 1
            MESH_SCOPE.add("shardRetries", 1)


def mesh_hash_exchange(mesh, dtypes: Sequence[T.DataType],
                       key_idx: Sequence[int], axis_name: str = "data"):
    """Back-compat wrapper over MeshExchange for non-string columns where
    the hash keys are table columns (older tests / dryrun helper)."""
    dts = list(dtypes)
    kset = list(key_idx)

    def run(datas: List[jax.Array], valids: List[jax.Array]):
        ex = MeshExchange(mesh, [dts[i] for i in kset], axis_name)
        live = jnp.ones(datas[0].shape[0], jnp.bool_)
        out_d, out_v, counts = ex.run(
            datas, valids, [datas[i] for i in kset],
            [valids[i] for i in kset], live)
        ndev = mesh.shape[axis_name]
        cap = datas[0].shape[0] // ndev
        out_live = []
        shard = ndev * cap
        liv = np.zeros(ndev * shard, dtype=bool)
        for d in range(ndev):
            liv[d * shard:d * shard + int(counts[d])] = True
        return out_d, out_v, jnp.asarray(liv)

    return run


def mesh_partial_then_merge(mesh, axis_name: str = "data"):
    """Partial-aggregate-per-shard + psum merge (the distributed two-phase
    GpuHashAggregate shape); used by the multichip dry run."""
    from jax.sharding import PartitionSpec as P_

    def build(local_fn):
        def wrapper(*args):
            partial_out = local_fn(*args)
            return jax.tree.map(lambda x: jax.lax.psum(x, axis_name),
                                partial_out)

        sm = _shard_map()
        return tpu_jit(sm(wrapper, mesh=mesh,
                          in_specs=P_(axis_name), out_specs=P_()))
    return build
