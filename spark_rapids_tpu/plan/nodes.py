"""CPU physical plan nodes with Spark-exact execution.

Reference analog: the Spark physical operators that GpuOverrides walks
(ProjectExec, FilterExec, HashAggregateExec, SortExec, *Join*Exec,
ShuffleExchangeExec ... — SURVEY.md §2.3 / Appendix B). Here they double as
the fallback implementations."""

from __future__ import annotations

import os

from dataclasses import dataclass
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.ops import aggregates as agg
from spark_rapids_tpu.ops.expr import (
    Alias,
    Expression,
    bind,
    evaluate_cpu,
    output_name,
)

Schema = List[Tuple[str, T.DataType]]


class PlanNode:
    children: Tuple["PlanNode", ...] = ()

    def output_schema(self) -> Schema:
        raise NotImplementedError

    def execute_cpu(self) -> Iterator[HostTable]:
        raise NotImplementedError

    def estimate_bytes(self) -> Optional[int]:
        """Rough output-size upper bound for physical planning (broadcast
        vs shuffle — the stats the reference reads from Spark's logical
        plan). None = unknown. Row-preserving/shrinking unary nodes
        propagate their child's estimate."""
        return None

    @property
    def name(self) -> str:
        return type(self).__name__

    def collect_cpu(self) -> HostTable:
        batches = list(self.execute_cpu())
        if not batches:
            return _empty_table(self.output_schema())
        return HostTable.concat(batches)

    def tree_string(self, indent: int = 0) -> str:
        s = "  " * indent + self.describe() + "\n"
        for c in self.children:
            s += c.tree_string(indent + 1)
        return s

    def describe(self) -> str:
        return self.name


def _empty_table(schema: Schema) -> HostTable:
    cols = []
    for _, dt in schema:
        if isinstance(dt, T.StringType):
            cols.append(HostColumn(dt, np.array([], dtype=object), np.array([], dtype=np.bool_)))
        else:
            cols.append(HostColumn(dt, np.array([], dtype=dt.np_dtype), np.array([], dtype=np.bool_)))
    return HostTable([n for n, _ in schema], cols)


class LocalScan(PlanNode):
    """In-memory scan over pre-built host batches (test/demo source; file
    scans live in io/)."""

    def __init__(self, batches: Sequence[HostTable]):
        if not batches:
            raise ColumnarProcessingError("LocalScan needs at least one batch")
        self.batches = list(batches)

    def output_schema(self):
        return self.batches[0].schema()

    def execute_cpu(self):
        yield from self.batches

    def describe(self):
        return f"LocalScan[{len(self.batches)} batches]"

    def estimate_bytes(self):
        return sum(b.nbytes() for b in self.batches)


class RangeNode(PlanNode):
    """spark.range analog (reference: GpuRangeExec)."""

    def __init__(self, start: int, end: int, step: int = 1, batch_rows: int = 1 << 20,
                 name: str = "id"):
        self.start, self.end, self.step = start, end, step
        self.batch_rows = batch_rows
        self.col_name = name

    def output_schema(self):
        return [(self.col_name, T.LONG)]

    def execute_cpu(self):
        total = max(0, -(-(self.end - self.start) // self.step))
        pos = 0
        while pos < total:
            cnt = min(self.batch_rows, total - pos)
            vals = self.start + (pos + np.arange(cnt, dtype=np.int64)) * self.step
            yield HostTable([self.col_name], [HostColumn(T.LONG, vals)])
            pos += cnt

    def describe(self):
        return f"Range({self.start}, {self.end}, {self.step})"


class Project(PlanNode):
    def __init__(self, child: PlanNode, exprs: Sequence[Expression]):
        from spark_rapids_tpu.ops.collections import Explode

        def _no_generators(e, top=False):
            if isinstance(e, Explode) and not top:
                raise ColumnarProcessingError(
                    "generators (explode/posexplode) are only valid as "
                    "top-level select expressions (Spark rule); use "
                    "df.select(..., F.explode(col))")
            for c in e.children:
                _no_generators(c)

        for e in exprs:
            # Alias(Explode) and bare Explode at top level are rewritten to
            # Generate by DataFrame.select BEFORE Project sees them; any
            # generator reaching here is misplaced
            _no_generators(e)
        self.children = (child,)
        schema = child.output_schema()
        self.exprs = [bind(e, schema) for e in exprs]
        self.names = [output_name(e, f"col{i}") for i, e in enumerate(exprs)]

    @property
    def child(self):
        return self.children[0]

    def output_schema(self):
        return [(n, e.data_type) for n, e in zip(self.names, self.exprs)]

    def execute_cpu(self):
        for batch in self.child.execute_cpu():
            yield evaluate_cpu(self.exprs, batch, self.names)

    def describe(self):
        return f"Project{self.names}"

    def estimate_bytes(self):
        # projections can WIDEN rows (duplicated/derived columns); scale the
        # child estimate by the column-count ratio so the broadcast
        # threshold check stays an upper-bound-ish heuristic
        est = self.children[0].estimate_bytes()
        if est is None:
            return None
        n_in = max(len(self.children[0].output_schema()), 1)
        return int(est * max(len(self.names), 1) / n_in) \
            if len(self.names) > n_in else est


class Filter(PlanNode):
    def __init__(self, child: PlanNode, condition: Expression):
        self.children = (child,)
        self.condition = bind(condition, child.output_schema())

    @property
    def child(self):
        return self.children[0]

    def output_schema(self):
        return self.children[0].output_schema()

    def execute_cpu(self):
        for batch in self.children[0].execute_cpu():
            pred = self.condition.eval_cpu(batch)
            keep = pred.validity & pred.data.astype(np.bool_)
            idx = np.nonzero(keep)[0]
            cols = []
            for c in batch.columns:
                cols.append(HostColumn(c.dtype, c.data[idx], c.validity[idx]))
            yield HostTable(batch.names, cols)

    def describe(self):
        return f"Filter[{self.condition!r}]"

    def estimate_bytes(self):
        return self.children[0].estimate_bytes()


class Aggregate(PlanNode):
    """Hash aggregate (group-by or global)."""

    def __init__(self, child: PlanNode, grouping: Sequence[Expression],
                 aggregates: Sequence[Expression]):
        self.children = (child,)
        schema = child.output_schema()
        self.grouping = [bind(g, schema) for g in grouping]
        self.agg_specs: List[Tuple[str, agg.AggregateFunction]] = []
        for i, a in enumerate(aggregates):
            name = output_name(a, f"agg{i}")
            fn = a.children[0] if isinstance(a, Alias) else a
            if not isinstance(fn, agg.AggregateFunction):
                raise ColumnarProcessingError(f"not an aggregate: {a!r}")
            bound = bind(fn, schema)
            self.agg_specs.append((name, bound))
        self.grouping_names = [output_name(g, f"k{i}") for i, g in enumerate(self.grouping)]

    @property
    def child(self):
        return self.children[0]

    def output_schema(self):
        out = [(n, g.data_type) for n, g in zip(self.grouping_names, self.grouping)]
        out += [(n, fn.data_type) for n, fn in self.agg_specs]
        return out

    def execute_cpu(self):
        from spark_rapids_tpu.plan.cpu_agg import aggregate_cpu
        table = self.children[0].collect_cpu()
        yield aggregate_cpu(table, self.grouping, self.agg_specs)

    def describe(self):
        return f"Aggregate[keys={self.grouping_names}, aggs={[n for n, _ in self.agg_specs]}]"


@dataclass
class SortOrder:
    expr: Expression
    ascending: bool = True
    nulls_first: Optional[bool] = None  # Spark default: asc->first, desc->last

    def resolved_nulls_first(self) -> bool:
        return self.ascending if self.nulls_first is None else self.nulls_first


def _stable_sort_indices(cols: List[HostColumn], orders: List[SortOrder], n: int) -> np.ndarray:
    """Multi-key stable sort: apply keys least-significant first; each key is
    reduced to a dense integer rank (works for strings too, and makes
    descending order stable), with nulls ranked before/after all values per
    the order's nulls_first."""
    idx = np.arange(n)
    for col, order in reversed(list(zip(cols, orders))):
        if isinstance(col.dtype, T.StringType):
            vals = np.where(col.validity, col.data, "")
        else:
            vals = col.data
        sub_vals = vals[idx]
        sub_valid = col.validity[idx]
        uniq = np.unique(sub_vals)
        rank = np.searchsorted(uniq, sub_vals).astype(np.int64)
        if not order.ascending:
            rank = len(uniq) - 1 - rank
        null_rank = -1 if order.resolved_nulls_first() else len(uniq)
        rank = np.where(sub_valid, rank, null_rank)
        idx = idx[np.argsort(rank, kind="stable")]
    return idx


class Sort(PlanNode):
    def __init__(self, child: PlanNode, orders: Sequence[SortOrder], global_sort: bool = True):
        self.children = (child,)
        schema = child.output_schema()
        self.orders = [SortOrder(bind(o.expr, schema), o.ascending, o.nulls_first) for o in orders]
        self.global_sort = global_sort

    @property
    def child(self):
        return self.children[0]

    def output_schema(self):
        return self.children[0].output_schema()

    def execute_cpu(self):
        table = self.children[0].collect_cpu()
        n = table.num_rows
        key_cols = [o.expr.eval_cpu(table) for o in self.orders]
        idx = _stable_sort_indices(key_cols, self.orders, n)
        cols = [HostColumn(c.dtype, c.data[idx], c.validity[idx]) for c in table.columns]
        yield HostTable(table.names, cols)

    def describe(self):
        return f"Sort[{len(self.orders)} keys]"

    def estimate_bytes(self):
        return self.children[0].estimate_bytes()


class Limit(PlanNode):
    def __init__(self, child: PlanNode, limit: int):
        self.children = (child,)
        self.limit = limit

    def output_schema(self):
        return self.children[0].output_schema()

    def execute_cpu(self):
        remaining = self.limit
        for batch in self.children[0].execute_cpu():
            if remaining <= 0:
                return
            if batch.num_rows <= remaining:
                remaining -= batch.num_rows
                yield batch
            else:
                yield batch.slice(0, remaining)
                return

    def describe(self):
        return f"Limit[{self.limit}]"

    def estimate_bytes(self):
        return self.children[0].estimate_bytes()


class Union(PlanNode):
    def __init__(self, children: Sequence[PlanNode]):
        self.children = tuple(children)
        s0 = self.children[0].output_schema()
        for c in self.children[1:]:
            if [dt for _, dt in c.output_schema()] != [dt for _, dt in s0]:
                raise ColumnarProcessingError("UNION schema mismatch")

    def output_schema(self):
        return self.children[0].output_schema()

    def execute_cpu(self):
        for c in self.children:
            yield from c.execute_cpu()


class Expand(PlanNode):
    """Rollup/cube support: replicate each input row through N projections
    (reference: GpuExpandExec)."""

    def __init__(self, child: PlanNode, projections: Sequence[Sequence[Expression]],
                 names: Sequence[str]):
        self.children = (child,)
        schema = child.output_schema()
        self.projections = [[bind(e, schema) for e in proj] for proj in projections]
        self.names = list(names)

    def output_schema(self):
        return [(n, e.data_type) for n, e in zip(self.names, self.projections[0])]

    def execute_cpu(self):
        for batch in self.children[0].execute_cpu():
            for proj in self.projections:
                yield evaluate_cpu(proj, batch, self.names)


class WindowNode(PlanNode):
    """Appends window-function columns (reference: GpuWindowExec appends
    window expressions to the child's output)."""

    def __init__(self, child: PlanNode, window_cols: Sequence[Tuple[str, "object"]]):
        self.children = (child,)
        schema = child.output_schema()
        self.window_cols = [(name, w.bind(schema)) for name, w in window_cols]

    def output_schema(self):
        return (self.children[0].output_schema()
                + [(n, w.data_type) for n, w in self.window_cols])

    def execute_cpu(self):
        from spark_rapids_tpu.ops.window import eval_window_cpu
        table = self.children[0].collect_cpu()
        cols = list(table.columns)
        names = list(table.names)
        for name, w in self.window_cols:
            cols.append(eval_window_cpu(table, w))
            names.append(name)
        yield HostTable(names, cols)

    def describe(self):
        return f"Window[{[n for n, _ in self.window_cols]}]"


class Join(PlanNode):
    """Equi-join (hash join analog). Types: inner, left, right, full, leftsemi,
    leftanti, cross."""

    def __init__(self, left: PlanNode, right: PlanNode, join_type: str,
                 left_keys: Sequence[Expression], right_keys: Sequence[Expression],
                 condition: Optional[Expression] = None):
        self.children = (left, right)
        self.join_type = join_type
        ls, rs = left.output_schema(), right.output_schema()
        self.left_keys = [bind(k, ls) for k in left_keys]
        self.right_keys = [bind(k, rs) for k in right_keys]
        self.condition = bind(condition, ls + rs) if condition is not None else None

    def output_schema(self):
        ls = self.children[0].output_schema()
        rs = self.children[1].output_schema()
        if self.join_type in ("leftsemi", "leftanti"):
            return ls
        return ls + rs

    def execute_cpu(self):
        from spark_rapids_tpu.plan.cpu_join import join_cpu
        left = self.children[0].collect_cpu()
        right = self.children[1].collect_cpu()
        yield join_cpu(left, right, self.join_type, self.left_keys,
                       self.right_keys, self.condition)

    def describe(self):
        return f"Join[{self.join_type}]"


class Generate(PlanNode):
    """Generator node (explode/posexplode [outer]) — reference:
    GpuGenerateExec.scala. Output = child columns + [pos] + element column;
    non-outer drops rows with null/empty arrays, outer emits one null row."""

    def __init__(self, child: PlanNode, gen_child: Expression,
                 pos: bool, outer: bool, out_names: Sequence[str],
                 required: Optional[Sequence[str]] = None):
        self.children = (child,)
        schema = child.output_schema()
        self.gen_child = bind(gen_child, schema)
        if not isinstance(self.gen_child.data_type, T.ArrayType):
            raise ColumnarProcessingError(
                f"explode input must be an array, got "
                f"{self.gen_child.data_type.simple_string()}")
        self.pos = pos
        self.outer = outer
        self.out_names = list(out_names)
        # requiredChildOutput pruning (Spark Generate): only child columns
        # consumers actually reference pass through
        names = [n for n, _ in schema]
        self.required = [n for n in names
                         if required is None or n in set(required)]

    def output_schema(self):
        child_schema = dict(self.children[0].output_schema())
        out = [(n, child_schema[n]) for n in self.required]
        i = 0
        if self.pos:
            out.append((self.out_names[i], T.INT))
            i += 1
        out.append((self.out_names[i], self.gen_child.data_type.element_type))
        return out

    def execute_cpu(self):
        for full in self.children[0].execute_cpu():
            arr = self.gen_child.eval_cpu(full)
            keep = [full.names.index(n) for n in self.required]
            batch = HostTable([full.names[i] for i in keep],
                              [full.columns[i] for i in keep])
            e_dt = self.gen_child.data_type.element_type
            rows_idx, poss, vals, vvalid, pvalid = [], [], [], [], []
            # iterate the FULL batch: the pruned pass-through table may
            # have zero columns (explode with nothing else selected),
            # which would read as zero rows
            for i in range(full.num_rows):
                if arr.validity[i] and len(arr.data[i]):
                    for k, v in enumerate(arr.data[i]):
                        rows_idx.append(i)
                        poss.append(k)
                        vals.append(v if v is not None else 0)
                        vvalid.append(v is not None)
                        pvalid.append(True)
                elif self.outer:
                    rows_idx.append(i)
                    poss.append(0)
                    vals.append(0)
                    vvalid.append(False)
                    pvalid.append(False)  # pos null ONLY on outer null rows
            idx = np.asarray(rows_idx, dtype=np.int64)
            cols = [HostColumn(c.dtype, c.data[idx], c.validity[idx])
                    for c in batch.columns]
            names = list(batch.names)
            i = 0
            if self.pos:
                pv = np.asarray(poss, dtype=np.int32)
                cols.append(HostColumn(
                    T.INT, pv, np.asarray(pvalid, dtype=np.bool_)))
                names.append(self.out_names[i])
                i += 1
            cols.append(HostColumn(
                e_dt, np.asarray(vals, dtype=e_dt.np_dtype),
                np.asarray(vvalid, dtype=np.bool_)))
            names.append(self.out_names[i])
            yield HostTable(names, cols)

    def describe(self):
        kind = ("posexplode" if self.pos else "explode") + \
            ("_outer" if self.outer else "")
        return f"Generate[{kind}({self.gen_child!r})]"


class Sample(PlanNode):
    """Bernoulli sample without replacement (reference: GpuSampleExec /
    Spark SampleExec). Deterministic per (seed, row position)."""

    def __init__(self, child: PlanNode, fraction: float, seed: int = 0):
        self.children = (child,)
        self.fraction = float(fraction)
        self.seed = int(seed)

    def output_schema(self):
        return self.children[0].output_schema()

    def execute_cpu(self):
        rng = np.random.default_rng(self.seed)
        for batch in self.children[0].execute_cpu():
            keep = rng.random(batch.num_rows) < self.fraction
            idx = np.nonzero(keep)[0]
            yield HostTable(batch.names,
                            [HostColumn(c.dtype, c.data[idx], c.validity[idx])
                             for c in batch.columns])

    def describe(self):
        return f"Sample[fraction={self.fraction}, seed={self.seed}]"


class TakeOrderedAndProject(PlanNode):
    """ORDER BY ... LIMIT n (+ optional projection) — reference:
    GpuTakeOrderedAndProjectExec: per-batch top-k, then merge."""

    def __init__(self, child: PlanNode, orders: Sequence["SortOrder"],
                 limit: int, project: Optional[Sequence[Expression]] = None):
        self.children = (child,)
        schema = child.output_schema()
        self.orders = [SortOrder(bind(o.expr, schema), o.ascending,
                                 o.nulls_first) for o in orders]
        self.limit = int(limit)
        self.project = ([bind(e, schema) for e in project]
                        if project is not None else None)
        self.project_names = ([output_name(e, f"col{i}")
                               for i, e in enumerate(project)]
                              if project is not None else None)

    def output_schema(self):
        if self.project is None:
            return self.children[0].output_schema()
        return [(n, e.data_type)
                for n, e in zip(self.project_names, self.project)]

    def execute_cpu(self):
        table = self.children[0].collect_cpu()
        cols = [o.expr.eval_cpu(table) for o in self.orders]
        perm = _stable_sort_indices(cols, self.orders, table.num_rows)
        take = perm[:self.limit]
        out = HostTable(table.names,
                        [HostColumn(c.dtype, c.data[take], c.validity[take])
                         for c in table.columns])
        if self.project is None:
            yield out
        else:
            yield evaluate_cpu(self.project, out, self.project_names)

    def describe(self):
        return f"TakeOrderedAndProject[limit={self.limit}]"


class WindowGroupLimit(PlanNode):
    """Pre-window group-limit (reference: GpuWindowGroupLimitExec, Spark
    3.5's WindowGroupLimit): when a rank()/row_number()/dense_rank()
    column is filtered to <= k right above the window, at most k(+ties)
    rows per partition need to ENTER the window at all. This node is a
    pure optimization — the exact filter stays above — so the CPU path
    is a passthrough and the device exec prunes."""

    def __init__(self, child: PlanNode, partition_exprs, orders,
                 rank_kind: str, limit: int):
        self.children = (child,)
        self.partition_exprs = list(partition_exprs)
        self.orders = list(orders)
        self.rank_kind = rank_kind  # rownumber | rank | denserank
        self.limit = int(limit)

    def output_schema(self):
        return self.children[0].output_schema()

    def execute_cpu(self):
        yield from self.children[0].execute_cpu()

    def describe(self):
        return f"WindowGroupLimit[{self.rank_kind} <= {self.limit}]"


class CollectLimit(PlanNode):
    """LIMIT without ordering (reference: GpuCollectLimitExec)."""

    def __init__(self, child: PlanNode, limit: int):
        self.children = (child,)
        self.limit = int(limit)

    def output_schema(self):
        return self.children[0].output_schema()

    def execute_cpu(self):
        remaining = self.limit
        for batch in self.children[0].execute_cpu():
            if remaining <= 0:
                return
            take = min(batch.num_rows, remaining)
            yield batch.slice(0, take)
            remaining -= take

    def describe(self):
        return f"CollectLimit[{self.limit}]"


class CachedRelation(PlanNode):
    """df.cache(): lazily materializes the child ONCE (through the full
    engine when a session is attached) and serves the result from memory;
    re-uploads hit the scan device cache, so repeated queries stay device-
    resident (reference: InMemoryTableScanExec + GpuInMemoryTableScan)."""

    def __init__(self, child: PlanNode, session=None):
        self.children = (child,)
        self._session = session
        self._table: Optional[HostTable] = None

    def materialize(self) -> HostTable:
        if self._table is None:
            if self._session is not None:
                self._table = self._session.execute(self.children[0])
            else:
                self._table = self.children[0].collect_cpu()
        return self._table

    def output_schema(self):
        return self.children[0].output_schema()

    def execute_cpu(self):
        yield self.materialize()

    def estimate_bytes(self):
        if self._table is not None:
            return self._table.nbytes()
        return self.children[0].estimate_bytes()

    def describe(self):
        state = "materialized" if self._table is not None else "lazy"
        return f"CachedRelation[{state}]"


class WriteFiles(PlanNode):
    """Data-writing command (reference: GpuDataWritingCommandExec +
    GpuFileFormatDataWriter): runs the child (on device when convertible —
    this node itself stays host-side like the reference's write encode),
    writes files under the TRANSACTIONAL commit protocol
    (io/committer.py: stage into _temporary/<job>/<attempt>/, atomic
    per-file promotion at task commit, a _SUCCESS MANIFEST at job
    commit, full rollback on abort), and returns one stats row
    (numFiles, numRows, numBytes).

    The job id is fixed at plan time, so re-executing the SAME node —
    the query service's worker-loss/device-loss replay resubmits the
    handle's original plan — is idempotent: a rerun that finds its own
    job id in the destination manifest returns the recorded stats
    instead of writing twice; a rerun after a mid-write crash
    re-stages and re-promotes the same deterministic filenames."""

    def __init__(self, child: PlanNode, fmt: str, path: str,
                 partition_by: Optional[Sequence[str]] = None,
                 options: Optional[dict] = None):
        import uuid as _uuid
        self.children = (child,)
        self.fmt = fmt
        self.path = path
        self.partition_by = list(partition_by) if partition_by else None
        self.options = dict(options or {})
        #: idempotency key: stable across replays of this plan node
        self.job_id = _uuid.uuid4().hex[:16]
        self._attempt = 0

    def output_schema(self):
        return [("numFiles", T.LONG), ("numRows", T.LONG),
                ("numBytes", T.LONG)]

    def _writer(self):
        from spark_rapids_tpu import io as _io_pkg
        return {
            "parquet": _io_pkg.write_parquet,
            "orc": _io_pkg.write_orc,
            "csv": _io_pkg.write_csv,
            "json": _io_pkg.write_json,
            "hive_text": _io_pkg.write_hive_text,
        }[self.fmt]

    def _stats_row(self, num_files: int, num_rows: int, num_bytes: int):
        return HostTable(
            ["numFiles", "numRows", "numBytes"],
            [HostColumn(T.LONG, np.asarray([num_files], dtype=np.int64)),
             HostColumn(T.LONG, np.asarray([num_rows], dtype=np.int64)),
             HostColumn(T.LONG, np.asarray([num_bytes], dtype=np.int64))])

    def execute_cpu(self):
        from spark_rapids_tpu.io.committer import WriteJob, read_manifest

        # exactly-once replay: this job already committed (the service
        # requeued a write whose worker died AFTER job commit) — serve
        # the manifest's stats, do not double-write
        manifest = read_manifest(self.path)
        if manifest is not None and manifest.get("jobId") == self.job_id:
            yield self._stats_row(manifest["numFiles"],
                                  manifest["numRows"],
                                  manifest["numBytes"])
            return

        table = self.children[0].collect_cpu()
        job = WriteJob(self.path, job_id=self.job_id,
                       attempt=self._attempt)
        self._attempt += 1
        try:
            self._writer()(table, self.path,
                           partition_by=self.partition_by,
                           committer=job, **self.options)
            final_files = job.commit_task()
            manifest = job.commit_job(num_rows=table.num_rows)
        except BaseException:
            # any failure — injected fault, device loss mid-drain of a
            # downstream re-read, a full disk — rolls the job back:
            # promoted files deleted, staging swept
            job.abort()
            raise
        yield self._stats_row(len(final_files), table.num_rows,
                              manifest["numBytes"])

    def describe(self):
        part = f", partitionBy={self.partition_by}" if self.partition_by else ""
        return f"WriteFiles[{self.fmt} -> {self.path}{part}]"


class Exchange(PlanNode):
    """Shuffle exchange placeholder: single-process CPU path is pass-through;
    the TPU path repartitions batches (parallel/exchange.py)."""

    def __init__(self, child: PlanNode, partitioning: str, num_partitions: int,
                 keys: Sequence[Expression] = ()):
        self.children = (child,)
        self.partitioning = partitioning
        self.num_partitions = num_partitions
        schema = child.output_schema()
        self.keys = [bind(k, schema) for k in keys]

    def output_schema(self):
        return self.children[0].output_schema()

    def execute_cpu(self):
        yield from self.children[0].execute_cpu()

    def describe(self):
        return f"Exchange[{self.partitioning}, n={self.num_partitions}]"
