"""Transactional write path: commit protocol, exactly-once under kill,
Delta commit retry/conflicts, vacuum (io/committer.py, delta/table.py
OptimisticTransaction, tools vacuum).

The full seeded corpus is ``python scale_test.py --chaos`` (run_write_chaos);
this tier-1 slice pins every contract on small frames:
* staged writes + atomic promotion + the _SUCCESS manifest;
* a killed write leaves old data untouched and sweeps staging;
* reruns and runtime-fallback replays converge exactly-once;
* a requeued service write is idempotent by job uuid;
* Delta blind appends rebase through the retry loop, true conflicts
  raise typed, failed transactions sweep their orphans;
* vacuum (library + CLI, dry-run default) reports/removes orphans.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from spark_rapids_tpu.io.committer import (
    TEMP_DIR,
    WRITE_METRICS,
    WriteJob,
    read_manifest,
    sweep_active_jobs,
)
from spark_rapids_tpu.plan import nodes as P
from spark_rapids_tpu.runtime.faults import CIRCUIT_BREAKER, FAULTS
from spark_rapids_tpu.session import TpuSession


@pytest.fixture(autouse=True)
def _clean_fault_state():
    FAULTS.disarm()
    CIRCUIT_BREAKER.reset()
    yield
    FAULTS.disarm()
    CIRCUIT_BREAKER.reset()


def _df(s, n=40):
    return s.create_dataframe({
        "k": [f"k{i % 3}" for i in range(n)],
        "v": list(range(n))})


def _visible_parts(path):
    """Files a scan would list (hidden files/dirs pruned)."""
    out = []
    for root, dirs, files in os.walk(path):
        dirs[:] = [d for d in dirs if not d.startswith(("_", "."))]
        out.extend(f for f in files if not f.startswith(("_", ".")))
    return sorted(out)


# -- commit protocol ---------------------------------------------------------

def test_write_commits_manifest(session, tmp_path):
    out = str(tmp_path / "t")
    stats = _df(session).write_parquet(out).to_pydict()
    m = read_manifest(out)
    assert m is not None and m["numFiles"] == stats["numFiles"][0]
    assert m["numRows"] == stats["numRows"][0] == 40
    assert m["numBytes"] == stats["numBytes"][0] > 0
    assert sorted(m["files"]) == _visible_parts(out)
    assert m["jobId"]
    assert not os.path.exists(os.path.join(out, TEMP_DIR))


def test_standalone_writer_commits(tmp_path):
    """Direct write_csv (no session) runs the whole protocol itself."""
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.io.csv import write_csv
    out = str(tmp_path / "c")
    files = write_csv(HostTable.from_pydict({"a": [1, 2, 3]}), out)
    assert files == [os.path.join(out, "part-00000.csv")]
    assert os.path.exists(files[0])
    assert read_manifest(out)["files"] == ["part-00000.csv"]
    assert not os.path.exists(os.path.join(out, TEMP_DIR))


# -- exactly-once under kills ------------------------------------------------

@pytest.mark.chaos
def test_kill_mid_file_write_aborts_clean(tmp_path):
    s = TpuSession({
        "spark.rapids.test.faults": "io.write.file:crash:1",
        "spark.rapids.sql.runtimeFallback.enabled": "false"})
    out = str(tmp_path / "k")
    df = _df(s)
    node = P.WriteFiles(df.plan, "parquet", out, ["k"], {})
    with pytest.raises(Exception):
        s.execute(node)
    # nothing reader-visible, no marker, staging swept
    assert _visible_parts(out) == []
    assert read_manifest(out) is None
    assert not os.path.exists(os.path.join(out, TEMP_DIR))
    # rerun: the armed count is spent; the SAME plan converges
    s.execute(node)
    clean = str(tmp_path / "clean")
    _df(s).write_parquet(clean, partition_by=["k"])
    assert sorted(s.read_parquet(out).collect(), key=repr) == \
        sorted(s.read_parquet(clean).collect(), key=repr)


@pytest.mark.chaos
def test_kill_mid_task_commit_rolls_back_promoted(tmp_path):
    """A crash DURING promotion (some files already renamed into place)
    must roll the promoted subset back — readers never see a partial
    job."""
    s = TpuSession({
        "spark.rapids.test.faults": "io.write.commit:crash:2",
        "spark.rapids.sql.runtimeFallback.enabled": "false"})
    out = str(tmp_path / "p")
    df = _df(s)  # 3 partitions -> 3 files, crash on the SECOND rename
    node = P.WriteFiles(df.plan, "parquet", out, ["k"], {})
    with pytest.raises(Exception):
        s.execute(node)
    assert _visible_parts(out) == []
    assert not os.path.exists(os.path.join(out, TEMP_DIR))


@pytest.mark.chaos
def test_crash_mid_write_replays_exactly_once(tmp_path):
    """With the runtime-fallback replay armed (the default), a crash
    mid-write replays transparently and the committed output is
    exactly-once — no doubled or torn files."""
    s = TpuSession({"spark.rapids.test.faults": "io.write.file:crash:1"})
    out = str(tmp_path / "r")
    stats = _df(s).write_parquet(out, partition_by=["k"]).to_pydict()
    assert (s.last_fault_replays or 0) == 1
    m = read_manifest(out)
    assert m["numFiles"] == stats["numFiles"][0] == 3
    assert _visible_parts(out) == sorted(
        os.path.basename(f) for f in m["files"])
    assert s.read_parquet(out).count() == 40


@pytest.mark.chaos
def test_killed_overwrite_keeps_old_data_visible(tmp_path):
    out = str(tmp_path / "o")
    clean = TpuSession()
    _df(clean, 10).write_parquet(out)
    before = sorted(clean.read_parquet(out).collect())
    s = TpuSession({
        "spark.rapids.test.faults": "io.write.file:crash:1",
        "spark.rapids.sql.runtimeFallback.enabled": "false"})
    with pytest.raises(Exception):
        s.execute(P.WriteFiles(_df(s).plan, "parquet", out, None, {}))
    # the reader's view is EXACTLY the old data
    assert sorted(clean.read_parquet(out).collect()) == before


def test_abort_mid_promotion_restores_clobbered_originals(tmp_path):
    """An overwrite whose promotion clobbers an earlier job's files at
    the SAME relative paths, then dies partway: abort must RESTORE the
    originals from backup — unlinking them would destroy the only copy
    of committed data the old manifest still references."""
    out = str(tmp_path / "c")
    os.makedirs(out)
    for rel in ("part-00000.parquet", "part-00001.parquet"):
        with open(os.path.join(out, rel), "w") as f:
            f.write(f"OLD:{rel}")
    job = WriteJob(out)
    for rel in ("part-00000.parquet", "part-00001.parquet"):
        with open(job.stage_path(rel), "w") as f:
            f.write(f"NEW:{rel}")
    # first file promoted OVER the original, then the job dies before
    # the rest (partial promotion is exactly the dangerous window)
    job._staged, rest = job._staged[:1], job._staged[1:]
    job.commit_task()
    assert open(os.path.join(out, "part-00000.parquet")).read() == \
        "NEW:part-00000.parquet"
    job._staged = rest
    job.abort()
    for rel in ("part-00000.parquet", "part-00001.parquet"):
        assert open(os.path.join(out, rel)).read() == f"OLD:{rel}"
    assert not os.path.exists(os.path.join(out, TEMP_DIR))


def test_requeued_write_idempotent_by_job_uuid(tmp_path):
    """Re-executing the SAME WriteFiles node (what the query service's
    worker-loss replay does) after a committed job serves the manifest
    stats and writes nothing."""
    s = TpuSession()
    out = str(tmp_path / "i")
    node = P.WriteFiles(_df(s).plan, "parquet", out, None, {})
    r1 = s.execute(node).to_pydict()
    f = os.path.join(out, "part-00000.parquet")
    mtime = os.path.getmtime(f)
    before = WRITE_METRICS["filesWritten"]
    r2 = s.execute(node).to_pydict()
    assert r1 == r2
    assert WRITE_METRICS["filesWritten"] == before
    assert os.path.getmtime(f) == mtime


@pytest.mark.chaos
def test_partitioned_write_fires_fault_point(tmp_path):
    """The io.write.file point fires on the PARTITIONED branch too —
    it used to fire only on single-file writes, leaving dynamic
    partition writes invisible to the chaos harness."""
    s = TpuSession({
        "spark.rapids.test.faults": "io.write.file:crash:1",
        "spark.rapids.sql.runtimeFallback.enabled": "false"})
    with pytest.raises(Exception):
        _df(s).write_parquet(str(tmp_path / "f"), partition_by=["k"])
    assert FAULTS.counters().get("io.write.file") == 1


def test_crash_handler_sweep_clears_staging(tmp_path):
    out = str(tmp_path / "s")
    job = WriteJob(out)
    staged = job.stage_path("part-00000.parquet")
    with open(staged, "w") as f:
        f.write("torn")
    assert sweep_active_jobs() >= 1
    assert not os.path.exists(os.path.join(out, TEMP_DIR))
    assert sweep_active_jobs() == 0  # job unregistered


# -- listing hygiene (io/common.py satellite) --------------------------------

def test_expand_paths_prunes_hidden_dirs_and_files(tmp_path):
    from spark_rapids_tpu.io.common import expand_paths
    d = tmp_path / "data"
    (d / TEMP_DIR / "job1" / "0").mkdir(parents=True)
    (d / ".stage").mkdir()
    (d / "sub").mkdir()
    (d / "a.parquet").write_text("x")
    (d / "sub" / "b.parquet").write_text("x")
    (d / "_SUCCESS").write_text("{}")
    (d / ".hidden").write_text("x")
    # staged part file does NOT start with '_' — only directory
    # pruning keeps it out of the scan
    (d / TEMP_DIR / "job1" / "0" / "part-00000.parquet").write_text("x")
    (d / ".stage" / "part-00001.parquet").write_text("x")
    got = expand_paths([str(d)])
    assert got == [str(d / "a.parquet"), str(d / "sub" / "b.parquet")]
    # glob branch filters _/. basenames too (_SUCCESS, _temporary,
    # .hidden all matched "*" before this fix)
    got_glob = expand_paths([str(d / "*")])
    assert str(d / "a.parquet") in got_glob
    assert not any(os.path.basename(p).startswith(("_", "."))
                   for p in got_glob)
    # a glob CROSSING a hidden dir must not surface staged files —
    # only the wildcard-matched components are checked, so a caller
    # explicitly naming a hidden prefix still gets their files
    from spark_rapids_tpu.errors import ColumnarProcessingError
    with pytest.raises(ColumnarProcessingError, match="no input files"):
        expand_paths([str(d / "*" / "*" / "*" / "*.parquet")])
    explicit = expand_paths([str(d / TEMP_DIR / "job1" / "0" / "*")])
    assert explicit == [str(d / TEMP_DIR / "job1" / "0"
                            / "part-00000.parquet")]


def test_vacuum_spares_inflight_staging_and_retention(tmp_path):
    from spark_rapids_tpu.tools.vacuum import run_vacuum
    out = str(tmp_path / "live")
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.io.csv import write_csv
    write_csv(HostTable.from_pydict({"a": [1]}), out)
    # a job in flight over the same destination
    job = WriteJob(out)
    staged = job.stage_path("part-00001.csv")
    with open(staged, "w") as f:
        f.write("a\n2\n")
    rep = run_vacuum(out, delete=True)
    assert rep["orphans"] == []  # live staging is not an orphan
    assert os.path.exists(staged)
    # promoted-but-not-yet-manifested files are protected too: between
    # commit_task and commit_job the old manifest doesn't list them,
    # but a concurrent vacuum must not unlink them under the live job
    promoted = job.commit_task()
    assert run_vacuum(out, delete=True)["orphans"] == []
    assert all(os.path.exists(p) for p in promoted)
    job.abort()
    # dead staging younger than the retention window is kept too
    dead = os.path.join(out, TEMP_DIR, "deadjob", "0", "x.csv")
    os.makedirs(os.path.dirname(dead))
    with open(dead, "w") as f:
        f.write("torn")
    assert run_vacuum(out, retention_hours=1.0)["orphans"] == []
    rep2 = run_vacuum(out, delete=True)  # retention 0: swept
    assert rep2["deleted"] == 1 and not os.path.exists(dead)


# -- Delta: conflict classification + retry ----------------------------------

def _make_delta(session, path, n=20):
    from spark_rapids_tpu.delta.table import write_delta
    write_delta(_df(session, n).plan, session, path, mode="error")


def test_delta_concurrent_disjoint_appends_both_land(session, tmp_path):
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.delta.log import DeltaLog
    from spark_rapids_tpu.delta.table import (
        OptimisticTransaction,
        _write_data_file,
    )
    path = str(tmp_path / "dt")
    _make_delta(session, path)
    log = DeltaLog(path)
    base = log.latest_version()
    retries0 = WRITE_METRICS["commitRetries"]
    errs = []
    barrier = threading.Barrier(2)

    def append(tag):
        txn = OptimisticTransaction(log, session.conf, read_version=base)
        txn.stage(_write_data_file(path, HostTable.from_pydict(
            {"k": [tag], "v": [99]}), {}))
        barrier.wait()  # both read the SAME snapshot, then race
        try:
            txn.commit("WRITE (append)")
        except Exception as exc:  # noqa: BLE001
            errs.append(exc)

    ts = [threading.Thread(target=append, args=(t,)) for t in ("a", "b")]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert errs == []
    assert log.latest_version() == base + 2
    assert WRITE_METRICS["commitRetries"] > retries0
    assert session.read_delta(path).count() == 22


def test_delta_overlapping_overwrite_raises_typed_and_sweeps(
        session, tmp_path):
    import time as _time

    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.delta.log import (
        DeltaConcurrentWriteException,
        DeltaLog,
        RemoveFile,
    )
    from spark_rapids_tpu.delta.table import (
        OptimisticTransaction,
        _write_data_file,
    )
    path = str(tmp_path / "ow")
    _make_delta(session, path)
    log = DeltaLog(path)
    base = log.latest_version()
    now = int(_time.time() * 1000)

    def overwrite_txn():
        txn = OptimisticTransaction(log, session.conf, read_version=base)
        for a in log.snapshot(base).files:
            txn.stage(RemoveFile(a.path, now))
        txn.stage(_write_data_file(path, HostTable.from_pydict(
            {"k": ["x"], "v": [1]}), {}))
        return txn

    t1, t2 = overwrite_txn(), overwrite_txn()
    t1.commit("WRITE (overwrite)")
    orphan = [a["add"]["path"] for a in t2.actions if "add" in a][0]
    assert os.path.exists(os.path.join(path, orphan))
    with pytest.raises(DeltaConcurrentWriteException):
        t2.commit("WRITE (overwrite)")
    # the loser's staged data file was swept, not left as an orphan
    assert not os.path.exists(os.path.join(path, orphan))
    # the winner's overwrite is intact
    assert session.read_delta(path).count() == 1


def test_delta_metadata_conflict_raises_typed(session, tmp_path):
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.delta.log import (
        DeltaLog,
        DeltaMetadataChangedException,
    )
    from spark_rapids_tpu.delta.table import (
        OptimisticTransaction,
        _write_data_file,
    )
    path = str(tmp_path / "md")
    _make_delta(session, path)
    log = DeltaLog(path)
    base = log.latest_version()
    # a blind append staged against the old snapshot...
    txn = OptimisticTransaction(log, session.conf, read_version=base)
    txn.stage(_write_data_file(path, HostTable.from_pydict(
        {"k": ["z"], "v": [7]}), {}))
    # ...loses to a METADATA winner: rebase would commit rows under a
    # schema/config the writer never saw — must surface typed
    session.delta_table(path).set_properties({"foo": "bar"})
    with pytest.raises(DeltaMetadataChangedException):
        txn.commit("WRITE (append)")


@pytest.mark.chaos
def test_delta_commit_race_injection_retries(tmp_path):
    from spark_rapids_tpu.delta.log import DeltaLog
    from spark_rapids_tpu.delta.table import write_delta
    s = TpuSession({
        "spark.rapids.test.faults": "delta.commit.race:race:1"})
    path = str(tmp_path / "race")
    retries0 = WRITE_METRICS["commitRetries"]
    write_delta(_df(s, 10).plan, s, path, mode="error")
    assert WRITE_METRICS["commitRetries"] == retries0 + 1
    assert DeltaLog(path).latest_version() == 0
    assert s.read_delta(path).count() == 10


def test_delta_retry_budget_conf_exhausts_typed(session, tmp_path):
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.delta.log import (
        DeltaConcurrentModificationException,
        DeltaLog,
    )
    from spark_rapids_tpu.delta.table import (
        OptimisticTransaction,
        _write_data_file,
    )
    path = str(tmp_path / "budget")
    _make_delta(session, path)
    log = DeltaLog(path)
    conf = RapidsConf({"spark.rapids.test.faults":
                       "delta.commit.race:race:99",
                       "spark.rapids.sql.write.maxCommitRetries": "2",
                       "spark.rapids.sql.write.commitRetryWaitMs": "0"})
    FAULTS.arm(str(conf.get("spark.rapids.test.faults")))
    txn = OptimisticTransaction(log, conf,
                                read_version=log.latest_version())
    add = _write_data_file(path, HostTable.from_pydict({"k": ["q"],
                                                        "v": [1]}), {})
    txn.stage(add)
    with pytest.raises(DeltaConcurrentModificationException,
                       match="gave up"):
        txn.commit("WRITE (append)")
    # exhaustion swept the staged file too
    assert not os.path.exists(os.path.join(path, add.path))


# -- vacuum ------------------------------------------------------------------

def test_vacuum_spares_uncommitted_delta_txn_files(session, tmp_path):
    """A Delta transaction's data files land in the table dir BEFORE
    its log commit — a concurrent vacuum (default retention 0) must
    not sweep them; after commit they are live; an abandoned txn's
    protection expires with the object and vacuum reclaims the file."""
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.delta.log import DeltaLog
    from spark_rapids_tpu.delta.table import (
        OptimisticTransaction,
        _write_data_file,
    )
    path = str(tmp_path / "txn")
    _make_delta(session, path)
    log = DeltaLog(path)
    txn = OptimisticTransaction(log, session.conf,
                                read_version=log.latest_version())
    add = _write_data_file(path, HostTable.from_pydict(
        {"k": ["t"], "v": [1]}), {})
    txn.stage(add)
    staged = os.path.join(path, add.path)
    rep = session.delta_table(path).vacuum()  # deleting vacuum
    assert rep["files_deleted"] == 0 and os.path.exists(staged)
    txn.commit("WRITE (append)")
    assert session.delta_table(path).vacuum()["files_deleted"] == 0
    assert session.read_delta(path).count() == 21
    # abandoned txn: file written, never committed, txn dropped
    txn2 = OptimisticTransaction(log, session.conf,
                                 read_version=log.latest_version())
    add2 = _write_data_file(path, HostTable.from_pydict(
        {"k": ["u"], "v": [2]}), {})
    txn2.stage(add2)
    del txn2
    rep2 = session.delta_table(path).vacuum()
    assert rep2["files_deleted"] == 1
    assert not os.path.exists(os.path.join(path, add2.path))


def test_vacuum_dry_run_default_then_delete(session, tmp_path):
    from spark_rapids_tpu.delta.table import write_delta
    from spark_rapids_tpu.tools.vacuum import run_vacuum
    path = str(tmp_path / "v")
    _make_delta(session, path)
    write_delta(_df(session, 5).plan, session, path, mode="overwrite")
    rep = run_vacuum(path)  # DRY RUN default
    assert rep["dryRun"] and rep["deleted"] == 0
    assert len(rep["orphans"]) >= 1
    for rel in rep["orphans"]:
        assert os.path.exists(os.path.join(path, rel))
    rep2 = run_vacuum(path, delete=True)
    assert rep2["deleted"] == len(rep["orphans"])
    assert run_vacuum(path)["orphans"] == []
    assert session.read_delta(path).count() == 5


def test_vacuum_keeps_live_deletion_vectors(session, tmp_path):
    """A DV-carrying snapshot: vacuum must resolve the descriptor's
    encoded path and KEEP the live DV file (matching the raw base85
    token against filenames would sweep it)."""
    from spark_rapids_tpu.ops.expr import col, lit
    path = str(tmp_path / "dv")
    _make_delta(session, path)
    dt = session.delta_table(path)
    dt.delete(col("v") < lit(3))  # partial file -> deletion vector
    before = sorted(session.read_delta(path).collect())
    assert len(before) == 17
    res = dt.vacuum()
    assert res["files_deleted"] == 0
    assert sorted(session.read_delta(path).collect()) == before


def test_vacuum_manifest_dir_and_staging(session, tmp_path):
    from spark_rapids_tpu.tools.vacuum import run_vacuum
    out = str(tmp_path / "m")
    _df(session).write_parquet(out, partition_by=["k"])
    # superseding job: fewer partitions -> old job's extra files are
    # now unreferenced by the manifest
    _df(session, 6).write_parquet(out)
    # plus staging debris of a job that died without abort — incl. a
    # .backup tree (hidden names inside _temporary are still orphans)
    debris = os.path.join(out, TEMP_DIR, "deadjob", "0",
                          "part-00000.parquet")
    backup = os.path.join(out, TEMP_DIR, "deadjob", "0", ".backup",
                          "part-00000.parquet")
    os.makedirs(os.path.dirname(backup))
    for p in (debris, backup):
        with open(p, "w") as f:
            f.write("torn")
    rep = run_vacuum(out)
    assert rep["mode"] == "manifest" and rep["dryRun"]
    assert any("deadjob" in o for o in rep["orphans"])
    assert any(".backup" in o for o in rep["orphans"])
    assert any(o.startswith("k=") for o in rep["orphans"])
    run_vacuum(out, delete=True)
    rep2 = run_vacuum(out)
    assert rep2["orphans"] == []
    assert not os.path.exists(os.path.join(out, TEMP_DIR))
    assert session.read_parquet(out).count() == 6


def test_vacuum_cli_subprocess_smoke(session, tmp_path):
    """CI contract: `tools vacuum` runs as a subprocess, dry-run by
    default (files intact), --delete removes; --json parses."""
    from spark_rapids_tpu.delta.table import write_delta
    path = str(tmp_path / "cli")
    _make_delta(session, path)
    write_delta(_df(session, 5).plan, session, path, mode="overwrite")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "vacuum",
         path, "--json"],
        capture_output=True, text=True, env=env, timeout=120)
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout)
    assert rep["dryRun"] and rep["mode"] == "delta" and rep["orphans"]
    for rel in rep["orphans"]:
        assert os.path.exists(os.path.join(path, rel))
    out2 = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "vacuum",
         path, "--delete", "--json"],
        capture_output=True, text=True, env=env, timeout=120)
    assert out2.returncode == 0, out2.stderr
    assert json.loads(out2.stdout)["deleted"] == len(rep["orphans"])
    for rel in rep["orphans"]:
        assert not os.path.exists(os.path.join(path, rel))


# -- observability -----------------------------------------------------------

def test_event_log_write_fields(tmp_path):
    s = TpuSession({"spark.rapids.sql.eventLog.enabled": "true",
                    "spark.rapids.sql.eventLog.dir": str(tmp_path / "ev")})
    _df(s).write_parquet(str(tmp_path / "w"), partition_by=["k"])
    rec = s.last_event_record
    assert rec["schema"] == 11
    assert rec["filesWritten"] == 3
    assert rec["bytesWritten"] > 0
    assert rec["commitRetries"] == 0
    # a read-only query on the same session records zeros
    s.read_parquet(str(tmp_path / "w")).count()
    rec2 = s.last_event_record
    assert rec2["filesWritten"] == 0 and rec2["bytesWritten"] == 0
