"""Plan -> executable cache: skip lowering for repeated query templates.

Reference: the reference plugin compiles its kernels ONCE (cuDF ships
precompiled); its per-plan cost is Catalyst planning only. This engine
pays two extra costs per query: overrides conversion + plan
verification (host work, milliseconds) and — far worse on the TPU
backend — an XLA trace/lower/compile per kernel shape (~1-2 min cold,
PERF.md). The kernel caches (ops/expr.py `_GLOBAL_KERNEL_CACHE`,
`shared_traces`) already dedupe traces by STRUCTURAL key; what was
missing is the whole-plan layer: the service cached *results* only
(PR 5), so every admitted query still re-converted, re-verified and
re-walked its plan, and a template it had seen before still had to
rebuild every exec instance before the structural keys could hit.

This cache closes that gap. Entries are grouped by the
LITERAL-STRIPPED structural fingerprint (plan/fingerprint.py) — the
TEMPLATE — and within a template keyed by the full fingerprint, so:

* an exactly-repeated plan (same literals) checks out the cached
  converted tree and skips overrides, verification and kernel
  re-tracing entirely (the tree's kernels are already traced);
* a distinct-literal variant of a known template counts a
  ``executableCacheTemplateHits`` — it re-converts (literal values
  live in the exec tree), but every kernel whose structural key is
  literal-value-free (string predicates, joins, aggregates over the
  same shapes) hits the shared trace caches filled by its
  template-mates.

Correctness:

* **Exclusive checkout** — a tree is executed by ONE query at a time
  (exec instances hold per-run metrics and drain state). Each variant
  keeps a small POOL of trees: a burst of concurrent identical queries
  (the serving workload) checks out one tree each; only a burst wider
  than the pool converts fresh — and the fresh trees join the pool on
  release, so sustained concurrency converges to all-hits.
* **Warehouse epoch** — entries remember the invalidation epoch they
  were filled under (plan/fingerprint.py); a write/commit/catalog
  mutation stales them on lookup, exactly like the result cache.
* **Circuit-breaker demotions** — apply_overrides consults the
  breaker's demoted-op set, so entries also pin the demotion snapshot
  they were converted under and drop when it changes.
* **Failure** — an entry whose execution raises is dropped (the tree
  may hold partially-drained state); fills only happen after a fully
  successful run.

Counters live in the ``compile`` metric scope next to the kernel
trace/bucket accounting (dispatch.py).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Tuple

from spark_rapids_tpu.dispatch import COMPILE_SCOPE
from spark_rapids_tpu.obs.metrics import register_metric
from spark_rapids_tpu.plan.fingerprint import (
    invalidation_epoch,
    plan_fingerprints,
)

register_metric("executableCacheHits", "count", "ESSENTIAL",
                "queries that checked out a cached converted "
                "executable (no overrides run, no verification, no "
                "kernel re-tracing)")
register_metric("executableCacheMisses", "count", "ESSENTIAL",
                "queries that converted their plan fresh (template "
                "unseen, literal variant, stale entry, uncacheable "
                "plan, or entry busy)")
register_metric("executableCacheTemplateHits", "count", "MODERATE",
                "misses whose literal-stripped TEMPLATE was already "
                "cached: the fresh conversion reuses the template's "
                "compiled kernel set through the structural trace "
                "caches")
register_metric("executableCacheInvalidations", "count", "MODERATE",
                "cached executables dropped on lookup after a "
                "warehouse epoch bump or a circuit-breaker demotion")
register_metric("executableCacheEvictions", "count", "MODERATE",
                "cached executables evicted by the LRU bounds")


def _demotions_token() -> tuple:
    """The coherency component of an entry's generation beyond the
    warehouse epoch: circuit-breaker demotions reshape the converted
    tree, the health monitor's recovery generation bumps per backend
    reinit (a tree converted against the pre-loss device must never
    re-park into a post-recovery pool, even though the recovery itself
    also cleared the cache), and the MESH generation bumps per mesh
    reconfiguration — a tree whose scans landed shards under one mesh
    can neither serve nor re-park under another (its cached device
    tables and sharded layouts reference the old placement)."""
    from spark_rapids_tpu.parallel.mesh import MESH
    from spark_rapids_tpu.runtime.cluster import CLUSTER
    from spark_rapids_tpu.runtime.faults import CIRCUIT_BREAKER
    from spark_rapids_tpu.runtime.health import HEALTH
    return (tuple(sorted(CIRCUIT_BREAKER.demoted_ops().items())),
            HEALTH.generation(), MESH.generation(), CLUSTER.generation())


def _reset_for_reuse(executable) -> None:
    """Clear per-run state on a checked-out tree: exec metrics (each
    query's event record must report its OWN numbers) and any deferred
    row-count scalars a never-finalized previous run left behind."""
    from spark_rapids_tpu.lore import _iter_tree
    for e in _iter_tree(executable):
        m = getattr(e, "metrics", None)
        if m is not None:
            m.clear()
        if getattr(e, "_obs_pending_rows", None):
            e._obs_pending_rows = []


#: converted trees retained per (template, literal variant): exec
#: instances hold per-run state, so CONCURRENT identical queries each
#: need their own tree — the pool lets a burst of one query check out
#: one tree each instead of all but the first missing
_MAX_TREES_PER_VARIANT = 4


class _Variant:
    """One literal variant's tree pool: ``idle`` trees are available
    for checkout, ``busy`` counts trees currently executing (they pin
    the variant against LRU eviction)."""

    __slots__ = ("idle", "busy", "epoch", "demotions")

    def __init__(self, epoch, demotions):
        self.idle = []  # list of (executable, meta)
        self.busy = 0
        self.epoch = epoch
        self.demotions = demotions


class CheckoutToken:
    """Handle for one query's use of the cache. ``executable`` is None
    on a miss — the holder converts fresh and calls :meth:`fill` after
    a successful run; either way :meth:`release` must be called exactly
    once when the query's envelope (event record included) is done with
    the tree."""

    __slots__ = ("cache", "template_fp", "full_fp", "executable", "meta",
                 "hit", "template_hit", "epoch", "demotions", "_released",
                 "_filled")

    def __init__(self, cache, template_fp, full_fp, executable, meta,
                 hit, template_hit, epoch, demotions):
        self.cache = cache
        self.template_fp = template_fp
        self.full_fp = full_fp
        self.executable = executable
        self.meta = meta
        self.hit = hit
        self.template_hit = template_hit
        #: the coherency generation this token's tree belongs to,
        #: captured at CHECKOUT (i.e. before execution): fills stamp it
        #: and release only re-parks into a generation-matching variant
        #: — a tree converted before a write must never join the
        #: post-write pool, and a mid-run write stales the fill
        self.epoch = epoch
        self.demotions = demotions
        self._released = False
        self._filled = False

    def fill(self, executable, meta) -> None:
        """Register a freshly converted tree after a SUCCESSFUL run.
        The tree stays checked out (busy) until release(). A token the
        envelope already released (e.g. dropped by a recovery replay)
        must not fill — the busy increment would never be paired."""
        if self.hit or self.template_fp is None or self._released:
            return
        self.executable = executable
        self.meta = meta
        self._filled = self.cache._fill(
            self.template_fp, self.full_fp, self.epoch, self.demotions)

    def release(self, drop: bool = False) -> None:
        if self._released:
            return
        self._released = True
        if self.template_fp is not None and self.executable is not None \
                and (self.hit or self._filled):
            self.cache._release(self.template_fp, self.full_fp,
                                self.executable, self.meta, drop,
                                self.epoch, self.demotions)


class ExecutableCache:
    """Two-level LRU: templates (literal-stripped fingerprints) ->
    literal variants (full fingerprints) -> converted executables.

    Bounded by ENTRY COUNT, and a cached tree strongly pins its plan's
    in-memory source tables — ``maxPlans`` is therefore also the memory
    bound and defaults low (64); a serving workload's template set is
    small. (The result cache bounds by bytes because results are
    arbitrary-size outputs; here each template pins roughly its input
    working set, which entry count tracks.)"""

    def __init__(self, max_plans: int = 64, max_variants: int = 4):
        self.max_plans = int(max_plans)
        self.max_variants = int(max_variants)
        self._lock = threading.Lock()
        #: template_fp -> OrderedDict[full_fp, _Variant]
        self._templates: "OrderedDict[str, OrderedDict]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.template_hits = 0
        self.invalidations = 0
        self.evictions = 0

    def configure(self, max_plans: int, max_variants: int) -> None:
        with self._lock:
            self.max_plans = int(max_plans)
            self.max_variants = int(max_variants)

    # -- lookup --------------------------------------------------------------
    def checkout(self, plan, conf) -> CheckoutToken:
        """Resolve ``plan`` against the cache. Returns a token whose
        ``executable`` is a cached converted tree on a hit (reset for
        reuse, exclusively checked out from the variant's pool) or None
        on a miss."""
        template_fp, full_fp = plan_fingerprints(plan, conf)
        if template_fp is None:
            with self._lock:
                self.misses += 1
            COMPILE_SCOPE.add("executableCacheMisses", 1)
            return CheckoutToken(self, None, None, None, None, False,
                                 False, 0, ())
        epoch = invalidation_epoch()
        demotions = _demotions_token()
        tree = None
        template_hit = False
        with self._lock:
            variants = self._templates.get(template_fp)
            if variants is not None:
                self._templates.move_to_end(template_fp)
                template_hit = True
                v = variants.get(full_fp)
                if v is not None and (v.epoch != epoch
                                      or v.demotions != demotions):
                    # stale: idle trees drop now; busy ones are simply
                    # never returned (release discards on mismatch)
                    del variants[full_fp]
                    self.invalidations += 1
                    COMPILE_SCOPE.add("executableCacheInvalidations", 1)
                    v = None
                if v is not None and v.idle:
                    tree = v.idle.pop()
                    v.busy += 1
                    variants.move_to_end(full_fp)
            if tree is not None:
                self.hits += 1
            else:
                self.misses += 1
                if template_hit:
                    self.template_hits += 1
        if tree is not None:
            COMPILE_SCOPE.add("executableCacheHits", 1)
            executable, meta = tree
            _reset_for_reuse(executable)
            return CheckoutToken(self, template_fp, full_fp, executable,
                                 meta, True, True, epoch, demotions)
        COMPILE_SCOPE.add("executableCacheMisses", 1)
        if template_hit:
            COMPILE_SCOPE.add("executableCacheTemplateHits", 1)
        return CheckoutToken(self, template_fp, full_fp, None, None,
                             False, template_hit, epoch, demotions)

    # -- internal (token-driven) ---------------------------------------------
    def _fill(self, template_fp, full_fp, epoch, demotions) -> bool:
        """A miss's freshly converted tree becomes a BUSY member of its
        variant's pool (stamped with the CHECKOUT-time generation, so a
        write landing mid-run stales the entry on its first lookup
        instead of being masked); release() parks it idle. Returns
        False — and caches nothing — when a different generation's
        variant already occupies the slot."""
        with self._lock:
            variants = self._templates.get(template_fp)
            if variants is None:
                variants = self._templates[template_fp] = OrderedDict()
                while len(self._templates) > self.max_plans:
                    tkey = next(iter(self._templates))
                    if tkey == template_fp:
                        break
                    dropped = self._templates.pop(tkey)
                    n = sum(len(v.idle) for v in dropped.values())
                    self.evictions += n
                    if n:
                        COMPILE_SCOPE.add("executableCacheEvictions", n)
            else:
                self._templates.move_to_end(template_fp)
            v = variants.get(full_fp)
            if v is not None and (v.epoch, v.demotions) != (epoch,
                                                            demotions):
                # another generation owns the slot (e.g. a post-write
                # refill while this pre-write run was still executing):
                # never displace it with this token's generation
                return False
            if v is None:
                v = variants[full_fp] = _Variant(epoch, demotions)
                while len(variants) > self.max_variants:
                    vkey = next((k for k in variants if k != full_fp),
                                None)
                    if vkey is None:
                        break
                    dropped_v = variants.pop(vkey)
                    n = len(dropped_v.idle)
                    self.evictions += n
                    if n:
                        COMPILE_SCOPE.add("executableCacheEvictions", n)
            variants.move_to_end(full_fp)
            v.busy += 1
            return True

    def _release(self, template_fp, full_fp, executable, meta,
                 drop, epoch, demotions) -> None:
        with self._lock:
            variants = self._templates.get(template_fp)
            v = variants.get(full_fp) if variants is not None else None
            if v is not None and v.busy > 0 \
                    and (v.epoch, v.demotions) == (epoch, demotions):
                # generation must match the TOKEN's: a stale lookup may
                # have dropped this tree's variant and a fresh fill
                # re-created the slot — a pre-invalidation tree must
                # neither join the new pool nor corrupt its busy count
                v.busy -= 1
                if not drop and len(v.idle) < _MAX_TREES_PER_VARIANT:
                    v.idle.append((executable, meta))
            # drop / stale / evicted-variant trees are simply discarded

    # -- introspection -------------------------------------------------------
    def clear(self) -> None:
        with self._lock:
            self._templates.clear()

    def invalidate_all(self) -> int:
        """Device-loss recovery (runtime/health.py): every cached tree
        references the dead backend's state (interned device constants,
        compiled programs), so the whole cache drops — COUNTED as
        invalidations, unlike the test-support clear(). Busy trees are
        simply never returned (release discards on generation
        mismatch). Returns entries invalidated."""
        with self._lock:
            n = sum(len(vv.idle) for v in self._templates.values()
                    for vv in v.values())
            self._templates.clear()
            if n:
                self.invalidations += n
        if n:
            COMPILE_SCOPE.add("executableCacheInvalidations", n)
        return n

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "templateHits": self.template_hits,
                "invalidations": self.invalidations,
                "evictions": self.evictions,
                "templates": len(self._templates),
                "variants": sum(len(v) for v in
                                self._templates.values()),
                "idleTrees": sum(
                    len(vv.idle) for v in self._templates.values()
                    for vv in v.values()),
                "busyTrees": sum(
                    vv.busy for v in self._templates.values()
                    for vv in v.values()),
            }


#: the process-wide cache (kernel traces are process-wide, so the plan
#: layer above them is too — two sessions with identical
#: executable-affecting conf share entries, like they share kernels)
EXEC_CACHE = ExecutableCache()
