"""Device partitioners + contiguous split.

Reference (SURVEY.md §2.6): GpuPartitioning.sliceInternalOnGpuAndClose
(GpuPartitioning.scala:64 — device split into per-partition contiguous
tables), GpuHashPartitioningBase (murmur3-compatible, pmod), GpuRange-
Partitioner (sampled bounds, CPU-row-order compatible), GpuRoundRobin-
Partitioning, GpuSinglePartitioning.

TPU design: a jitted kernel computes each row's partition id, sorts rows by
(pid) with a payload permutation — one lax.sort = the contiguous_split —
and segment-counts give the partition boundaries. The host then slices the
sorted columns per partition (zero-copy views after one D2H)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
from spark_rapids_tpu.dispatch import tpu_jit
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import DeviceTable, HostColumn, HostTable
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.ops.expr import Expression, compile_project
from spark_rapids_tpu.shuffle.hashing import (
    SPARK_SEED,
    murmur3_hash_device,
    string_dict_bytes,
)


class Partitioner:
    num_partitions: int

    def partition_ids(self, table: DeviceTable):
        """Return an int32 device array of partition ids for [0, capacity)
        (padding rows get id 0; they are dropped by the split)."""
        raise NotImplementedError


class HashPartitioner(Partitioner):
    """Spark-compatible: pmod(murmur3(keys, seed=42), n)."""

    def __init__(self, keys: Sequence[Expression], num_partitions: int):
        self.keys = list(keys)
        self.num_partitions = num_partitions

    def partition_ids(self, table: DeviceTable):
        from spark_rapids_tpu.ops.expr import cached_kernel
        key_cols = compile_project(self.keys, table)
        string_bytes = {}
        datas, valids, dts = [], [], []
        for i, c in enumerate(key_cols):
            datas.append(c.data)
            valids.append(c.validity)
            dts.append(c.dtype)
            if isinstance(c.dtype, T.StringType):
                mat, lens = string_dict_bytes(c.dictionary)
                string_bytes[i] = (jnp.asarray(mat), jnp.asarray(lens))

        n = self.num_partitions
        # PROCESS-WIDE kernel cache keyed by structure: partitioner
        # instances are per-plan, and a per-instance trace dict made
        # every fresh conversion of a repeated template re-trace the
        # partition-id kernel (the VERDICT r1 per-instance-jit bug
        # class, surfaced by the executable cache's racing misses)
        tkey = ("hashpart", table.capacity,
                tuple(str(d) for d in dts),
                tuple((i, sb[0].shape) for i, sb in string_bytes.items()),
                n)
        dts_c = list(dts)

        def build():
            def run(datas, valids, sbytes):
                cols = [(d, v, dt) for d, v, dt in zip(datas, valids, dts_c)]
                h = murmur3_hash_device(cols, SPARK_SEED, sbytes)
                # Spark pmod: ((h % n) + n) % n
                m = h % jnp.int32(n)
                return jnp.where(m < 0, m + n, m)
            return run

        fn = cached_kernel(tkey, build)
        return fn(tuple(datas), tuple(valids), string_bytes)


class RoundRobinPartitioner(Partitioner):
    def __init__(self, num_partitions: int, start: int = 0):
        self.num_partitions = num_partitions
        self.start = start

    def partition_ids(self, table: DeviceTable):
        n = self.num_partitions
        return ((jnp.arange(table.capacity, dtype=jnp.int32) + self.start) % n)


class SinglePartitioner(Partitioner):
    num_partitions = 1

    def partition_ids(self, table: DeviceTable):
        return jnp.zeros(table.capacity, dtype=jnp.int32)


class RangePartitioner(Partitioner):
    """Sampled-bounds range partitioning. Bounds come from a host sample of
    the SAME key projection (order matches the CPU sort order); rows map to
    partitions by lexicographic comparison against the bounds on device.
    String keys compare by order-preserving dictionary code."""

    def __init__(self, keys: Sequence[Expression], num_partitions: int,
                 ascending: Optional[Sequence[bool]] = None,
                 samples_per_partition: int = 100):
        self.keys = list(keys)
        self.num_partitions = num_partitions
        self.ascending = list(ascending) if ascending else [True] * len(self.keys)
        self.samples_per_partition = samples_per_partition
        self._bounds: Optional[List[HostColumn]] = None

    def compute_bounds_multi(self, tables: Sequence[DeviceTable]):
        """Sample key rows across ALL input batches (Spark samples the whole
        input, not the first batch) -> num_partitions-1 bounds."""
        per_batch: List[List[HostColumn]] = []
        for t in tables:
            if t.num_rows == 0:
                continue
            key_cols = compile_project(self.keys, t)
            per_batch.append([c.to_host(t.num_rows) for c in key_cols])
        if not per_batch:
            self._bounds = []
            return
        merged = [
            HostColumn(per_batch[0][i].dtype,
                       np.concatenate([b[i].data for b in per_batch]),
                       np.concatenate([b[i].validity for b in per_batch]))
            for i in range(len(per_batch[0]))]
        self._compute_bounds_host(merged)

    def compute_bounds(self, table: DeviceTable):
        """Single-batch bounds (multi-batch callers use compute_bounds_multi)."""
        if table.num_rows == 0 or self.num_partitions <= 1:
            self._bounds = []
            return
        key_cols = compile_project(self.keys, table)
        self._compute_bounds_host([c.to_host(table.num_rows) for c in key_cols])

    def _compute_bounds_host(self, host_cols: List[HostColumn]):
        n = len(host_cols[0].data)
        if n == 0 or self.num_partitions <= 1:
            self._bounds = []
            return
        rng = np.random.default_rng(42)
        k = min(n, self.samples_per_partition * self.num_partitions)
        idx = np.sort(rng.choice(n, size=k, replace=False))
        sampled = [HostColumn(c.dtype, c.data[idx], c.validity[idx])
                   for c in host_cols]
        from spark_rapids_tpu.plan.nodes import SortOrder, _stable_sort_indices
        orders = [SortOrder(kexpr, asc)
                  for kexpr, asc in zip(self.keys, self.ascending)]
        perm = _stable_sort_indices(sampled, orders, k)
        bound_pos = [int(k * (i + 1) / self.num_partitions)
                     for i in range(self.num_partitions - 1)]
        bound_pos = [min(p, k - 1) for p in bound_pos]
        sel = perm[bound_pos]
        self._bounds = [HostColumn(c.dtype, c.data[sel], c.validity[sel])
                        for c in sampled]

    def partition_ids(self, table: DeviceTable):
        if self._bounds is None:
            self.compute_bounds(table)
        if not self._bounds or self.num_partitions <= 1:
            return jnp.zeros(table.capacity, dtype=jnp.int32)
        key_cols = compile_project(self.keys, table)
        nb = len(self._bounds[0].data)

        # per key: device data + bound values in comparable integer space
        pid = jnp.zeros(table.capacity, dtype=jnp.int32)
        # lexicographic: row > bound_j  <=>  exists first k where differs and
        # row_k > bound_jk (per direction). Compute (cap, nb) "row after
        # bound" matrix iteratively from last key to first.
        after = None  # row strictly after bound (in sort order)
        for c, bcol, asc in zip(reversed(key_cols),
                                list(reversed(self._bounds)),
                                list(reversed(self.ascending))):
            d, v = self._comparable(c)
            bd, bv, bexact = self._comparable_bounds(bcol, c)
            dd = d[:, None]
            vv = v[:, None]
            # Spark null ordering in range partitioning: nulls first (asc).
            # Inexact bounds (absent from this batch's dictionary) sit just
            # BELOW the entry whose code they borrowed: >= means after.
            cmp_gt = jnp.where(bexact, dd > bd, dd >= bd)
            gt = jnp.where(vv & bv, cmp_gt, vv & ~bv)
            lt = jnp.where(vv & bv, dd < bd, ~vv & bv)
            if not asc:
                gt, lt = lt, gt
            eq = ~gt & ~lt
            after = gt if after is None else (gt | (eq & after))
        pid = jnp.sum(after.astype(jnp.int32), axis=1)
        return pid

    @staticmethod
    def _comparable(c):
        d = c.data
        if jnp.issubdtype(d.dtype, jnp.floating):
            d = jnp.where(d == 0.0, jnp.zeros_like(d), d)
        if d.dtype == jnp.bool_:
            d = d.astype(jnp.int32)
        return d, c.validity

    def _comparable_bounds(self, bcol: HostColumn, dev_col):
        """Bounds as device row-vectors (values, validity, is_exact);
        strings map into the column's dictionary code space. A bound value
        ABSENT from this batch's dictionary takes the code of the next
        larger entry with is_exact=False: rows carrying that code are
        strictly greater than the bound, and the comparison kernel treats
        code >= bound_code as 'after' — without the flag, equal-to-next-
        entry rows would land in different partitions across batches
        (ADVICE r1: breaks the range-partition ordering invariant)."""
        if isinstance(bcol.dtype, T.StringType):
            dictionary = dev_col.dictionary
            if dictionary is None or len(dictionary) == 0:
                codes = np.zeros(len(bcol.data), dtype=np.int32)
                exact = np.zeros(len(bcol.data), dtype=np.bool_)
            else:
                codes = np.searchsorted(dictionary, bcol.data.astype(object),
                                        side="left").astype(np.int32)
                safe = np.minimum(codes, len(dictionary) - 1)
                exact = (codes < len(dictionary)) & (
                    dictionary[safe] == bcol.data.astype(object))
                # codes == len(dictionary) stays UN-clamped: the bound is
                # above every entry of this batch, so no row may compare
                # 'after' it (clamping to the last entry would push rows
                # equal to that entry across the bound)
            return (jnp.asarray(codes)[None, :],
                    jnp.asarray(bcol.validity)[None, :],
                    jnp.asarray(exact)[None, :])
        vals = bcol.data
        if np.issubdtype(vals.dtype, np.floating):
            vals = np.where(vals == 0.0, 0.0, vals)
        if vals.dtype == np.bool_:
            vals = vals.astype(np.int32)
        return (jnp.asarray(vals)[None, :],
                jnp.asarray(bcol.validity)[None, :],
                jnp.ones((1, len(bcol.data)), dtype=jnp.bool_))


class _SplitKernel:
    """pid -> (sorted columns, per-partition counts); one lax.sort."""

    _traces = {}

    @classmethod
    def run(cls, table: DeviceTable, pids, num_partitions: int):
        key = (table.capacity, num_partitions, table.schema_key()[0])
        fn = cls._traces.get(key)
        if fn is None:
            cap = table.capacity
            nparts = num_partitions

            def split(datas, valids, pids, nrows):
                live = jnp.arange(cap, dtype=jnp.int32) < nrows
                sort_pid = jnp.where(live, pids, nparts)  # padding last
                operands = [sort_pid, jnp.arange(cap, dtype=jnp.int32)]
                _, perm = jax.lax.sort(operands, num_keys=1, is_stable=True)
                counts = jax.ops.segment_sum(
                    jnp.where(live, 1, 0), jnp.clip(sort_pid, 0, nparts),
                    num_segments=nparts + 1)[:nparts]
                outs = [(d[perm], v[perm]) for d, v in zip(datas, valids)]
                return outs, counts

            fn = tpu_jit(split)
            cls._traces[key] = fn
        datas = tuple(c.data for c in table.columns)
        valids = tuple(c.validity for c in table.columns)
        return fn(datas, valids, pids, table.nrows_dev)


def split_by_partition(table: DeviceTable, partitioner: Partitioner
                       ) -> List[HostTable]:
    """Contiguous split: one device sort by pid, one D2H, then zero-copy
    host slices per partition (sliceInternalOnGpuAndClose analog; the host
    tables feed the shuffle serializer)."""
    pids = partitioner.partition_ids(table)
    outs, counts = _SplitKernel.run(table, pids, partitioner.num_partitions)
    counts = np.asarray(jax.device_get(counts))
    # live rows sort to the front: transfer only the live bucket, not padding
    from spark_rapids_tpu.columnar import bucket_for
    k = bucket_for(max(int(counts.sum()), 1))
    k = min(k, table.capacity)
    host_datas = [np.asarray(jax.device_get(d[:k])) for d, _ in outs]
    host_valids = [np.asarray(jax.device_get(v[:k])) for _, v in outs]

    results: List[HostTable] = []
    start = 0
    for p in range(partitioner.num_partitions):
        cnt = int(counts[p])
        cols = []
        for c, d, v in zip(table.columns, host_datas, host_valids):
            dd = d[start:start + cnt]
            vv = np.ascontiguousarray(v[start:start + cnt])
            # decode_host rebuilds the LOGICAL host column (string
            # dictionary decode, dec128 limb recombination)
            cols.append(c.decode_host(dd, vv))
        results.append(HostTable(table.names, cols))
        start += cnt
    return results
