"""Supported-operators documentation generator (reference:
TypeChecks.scala's supported_ops.md generation — `TypeChecks.main` emits
the per-operator type-support matrix the reference docs ship; SURVEY.md
§2.2 #5). The matrix is derived from the SAME registries the tagging
layer consults (_EXPR_SIGS / _EXEC_RULES), so docs cannot drift from the
actual fallback behavior."""

from __future__ import annotations

from typing import List

from spark_rapids_tpu import types as T

#: probe instance per doc column — a sig supports the column iff it
#: supports this representative type
_TYPE_COLUMNS = [
    ("BOOLEAN", T.BOOLEAN),
    ("BYTE", T.BYTE),
    ("SHORT", T.SHORT),
    ("INT", T.INT),
    ("LONG", T.LONG),
    ("FLOAT", T.FLOAT),
    ("DOUBLE", T.DOUBLE),
    ("DATE", T.DATE),
    ("TIMESTAMP", T.TIMESTAMP),
    ("STRING", T.STRING),
    ("DECIMAL", T.DecimalType(18, 2)),
    ("DECIMAL128", T.DecimalType(38, 2)),
    ("ARRAY", T.ArrayType(T.LONG)),
    ("MAP", T.MapType(key_type=T.LONG, value_type=T.DOUBLE)),
    ("STRUCT", T.StructType([T.StructField("f", T.LONG)])),
]

#: exec node -> TypeSig used by its tag function (kept in sync with the
#: _tag_* implementations in rules.py; scan/project accept nested)
_EXEC_SIGS = {}


def register_exec_sig(node_cls, sig) -> None:
    _EXEC_SIGS[node_cls] = sig


def _matrix_row(name: str, sig, notes: str = "") -> str:
    cells = []
    for _, probe in _TYPE_COLUMNS:
        cells.append("S" if sig.supports(probe) else "NS")
    return "| " + name + " | " + " | ".join(cells) + " | " + notes + " |"


def generate_supported_ops() -> str:
    """supported_ops.md content: one row per exec and per expression with
    an S/NS cell per type column."""
    import importlib

    from spark_rapids_tpu.overrides import rules as R
    from spark_rapids_tpu.overrides.typesig import COMMON_128

    # file-format / Delta scan rules register at THEIR package's import
    # time (register_file_scan) so the core engine never hard-requires
    # pyarrow; pull them in here so the matrix is complete and identical
    # no matter what the process imported first
    for _mod in ("spark_rapids_tpu.io", "spark_rapids_tpu.delta",
                 "spark_rapids_tpu.iceberg"):
        try:
            importlib.import_module(_mod)
        except ImportError:
            pass
    R._build_expr_sigs()

    header = ("| Operator | " +
              " | ".join(n for n, _ in _TYPE_COLUMNS) + " | Notes |")
    sep = "|" + "---|" * (len(_TYPE_COLUMNS) + 2)

    lines: List[str] = [
        "# Supported operators and types",
        "",
        "Generated from the overrides registries "
        "(`spark_rapids_tpu.overrides.docs.generate_supported_ops`) — the "
        "same `TypeSig` objects drive tag-time CPU fallback, so this "
        "matrix cannot drift from runtime behavior. `S` = runs on TPU for "
        "that type; `NS` = the operator (or the column of that type) "
        "falls back to the CPU path. Every operator also has a kill "
        "switch conf `spark.rapids.sql.exec.<Name>` / "
        "`spark.rapids.sql.expression.<Name>` (see CONFIGS.md).",
        "",
        "## Execs",
        "",
        header,
        sep,
    ]
    for node_cls, rule in sorted(R._EXEC_RULES.items(),
                                 key=lambda kv: kv[0].__name__):
        # unregistered execs doc as COMMON_128: the _check_output_schema
        # default their tag functions apply (storage-level DECIMAL128
        # flows through; per-construct carve-outs — e.g. avg over a
        # dec128 input — still tag fallback at the expression level)
        sig = _EXEC_SIGS.get(node_cls, COMMON_128)
        lines.append(_matrix_row(node_cls.__name__, sig))
    lines += [
        "",
        "## Expressions",
        "",
        header,
        sep,
    ]
    for cls, sig in sorted(R._EXPR_SIGS.items(),
                           key=lambda kv: kv[0].__name__):
        note = ""
        if getattr(cls, "device_supported", True) is False:
            note = "CPU-path expression (no device kernel)"
        # per-PARAM rows where input checks exist (ExprChecks analog —
        # `Acos / param 0 / STRING` reads NS even though the result row
        # is always DOUBLE)
        from spark_rapids_tpu.overrides.typesig import lookup_mro
        checks = lookup_mro(R._EXPR_CHECKS, cls)
        if checks is None:
            lines.append(_matrix_row(cls.__name__, sig, note))
            continue
        lines.append(_matrix_row(f"{cls.__name__} / result", sig, note))
        for label, psig in checks.doc_param_rows():
            lines.append(_matrix_row(f"{cls.__name__} / {label}", psig))
    lines.append("")
    return "\n".join(lines)
