"""Transactional output committer — the engine's FileCommitProtocol.

Reference: Spark's HadoopMapReduceCommitProtocol under
DataWritingCommandExec (SURVEY.md §2.3): task output stages under
``_temporary/<jobId>/<attempt>/`` mirroring the final directory layout,
task commit promotes each staged file into place with an atomic rename,
job commit publishes the ``_SUCCESS`` marker, and abort deletes the
attempt's staging tree so a killed task can never leave torn files a
scan would read.

This engine's version strengthens the marker into a MANIFEST: the
``_SUCCESS`` file is JSON recording the job id and the committed file
list (with row/byte totals), which buys two contracts the reference
gets from Spark's scheduler instead:

* **exactly-once under replay** — a requeued service write (PR 7's
  worker-loss/device-loss replay machinery re-submits the SAME plan
  node, hence the same job id) finds its own id in the manifest and
  returns the recorded stats instead of double-writing;
* **vacuum** — ``tools vacuum`` diffs the directory against the
  manifest to find un-referenced/staged orphans.

Every byte of table output written under ``io/`` must flow through a
:class:`WriteJob` staging path (enforced by the RL-WRITE-COMMIT lint
rule); a torn file can therefore only ever exist under ``_temporary/``,
which the scan listing prunes (io/common.expand_paths).

Crash story: abort() rolls back promoted files and sweeps staging on
any in-process failure; the crash handler's exit-20 path and an atexit
hook sweep the staging trees of jobs still in flight when the process
dies (the committed destination is untouched — a rerun of the same job
re-stages and re-promotes the same deterministic filenames, so reruns
converge bit-identically).
"""

from __future__ import annotations

import atexit
import json
import os
import shutil
import threading
import time
import uuid
import weakref
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.conf import float_conf, int_conf
from spark_rapids_tpu.obs.metrics import metric_scope, register_metric
from spark_rapids_tpu.runtime.faults import fault_point
from spark_rapids_tpu.lockorder import ordered_lock

#: staging root inside the destination directory; '_'-prefixed so the
#: scan listing (io/common.expand_paths) prunes it
TEMP_DIR = "_temporary"
SUCCESS_MARKER = "_SUCCESS"

WRITE_MAX_COMMIT_RETRIES = int_conf(
    "spark.rapids.sql.write.maxCommitRetries", 10,
    "Bound on the Delta optimistic-commit retry loop: a blind append "
    "that keeps losing the version race rebases and retries at most "
    "this many times before raising "
    "DeltaConcurrentModificationException.", commonly_used=True)

WRITE_COMMIT_RETRY_WAIT_MS = int_conf(
    "spark.rapids.sql.write.commitRetryWaitMs", 5,
    "Sleep between Delta optimistic-commit retries, milliseconds "
    "(linear; the conflict window is one log-file create, not a "
    "network round trip).")

DELTA_VACUUM_RETENTION_HOURS = float_conf(
    "spark.rapids.delta.vacuum.retentionHours", 0.0,
    "Vacuum retention window: un-referenced files younger than this "
    "many hours are kept (a concurrent uncommitted writer may still "
    "reference them). 0 disables the age check and removes every "
    "orphan.")

#: the ``write`` metric scope — committer + Delta transaction counters
#: the event log snapshots per query (filesWritten/bytesWritten/
#: commitRetries ride the record as explicit fields)
WRITE_METRICS = metric_scope("write")
for _name, _kind, _doc in (
        ("filesWritten", "count", "data files committed into place by "
                                  "the transactional writer"),
        ("bytesWritten", "bytes", "bytes of committed data files"),
        ("jobsCommitted", "count", "write jobs that published their "
                                   "_SUCCESS manifest"),
        ("jobsAborted", "count", "write jobs rolled back (promoted "
                                 "files deleted, staging swept)"),
        ("stagingFilesSwept", "count", "staged files removed by write-"
                                       "job abort/rollback and failed "
                                       "Delta transactions (write-path "
                                       "failure signal — vacuum "
                                       "housekeeping counts separately "
                                       "as vacuumedFiles)"),
        ("vacuumedFiles", "count", "un-referenced files removed by "
                                   "vacuum (routine housekeeping: "
                                   "overwritten versions, superseded "
                                   "jobs, dead staging)"),
        ("commitRetries", "count", "Delta optimistic commits rebased "
                                   "and retried after losing the "
                                   "version race"),
        ("commitConflicts", "count", "Delta commit conflicts observed "
                                     "(retried blind appends plus "
                                     "typed metadata/overlap raises)"),
):
    register_metric(_name, _kind, "ESSENTIAL", _doc)
    WRITE_METRICS.setdefault(_name, 0)
del _name, _kind, _doc

#: in-flight jobs, keyed by (path, job_id) — the crash handler's
#: exit-20 path and the atexit hook sweep these staging trees so a
#: dying process cannot leak _temporary/ files into later scans
_ACTIVE_JOBS: Dict[Tuple[str, str], "WriteJob"] = {}
_ACTIVE_LOCK = ordered_lock("io.committer.jobs")

#: files other in-flight writers own, owner -> (base_path, full paths)
#: — Delta OptimisticTransactions write data files into the table dir
#: BEFORE their log commit lands, and vacuum must not sweep those out
#: from under them. Weak keys: an abandoned transaction auto-expires
#: its protection.
_PROTECTED_OWNERS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def protect_files(owner, base_path: str, full_paths) -> None:
    """Shield ``full_paths`` (under ``base_path``) from vacuum for the
    owner's lifetime (or until :func:`unprotect_files`)."""
    with _ACTIVE_LOCK:
        _PROTECTED_OWNERS[owner] = (base_path, set(full_paths))


def unprotect_files(owner) -> None:
    with _ACTIVE_LOCK:
        _PROTECTED_OWNERS.pop(owner, None)


class WriteJob:
    """One transactional write job over a destination directory.

    Lifecycle: ``stage_path()`` per output file (the writer writes the
    staged path), ``commit_task()`` promotes every staged file with an
    atomic ``os.replace``, ``commit_job()`` publishes the ``_SUCCESS``
    manifest and sweeps staging, ``abort()`` rolls the job back. A job
    is single-use; the job id is the idempotency key reruns check."""

    def __init__(self, path: str, job_id: Optional[str] = None,
                 attempt: int = 0):
        self.path = path
        self.job_id = job_id or uuid.uuid4().hex[:16]
        self.attempt = attempt
        self.staging = os.path.join(path, TEMP_DIR, self.job_id,
                                    str(attempt))
        self._staged: List[Tuple[str, str]] = []   # (staged abs, rel)
        #: (final abs path, backup abs path or None) per promoted file
        self._promoted: List[Tuple[str, Optional[str]]] = []
        self._done = False
        os.makedirs(self.staging, exist_ok=True)
        with _ACTIVE_LOCK:
            _ACTIVE_JOBS[(self.path, self.job_id)] = self

    # -- task side -----------------------------------------------------------
    def stage_path(self, rel: str) -> str:
        """Staging location for one output file at final relative path
        ``rel`` (partition subdirs included); registers the file for
        promotion at task commit."""
        staged = os.path.join(self.staging, rel)
        os.makedirs(os.path.dirname(staged), exist_ok=True)
        self._staged.append((staged, rel))
        return staged

    def commit_task(self) -> List[str]:
        """Promote every staged file into its final destination —
        atomic per file (os.replace), so a reader concurrently listing
        the directory sees each file either absent or complete, never
        torn. A destination file that already exists (an overwrite of
        an earlier job's output at the same relative path) is first
        moved aside into the staging tree, so abort() can RESTORE it —
        without the backup, a crash mid-promotion would have destroyed
        the only copy of previously committed data."""
        final = []
        for staged, rel in self._staged:
            dst = os.path.join(self.path, rel)
            d = os.path.dirname(dst)
            if d:
                os.makedirs(d, exist_ok=True)
            fault_point("io.write.commit")
            backup = None
            if os.path.exists(dst):
                backup = os.path.join(self.staging, ".backup", rel)
                os.makedirs(os.path.dirname(backup), exist_ok=True)
                os.replace(dst, backup)
            # record BEFORE the promoting replace: a failure between
            # the two renames must still restore the backup (an
            # unrecorded backup would be swept with staging — the only
            # copy of the old committed file gone)
            self._promoted.append((dst, backup))
            os.replace(staged, dst)
            final.append(dst)
        self._staged = []
        return final

    # -- job side ------------------------------------------------------------
    def commit_job(self, num_rows: int = 0) -> dict:
        """Publish the ``_SUCCESS`` manifest (atomically, via a staged
        temp file) listing every committed file, then sweep this job's
        staging tree. Returns the manifest dict."""
        if self._staged:
            self.commit_task()
        rels = sorted(os.path.relpath(p, self.path)
                      for p, _backup in self._promoted)
        num_bytes = sum(os.path.getsize(p)
                        for p, _backup in self._promoted)
        manifest = {
            "jobId": self.job_id,
            "attempt": self.attempt,
            "numFiles": len(rels),
            "numRows": int(num_rows),
            "numBytes": int(num_bytes),
            "files": rels,
            "committedAt": int(time.time() * 1000),
        }
        tmp = os.path.join(self.staging, SUCCESS_MARKER)
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, os.path.join(self.path, SUCCESS_MARKER))
        # routine cleanup, not a failure signal: the sweep here only
        # discards .backup copies of files this job overwrote
        self._sweep_staging(record=False)
        self._finish()
        WRITE_METRICS.add("filesWritten", len(rels))
        WRITE_METRICS.add("bytesWritten", num_bytes)
        WRITE_METRICS.add("jobsCommitted", 1)
        return manifest

    def abort(self) -> None:
        """Roll the job back: every promoted file is removed and any
        destination file it clobbered is RESTORED from its backup,
        then the staging tree is swept. Idempotent; cleanup never
        raises (an abort runs inside exception handlers) though an
        armed ``io.write.abort`` fault surfaces after it."""
        if self._done:
            return
        try:
            fault_point("io.write.abort")
        finally:
            for dst, backup in reversed(self._promoted):
                try:
                    if backup is not None:
                        os.replace(backup, dst)  # restore the original
                    else:
                        os.unlink(dst)
                    WRITE_METRICS.add("stagingFilesSwept", 1)
                except OSError:
                    pass
            self._promoted = []
            self._sweep_staging()
            self._finish()
            WRITE_METRICS.add("jobsAborted", 1)

    # -- internals -----------------------------------------------------------
    def _sweep_staging(self, record: bool = True) -> None:
        """``record=False`` on the SUCCESS path: stagingFilesSwept is
        the write-path failure signal and must not count the routine
        discard of .backup copies after a healthy commit."""
        job_root = os.path.join(self.path, TEMP_DIR, self.job_id)
        swept = 0
        for _root, _dirs, files in os.walk(job_root):
            swept += len(files)
        shutil.rmtree(job_root, ignore_errors=True)
        if swept and record:
            WRITE_METRICS.add("stagingFilesSwept", swept)
        # drop _temporary/ itself once the last job under it is gone
        try:
            os.rmdir(os.path.join(self.path, TEMP_DIR))
        except OSError:
            pass
        self._staged = []

    def _finish(self) -> None:
        self._done = True
        with _ACTIVE_LOCK:
            _ACTIVE_JOBS.pop((self.path, self.job_id), None)


def read_manifest(path: str) -> Optional[dict]:
    """The destination's ``_SUCCESS`` manifest, or None when absent or
    a legacy empty marker (pre-committer writes touched an empty
    file)."""
    p = os.path.join(path, SUCCESS_MARKER)
    try:
        with open(p) as f:
            m = json.load(f)
        return m if isinstance(m, dict) and "jobId" in m else None
    except (OSError, ValueError):
        return None


def sweep_active_jobs() -> int:
    """Abort every in-flight job — the crash-handler exit-20 path
    (os._exit skips normal unwinding, so no abort() would run) and the
    atexit backstop. Runs the full rollback: promoted files removed,
    clobbered originals restored from backup, staging swept."""
    with _ACTIVE_LOCK:
        jobs = list(_ACTIVE_JOBS.values())
    for job in jobs:
        try:
            job.abort()
        except Exception:
            pass  # an armed io.write.abort fault must not stop the sweep
    return len(jobs)


def active_staging_dirs(path: str) -> List[str]:
    """Staging roots of jobs currently in flight over ``path`` —
    vacuum must never sweep these out from under a live writer."""
    with _ACTIVE_LOCK:
        return [j.staging for j in _ACTIVE_JOBS.values()
                if j.path == path]


def vacuum_protection(path: str, retention_hours: float):
    """THE keep-predicate both vacuum implementations share
    (tools/vacuum.py and delta/commands.vacuum_table): a file must be
    kept when (a) it belongs to a writer in flight in this process —
    a WriteJob's staging tree, files it has promoted but not yet
    recorded in a manifest, or a Delta transaction's staged data files
    (protect_files) — or (b) it is younger than the retention window
    (a writer in ANOTHER process may be about to commit it; unreadable
    mtimes count as young). Returns ``protected(full_path) -> bool``."""
    with _ACTIVE_LOCK:
        staging = [j.staging for j in _ACTIVE_JOBS.values()
                   if j.path == path]
        promoted = {p for j in _ACTIVE_JOBS.values() if j.path == path
                    for p, _backup in list(j._promoted)}
        promoted |= {p for bp, paths in _PROTECTED_OWNERS.values()
                     if bp == path for p in paths}
    cutoff = (time.time() - retention_hours * 3600.0
              if retention_hours > 0 else None)

    def protected(full: str) -> bool:
        if full in promoted or any(
                full.startswith(s + os.sep) for s in staging):
            return True
        if cutoff is not None:
            try:
                return os.path.getmtime(full) > cutoff
            except OSError:
                return True
        return False

    return protected


def unlink_and_prune(base: str, rels, keep_dirs=()) -> int:
    """Delete ``rels`` (relative to ``base``) then prune emptied
    directories bottom-up; directories whose path contains a
    ``keep_dirs`` name are never pruned. A live job's staging keeps
    its files, so its directories survive the rmdir attempts. Returns
    the count actually deleted."""
    deleted = 0
    for rel in rels:
        try:
            os.unlink(os.path.join(base, rel))
            deleted += 1
        except OSError:
            pass
    for root, _dirs, _files in os.walk(base, topdown=False):
        if root == base or any(k in root.split(os.sep)
                               for k in keep_dirs):
            continue
        try:
            os.rmdir(root)
        except OSError:
            pass
    return deleted


atexit.register(sweep_active_jobs)


def find_staging_orphans(path: str) -> List[str]:
    """Every file under ``<path>/_temporary/`` — staged output of jobs
    that died without abort (vacuum removes these)."""
    root = os.path.join(path, TEMP_DIR)
    out: List[str] = []
    for dirpath, _dirs, files in os.walk(root):
        for f in sorted(files):
            out.append(os.path.join(dirpath, f))
    return out
