"""Micro-batch streaming sources.

A source exposes three things: a deterministic ``latest_offset`` (where
the stream COULD read up to right now, given where it is), and a
``read_batch`` that builds a plan over exactly the ``[start, end)`` range
recorded in the offset log. Determinism is the exactly-once contract's
other half: re-running a pending batch over the same recorded offsets
must produce the same rows.

Offsets are JSON-serializable values (ints for rate/CDF, sorted filename
lists for file-watch) so the OffsetLog can persist them verbatim.
"""

from __future__ import annotations

import os
from typing import List, Optional

from spark_rapids_tpu.conf import STREAMING_MAX_FILES_PER_TRIGGER
from spark_rapids_tpu.errors import ColumnarProcessingError

__all__ = ["StreamingSource", "RateSource", "FileWatchSource",
           "DeltaCDFSource"]


class StreamingSource:
    """Contract for micro-batch sources."""

    kind = "source"

    def initial_offset(self):
        """Offset a brand-new stream starts from (exclusive start)."""
        raise NotImplementedError

    def latest_offset(self, start):
        """Furthest offset available now, bounded by per-trigger limits.
        Returning ``start`` (==) means no new data this trigger."""
        raise NotImplementedError

    def read_batch(self, session, start, end):
        """Plan (PlanNode) producing exactly the rows in (start, end]."""
        raise NotImplementedError

    def describe(self) -> dict:
        return {"kind": self.kind}


class RateSource(StreamingSource):
    """Deterministic seeded row generator — the test/bench workhorse.

    Offset = total rows emitted so far. Row ``i`` is a pure function of
    (seed, i), so any replayed range regenerates bit-identical rows.
    Schema: id LONG, value LONG, key LONG.
    """

    kind = "rate"

    def __init__(self, rows_per_batch: int = 100, seed: int = 0,
                 total_rows: Optional[int] = None, num_keys: int = 17):
        if rows_per_batch < 1:
            raise ColumnarProcessingError("rate source: rows_per_batch < 1")
        self.rows_per_batch = int(rows_per_batch)
        self.seed = int(seed)
        self.total_rows = None if total_rows is None else int(total_rows)
        self.num_keys = int(num_keys)

    def initial_offset(self):
        return 0

    def latest_offset(self, start):
        end = int(start) + self.rows_per_batch
        if self.total_rows is not None:
            end = min(end, self.total_rows)
        return max(end, int(start))

    def read_batch(self, session, start, end):
        import numpy as np

        from spark_rapids_tpu.columnar.table import HostTable
        from spark_rapids_tpu.plan import nodes as P
        ids = np.arange(int(start), int(end), dtype=np.int64)
        # Knuth multiplicative hash keyed by the seed: deterministic,
        # replay-stable, and uncorrelated with id for grouping tests
        value = (ids * np.int64(2654435761) + np.int64(self.seed)) % np.int64(1000)
        key = ids % np.int64(self.num_keys)
        table = HostTable.from_pydict(
            {"id": ids.tolist(), "value": value.tolist(),
             "key": key.tolist()})
        return P.LocalScan([table])

    def describe(self) -> dict:
        return {"kind": self.kind, "rowsPerBatch": self.rows_per_batch,
                "seed": self.seed}


class FileWatchSource(StreamingSource):
    """New files appearing under a directory become the next micro-batch.

    Offset = sorted list of file basenames already consumed. Each trigger
    picks up to ``spark.rapids.streaming.maxFilesPerTrigger`` unseen
    files in sorted order, so a replayed batch re-reads the same files.
    """

    kind = "file-watch"

    def __init__(self, directory: str, conf, fmt: str = "parquet",
                 max_files_per_trigger: Optional[int] = None):
        if fmt != "parquet":
            raise ColumnarProcessingError(
                f"file-watch source supports parquet, not {fmt!r}")
        self.directory = os.path.abspath(directory)
        self.fmt = fmt
        self.conf = conf
        self.max_files = (int(max_files_per_trigger)
                          if max_files_per_trigger is not None
                          else STREAMING_MAX_FILES_PER_TRIGGER.get(conf))

    def initial_offset(self):
        return []

    def _listing(self) -> List[str]:
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        return sorted(f for f in names if f.endswith("." + self.fmt))

    def latest_offset(self, start):
        seen = set(start)
        new = [f for f in self._listing() if f not in seen][:self.max_files]
        if not new:
            return list(start)
        return sorted(set(start) | set(new))

    def read_batch(self, session, start, end):
        from spark_rapids_tpu.io.parquet import ParquetScanNode
        new = sorted(set(end) - set(start))
        if not new:
            raise ColumnarProcessingError(
                "file-watch read_batch over an empty range")
        paths = [os.path.join(self.directory, f) for f in new]
        return ParquetScanNode(paths, self.conf)

    def describe(self) -> dict:
        return {"kind": self.kind, "directory": self.directory,
                "maxFilesPerTrigger": self.max_files}


class DeltaCDFSource(StreamingSource):
    """Tail a Delta table's change-data feed.

    Offset = last CONSUMED commit version; each batch reads
    ``table_changes(start+1, end)``. ``starting_version`` lets a new
    stream resume from a historical commit epoch (rows of version
    ``starting_version`` itself are NOT re-delivered). The batch keeps
    the CDF metadata columns (``_change_type``, ``_commit_version``) so
    the transform decides what a change means.
    """

    kind = "delta-cdf"

    def __init__(self, table_path: str, starting_version: Optional[int] = None):
        self.table_path = os.path.abspath(table_path)
        self.starting_version = starting_version

    def _log(self):
        from spark_rapids_tpu.delta.log import DeltaLog
        return DeltaLog(self.table_path)

    def initial_offset(self):
        if self.starting_version is not None:
            return int(self.starting_version)
        log = self._log()
        return log.latest_version() if log.exists() else -1

    def latest_offset(self, start):
        log = self._log()
        if not log.exists():
            return int(start)
        return max(int(start), log.latest_version())

    def read_batch(self, session, start, end):
        from spark_rapids_tpu.delta.commands import DeltaTable
        dt = DeltaTable(session, self.table_path)
        return dt.table_changes(int(start) + 1, int(end)).plan

    def describe(self) -> dict:
        return {"kind": self.kind, "tablePath": self.table_path,
                "startingVersion": self.starting_version}
