"""``tools top`` — live view of a running QueryService.

Polls the loopback introspection endpoint
(``spark.rapids.service.introspect.enabled`` — service/introspect.py)
and renders the service the way ``top`` renders a machine: health +
topology header, rolling per-pool/tenant p50/p95 SLOs over finished
handles, the live query table, and the telemetry ring's latest
deltas. One-shot by default; ``--watch SECONDS`` refreshes in place.
Stdlib-only over the JSON surface — runs anywhere that can reach
127.0.0.1 of the serving process."""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import List, Optional


def fetch_top(url: str, timeout_s: float = 5.0) -> dict:
    """GET the /top document. Raises ConnectionError with a usable
    message when nothing is listening."""
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except (urllib.error.URLError, OSError, ValueError) as exc:
        raise ConnectionError(
            f"cannot read the introspection endpoint at {url} "
            f"({exc}); is the service running with "
            "spark.rapids.service.introspect.enabled=true?") from exc


def _fmt_slo(entry: dict) -> str:
    lat, run = entry["latency"], entry["run"]
    return (f"n={entry['count']:<5d} latency p50 {lat['p50S']:8.4f}s "
            f"p95 {lat['p95S']:8.4f}s | run p50 {run['p50S']:8.4f}s "
            f"p95 {run['p95S']:8.4f}s")


def render_top(doc: dict) -> str:
    """Human rendering of one /top document."""
    lines: List[str] = []
    health = doc.get("health") or {}
    stats = doc.get("stats") or {}
    mesh = health.get("mesh") or {}
    hosts = health.get("hosts") or {}
    lines.append(
        f"Service: {health.get('state', '?')}   workers "
        f"{health.get('workerCount', '?')} "
        f"(lost {health.get('workersLost', 0)}, respawned "
        f"{health.get('workersRespawned', 0)})   running "
        f"{stats.get('running', 0)}   queued "
        f"{sum((stats.get('queued') or {}).values())}")
    topo = []
    if mesh.get("shape"):
        topo.append(f"mesh {mesh['shape']}")
    if hosts.get("enabled"):
        live = len(hosts.get("liveHosts") or [])
        topo.append(f"hosts {live}/{hosts.get('declaredHosts', '?')}"
                    + (f" (lost: {','.join(hosts['lostHosts'])})"
                       if hosts.get("lostHosts") else ""))
    if health.get("cpuOnlyReason"):
        topo.append(f"CPU-ONLY: {health['cpuOnlyReason']}")
    if topo:
        lines.append("Topology: " + " | ".join(topo))
    counters = {k: stats.get(k, 0)
                for k in ("submitted", "finished", "failed", "cancelled",
                          "timed_out", "rejected", "requeued")}
    lines.append("Lifecycle: " + "  ".join(f"{k}={v}"
                                           for k, v in counters.items()))
    slo = doc.get("slo") or {}
    if slo.get("pools"):
        lines.append("")
        lines.append(f"SLOs (rolling {slo.get('window')} finished):")
        for pool, entry in sorted(slo["pools"].items()):
            lines.append(f"  pool   {pool:20s} {_fmt_slo(entry)}")
        for tenant, entry in (slo.get("tenants") or {}).items():
            lines.append(f"  tenant {tenant:20s} {_fmt_slo(entry)}")
    streams = doc.get("streams") or []
    if streams:
        lines.append("")
        lines.append(f"Streams: {len(streams)} recurring")
        for st in streams:
            src = (st.get("source") or {}).get("kind", "?")
            lines.append(
                f"  {st.get('name', '?'):20s} {st.get('state', '?'):9s} "
                f"{st.get('pool')}/{st.get('tenant')}  src={src}  "
                f"batches={st.get('batchesRun', 0)} "
                f"(committed #{st.get('lastCommittedId', -1)}) "
                f"rows={st.get('rowsSunk', 0)}")
    queries = doc.get("queries") or []
    lines.append("")
    lines.append(f"Live queries: {len(queries)}")
    for q in queries:
        age = (f"running {q['runningS']}s" if q.get("runningS") is not None
               else f"queued {q.get('queuedS')}s")
        lines.append(
            f"  #{q['id']:<5d} {q['state']:9s} {q['pool']}/{q['tenant']}"
            f"  tag={q.get('tag') or '-'}  {age}"
            + (f"  [{q['worker']}]" if q.get("worker") else ""))
    tele = doc.get("telemetry") or {}
    sampler = tele.get("sampler") or {}
    tail = tele.get("tail") or []
    lines.append("")
    lines.append(
        f"Telemetry: {'on' if sampler.get('enabled') else 'off'} "
        f"(interval {sampler.get('intervalMs', '?')}ms, "
        f"{sampler.get('samples', 0)} samples, "
        f"{sampler.get('buffered', 0)} buffered)")
    if tail:
        last = tail[-1]
        lines.append(
            f"  last sample: health={last.get('health')} "
            f"mesh={last.get('meshShape')} "
            f"hosts={last.get('hostTopology')}")
        for scope, deltas in sorted((last.get("deltas") or {}).items()):
            parts = [f"{k}={v}" for k, v in sorted(deltas.items())]
            lines.append(f"    {scope}: " + " ".join(parts))
    return "\n".join(lines)


def run_top(url: Optional[str] = None, port: Optional[int] = None,
            watch_s: float = 0.0, iterations: Optional[int] = None,
            as_json: bool = False) -> int:
    """CLI driver: one-shot (default) or --watch polling loop.
    ``iterations`` bounds a watch loop (tests); exit 1 when the
    endpoint is unreachable."""
    import sys
    import time
    if url is None:
        if port is None:
            print("tools top: need --url or --port (the service "
                  "reports its bound port as introspect_port)",
                  file=sys.stderr)
            return 2
        url = f"http://127.0.0.1:{int(port)}/top"
    n = 0
    while True:
        try:
            doc = fetch_top(url)
        except ConnectionError as exc:
            print(str(exc), file=sys.stderr)
            return 1
        print(json.dumps(doc, sort_keys=True) if as_json
              else render_top(doc))
        n += 1
        if watch_s <= 0 or (iterations is not None and n >= iterations):
            return 0
        time.sleep(watch_s)
