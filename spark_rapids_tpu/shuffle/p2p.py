"""P2P (cached, transport-served) shuffle mode.

Reference (SURVEY.md §2.6): UCX mode — ``RapidsCachingWriter``
(RapidsShuffleInternalManagerBase.scala:1078) keeps map output resident in
the ShuffleBufferCatalog instead of writing shuffle files; readers fetch
blocks from peer executors through RapidsShuffleClient/Server over the
transport, discovered via driver heartbeats.

TPU mapping: one ``P2PShuffleEnv`` per executor wires catalog + server +
transport + heartbeat endpoint. Within one engine process (one executor)
the fetch still runs the full client/server protocol over the in-process
transport (or TCP loopback), so the wire path is exercised in production
use, not just tests; multi-executor topologies connect the same pieces
over TCP (tests/test_shuffle_transport.py builds 2-3 executor meshes)."""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional

from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.conf import (
    RapidsConf,
    SHUFFLE_COMPRESSION_CODEC,
    P2P_BOUNCE_BUFFER_SIZE,
    P2P_BOUNCE_BUFFERS,
    P2P_CACHE_LIMIT,
    P2P_TRANSPORT,
)
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.shuffle.catalogs import (
    ShuffleBufferCatalog,
    ShuffleReceivedBufferCatalog,
)
from spark_rapids_tpu.shuffle.client_server import ShuffleClient, ShuffleServer
from spark_rapids_tpu.shuffle.heartbeat import (
    ShuffleHeartbeatEndpoint,
    ShuffleHeartbeatManager,
)
from spark_rapids_tpu.shuffle.manager import (
    _compress,
    _decompress,
    resolve_codec,
)
from spark_rapids_tpu.shuffle.serializer import pack_table, unpack_table
from spark_rapids_tpu.shuffle.transport import (
    BounceBufferManager,
    Connection,
    InProcessTransport,
    PeerInfo,
    TcpShuffleServerListener,
    TcpTransport,
)


class P2PShuffleEnv:
    """Executor-side wiring of the p2p shuffle (GpuShuffleEnv analog for
    UCX mode). ``driver`` is the shared heartbeat manager; standalone use
    (single executor) creates a private one."""

    def __init__(self, conf: RapidsConf, executor_id: str = "exec-0",
                 driver: Optional[ShuffleHeartbeatManager] = None):
        self.executor_id = executor_id
        self.codec = resolve_codec(
            str(conf.get_entry(SHUFFLE_COMPRESSION_CODEC)).lower())
        bounce_size = int(conf.get_entry(P2P_BOUNCE_BUFFER_SIZE))
        bounce_n = int(conf.get_entry(P2P_BOUNCE_BUFFERS))
        self.catalog = ShuffleBufferCatalog(
            host_limit_bytes=int(conf.get_entry(P2P_CACHE_LIMIT)))
        self.send_pool = BounceBufferManager(bounce_size, bounce_n)
        self.recv_pool = BounceBufferManager(bounce_size, bounce_n)
        self.server = ShuffleServer(self.catalog, self.send_pool)
        self.window_size = bounce_size

        kind = str(conf.get_entry(P2P_TRANSPORT)).lower()
        self._listener: Optional[TcpShuffleServerListener] = None
        if kind == "tcp":
            self._listener = TcpShuffleServerListener(self.server)
            self.transport = TcpTransport(self.recv_pool)
            self.me = PeerInfo(executor_id, self._listener.host,
                               self._listener.port)
        elif kind == "inprocess":
            InProcessTransport.register_server(executor_id, self.server)
            self.transport = InProcessTransport(self.recv_pool)
            self.me = PeerInfo(executor_id)
        else:
            raise ColumnarProcessingError(f"unknown p2p transport {kind}")

        self._peers: Dict[str, PeerInfo] = {}
        self._connections: Dict[str, Connection] = {}
        self._conn_lock = threading.Lock()
        self._shuffle_id_lock = threading.Lock()
        self._next_shuffle = 0
        from spark_rapids_tpu.conf import HEARTBEAT_INTERVAL_S
        self.driver = driver or ShuffleHeartbeatManager()
        self.heartbeat = ShuffleHeartbeatEndpoint(
            self.driver, self.me, self._on_new_peer,
            interval_s=float(conf.get_entry(HEARTBEAT_INTERVAL_S)))
        self.heartbeat.start()

    def _on_new_peer(self, peer: PeerInfo):
        self._peers[peer.executor_id] = peer

    def connection_to(self, executor_id: str) -> Connection:
        with self._conn_lock:
            conn = self._connections.get(executor_id)
            if conn is not None and getattr(conn, "broken", False):
                # dead/desynced socket (ADVICE r2): evict so this fetch
                # reconnects instead of failing forever
                self._connections.pop(executor_id, None)
                conn = None
        if conn is not None:
            return conn
        peer = self.me if executor_id == self.executor_id \
            else self._peers.get(executor_id)
        if peer is None:
            raise ColumnarProcessingError(
                f"unknown peer {executor_id} (not heartbeat-discovered)")
        # connect OUTSIDE the lock: a slow/unreachable peer must not stall
        # connections to healthy ones (TCP connect can block for seconds)
        conn = self.transport.connect(peer)
        with self._conn_lock:
            existing = self._connections.get(executor_id)
            if existing is not None and getattr(existing, "broken", False):
                existing.close()
                existing = None
            if existing is None:
                self._connections[executor_id] = conn
                return conn
        # lost the race to a healthy connection: use it, free ours
        conn.close()
        return existing

    def client_for(self, executor_id: str) -> ShuffleClient:
        return ShuffleClient(self.connection_to(executor_id),
                             window_size=self.window_size)

    def peers(self) -> List[str]:
        return list(self._peers)

    # -- engine ShuffleManager interface ------------------------------------
    def new_shuffle(self, num_partitions: int) -> "P2PWriteHandle":
        with self._shuffle_id_lock:
            sid = self._next_shuffle
            self._next_shuffle = sid + 1
        return P2PWriteHandle(self, sid, num_partitions)

    def reader(self, handle: "P2PWriteHandle") -> "P2PReadHandle":
        return P2PReadHandle(self, handle)

    def remove_shuffle(self, handle: "P2PWriteHandle"):
        self.catalog.remove_shuffle(handle.shuffle_id)

    def close(self):
        self.heartbeat.close()
        if self._listener is not None:
            self._listener.close()
        else:
            InProcessTransport.unregister_server(self.executor_id)


class P2PWriteHandle:
    """Caching writer: each batch's partition split lands in the local
    spillable catalog as one block per (map, partition)."""

    def __init__(self, env: P2PShuffleEnv, shuffle_id: int,
                 num_partitions: int):
        self.env = env
        self.shuffle_id = shuffle_id
        self.num_partitions = num_partitions
        self.num_maps = 0
        self.bytes_written = 0

    def write_partitions(self, partitions: List[HostTable]):
        """Idempotent under retry (ADVICE r2): all blobs are serialized
        BEFORE the map id is claimed or any block lands in the catalog, so
        a retryable failure mid-serialization leaves no partial map output
        and the replay starts clean (no duplicated partitions)."""
        if len(partitions) != self.num_partitions:
            raise ColumnarProcessingError("partition count mismatch")
        staged = []
        for p, table in enumerate(partitions):
            if table.num_rows == 0:
                continue
            staged.append((p, _compress(self.env.codec, pack_table(table))))
        map_id = self.num_maps
        added = []
        try:
            for p, blob in staged:
                bid = (self.shuffle_id, map_id, p)
                self.env.catalog.add_block(bid, blob)
                added.append(bid)
                self.bytes_written += len(blob)
        except BaseException:
            # leave no partial map output behind: a replay re-adds the
            # same (map, partition) block ids and must start clean
            for bid in added:
                self.env.catalog.remove_block(bid)
            self.bytes_written -= sum(len(b) for _, b in staged[:len(added)])
            raise
        self.num_maps += 1

    @property
    def map_outputs(self):  # parity with ShuffleWriteHandle for metrics
        return list(range(self.num_maps))


class P2PReadHandle:
    """Reader: fetches a reduce partition through the full client/server
    protocol from every executor that holds blocks for it."""

    def __init__(self, env: P2PShuffleEnv, handle: P2PWriteHandle):
        self.env = env
        self.handle = handle
        self.bytes_read = 0

    def read_partition(self, p: int) -> Iterator[HostTable]:
        sources = [self.env.executor_id] + [
            ex for ex in self.env.peers() if ex != self.env.executor_id]
        for executor_id in sources:
            client = self.env.client_for(executor_id)
            received = ShuffleReceivedBufferCatalog()
            blocks = client.fetch_metadata(self.handle.shuffle_id, p)
            if not blocks:
                continue
            # stream on this thread; drain inline (single-peer sequential
            # fetch — the multi-peer overlap lives in the tests' threads)
            client.fetch_blocks(blocks, received)
            for _bid, blob in received.drain():
                self.bytes_read += len(blob)
                table, _ = unpack_table(_decompress(self.env.codec, blob))
                if table.num_rows > 0:
                    yield table


_P2P_ENVS: Dict[tuple, P2PShuffleEnv] = {}
_P2P_LOCK = threading.Lock()


def get_p2p_env(conf: RapidsConf) -> P2PShuffleEnv:
    key = (str(conf.get_entry(SHUFFLE_COMPRESSION_CODEC)).lower(),
           str(conf.get_entry(P2P_TRANSPORT)).lower(),
           int(conf.get_entry(P2P_BOUNCE_BUFFER_SIZE)),
           int(conf.get_entry(P2P_BOUNCE_BUFFERS)),
           int(conf.get_entry(P2P_CACHE_LIMIT)))
    with _P2P_LOCK:
        env = _P2P_ENVS.get(key)
        if env is None:
            env = P2PShuffleEnv(conf, executor_id=f"exec-local-{len(_P2P_ENVS)}")
            _P2P_ENVS[key] = env
        return env
