"""Collection (array) expressions + generator markers.

Reference: collectionOperations.scala (ArraySize/Contains/Min/Max/SortArray/
CreateArray...), GpuGenerateExec.scala (explode/posexplode) — SURVEY.md
§2.3 / VERDICT r1 item 6.

TPU-first representation: a device array column is
``data = (offsets[cap+1] i32, elem_data[ecap], elem_validity[ecap])`` with
the row validity mask as usual (columnar/column.py). Canonical invariant at
upload: null/padding rows own ZERO elements, so live elements are the
prefix [0, offsets[cap]). Elementwise collection functions evaluate with
segment reductions keyed by each element's row id
(``searchsorted(offsets, arange(ecap)) - 1``) — dense integer work the VPU
is good at, no per-row loops."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.errors import UnsupportedOnTpu
from spark_rapids_tpu.ops.common import UnaryExpression
from spark_rapids_tpu.ops.expr import DevVal, Expression, Literal, NodePrep

#: element types the device representation supports (fixed width)
FIXED_ELEMENT_TYPES = (T.BooleanType, T.ByteType, T.ShortType, T.IntegerType,
                       T.LongType, T.FloatType, T.DoubleType, T.DateType,
                       T.TimestampType)


def is_fixed_array(dt) -> bool:
    return (isinstance(dt, T.ArrayType)
            and isinstance(dt.element_type, FIXED_ELEMENT_TYPES))


def _elem_rids(off, ecap: int, cap: int):
    """Row id per element slot; slots beyond the live prefix get ``cap``
    (an overflow segment callers must ignore)."""
    j = jnp.arange(ecap, dtype=jnp.int32)
    rid = jnp.searchsorted(off, j, side="right").astype(jnp.int32) - 1
    return jnp.where(j < off[-1], jnp.clip(rid, 0, cap - 1), cap)


class Size(UnaryExpression):
    """size(array) — Spark 3 default (legacy.sizeOfNull=false): null in,
    null out."""

    @property
    def data_type(self):
        return T.INT

    def key(self):
        return ("size", self.children[0].key())

    @property
    def device_supported(self):
        return is_fixed_array(self.children[0].data_type)

    def eval_cpu(self, table: HostTable) -> HostColumn:
        c = self.children[0].eval_cpu(table)
        out = np.zeros(len(c), dtype=np.int32)
        for i in range(len(c)):
            if c.validity[i]:
                out[i] = len(c.data[i])
        return HostColumn(T.INT, out, c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        (c,) = child_vals
        off, _, _ = c.data
        return DevVal((off[1:] - off[:-1]).astype(jnp.int32), c.validity)


class GetArrayItem(Expression):
    """arr[i] — 0-based; out-of-bounds or negative index -> null."""

    def __init__(self, child: Expression, index: Expression):
        self.children = (child, index)

    @property
    def data_type(self):
        return self.children[0].data_type.element_type

    def key(self):
        return ("getarrayitem", tuple(c.key() for c in self.children))

    def with_children(self, children):
        return GetArrayItem(children[0], children[1])

    @property
    def device_supported(self):
        return (is_fixed_array(self.children[0].data_type)
                and isinstance(self.children[1], Literal))

    def eval_cpu(self, table):
        from spark_rapids_tpu.dispatch import ANSI_MODE
        from spark_rapids_tpu.errors import AnsiViolation
        c = self.children[0].eval_cpu(table)
        idx = self.children[1].eval_cpu(table)
        ansi = ANSI_MODE.get()
        np_dt = self.data_type.np_dtype
        out = np.zeros(len(c), dtype=np_dt)
        validity = np.zeros(len(c), dtype=np.bool_)
        for i in range(len(c)):
            if c.validity[i] and idx.validity[i]:
                k = int(idx.data[i])
                if 0 <= k < len(c.data[i]):
                    if c.data[i][k] is not None:
                        out[i] = c.data[i][k]
                        validity[i] = True
                elif ansi:
                    raise AnsiViolation(
                        f"array index {k} out of bounds "
                        "(spark.sql.ansi.enabled)")
        return HostColumn(self.data_type, out, validity)

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        c, ix = child_vals
        off, ed, ev = c.data
        k = ix.data[0].astype(jnp.int32)  # literal broadcast
        pos = off[:-1] + k
        inb = (k >= 0) & (pos < off[1:])
        if ctx.ansi:
            ctx.ansi_check("array index out of bounds",
                           c.validity & ix.validity & ~inb)
        safe = jnp.clip(pos, 0, ed.shape[0] - 1)
        validity = c.validity & ix.validity & inb & ev[safe]
        data = ed[safe]
        return DevVal(jnp.where(validity, data, jnp.zeros_like(data)), validity)


class ArrayContains(Expression):
    """array_contains(arr, v): true on match; null if arr is null, v is
    null, or no match while the array has a null element; else false."""

    def __init__(self, child: Expression, value: Expression):
        self.children = (child, value)

    @property
    def data_type(self):
        return T.BOOLEAN

    def key(self):
        return ("arraycontains", tuple(c.key() for c in self.children))

    def with_children(self, children):
        return ArrayContains(children[0], children[1])

    @property
    def device_supported(self):
        return (is_fixed_array(self.children[0].data_type)
                and isinstance(self.children[1], Literal))

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        v = self.children[1].eval_cpu(table)
        out = np.zeros(len(c), dtype=np.bool_)
        validity = np.zeros(len(c), dtype=np.bool_)
        for i in range(len(c)):
            if not (c.validity[i] and v.validity[i]):
                continue
            arr = c.data[i]
            found = any(x is not None and x == v.data[i] for x in arr)
            has_null = any(x is None for x in arr)
            if found:
                out[i] = True
                validity[i] = True
            elif not has_null:
                validity[i] = True
        return HostColumn(T.BOOLEAN, out, validity)

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        c, v = child_vals
        off, ed, ev = c.data
        cap = ctx.capacity
        rid = _elem_rids(off, ed.shape[0], cap)
        val = v.data[0]
        hit = ((ed == val) & ev).astype(jnp.int32)
        nul = (~ev).astype(jnp.int32)
        hits = jax.ops.segment_sum(hit, rid, num_segments=cap + 1)[:cap]
        nulls = jax.ops.segment_sum(nul * (rid < cap), rid,
                                    num_segments=cap + 1)[:cap]
        found = hits > 0
        validity = c.validity & v.validity & (found | (nulls == 0))
        return DevVal(found & validity, validity)


class _ArrayMinMax(UnaryExpression):
    is_min = True

    @property
    def data_type(self):
        return self.children[0].data_type.element_type

    def key(self):
        return ("arraymin" if self.is_min else "arraymax",
                self.children[0].key())

    @property
    def device_supported(self):
        return is_fixed_array(self.children[0].data_type)

    def eval_cpu(self, table):
        import math
        c = self.children[0].eval_cpu(table)
        np_dt = self.data_type.np_dtype
        out = np.zeros(len(c), dtype=np_dt)
        validity = np.zeros(len(c), dtype=np.bool_)

        def isnan(x):
            return isinstance(x, float) and math.isnan(x)

        for i in range(len(c)):
            if c.validity[i]:
                vals = [x for x in c.data[i] if x is not None]
                if vals:
                    # Spark total order: NaN is the GREATEST value
                    key = lambda x: (isnan(x), x if not isnan(x) else 0.0)  # noqa: E731
                    out[i] = (min if self.is_min else max)(vals, key=key)
                    validity[i] = True
        return HostColumn(self.data_type, out, validity)

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        (c,) = child_vals
        off, ed, ev = c.data
        cap = ctx.capacity
        rid = _elem_rids(off, ed.shape[0], cap)
        d = ed
        if d.dtype == jnp.bool_:
            d = d.astype(jnp.int32)
        use = ev & (rid < cap)
        is_float = jnp.issubdtype(d.dtype, jnp.floating)
        if is_float:
            # Spark total order: NaN is GREATEST. min: NaNs never win
            # (unless all values are NaN); max: a single NaN wins.
            nanmask = jnp.isnan(d)
            if self.is_min:
                d = jnp.where(nanmask, jnp.inf, d)
            else:
                d = jnp.where(nanmask, jnp.inf, d)  # +inf stands in for NaN
            ident = jnp.asarray(jnp.inf if self.is_min else -jnp.inf, d.dtype)
        else:
            info = jnp.iinfo(d.dtype)
            ident = jnp.asarray(info.max if self.is_min else info.min, d.dtype)
        vv = jnp.where(use, d, ident)
        seg = jax.ops.segment_min if self.is_min else jax.ops.segment_max
        r = seg(vv, rid, num_segments=cap + 1)[:cap]
        nonnull = jax.ops.segment_sum(use.astype(jnp.int32),
                                      rid, num_segments=cap + 1)[:cap]
        if is_float:
            n_nan = jax.ops.segment_sum((use & nanmask).astype(jnp.int32),
                                        rid, num_segments=cap + 1)[:cap]
            if self.is_min:
                # all-NaN array: the min IS NaN
                r = jnp.where(n_nan == nonnull, jnp.nan, r)
            else:
                # any NaN: the max IS NaN (r holds the +inf stand-in)
                r = jnp.where(n_nan > 0, jnp.nan, r)
        validity = c.validity & (nonnull > 0)
        if isinstance(self.data_type, T.BooleanType):
            r = r.astype(jnp.bool_)
        return DevVal(jnp.where(validity, r, jnp.zeros_like(r)), validity)


class ArrayMin(_ArrayMinMax):
    is_min = True


class ArrayMax(_ArrayMinMax):
    is_min = False


class SortArray(Expression):
    """sort_array(arr, asc): elements sorted within each row; Spark places
    nulls FIRST ascending, LAST descending."""

    def __init__(self, child: Expression, ascending: Expression = None):
        asc = ascending if ascending is not None else Literal(True, T.BOOLEAN)
        self.children = (child, asc)

    @property
    def data_type(self):
        return self.children[0].data_type

    def key(self):
        return ("sortarray", tuple(c.key() for c in self.children))

    def with_children(self, children):
        return SortArray(children[0], children[1] if len(children) > 1 else None)

    @property
    def device_supported(self):
        return (is_fixed_array(self.children[0].data_type)
                and isinstance(self.children[1], Literal))

    def eval_cpu(self, table):
        import math
        c = self.children[0].eval_cpu(table)
        asc = bool(self.children[1].value)
        out = np.empty(len(c), dtype=object)

        def key(x):
            # Spark total order: NaN greatest (and -0.0 == 0.0)
            if isinstance(x, float):
                if math.isnan(x):
                    return (1, 0.0)
                return (0, x + 0.0)
            return (0, x)

        for i in range(len(c)):
            if c.validity[i]:
                vals = sorted((x for x in c.data[i] if x is not None),
                              key=key, reverse=not asc)
                nulls = [None] * (len(c.data[i]) - len(vals))
                out[i] = (nulls + vals) if asc else (vals + nulls)
        return HostColumn(self.data_type, out, c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        from spark_rapids_tpu.ops.ordering import (
            comparable_operands,
            descending_operands,
        )
        c = child_vals[0]  # children[1] is the static asc literal
        off, ed, ev = c.data
        cap = ctx.capacity
        ecap = ed.shape[0]
        asc = bool(self.children[1].value)
        rid = _elem_rids(off, ecap, cap)
        zeroed = jnp.where(ev, ed, jnp.zeros_like(ed))
        ops = comparable_operands(zeroed)
        if not asc:
            ops = descending_operands(ops)
        nf = jnp.where(ev, 1 if asc else 0, 0 if asc else 1)
        idx = jnp.arange(ecap, dtype=jnp.int32)
        res = jax.lax.sort([rid, nf] + ops + [idx], num_keys=2 + len(ops))
        perm = res[-1]
        return DevVal((off, ed[perm], ev[perm]), c.validity)


class CreateArray(Expression):
    """array(e1, e2, ...) — fixed element count per row."""

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    @property
    def data_type(self):
        return T.ArrayType(self.children[0].data_type)

    def key(self):
        return ("createarray", tuple(c.key() for c in self.children))

    def with_children(self, children):
        return CreateArray(*children)

    def resolve(self, bound_children):
        # coerce every element expression to the common promoted type
        # (Spark: implicit cast to the tightest common type)
        from spark_rapids_tpu.ops.cast import Cast
        target = bound_children[0].data_type
        for c in bound_children[1:]:
            if c.data_type != target:
                target = T.promote(target, c.data_type)
        coerced = [c if c.data_type == target else Cast(c, target)
                   for c in bound_children]
        return CreateArray(*coerced)

    @property
    def device_supported(self):
        dts = [c.data_type for c in self.children]
        return (len(self.children) > 0
                and all(isinstance(dt, FIXED_ELEMENT_TYPES) for dt in dts)
                and all(dt == dts[0] for dt in dts))

    @property
    def nullable(self):
        return False

    def eval_cpu(self, table):
        kids = [c.eval_cpu(table) for c in self.children]
        n = table.num_rows
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = [
                (k.data[i].item() if hasattr(k.data[i], "item") else k.data[i])
                if k.validity[i] else None for k in kids]
        return HostColumn(self.data_type, out, np.ones(n, dtype=np.bool_))

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        from spark_rapids_tpu.columnar import bucket_for
        cap = ctx.capacity
        k = len(child_vals)
        ecap = bucket_for(max(cap * k, 1))
        ed = jnp.zeros(ecap, dtype=child_vals[0].data.dtype)
        ev = jnp.zeros(ecap, dtype=jnp.bool_)
        data = jnp.stack([cv.data for cv in child_vals],
                         axis=1).reshape(cap * k)
        valid = jnp.stack([cv.validity for cv in child_vals],
                          axis=1).reshape(cap * k)
        ed = ed.at[:cap * k].set(data)
        ev = ev.at[:cap * k].set(valid)
        off = (jnp.arange(cap + 1, dtype=jnp.int32) * k)
        return DevVal((off, ed, ev),
                      jnp.ones(cap, dtype=jnp.bool_) & ctx.row_mask())


class Explode(UnaryExpression):
    """Generator marker: consumed by the Generate plan node, never
    evaluated as a row expression."""

    pos = False
    outer = False

    @property
    def data_type(self):
        return self.children[0].data_type.element_type

    def key(self):
        return ("explode", self.pos, self.outer, self.children[0].key())

    def eval_cpu(self, table):
        raise UnsupportedOnTpu("Explode must be planned as a Generate node")

    def eval_dev(self, ctx, child_vals, prep):
        raise UnsupportedOnTpu("Explode must be planned as a Generate node")


class PosExplode(Explode):
    pos = True


class ExplodeOuter(Explode):
    outer = True


class PosExplodeOuter(Explode):
    pos = True
    outer = True


class Sequence(Expression):
    """sequence(start, stop[, step]) -> array<integral> (reference:
    GpuGenerateExec's GpuSequence / collectionOperations). Step defaults
    to 1 or -1 by direction (Spark semantics); a zero step or a step
    pointing away from stop is a runtime error.

    TPU sizing: per-row lengths are data-dependent, so the element buffer
    takes a STATIC speculative capacity (input capacity x
    SEQ_ELEMENT_MULT); overflow raises through the runtime-error flag
    channel (rides the collect fetch — ops/expr.deliver_ansi_flags) with
    a message naming the knob."""

    #: element capacity = bucket(row capacity * this)
    SEQ_ELEMENT_MULT = 4

    def __init__(self, *children: Expression):
        if len(children) not in (2, 3):
            raise ColumnarProcessingError("sequence(start, stop[, step])")
        self.children = tuple(children)

    @property
    def data_type(self):
        return T.ArrayType(T.LONG)

    def key(self):
        # the element multiplier shapes the trace (static ecap), so it
        # must key the compile cache — sessions set it per query
        return ("sequence", self.SEQ_ELEMENT_MULT,
                tuple(c.key() for c in self.children))

    def with_children(self, children):
        return Sequence(*children)

    def resolve(self, bound):
        from spark_rapids_tpu.ops.cast import Cast
        for c in bound:
            if not isinstance(c.data_type, T.IntegralType):
                raise ColumnarProcessingError(
                    "sequence() boundaries must be integral, got "
                    f"{c.data_type.simple_string()} (temporal sequences "
                    "are not supported)")
        out = [c if isinstance(c.data_type, T.LongType) else Cast(c, T.LONG)
               for c in bound]
        return Sequence(*out)

    @property
    def device_supported(self):
        return all(isinstance(c.data_type, T.IntegralType)
                   for c in self.children)

    def eval_cpu(self, table: HostTable) -> HostColumn:
        kids = [c.eval_cpu(table) for c in self.children]
        n = table.num_rows
        out = np.empty(n, dtype=object)
        validity = np.zeros(n, dtype=np.bool_)
        for i in range(n):
            if not all(k.validity[i] for k in kids):
                continue
            start, stop = int(kids[0].data[i]), int(kids[1].data[i])
            step = int(kids[2].data[i]) if len(kids) > 2 else (
                1 if stop >= start else -1)
            if step == 0 or (stop - start) * step < 0 and start != stop:
                raise ColumnarProcessingError(
                    "sequence step must move start toward stop")
            if abs(stop - start) // abs(step) + 1 > 100_000_000:
                raise ColumnarProcessingError(
                    "sequence length exceeds the 1e8-element bound")
            out[i] = list(range(start, stop + (1 if step > 0 else -1),
                                step))
            validity[i] = True
        return HostColumn(self.data_type, out, validity)

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        from spark_rapids_tpu.columnar import bucket_for
        cap = ctx.capacity
        start = child_vals[0]
        stop = child_vals[1]
        validity = start.validity & stop.validity
        s64 = start.data.astype(jnp.int64)
        e64 = stop.data.astype(jnp.int64)
        if len(child_vals) > 2:
            validity = validity & child_vals[2].validity
            step = child_vals[2].data.astype(jnp.int64)
        else:
            step = jnp.where(e64 >= s64, 1, -1).astype(jnp.int64)
        live = validity & ctx.row_mask()
        bad_step = live & ((step == 0)
                           | (((e64 - s64) * jnp.where(step == 0, 1, step)
                               < 0) & (s64 != e64)))
        # Spark raises for invalid steps regardless of ANSI mode: route
        # through the runtime-error flag channel unconditionally
        ctx.ansi_errors.append((
            "sequence step must move start toward stop",
            jnp.any(bad_step)))
        safe_step = jnp.where(step == 0, 1, step)
        lengths64 = jnp.where(
            live & ~bad_step,
            jnp.maximum((e64 - s64) // safe_step + 1, 0),
            jnp.zeros_like(s64))
        ecap = bucket_for(max(cap * self.SEQ_ELEMENT_MULT, 1))
        # flag BEFORE narrowing: an int64 length past 2^31 would wrap
        # negative in int32 and silently dodge the capacity check
        over = jnp.any(lengths64 > ecap) | (jnp.sum(lengths64) > ecap)
        ctx.ansi_errors.append((
            "sequence output exceeded the element capacity "
            f"(rows x {self.SEQ_ELEMENT_MULT}); reduce sequence lengths "
            "or raise Sequence.SEQ_ELEMENT_MULT", over))
        lengths = jnp.clip(lengths64, 0, ecap).astype(jnp.int32)
        # clamp offsets into the element buffer: when the capacity flag
        # fired the collect still decodes in-bounds (garbage content) and
        # the flagged error raises at validation, not an IndexError
        offsets = jnp.minimum(jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(lengths)]), ecap)
        rid = _elem_rids(offsets, ecap, cap)
        safe_rid = jnp.clip(rid, 0, cap - 1)
        pos = jnp.arange(ecap, dtype=jnp.int64) - offsets[safe_rid]
        ed = s64[safe_rid] + pos * safe_step[safe_rid]
        ev = rid < cap
        return DevVal((offsets, ed, ev), validity)
