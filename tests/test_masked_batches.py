"""Deferred-compaction (masked batch) semantics — columnar/table.py
DeviceTable.live, execs/base.py execute_masked protocol.

Covers the review findings from the round-4 masked-batch change:
top-k limit must key the trace cache (not just its bucket), and
position-dependent expressions (rand, monotonically_increasing_id) must
see prefix-compacted input everywhere, not only under Project."""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.session import TpuSession


def _sessions():
    return (TpuSession(),
            TpuSession({"spark.rapids.sql.enabled": "false"}))


def _data(n=4000, seed=3):
    rng = np.random.default_rng(seed)
    return {
        "k": rng.integers(0, 40, n).astype(np.int64),
        "v": rng.random(n),
        "w": rng.integers(-50, 50, n).astype(np.int64),
    }


def test_masked_filter_matches_compacted():
    data = _data()
    tpu, cpu = _sessions()
    nomask = TpuSession({"spark.rapids.tpu.maskedBatches.enabled": "false"})
    q = lambda s: sorted(
        s.create_dataframe(data).filter(col("w") > lit(0))
        .select(col("k"), col("w")).collect())
    assert q(tpu) == q(cpu) == q(nomask)


def test_masked_join_agg_topk_pipeline():
    data = _data()
    dim = {"k": np.arange(40, dtype=np.int64),
           "boost": (np.arange(40) % 7).astype(np.int64)}
    tpu, cpu = _sessions()

    def q(s):
        df = s.create_dataframe(data).filter(col("w") != lit(0))
        d = s.create_dataframe(dim).filter(col("boost") < lit(6))
        return (df.join(d, on="k", how="inner")
                .group_by("boost")
                .agg(F.count().alias("c"), F.sum(col("w")).alias("sw"))
                .sort("c", ascending=False).limit(3).collect())
    assert q(tpu) == q(cpu)


def test_topk_distinct_limits_share_bucket():
    """Two limits inside one power-of-two bucket must not share a trace
    (review finding: k was baked into the jit closure but missing from the
    cache key)."""
    data = _data(600)
    tpu, cpu = _sessions()
    for k in (100, 128, 97):
        q = lambda s: (s.create_dataframe(data)
                       .sort("v", ascending=False).limit(k).collect())
        got, want = q(tpu), q(cpu)
        assert len(got) == len(want) == k
        assert [r[0] for r in got] == [r[0] for r in want]


@pytest.mark.parametrize("expr_maker", [
    lambda: F.monotonically_increasing_id().alias("id"),
])
def test_position_dependent_over_masked_filter(expr_maker):
    """Slot-based ids over a masked batch must match the prefix form the
    CPU oracle produces (project path compacts first)."""
    data = _data()
    tpu, cpu = _sessions()
    q = lambda s: (s.create_dataframe(data).filter(col("w") > lit(0))
                   .select(col("k"), expr_maker()).collect())
    assert q(tpu) == q(cpu)


def test_rand_in_filter_over_masked_input():
    """rand() inside a second filter above a masked filter (review finding:
    only Project guarded position-dependent expressions)."""
    data = _data()
    tpu, cpu = _sessions()
    q = lambda s: sorted(
        s.create_dataframe(data).filter(col("w") > lit(0))
        .filter(F.rand(42) < lit(0.5)).select(col("k"), col("w")).collect())
    assert q(tpu) == q(cpu)


def test_rand_in_sort_keys_over_masked_input():
    data = _data(500)
    tpu, cpu = _sessions()

    def q(s):
        from spark_rapids_tpu.plan.nodes import SortOrder
        df = s.create_dataframe(data).filter(col("w") > lit(0))
        return df.sort(SortOrder(F.rand(7), ascending=True)).collect()
    assert q(tpu) == q(cpu)


def test_masked_batch_spill_and_split_survive():
    """Injected OOM forces spill (host-side compaction of the masked
    batch) and split-and-retry (device compaction before slicing)."""
    data = _data()
    for inject in ("retry:2", "split:1"):
        tpu = TpuSession({"spark.rapids.sql.test.injectRetryOOM": inject})
        cpu = TpuSession({"spark.rapids.sql.enabled": "false"})
        q = lambda s: sorted(
            s.create_dataframe(data).filter(col("w") > lit(10))
            .select(col("k"), (col("w") * lit(2)).alias("w2")).collect())
        assert q(tpu) == q(cpu)


def test_masked_semi_anti_counts():
    data = _data()
    dim = {"k": np.arange(0, 40, 2, dtype=np.int64)}
    tpu, cpu = _sessions()
    for how in ("leftsemi", "leftanti"):
        q = lambda s: sorted(
            s.create_dataframe(data).filter(col("w") > lit(0))
            .join(s.create_dataframe(dim), on="k", how=how).collect())
        assert q(tpu) == q(cpu)


def test_concat_of_masked_batches():
    """Multi-batch masked filter output through coalesce's device concat
    (deferred compaction fuses into the concat scatter)."""
    data = _data(3000)
    tpu, cpu = _sessions()
    q = lambda s: sorted(
        s.create_dataframe(data, num_batches=3).filter(col("w") > lit(0))
        .group_by("k").agg(F.count().alias("c")).collect())
    assert q(tpu) == q(cpu)
