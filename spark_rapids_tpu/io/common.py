"""Shared file-scan machinery: the three reader modes.

Reference architecture (SURVEY.md §2.4, GpuMultiFileReader.scala):
  PERFILE        — decode one file at a time, one batch per file.
  COALESCING     — stitch many small files/row-groups into one large buffer
                   and do a single decode+upload (MultiFileCoalescingPartition-
                   ReaderBase analog). Best for many small files on fast storage.
  MULTITHREADED  — a thread pool prefetches and decodes a bounded window of
                   files ahead of the consumer so host decode overlaps device
                   compute (MultiFileCloudPartitionReaderBase analog).
  AUTO           — MULTITHREADED when more than one file, else PERFILE.

The TPU engine decodes on host via Arrow and uploads decoded columns; the
modes govern prefetch/stitching exactly as in the reference. Hive-style
``key=value`` directory components are recovered as partition columns
(GpuFileSourceScanExec partition-value reconstruction analog).
"""

from __future__ import annotations

import concurrent.futures as cf
import glob as _glob
import os
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.conf import (
    MULTITHREADED_READ_NUM_THREADS,
    RapidsConf,
    READER_COALESCE_TARGET_BYTES,
)
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.plan.nodes import PlanNode, Schema


class ReaderMode:
    PERFILE = "PERFILE"
    COALESCING = "COALESCING"
    MULTITHREADED = "MULTITHREADED"
    AUTO = "AUTO"


def expand_paths(paths: Sequence[str]) -> List[str]:
    """Expand globs and directories into a sorted file list.

    Hidden entries — ``_``/``.``-prefixed files AND directories — are
    excluded on every listing branch (Spark's InMemoryFileIndex
    contract). Pruning directories matters for correctness, not just
    hygiene: the transactional writer stages in-flight output under
    ``_temporary/<job>/<attempt>/``, and those staged ``part-*`` files
    must never be visible to a scan. Explicitly named single files are
    honored as given (the caller asked for that exact path)."""
    out: List[str] = []
    for p in paths:
        if any(ch in p for ch in "*?["):
            # reject hidden components anywhere a WILDCARD could have
            # matched them (a glob crossing _temporary/ must not
            # surface staged files) while honoring hidden components
            # the caller spelled out in the static prefix
            comps = p.split(os.sep)
            first_wild = next(i for i, seg in enumerate(comps)
                              if any(ch in seg for ch in "*?["))
            for m in sorted(_glob.glob(p)):
                tail = m.rstrip(os.sep).split(os.sep)[first_wild:]
                if not any(c.startswith(("_", ".")) for c in tail if c):
                    out.append(m)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(("_", ".")))
                for f in sorted(files):
                    if not f.startswith(("_", ".")):
                        out.append(os.path.join(root, f))
        else:
            out.append(p)
    if not out:
        raise ColumnarProcessingError(f"no input files for {list(paths)}")
    return out


HIVE_DEFAULT_PARTITION = "__HIVE_DEFAULT_PARTITION__"


def _unescape_partition_value(s: str) -> Optional[str]:
    if s == HIVE_DEFAULT_PARTITION:
        return None
    out, i = [], 0
    while i < len(s):
        if s[i] == "%" and i + 3 <= len(s):
            try:
                out.append(chr(int(s[i + 1:i + 3], 16)))
                i += 3
                continue
            except ValueError:
                pass
        out.append(s[i])
        i += 1
    return "".join(out)


def partition_spec_of(path: str) -> List[Tuple[str, Optional[str]]]:
    """Extract ordered (key, value) pairs from Hive-style path components."""
    spec = []
    for comp in os.path.dirname(path).split(os.sep):
        if "=" in comp and not comp.startswith("."):
            k, _, v = comp.partition("=")
            spec.append((k, _unescape_partition_value(v)))
    return spec


def _infer_partition_type(values: Iterable[Optional[str]]) -> T.DataType:
    """Spark-style partition value type inference: long -> double -> string."""
    saw_any = False
    all_long = all_double = True
    for v in values:
        if v is None:
            continue
        saw_any = True
        try:
            int(v)
        except ValueError:
            all_long = False
            try:
                float(v)
            except ValueError:
                all_double = False
    if not saw_any:
        return T.STRING
    if all_long:
        return T.LONG
    if all_double:
        return T.DOUBLE
    return T.STRING


def coalesce_batches(batches: Iterable[HostTable], target_bytes: int
                     ) -> Iterator[HostTable]:
    """Accumulate host batches until the byte target, then concat — the one
    shared stitching loop behind every COALESCING reader."""
    pending: List[HostTable] = []
    pending_bytes = 0
    for t in batches:
        pending.append(t)
        pending_bytes += t.nbytes()
        if pending_bytes >= target_bytes:
            yield HostTable.concat(pending)
            pending, pending_bytes = [], 0
    if pending:
        yield HostTable.concat(pending)


class FileScanNode(PlanNode):
    """Base scan node. Subclasses implement ``read_file`` (whole-file decode
    to an Arrow table) and ``file_arrow_schema``; COALESCING may be refined
    per-format (parquet splits at row-group granularity)."""

    format_name = "file"

    def __init__(self, paths: Sequence[str], conf: RapidsConf,
                 columns: Optional[Sequence[str]] = None,
                 reader_type: Optional[str] = None, **options):
        self.paths = expand_paths(paths)
        self.conf = conf
        self.columns = list(columns) if columns else None
        self.options = options
        self.reader_type = (reader_type or self._conf_reader_type()).upper()
        self._schema: Optional[Schema] = None
        self._data_schema: Optional[Schema] = None
        self._partition_schema: Optional[Schema] = None

    def _effective_paths(self, dynamic_prunes) -> list:
        """File list after dynamic partition pruning
        (GpuFileSourceScanExec partitionFilters with
        DynamicPruningExpression). ``dynamic_prunes`` is a list of
        (partition column name, provider) where provider() -> set of
        allowed values; it is EXECUTION-scoped state owned by the calling
        exec (execs/basic.TpuFileScanExec), never by this shared plan
        node — a prune must not leak into other queries over the same
        scan."""
        paths = list(self.paths)
        if not dynamic_prunes:
            return paths
        self._resolve_schemas()
        part_types = dict(self._partition_schema or [])
        for part_col, provider in dynamic_prunes:
            dt = part_types.get(part_col)
            if dt is None:
                continue
            allowed = provider()
            kept = []
            for p in paths:
                spec = dict(partition_spec_of(p))
                raw = spec.get(part_col)
                if raw is None:
                    kept.append(p)  # null partition: keep (null-safe)
                    continue
                if isinstance(dt, T.StringType):
                    val = raw
                elif isinstance(dt, T.DoubleType):
                    val = float(raw)
                else:
                    val = int(raw)
                if val in allowed:
                    kept.append(p)
            paths = kept
        return paths

    # -- subclass surface ---------------------------------------------------
    def _conf_reader_type(self) -> str:
        return ReaderMode.AUTO

    def file_schema(self, path: str) -> Schema:
        raise NotImplementedError

    def read_file(self, path: str) -> HostTable:
        """Decode one file to its data columns (partition columns appended
        by the driver loop)."""
        raise NotImplementedError

    # -- schema -------------------------------------------------------------
    def _resolve_schemas(self):
        if self._schema is not None:
            return
        data_schema = self.file_schema(self.paths[0])
        data_names = {n for n, _ in data_schema}
        # partition columns from Hive-style dirs, in first-seen key order
        part_values: dict = {}
        for p in self.paths:
            for k, v in partition_spec_of(p):
                if k not in data_names:
                    part_values.setdefault(k, []).append(v)
        part_schema = [(k, _infer_partition_type(vs))
                       for k, vs in part_values.items()]
        full = data_schema + part_schema
        if self.columns is not None:
            by_name = dict(full)
            for c in self.columns:
                if c not in by_name:
                    raise ColumnarProcessingError(
                        f"column {c!r} not in {[n for n, _ in full]}")
            full = [(c, by_name[c]) for c in self.columns]
            data_schema = [(n, dt) for n, dt in data_schema
                           if n in set(self.columns)]
            part_schema = [(n, dt) for n, dt in part_schema
                           if n in set(self.columns)]
        self._schema = full
        self._data_schema = data_schema
        self._partition_schema = part_schema

    #: set by overrides/input_file.py when the plan references
    #: input_file_name()/input_file_block_*: every batch gains hidden
    #: per-row provenance columns (reference: GpuInputFileName family +
    #: InputFileBlockRule keeping the exprs in the scan's stage)
    provide_file_info: bool = False

    def enable_file_info(self) -> None:
        self.provide_file_info = True

    def _attach_file_info(self, table: HostTable, path: str) -> HostTable:
        if not self.provide_file_info:
            return table
        from spark_rapids_tpu.ops.inputfile import (
            FILE_LENGTH_COL,
            FILE_NAME_COL,
            FILE_START_COL,
        )
        if FILE_NAME_COL in table.names:
            return table  # chunk already stamped
        n = table.num_rows
        name = np.empty(n, dtype=object)
        name[:] = path
        try:
            size = os.path.getsize(path)
            start = 0
        except OSError:
            # unreadable between decode and stamping: coherent Spark
            # no-info pair, not a 0/-1 mix
            size = start = -1
        cols = list(table.columns) + [
            HostColumn(T.STRING, name),
            HostColumn(T.LONG, np.full(n, start, dtype=np.int64)),
            HostColumn(T.LONG, np.full(n, size, dtype=np.int64))]
        return HostTable(
            list(table.names) + [FILE_NAME_COL, FILE_START_COL,
                                 FILE_LENGTH_COL], cols)

    def output_schema(self) -> Schema:
        self._resolve_schemas()
        if self.provide_file_info:
            from spark_rapids_tpu.ops.inputfile import (
                FILE_LENGTH_COL,
                FILE_NAME_COL,
                FILE_START_COL,
            )
            return list(self._schema) + [
                (FILE_NAME_COL, T.STRING), (FILE_START_COL, T.LONG),
                (FILE_LENGTH_COL, T.LONG)]
        return self._schema

    @property
    def data_schema(self) -> Schema:
        """Schema of columns read from file contents (post-pruning)."""
        self._resolve_schemas()
        return self._data_schema

    def _with_partition_columns(self, table: HostTable, path: str) -> HostTable:
        """Append recovered partition-value columns (and, when enabled,
        the input-file provenance columns) and order to the output
        schema."""
        self._resolve_schemas()
        if not self._partition_schema:
            return self._attach_file_info(table, path)
        spec = dict(partition_spec_of(path))
        n = table.num_rows
        names = list(table.names)
        cols = list(table.columns)
        for name, dt in self._partition_schema:
            raw = spec.get(name)
            if raw is None:
                validity = np.zeros(n, dtype=np.bool_)
                if isinstance(dt, T.StringType):
                    data = np.full(n, None, dtype=object)
                else:
                    data = np.zeros(n, dtype=dt.np_dtype)
            else:
                validity = np.ones(n, dtype=np.bool_)
                if isinstance(dt, T.StringType):
                    data = np.full(n, raw, dtype=object)
                elif isinstance(dt, T.DoubleType):
                    data = np.full(n, float(raw), dtype=np.float64)
                else:
                    data = np.full(n, int(raw), dtype=np.int64)
            names.append(name)
            cols.append(HostColumn(dt, data, validity))
        by_name = dict(zip(names, cols))
        out_names = [n for n, _ in self._schema]
        out = HostTable(out_names, [by_name[n] for n in out_names])
        return self._attach_file_info(out, path)

    # -- PlanNode -----------------------------------------------------------
    def execute_cpu(self, dynamic_prunes=None,
                    metrics: Optional[dict] = None) -> Iterator[HostTable]:
        paths = self._effective_paths(dynamic_prunes)
        if metrics is not None and dynamic_prunes:
            metrics["dppPrunedFiles"] = len(self.paths) - len(paths)
            metrics["dppScannedFiles"] = len(paths)
        if not paths:
            from spark_rapids_tpu.plan.nodes import _empty_table
            yield _empty_table(self.output_schema())
            return
        # multi-host cluster routing (runtime/cluster.py): with an
        # active cluster, source files partition BY HOST and each
        # executor process scans only its subset, shipping the decoded
        # shards back over the driver/executor wire — batch-per-file in
        # path order, byte-identical to the local PERFILE walk below.
        # Inactive/unroutable scans fall through to the local modes.
        from spark_rapids_tpu.runtime.cluster import CLUSTER
        routed = CLUSTER.scan_route(self, paths)
        if routed is not None:
            yield from routed
            return
        mode = self.reader_type
        if mode == ReaderMode.AUTO:
            mode = (ReaderMode.MULTITHREADED if len(paths) > 1
                    else ReaderMode.PERFILE)
        if mode == ReaderMode.PERFILE:
            it = self._perfile(paths)
        elif mode == ReaderMode.COALESCING:
            it = coalesce_batches(
                self._coalescing_chunks(paths),
                self.conf.get_entry(READER_COALESCE_TARGET_BYTES))
        elif mode == ReaderMode.MULTITHREADED:
            it = self._multithreaded(paths)
        else:
            raise ColumnarProcessingError(f"unknown reader type {mode}")
        yield from it

    def _cache_key_extra(self) -> tuple:
        """Subclasses add every decode-affecting option here (named kwargs
        consumed before **options never reach self.options)."""
        return ()

    def _cache_key(self) -> tuple:
        return (type(self).__name__, tuple(self.columns or ()),
                tuple(sorted((k, str(v)) for k, v in self.options.items())),
                self._cache_key_extra())

    def _read_decoded(self, path: str) -> HostTable:
        from spark_rapids_tpu.io.filecache import (
            FILE_CACHE,
            FILECACHE_ENABLED,
            FILECACHE_MAX_BYTES,
        )
        from spark_rapids_tpu.runtime.faults import fault_point
        fault_point("io.read.file")
        if not self.conf.get_entry(FILECACHE_ENABLED):
            return self.read_file(path)
        return FILE_CACHE.get_or_decode(
            path, self._cache_key(), lambda: self.read_file(path),
            self.conf.get_entry(FILECACHE_MAX_BYTES))

    def _read_with_partitions(self, path: str) -> HostTable:
        return self._with_partition_columns(self._read_decoded(path), path)

    def _perfile(self, paths=None) -> Iterator[HostTable]:
        for p in (self.paths if paths is None else paths):
            yield self._read_with_partitions(p)

    def _coalescing_chunks(self, paths=None) -> Iterator[HostTable]:
        """Chunk stream feeding the COALESCING stitcher. Default: whole
        files; formats with sub-file granularity (parquet row groups, ORC
        stripes) override."""
        return self._perfile(paths)

    def _multithreaded(self, paths=None) -> Iterator[HostTable]:
        """Ordered prefetch with a bounded in-flight window: at most
        ~2x pool-size files are decoded ahead of the consumer, so host
        memory stays bounded and early iterator abandonment (limits) does
        not decode the whole dataset."""
        if paths is None:
            paths = self.paths
        nthreads = max(1, self.conf.get_entry(MULTITHREADED_READ_NUM_THREADS))
        window = min(len(paths), nthreads * 2)
        with cf.ThreadPoolExecutor(max_workers=min(nthreads, len(paths))) as pool:
            futures = {}
            next_submit = 0
            for i in range(len(paths)):
                while next_submit < len(paths) and next_submit < i + window:
                    futures[next_submit] = pool.submit(
                        self._read_with_partitions, paths[next_submit])
                    next_submit += 1
                yield futures.pop(i).result()

    def describe(self):
        return (f"{type(self).__name__}[{len(self.paths)} files, "
                f"{self.reader_type}]")


def row_carrier_table(n: int) -> HostTable:
    """Placeholder 1-column table carrying only a row count — used when a
    projection touches no data columns (e.g. only Hive partition columns):
    the count still comes from the file, and the carrier column is dropped
    when _with_partition_columns re-selects the output schema."""
    return HostTable(["__rows__"], [
        HostColumn(T.LONG, np.zeros(n, dtype=np.int64))])
