"""ANSI mode (spark.sql.ansi.enabled) — overflow/cast/divide/array-index
error semantics (reference: GpuCast ansi variants, CheckOverflow shim
rules, ansi_cast integration tests).

Both evaluation paths must raise AnsiViolation for the same inputs, and
non-violating data must produce results identical to legacy mode."""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.errors import AnsiViolation
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.session import TpuSession

I64MAX = np.iinfo(np.int64).max
I64MIN = np.iinfo(np.int64).min


def _sessions():
    return (TpuSession({"spark.sql.ansi.enabled": "true"}),
            TpuSession({"spark.sql.ansi.enabled": "true",
                        "spark.rapids.sql.enabled": "false"}))


@pytest.mark.parametrize("expr_maker,vals", [
    (lambda: col("x") + lit(1), [1, I64MAX]),
    (lambda: col("x") - lit(1), [0, I64MIN]),
    (lambda: col("x") * lit(3), [5, I64MAX // 2 + 1]),
    (lambda: -col("x"), [1, I64MIN]),
    (lambda: F.abs(col("x")), [1, I64MIN]),
])
def test_integral_overflow_raises_both_paths(expr_maker, vals):
    for s in _sessions():
        df = s.create_dataframe({"x": np.asarray(vals, dtype=np.int64)})
        with pytest.raises(AnsiViolation):
            df.select(expr_maker().alias("y")).collect()


@pytest.mark.parametrize("expr_maker", [
    lambda: col("x") / lit(0.0),
    lambda: col("x") % lit(0),
    lambda: F.expr_integral_divide(col("x"), lit(0))
    if hasattr(F, "expr_integral_divide") else col("x") % lit(0),
])
def test_divide_by_zero_raises_both_paths(expr_maker):
    for s in _sessions():
        df = s.create_dataframe({"x": np.asarray([1, 2], dtype=np.int64)})
        with pytest.raises(AnsiViolation):
            df.select(expr_maker().alias("y")).collect()


def test_cast_overflow_raises_both_paths():
    for s in _sessions():
        df = s.create_dataframe({"x": np.asarray([1, 1 << 40],
                                                 dtype=np.int64)})
        with pytest.raises(AnsiViolation):
            df.select(col("x").cast("int").alias("y")).collect()
        df2 = s.create_dataframe({"f": np.asarray([1.5, 3e18])})
        with pytest.raises(AnsiViolation):
            df2.select(col("f").cast("int").alias("y")).collect()
        df3 = s.create_dataframe({"f": np.asarray([np.nan, 1.0])})
        with pytest.raises(AnsiViolation):
            df3.select(col("f").cast("bigint").alias("y")).collect()


def test_string_cast_failure_raises_both_paths():
    for s in _sessions():
        df = s.create_dataframe({"s": ["12", "oops"]},
                                dtypes={"s": T.STRING})
        with pytest.raises(AnsiViolation):
            df.select(col("s").cast("int").alias("y")).collect()


def test_array_index_out_of_bounds():
    for s in _sessions():
        df = s.create_dataframe({"a": np.asarray([1, 2], dtype=np.int64)})
        from spark_rapids_tpu.ops.collections import GetArrayItem
        with pytest.raises(AnsiViolation):
            df.select(GetArrayItem(
                F.array(col("a")), lit(3)).alias("y")).collect()


def test_ansi_error_in_filter_predicate():
    for s in _sessions():
        df = s.create_dataframe({"x": np.asarray([1, I64MAX],
                                                 dtype=np.int64)})
        with pytest.raises(AnsiViolation):
            df.filter((col("x") + lit(1)) > lit(0)).collect()


def test_no_violation_matches_legacy_results():
    ansi = TpuSession({"spark.sql.ansi.enabled": "true"})
    legacy = TpuSession()
    cpu = TpuSession({"spark.rapids.sql.enabled": "false"})
    rng = np.random.default_rng(0)
    data = {"x": rng.integers(-1000, 1000, 5000).astype(np.int64),
            "y": rng.integers(1, 50, 5000).astype(np.int64)}
    q = lambda s: sorted(s.create_dataframe(data).select(
        (col("x") * col("y")).alias("m"),
        (col("x") % col("y")).alias("r"),
        col("x").cast("int").alias("i")).collect())
    assert q(ansi) == q(legacy) == q(cpu)


def test_legacy_mode_still_wraps_and_nulls():
    legacy = TpuSession()
    cpu = TpuSession({"spark.rapids.sql.enabled": "false"})
    df = lambda s: s.create_dataframe(
        {"x": np.asarray([I64MAX, 4], dtype=np.int64)})
    q = lambda s: df(s).select((col("x") + lit(1)).alias("w"),
                               (col("x") % lit(0)).alias("z")).collect()
    got, want = q(legacy), q(cpu)
    assert got == want
    assert got[0][0] == I64MIN  # wrapped
    assert got[0][1] is None    # null on zero divisor


def test_ansi_violation_not_blocklisted_as_speculation():
    """An ANSI error must raise AnsiViolation (no replay, no blocklist)."""
    from spark_rapids_tpu.runtime import speculation as spec
    before = set(spec._BLOCKLIST)
    s = TpuSession({"spark.sql.ansi.enabled": "true"})
    df = s.create_dataframe({"x": np.asarray([I64MAX], dtype=np.int64)})
    with pytest.raises(AnsiViolation):
        df.select((col("x") + lit(1)).alias("y")).collect()
    assert set(spec._BLOCKLIST) == before


def test_ansi_guarded_branches_do_not_raise():
    """The canonical guard idiom — CASE WHEN b != 0 THEN a/b ELSE 0 —
    must NOT raise for rows the predicate excludes (review finding:
    eager branch evaluation fired ANSI checks on unselected rows)."""
    for s in _sessions():
        df = s.create_dataframe({"a": np.asarray([10.0, 20.0]),
                                 "b": np.asarray([0.0, 2.0])})
        got = df.select(
            F.when(col("b") != lit(0.0), col("a") / col("b"))
            .otherwise(lit(0.0)).alias("r")).collect()
        assert got == [(0.0,), (10.0,)]
        # IF form
        got2 = df.select(
            F.expr_if(col("b") != lit(0.0), col("a") / col("b"),
                      lit(-1.0)).alias("r")).collect() \
            if hasattr(F, "expr_if") else None
    # unguarded rows must still raise
    s = _sessions()[0]
    df = s.create_dataframe({"a": np.asarray([10.0]),
                             "b": np.asarray([0.0])})
    with pytest.raises(AnsiViolation):
        df.select((col("a") / col("b")).alias("r")).collect()


def test_ansi_nested_guards():
    for s in _sessions():
        df = s.create_dataframe({"a": np.asarray([1.0, 4.0]),
                                 "b": np.asarray([0.0, 2.0]),
                                 "c": np.asarray([0.0, 1.0])})
        got = df.select(
            F.when(col("b") != lit(0.0),
                   F.when(col("c") != lit(0.0), col("a") / col("c"))
                   .otherwise(col("a") / col("b")))
            .otherwise(lit(0.0)).alias("r")).collect()
        assert got == [(0.0,), (4.0,)]
