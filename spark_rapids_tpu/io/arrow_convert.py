"""Arrow <-> HostTable conversion.

Arrow is the host interchange format (SURVEY.md §7: "Columnar batches live in
HBM as XLA buffers; Arrow is the host format"). Spark internal representations
are preserved: DATE as int32 days, TIMESTAMP as int64 micros UTC, DECIMAL(p<=18)
as int64 unscaled, STRING as Python-str object arrays (dictionary-encoded at
device upload time)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.errors import ColumnarProcessingError


def arrow_type_to_spark(at: pa.DataType) -> T.DataType:
    if pa.types.is_boolean(at):
        return T.BOOLEAN
    if pa.types.is_int8(at):
        return T.BYTE
    if pa.types.is_int16(at):
        return T.SHORT
    if pa.types.is_int32(at):
        return T.INT
    if pa.types.is_int64(at):
        return T.LONG
    if pa.types.is_float32(at):
        return T.FLOAT
    if pa.types.is_float64(at):
        return T.DOUBLE
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return T.STRING
    if pa.types.is_date32(at):
        return T.DATE
    if pa.types.is_timestamp(at):
        return T.TIMESTAMP
    if pa.types.is_decimal(at):
        return T.DecimalType(at.precision, at.scale)
    if pa.types.is_null(at):
        return T.NULL
    if pa.types.is_dictionary(at):
        return arrow_type_to_spark(at.value_type)
    raise ColumnarProcessingError(f"unsupported Arrow type {at}")


def spark_type_to_arrow(dt: T.DataType) -> pa.DataType:
    if isinstance(dt, T.BooleanType):
        return pa.bool_()
    if isinstance(dt, T.ByteType):
        return pa.int8()
    if isinstance(dt, T.ShortType):
        return pa.int16()
    if isinstance(dt, T.IntegerType):
        return pa.int32()
    if isinstance(dt, T.LongType):
        return pa.int64()
    if isinstance(dt, T.FloatType):
        return pa.float32()
    if isinstance(dt, T.DoubleType):
        return pa.float64()
    if isinstance(dt, T.StringType):
        return pa.string()
    if isinstance(dt, T.DateType):
        return pa.date32()
    if isinstance(dt, T.TimestampType):
        return pa.timestamp("us", tz="UTC")
    if isinstance(dt, T.DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, T.NullType):
        return pa.null()
    raise ColumnarProcessingError(f"no Arrow type for {dt}")


def arrow_schema_to_spark(schema: pa.Schema) -> List[Tuple[str, T.DataType]]:
    return [(f.name, arrow_type_to_spark(f.type)) for f in schema]


def _chunked_to_array(col: pa.ChunkedArray) -> pa.Array:
    return col.combine_chunks() if col.num_chunks != 1 else col.chunk(0)


def arrow_array_to_host_column(arr, dt: T.DataType) -> HostColumn:
    if isinstance(arr, pa.ChunkedArray):
        arr = _chunked_to_array(arr)
    if pa.types.is_dictionary(arr.type):
        arr = arr.cast(arr.type.value_type)
    n = len(arr)
    validity = np.ones(n, dtype=np.bool_)
    if arr.null_count:
        validity = ~np.asarray(arr.is_null())

    if isinstance(dt, T.StringType):
        data = np.empty(n, dtype=object)
        pylist = arr.to_pylist()
        for i, v in enumerate(pylist):
            data[i] = v
        return HostColumn(dt, data, validity)
    if isinstance(dt, T.TimestampType):
        micros = arr.cast(pa.timestamp("us"))
        vals = np.asarray(micros.fill_null(0)).astype("datetime64[us]").astype(np.int64)
        return HostColumn(dt, vals, validity)
    if isinstance(dt, T.DateType):
        vals = np.asarray(arr.fill_null(0)).astype("datetime64[D]").astype(np.int32)
        return HostColumn(dt, vals, validity)
    if isinstance(dt, T.DecimalType):
        import decimal as _dec
        # default context precision (28) silently ROUNDS 38-digit
        # decimals; widen it for the exact unscaled conversion
        ctx = _dec.Context(prec=T.DecimalType.MAX_PRECISION + 10)
        scaled = [int(v.scaleb(dt.scale, context=ctx)) if v is not None
                  else 0 for v in arr.to_pylist()]
        if T.is_dec128(dt):
            # unscaled beyond int64: python-int object storage (two-limb
            # device columns — columnar/column.py dec128_limbs)
            data = np.empty(n, dtype=object)
            data[:] = scaled
            return HostColumn(dt, data, validity)
        # int64 unscaled value, exact for p<=18
        return HostColumn(dt, np.array(scaled, dtype=np.int64), validity)
    if isinstance(dt, T.NullType):
        return HostColumn(dt, np.zeros(n, dtype=np.int8), np.zeros(n, dtype=np.bool_))
    # fixed-width numerics/bool: zero-fill nulls then view as numpy
    if arr.null_count:
        arr = arr.fill_null(False if pa.types.is_boolean(arr.type) else 0)
    vals = np.asarray(arr)
    np_dtype = dt.np_dtype
    if vals.dtype != np_dtype:
        vals = vals.astype(np_dtype)
    return HostColumn(dt, np.ascontiguousarray(vals), validity)


def arrow_to_host_table(table: pa.Table,
                        schema: Optional[Sequence[Tuple[str, T.DataType]]] = None
                        ) -> HostTable:
    if schema is None:
        schema = arrow_schema_to_spark(table.schema)
    names, cols = [], []
    for (name, dt) in schema:
        arr = table.column(name)
        names.append(name)
        cols.append(arrow_array_to_host_column(arr, dt))
    return HostTable(names, cols)


def decode_to_schema(table: pa.Table, schema: Sequence[Tuple[str, T.DataType]]
                     ) -> HostTable:
    """Select the schema's columns present in ``table`` and SAFELY cast each
    to the expected Arrow type before conversion. This pins multi-file reads
    to the scan schema: a file whose inferred types drift (e.g. int column
    that parses as double in file 2) either casts losslessly or raises,
    instead of silently truncating at the numpy layer."""
    present = set(table.schema.names)
    use = [(n, dt) for n, dt in schema if n in present]
    names, cols = [], []
    for name, dt in use:
        arr = table.column(name)
        if isinstance(arr, pa.ChunkedArray):
            arr = _chunked_to_array(arr)
        target = spark_type_to_arrow(dt)
        if not pa.types.is_dictionary(arr.type) and arr.type != target \
                and not isinstance(dt, T.NullType):
            arr = arr.cast(target)  # safe cast: raises on lossy conversion
        names.append(name)
        cols.append(arrow_array_to_host_column(arr, dt))
    return HostTable(names, cols)


def host_column_to_arrow(col: HostColumn) -> pa.Array:
    dt = col.dtype
    mask = None if bool(col.validity.all()) else ~col.validity
    if isinstance(dt, T.StringType):
        vals = [v if ok else None for v, ok in zip(col.data, col.validity)]
        return pa.array(vals, type=pa.string())
    if isinstance(dt, T.TimestampType):
        return pa.array(col.data.astype("datetime64[us]"), mask=mask,
                        type=pa.timestamp("us", tz="UTC"))
    if isinstance(dt, T.DateType):
        return pa.array(col.data.astype("datetime64[D]"), mask=mask, type=pa.date32())
    if isinstance(dt, T.DecimalType):
        import decimal
        ctx = decimal.Context(prec=T.DecimalType.MAX_PRECISION + 10)
        q = decimal.Decimal(1).scaleb(-dt.scale)
        vals = [decimal.Decimal(int(v)).scaleb(-dt.scale, context=ctx)
                .quantize(q, context=ctx) if ok else None
                for v, ok in zip(col.data, col.validity)]
        return pa.array(vals, type=pa.decimal128(dt.precision, dt.scale))
    if isinstance(dt, T.NullType):
        return pa.nulls(len(col))
    return pa.array(col.data, mask=mask, type=spark_type_to_arrow(dt))


def host_table_to_arrow(table: HostTable) -> pa.Table:
    arrays = [host_column_to_arrow(c) for c in table.columns]
    return pa.table(dict(zip(table.names, arrays)))
