"""Runtime layer: device manager, task semaphore, spill catalog, OOM retry
(reference: GpuDeviceManager / GpuSemaphore / RapidsBufferCatalog /
RmmRapidsRetryIterator — SURVEY.md §2.5)."""

from spark_rapids_tpu.runtime.device_manager import TpuDeviceManager  # noqa: F401
from spark_rapids_tpu.runtime.semaphore import TpuSemaphore, acquired  # noqa: F401
from spark_rapids_tpu.runtime.spill import (  # noqa: F401
    BufferCatalog,
    SpillableBatch,
    TIER_DEVICE,
    TIER_DISK,
    TIER_HOST,
)
from spark_rapids_tpu.runtime.retry import (  # noqa: F401
    RMM_TPU,
    is_device_oom,
    retry_block,
    split_device_table_in_half,
    with_retry,
    with_retry_no_split,
)
