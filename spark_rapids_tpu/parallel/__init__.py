"""Distributed execution over a jax.sharding.Mesh.

Reference (SURVEY.md §2.6 TPU equivalent): the UCX peer-to-peer transport's
TPU analog — when all shuffle partitions live on one pod slice, a shuffle
exchange is ONE all-to-all collective over ICI instead of host files; DCN /
host shuffle (shuffle/manager.py) remains the cross-slice fallback."""

from spark_rapids_tpu.parallel.exchange import (
    mesh_hash_exchange,
    mesh_partial_then_merge,
)
from spark_rapids_tpu.parallel.mesh import MESH, MeshRuntime

__all__ = ["MESH", "MeshRuntime", "mesh_hash_exchange",
           "mesh_partial_then_merge"]
