"""Mesh re-land boundary: where sharded residency ends inside a plan.

Mesh-native execution (parallel/mesh.py) lands scan shards per-device
and lets the narrow pipeline — filter/project/masked ops, and the ICI
shuffle exchange — run on the resident shards (GSPMD partitions those
kernels; they are elementwise or pure data movement, so their results
are bitwise independent of the layout). Wide kernels are NOT layout-
independent: a float reduction partitioned over 8 shards accumulates in
a different order than the single-chip kernel, and the contract for
this engine is BIT-IDENTITY with single-chip results (scale_test
--mesh, MULTICHIP_r06). So every wide consumer (aggregate, sort, join,
window, ...) takes its input through a :class:`TpuMeshRelandExec`
boundary inserted at conversion time: one device-side gather (ICI on a
real pod — the host is never touched, pinned by RL-MESH-HOST and the
meshHostUploads counter) that re-lands the shards into the single-
device layout the wide kernel compiles against.

Post-exchange inputs are already per-device (the all-to-all emits each
partition on its owner device), so the boundary is a no-op there — the
distributed path through scan -> narrow ops -> ICI exchange ->
per-partition wide ops pays zero re-lands and zero host transfers.
"""

from __future__ import annotations

from spark_rapids_tpu.columnar import DeviceTable
from spark_rapids_tpu.execs.base import (
    DeviceToHost,
    HostToDevice,
    InputAdapter,
    TpuExec,
)


class TpuMeshRelandExec(TpuExec):
    """Schema-preserving residency boundary: re-lands physically
    sharded batches into the single-device layout (DeviceTable.
    unsharded) so the parent's kernels bitwise-match single-chip
    execution. Transparent to both batch protocols — masked batches
    stay masked (their live mask re-lands with the columns)."""

    def __init__(self, child: TpuExec):
        super().__init__()
        self.children = (child,)
        # mirror the child's protocol so mask-aware parents keep
        # consuming masked batches through the boundary
        self.produces_masked = bool(getattr(child, "produces_masked",
                                            False))

    def output_schema(self):
        return self.children[0].output_schema()

    def execute(self):
        for b in self.children[0].execute():
            yield self._reland(b)

    def execute_masked(self):
        for b in self.children[0].execute_masked():
            yield self._reland(b)

    def _reland(self, table: DeviceTable) -> DeviceTable:
        # count only PHYSICAL gathers: unsharded() also returns a new
        # object when it merely drops a shard_spec descriptor from
        # single-device buffers (1-device mesh) — no data moved there
        if table.physically_sharded() and table.columns:
            from spark_rapids_tpu.parallel.mesh import MESH_SCOPE
            self.add_metric("meshRelandRows", table.capacity)
            MESH_SCOPE.add("meshRelandRows", table.capacity)
        return table.unsharded()

    def describe(self):
        return "MeshReland"


#: consumers that accept physically sharded input: elementwise /
#: data-movement execs whose results are bitwise layout-independent
#: (GSPMD partitions them across the resident shards), the ICI
#: exchange (it re-shards explicitly via shard_put), and the re-land
#: boundary itself. Everything else sees the single-device layout.
def _shard_safe_consumers() -> tuple:
    from spark_rapids_tpu.execs.basic import TpuFilterExec, TpuProjectExec
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    return (TpuFilterExec, TpuProjectExec, TpuShuffleExchangeExec,
            TpuMeshRelandExec)


def insert_mesh_relands(executable):
    """Conversion-time pass (applied by apply_overrides when mesh-
    native execution is on): wrap the TpuExec children of every
    non-shard-safe consumer in a re-land boundary, and stamp every scan
    with the mesh generation the boundaries were planned against
    (``_mesh_scan_gen`` — execs/basic._scan_sharding). Sharded
    placement is therefore BOUND to the converted tree: an unstamped
    tree (converted with the mesh off) never lands sharded batches even
    if a concurrent session flips the process mesh on mid-query — it
    has no boundaries, so sharded input would let GSPMD repartition a
    wide float kernel and break bit-identity. The boundary is a no-op
    on unsharded batches, so liberal insertion is correct — the
    whitelist only determines where sharded residency may FLOW, and
    default-deny means a new exec is bit-identical by construction
    until it is proven layout-independent."""
    from spark_rapids_tpu.execs.basic import TpuFileScanExec, TpuScanExec
    from spark_rapids_tpu.parallel.mesh import MESH

    safe = _shard_safe_consumers()
    gen = MESH.generation()

    def rec(node):
        if isinstance(node, (TpuScanExec, TpuFileScanExec)):
            node._mesh_scan_gen = gen
        if isinstance(node, DeviceToHost):
            # the root/mid-plan transition gathers to host anyway (the
            # sanctioned materialization point) — sharded input is fine
            rec(node.tpu_exec)
            return
        if isinstance(node, HostToDevice):
            rec(node.cpu_node)
            return
        if isinstance(node, InputAdapter):
            rec(node.source)
            return
        scan_node = getattr(node, "scan_node", None)
        if scan_node is not None:
            rec(scan_node)
        children = tuple(getattr(node, "children", ()) or ())
        if not children:
            return
        if isinstance(node, TpuExec) and not isinstance(node, safe):
            node.children = tuple(
                TpuMeshRelandExec(c)
                if isinstance(c, TpuExec)
                and not isinstance(c, TpuMeshRelandExec) else c
                for c in node.children)
            children = node.children
        for c in children:
            rec(c)

    rec(executable)
    return executable
