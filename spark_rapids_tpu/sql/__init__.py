"""SQL front end: text -> lexer -> parser -> analyzer -> the existing
DataFrame/plan layer (reference: the Spark SQL planner surface
SQLExecPlugin hooks; here the front end is in-repo because there is no
Spark to delegate parsing to).

Entry points:
  * ``TpuSession.sql(text)``           — run a statement
  * ``spark_rapids_tpu.functions.expr``— parse one expression
  * ``SessionCatalog``                 — temp views / tables / functions

The analyzer lowers onto plan nodes only; every SQL query then flows
through overrides tagging, fallback, and AQE unchanged."""

from spark_rapids_tpu.sql.analyzer import lower_statement  # noqa: F401
from spark_rapids_tpu.sql.catalog import SessionCatalog  # noqa: F401
from spark_rapids_tpu.sql.errors import (  # noqa: F401
    SqlAnalysisError,
    SqlError,
    SqlParseError,
)
from spark_rapids_tpu.sql.parser import (  # noqa: F401
    parse_expression,
    parse_statement,
)
