"""Fleet closure: composable chaos planes, the shared-topology path,
and the --fleet flag surface.

The tentpole contract under test, WITHOUT paying for a fleet run:

* fault planes COMPOSE — ``validate_flags`` accepts the plane
  combinations (--fleet, --hosts x --device-budget x --concurrency)
  and still fails fast on the combinations no harness implements;
* ``--fleet --dry-run`` is an under-5s subprocess smoke: it builds the
  plan, validates the merged cross-domain schedule through the real
  spec parser, prints JSON and exits 0 — no backend, no cluster;
* incident bundles carry the process-monotonic ``seq`` id and the
  ``faultDomain`` classification the closure matches ladder actions
  against;
* the runtime lock witness counts rank inversions in-band
  (``lockorder.witness_violations``) — what every chaos artifact
  records as ``lockWitnessViolations``;
* ``consistent_topology_snapshot`` serves hosts + mesh + memory +
  quarantine under every owning lock at once, and
  ``QueryService.health()`` reads it (fleetDegradedReason,
  topologyGeneration).
"""

import json
import os
import subprocess
import sys
import time
from types import SimpleNamespace

import pytest

from spark_rapids_tpu.conf import RapidsConf

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# flag matrix: composable planes accepted, unimplemented combos rejected
# ---------------------------------------------------------------------------


def _args(**kw):
    base = dict(mesh=0, hosts=0, streaming=False, concurrency=0,
                service_faults=False, cpu_baseline=False,
                require_tpu=False, chaos=False, device_budget=0,
                fleet=False, dry_run=False)
    base.update(kw)
    return SimpleNamespace(**base)


def test_fleet_flag_matrix_accepted():
    """Plane combinations route to the fleet closure: --fleet alone,
    --fleet with explicit knobs, and any two of --hosts /
    --device-budget / --concurrency without the flag."""
    import scale_test as st

    for ok in (_args(fleet=True),
               _args(fleet=True, hosts=2),
               _args(fleet=True, device_budget=8192),
               _args(fleet=True, concurrency=4),
               _args(fleet=True, dry_run=True),
               _args(fleet=True, hosts=3, device_budget=8192,
                     concurrency=4, service_faults=True, chaos=True),
               # composition WITHOUT --fleet: two planes together
               _args(hosts=2, concurrency=4),
               _args(hosts=2, device_budget=8192),
               _args(device_budget=8192, concurrency=4),
               _args(hosts=2, device_budget=8192, concurrency=4)):
        st.validate_flags(ok)


def test_fleet_flag_matrix_rejected():
    """The combinations no harness implements still fail fast, naming
    the supported modes — including the floors inside the fleet path
    and --dry-run outside it."""
    import scale_test as st

    for bad in (_args(fleet=True, mesh=8),
                _args(fleet=True, streaming=True),
                _args(fleet=True, cpu_baseline=True),
                _args(fleet=True, require_tpu=True),
                _args(fleet=True, hosts=1),
                _args(fleet=True, device_budget=100),
                _args(dry_run=True),             # --dry-run needs --fleet
                _args(dry_run=True, chaos=True)):
        with pytest.raises(SystemExit) as ei:
            st.validate_flags(bad)
        assert "supported modes" in str(ei.value)


def test_single_plane_rejections_retained():
    """Composing planes did NOT loosen the single-plane modes: a lone
    mode keeps its original harness and its original rejections."""
    import scale_test as st

    # still supported single-plane invocations
    st.validate_flags(_args(chaos=True, concurrency=4,
                            service_faults=True))
    st.validate_flags(_args(hosts=2, chaos=True))
    st.validate_flags(_args(device_budget=8192, chaos=True))
    for bad in (_args(cpu_baseline=True, chaos=True),
                _args(mesh=8, concurrency=4),
                _args(hosts=2, service_faults=True),
                _args(streaming=True, device_budget=8192),
                _args(device_budget=100)):
        with pytest.raises(SystemExit) as ei:
            st.validate_flags(bad)
        assert "supported modes" in str(ei.value)


# ---------------------------------------------------------------------------
# --fleet --dry-run: the under-5s plan-and-validate subprocess smoke
# ---------------------------------------------------------------------------


def test_fleet_dry_run_subprocess_smoke():
    """``scale_test.py --fleet --dry-run`` plans the run, validates the
    merged schedule parses, prints the plan JSON and exits 0 — fast
    enough to live in tier-1 (no jax import, no cluster boot)."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scale_test.py"),
         "--fleet", "--dry-run"],
        capture_output=True, text=True, timeout=30, cwd=_REPO)
    wall = time.monotonic() - t0
    assert proc.returncode == 0, proc.stderr
    assert wall < 5.0, f"dry-run took {wall:.1f}s — not a smoke anymore"
    plan = json.loads(proc.stdout.strip().splitlines()[-1])
    assert plan["mode"] == "fleet-plan"
    assert set(plan["planes"]) == {"host", "mesh", "memory", "service",
                                   "exec"}
    # the merged schedule covers every assertable fault domain
    spec = plan["merged_fault_spec"]
    for prefix in ("host.", "mesh.", "mem.", "service."):
        assert prefix in spec
    assert plan["merged_fault_points"] == len(
        [e for e in spec.split(";") if e])
    # merged bounds are the per-plane maxima
    assert plan["merged_bounds"]["oomRetries"] == 4000
    assert plan["merged_bounds"]["query_replays"] == 30
    assert plan["merged_bounds"]["workersLost"] == 8


def test_fleet_plan_merges_planes_deterministically():
    import scale_test as st

    planes = st.fleet_planes(7)
    spec = st.fleet_fault_spec(7)
    assert spec == ";".join(p["spec"] for p in planes.values())
    # same seed -> same schedule; different seed -> different streams
    assert st.fleet_fault_spec(7) == spec
    assert st.fleet_fault_spec(8) != spec
    # the merged spec parses through the real arm-time parser
    from spark_rapids_tpu.runtime.faults import parse_fault_spec
    assert len(parse_fault_spec(spec)) >= 10
    bounds = st.fleet_bounds(planes)
    for plane in planes.values():
        for field, b in plane["bounds"].items():
            assert bounds[field] >= b


def test_fleet_point_domain_classification():
    import scale_test as st

    assert st._fleet_point_domain("host.dispatch") == "host"
    assert st._fleet_point_domain("mesh.gather") == "mesh"
    assert st._fleet_point_domain("mem.reserve") == "memory"
    assert st._fleet_point_domain("stream.batch") == "stream"
    for svc_point in ("service.worker_crash", "device.lost",
                      "dispatch.wedge", "exec.execute"):
        assert st._fleet_point_domain(svc_point) == "service"


# ---------------------------------------------------------------------------
# incident bundles: seq id + faultDomain
# ---------------------------------------------------------------------------


def test_incident_bundle_seq_and_fault_domain(tmp_path):
    """Every bundle carries a process-monotonic seq id (unique even
    when wall clocks collide) and the faultDomain its kind classifies
    into — what the fleet closure matches ladder actions against."""
    from spark_rapids_tpu.obs.telemetry import record_incident
    from spark_rapids_tpu.tools.incident import load_bundles
    conf = RapidsConf({
        "spark.rapids.obs.flightRecorder.dir": str(tmp_path)})
    expect = {"host.ladder": "host", "mesh.ladder": "mesh",
              "memory.ladder": "memory", "backend.ladder": "service",
              "stream.resume": "stream", "quarantine": "service"}
    for kind in expect:
        assert record_incident(kind, "act", "r", conf=conf)
    bundles = load_bundles(str(tmp_path))
    assert len(bundles) == len(expect)
    seqs = [b["seq"] for b in bundles]
    assert len(set(seqs)) == len(seqs)
    assert seqs == sorted(seqs)  # load_bundles sorts by filename = seq order
    for b in bundles:
        assert b["schema"] == 2
        assert b["faultDomain"] == expect[b["kind"]]


def test_fault_domain_prefix_table():
    from spark_rapids_tpu.obs.telemetry import fault_domain
    assert fault_domain("host.ladder") == "host"
    assert fault_domain("mesh.ladder") == "mesh"
    assert fault_domain("memory.ladder") == "memory"
    assert fault_domain("stream.resume") == "stream"
    assert fault_domain("backend.ladder") == "service"
    assert fault_domain("kernel.demotion") == "service"


# ---------------------------------------------------------------------------
# the runtime lock witness violation counter
# ---------------------------------------------------------------------------


def test_lock_witness_violation_counter():
    """Rank inversions are COUNTED, not just raised — the in-band
    evidence every chaos artifact records as lockWitnessViolations."""
    from spark_rapids_tpu import lockorder
    lockorder.arm_witness()
    try:
        before = lockorder.witness_violations()
        low = lockorder.ordered_lock("streaming.query")     # rank 100
        high = lockorder.ordered_lock("memory.arbiter")     # rank 740
        with low:
            with high:
                pass
        assert lockorder.witness_violations() == before  # ascending: clean
        with high:
            with pytest.raises(lockorder.LockOrderViolation):
                low.acquire()
        assert lockorder.witness_violations() == before + 1
        with pytest.raises(lockorder.LockOrderViolation):
            with low:
                low.acquire()  # self-deadlock counts too
        assert lockorder.witness_violations() == before + 2
        assert len(lockorder.witness_violation_records()) >= 2
    finally:
        lockorder.disarm_witness()
        # the counter is process-global: leave it clean or every later
        # in-process chaos closure reads these deliberate inversions
        lockorder.reset_witness_violations()


# ---------------------------------------------------------------------------
# the shared-topology path
# ---------------------------------------------------------------------------


def test_consistent_topology_snapshot_shape():
    """One generation-stamped document with hosts + mesh + memory +
    quarantine read under every owning lock at once — the view the
    service's admission control and the ladders both consult."""
    from spark_rapids_tpu.runtime.health import (
        consistent_topology_snapshot,
    )
    topo = consistent_topology_snapshot()
    assert set(topo) >= {"generation", "state", "backend", "hosts",
                         "mesh", "memory", "quarantine"}
    assert isinstance(topo["generation"], int)
    assert topo["state"] in ("HEALTHY", "DEGRADED", "CPU_ONLY")
    assert "hostsLost" in topo["hosts"]
    assert "meshDeviceLost" in topo["mesh"]
    assert "memoryPressureEvents" in topo["memory"]
    assert "budgetBytes" in topo["memory"]


def test_service_health_reads_fleet_topology():
    """QueryService.health() consults the shared topology: the merged
    view rides in-band (fleetDegradedReason, topologyGeneration) and
    /topology serves the same document."""
    from spark_rapids_tpu.service.scheduler import QueryService
    with QueryService({"spark.rapids.service.introspect.enabled":
                       "true"}) as svc:
        h = svc.health()
        assert "fleetDegradedReason" in h
        assert h["fleetDegradedReason"] is None  # quiet fleet: no reason
        assert isinstance(h["topologyGeneration"], int)
        topo = svc.topology_snapshot()
        assert topo["generation"] == h["topologyGeneration"]
        import urllib.request
        url = f"http://127.0.0.1:{svc.introspect_port}/topology"
        with urllib.request.urlopen(url, timeout=10) as resp:
            doc = json.loads(resp.read().decode("utf-8"))
        assert set(doc) == set(topo)
        assert doc["hosts"].keys() == topo["hosts"].keys()
