"""Decimal arithmetic with Spark semantics.

Reference (SURVEY.md §2.9): ``DecimalUtils`` (spark-rapids-jni) provides
128-bit decimal multiply/divide kernels; ``DecimalArithmeticOverrides``
registers the decimal Add/Subtract/Multiply/Divide rules with Spark's
precision/scale promotion and ``CheckOverflow`` (null on overflow in
non-ANSI mode); ``GpuUnscaledValue``/``GpuMakeDecimal`` reinterpret
between LongType and DecimalType.

TPU mapping:
- storage: p<=18 columns are int64 unscaled values (DECIMAL64 — the
  reference's original device tier); p>18 columns evaluate on the HOST
  path with exact Python-int arithmetic (device tags a fallback reason,
  the reference's early carve-out pattern).
- device kernels: int64xint64 products and rescales run in TWO-LIMB
  (hi int64, lo uint64) 128-bit arithmetic built from 32-bit partial
  products — exact Multiply/Divide for decimal64 operands whose
  intermediates exceed 64 bits (the DecimalUtils role).
- Spark result-type rules incl. ``adjustPrecisionScale`` precision-loss
  scale reduction; overflow -> NULL (non-ANSI default).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.ops.common import BinaryExpression, UnaryExpression, null_and
from spark_rapids_tpu.ops.expr import DevVal, Expression

MAX_PRECISION = 38
MAX_LONG_DIGITS = 18
_POW10 = [10 ** i for i in range(MAX_PRECISION + 1)]


# ---------------------------------------------------------------------------
# result-type rules (Spark DecimalPrecision + adjustPrecisionScale)
# ---------------------------------------------------------------------------

def _adjust(p: int, s: int) -> Tuple[int, int]:
    """Spark adjustPrecisionScale (allowPrecisionLoss=true default)."""
    if p <= MAX_PRECISION:
        return p, s
    int_digits = p - s
    min_scale = min(s, 6)
    adjusted_scale = max(MAX_PRECISION - int_digits, min_scale)
    return MAX_PRECISION, adjusted_scale

def add_result_type(a: T.DecimalType, b: T.DecimalType) -> T.DecimalType:
    s = max(a.scale, b.scale)
    p = max(a.precision - a.scale, b.precision - b.scale) + s + 1
    return T.DecimalType(*_adjust(p, s))


def mul_result_type(a: T.DecimalType, b: T.DecimalType) -> T.DecimalType:
    return T.DecimalType(*_adjust(a.precision + b.precision + 1,
                                  a.scale + b.scale))


def div_result_type(a: T.DecimalType, b: T.DecimalType) -> T.DecimalType:
    s = max(6, a.scale + b.precision + 1)
    p = a.precision - a.scale + b.scale + s
    return T.DecimalType(*_adjust(p, s))


def decimal_for(dt: T.DataType) -> Optional[T.DecimalType]:
    """Implicit integral->decimal promotion used by Spark's coercion."""
    if isinstance(dt, T.DecimalType):
        return dt
    if isinstance(dt, T.ByteType):
        return T.DecimalType(3, 0)
    if isinstance(dt, T.ShortType):
        return T.DecimalType(5, 0)
    if isinstance(dt, T.IntegerType):
        return T.DecimalType(10, 0)
    if isinstance(dt, T.LongType):
        return T.DecimalType(20, 0)
    return None


# ---------------------------------------------------------------------------
# host (exact Python-int) helpers — work at ANY precision
# ---------------------------------------------------------------------------

def host_unscaled(col: HostColumn):
    """Column unscaled values as a Python-int object array."""
    if col.data.dtype == object:
        return col.data
    return col.data.astype(object)


def host_store(values, validity, dtype: T.DecimalType) -> HostColumn:
    """Pack python-int unscaled values into the storage layout for
    ``dtype`` (int64 when p<=18, object otherwise); overflowed slots must
    already be nulled."""
    n = len(values)
    if dtype.precision <= MAX_LONG_DIGITS:
        out = np.zeros(n, dtype=np.int64)
        for i in range(n):
            if validity[i]:
                out[i] = values[i]
        return HostColumn(dtype, out, validity)
    out = np.empty(n, dtype=object)
    for i in range(n):
        out[i] = int(values[i]) if validity[i] else 0
    return HostColumn(dtype, out, validity)


def _round_half_up_div(v: int, d: int) -> int:
    """v / d with HALF_UP rounding (Java BigDecimal default in Spark)."""
    q, r = divmod(abs(v), d)
    if 2 * r >= d:
        q += 1
    return -q if v < 0 else q


def rescale_int(v: int, from_scale: int, to_scale: int) -> int:
    if to_scale >= from_scale:
        return v * _POW10[to_scale - from_scale]
    return _round_half_up_div(v, _POW10[from_scale - to_scale])


# ---------------------------------------------------------------------------
# device two-limb (hi int64, lo uint64) kernels — the DecimalUtils analog
# ---------------------------------------------------------------------------

_MASK32 = jnp.uint64(0xFFFFFFFF)


def i64_mul_to_i128(a, b):
    """Exact int64*int64 -> (hi int64, lo uint64) via 32-bit partials."""
    ua = a.astype(jnp.uint64)
    ub = b.astype(jnp.uint64)
    a_lo = ua & _MASK32
    a_hi = ua >> jnp.uint64(32)
    b_lo = ub & _MASK32
    b_hi = ub >> jnp.uint64(32)
    ll = a_lo * b_lo
    lh = a_lo * b_hi
    hl = a_hi * b_lo
    hh = a_hi * b_hi
    mid = (ll >> jnp.uint64(32)) + (lh & _MASK32) + (hl & _MASK32)
    lo = (ll & _MASK32) | ((mid & _MASK32) << jnp.uint64(32))
    hi_u = hh + (lh >> jnp.uint64(32)) + (hl >> jnp.uint64(32)) + \
        (mid >> jnp.uint64(32))
    # signed correction: for negative a, subtract b<<64; likewise for b
    hi = hi_u.astype(jnp.int64)
    hi = hi - jnp.where(a < 0, b, jnp.int64(0))
    hi = hi - jnp.where(b < 0, a, jnp.int64(0))
    return hi, lo


def i128_neg(hi, lo):
    nlo = (~lo) + jnp.uint64(1)
    nhi = (~hi).astype(jnp.int64) + jnp.where(nlo == 0, 1, 0).astype(jnp.int64)
    return nhi, nlo


def i128_abs(hi, lo):
    neg = hi < 0
    nhi, nlo = i128_neg(hi, lo)
    return jnp.where(neg, nhi, hi), jnp.where(neg, nlo, lo), neg


def u128_divmod_small(hi, lo, m):
    """(hi uint64, lo uint64) unsigned // m for m < 2**31 (python int or
    uint64 array), via 32-bit limb long division. Returns
    (qhi, qlo, rem); a zero divisor is guarded to 1 (callers null those
    slots)."""
    mm = jnp.uint64(m) if isinstance(m, int) else m
    mm = jnp.where(mm == 0, jnp.uint64(1), mm)
    limbs = [hi >> jnp.uint64(32), hi & _MASK32,
             lo >> jnp.uint64(32), lo & _MASK32]
    q = []
    rem = jnp.zeros_like(hi)
    for limb in limbs:
        acc = (rem << jnp.uint64(32)) | limb
        q.append(acc // mm)
        rem = acc % mm
    qhi = (q[0] << jnp.uint64(32)) | q[1]
    qlo = (q[2] << jnp.uint64(32)) | q[3]
    return qhi, qlo, rem


def i128_div_pow10_half_up(hi, lo, d: int):
    """(hi,lo)/10^d with HALF_UP rounding; signed. d in [0, 18] (callers
    gate — the remainder comparison needs 10^d to fit uint64)."""
    if d == 0:
        return hi, lo
    assert d <= 18, d
    ahi_s, alo, neg = i128_abs(hi, lo)
    ahi = ahi_s.astype(jnp.uint64)
    # divide by 10^d in <=2^31 chunks, accumulating the true remainder
    rem_scale = 1
    rem_total = jnp.zeros_like(alo)
    k = d
    while k > 0:
        step = min(k, 9)
        m = 10 ** step
        ahi, alo, r = u128_divmod_small(ahi, alo, m)
        rem_total = rem_total + r * jnp.uint64(rem_scale)
        rem_scale *= m
        k -= step
    # HALF_UP: round away from zero when 2*rem >= 10^d
    round_up = rem_total * jnp.uint64(2) >= jnp.uint64(_POW10[d])
    alo2 = alo + jnp.where(round_up, jnp.uint64(1), jnp.uint64(0))
    ahi2 = ahi + jnp.where((alo2 == 0) & round_up, jnp.uint64(1),
                           jnp.uint64(0))
    shi = ahi2.astype(jnp.int64)
    rhi, rlo = i128_neg(shi, alo2)
    return jnp.where(neg, rhi, shi), jnp.where(neg, rlo, alo2)


def i128_mul_pow10(hi, lo, d: int):
    """(hi,lo) * 10^d via repeated 64x64 partials; d <= 18 (call-site
    gated). Overflow beyond 128 bits is the caller's fits-check concern."""
    if d == 0:
        return hi, lo
    m = _POW10[d]
    # lo * m (unsigned 64x64 -> 128)
    ml = jnp.int64(m)
    lo_s = lo.astype(jnp.int64)  # reinterpret; i64_mul handles signs via
    p_hi, p_lo = i64_mul_to_i128(lo_s, ml)
    # correction: lo was UNSIGNED; i64_mul treated sign bit as negative:
    # if lo >= 2^63 it subtracted m<<64; add it back
    p_hi = p_hi + jnp.where(lo_s < 0, ml, jnp.int64(0))
    hi_m = hi * ml  # low 64 bits of hi*m feed the high limb
    return p_hi + hi_m, p_lo


def i128_fits_int64(hi, lo):
    """Value representable as int64?"""
    pos_ok = (hi == 0) & (lo <= jnp.uint64(0x7FFFFFFFFFFFFFFF))
    neg_ok = (hi == -1) & (lo >= jnp.uint64(1 << 63))
    return pos_ok | neg_ok


def i128_to_i64(hi, lo):
    return lo.astype(jnp.int64)


def i128_abs_fits_pow10(hi, lo, p: int):
    """|value| < 10^p — the CheckOverflow bound. p <= 38."""
    bound = _POW10[p]
    bhi = jnp.int64(bound >> 64)
    blo = jnp.uint64(bound & 0xFFFFFFFFFFFFFFFF)
    ahi_s, alo, _ = i128_abs(hi, lo)
    ahi = ahi_s.astype(jnp.uint64)
    return (ahi < bhi.astype(jnp.uint64)) | (
        (ahi == bhi.astype(jnp.uint64)) & (alo < blo))


# ---------------------------------------------------------------------------
# expressions
# ---------------------------------------------------------------------------

def dev_rescale_checked(data, validity, from_scale: int, to_scale: int,
                        precision: int):
    """Shared device decimal rescale-with-overflow-check (backs both
    CheckOverflow and the decimal->decimal Cast): 128-bit scale shift,
    HALF_UP on scale-down, null when the result misses int64 or 10^p."""
    d = to_scale - from_scale
    hi = jnp.where(data < 0, jnp.int64(-1), jnp.int64(0))
    lo = data.astype(jnp.uint64)
    if d >= 0:
        hi, lo = i128_mul_pow10(hi, lo, d)
    else:
        hi, lo = i128_div_pow10_half_up(hi, lo, -d)
    out_valid = validity & i128_fits_int64(hi, lo) & \
        i128_abs_fits_pow10(hi, lo, precision)
    return DevVal(jnp.where(out_valid, i128_to_i64(hi, lo),
                            jnp.int64(0)), out_valid)


class DecimalBinary(BinaryExpression):
    """Base: operands are decimals (coercion inserts promotions before)."""

    op_name = "?"

    def __init__(self, left: Expression, right: Expression):
        super().__init__(left, right)
        self._ltype: T.DecimalType = left.data_type
        self._rtype: T.DecimalType = right.data_type
        self._result = self._result_type(self._ltype, self._rtype)

    @property
    def data_type(self) -> T.DecimalType:
        return self._result

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def key(self):
        return (self.op_name, str(self._ltype), str(self._rtype),
                tuple(c.key() for c in self.children))

    def _result_type(self, a, b) -> T.DecimalType:
        raise NotImplementedError

    # host exact path -------------------------------------------------------
    def _host_op(self, lv: int, rv: int):
        """Exact unscaled result at the RESULT scale, or None (null)."""
        raise NotImplementedError

    def eval_cpu(self, table: HostTable) -> HostColumn:
        l = self.left.eval_cpu(table)
        r = self.right.eval_cpu(table)
        validity = (l.validity & r.validity).copy()
        ld = host_unscaled(l)
        rd = host_unscaled(r)
        bound = _POW10[self._result.precision]
        out = [0] * len(ld)
        for i in range(len(ld)):
            if not validity[i]:
                continue
            v = self._host_op(int(ld[i]), int(rd[i]))
            if v is None or abs(v) >= bound:
                validity[i] = False  # CheckOverflow: null (non-ANSI)
            else:
                out[i] = v
        return host_store(out, validity, self._result)


class DecimalAdd(DecimalBinary):
    op_name = "dec_add"
    _sign = 1

    def _result_type(self, a, b):
        return add_result_type(a, b)

    @property
    def device_supported(self):
        return (self._ltype.precision <= MAX_LONG_DIGITS
                and self._rtype.precision <= MAX_LONG_DIGITS
                and self._result.precision <= MAX_LONG_DIGITS + 1)

    def _host_op(self, lv, rv):
        s = self._result.scale
        v = rescale_int(lv, self._ltype.scale, s) + \
            self._sign * rescale_int(rv, self._rtype.scale, s)
        return v

    def eval_dev(self, ctx, child_vals, prep):
        lval, rval = child_vals
        s = self._result.scale
        dl = s - self._ltype.scale
        dr = s - self._rtype.scale
        # operands rescaled into 128-bit, added, checked against 10^p
        lhi, llo = i128_mul_pow10(
            jnp.where(lval.data < 0, jnp.int64(-1), jnp.int64(0)),
            lval.data.astype(jnp.uint64), dl)
        rhi, rlo = i128_mul_pow10(
            jnp.where(rval.data < 0, jnp.int64(-1), jnp.int64(0)),
            rval.data.astype(jnp.uint64), dr)
        if self._sign < 0:
            rhi, rlo = i128_neg(rhi, rlo)
        lo = llo + rlo
        hi = lhi + rhi + jnp.where(lo < llo, 1, 0).astype(jnp.int64)
        validity = null_and(lval.validity, rval.validity)
        # p+1-digit sums up to 10^19 may still be representable in int64
        # (device_supported admits p = 19); bound by the RESULT precision,
        # capped at 19 where i128_fits_int64 takes over
        fits = i128_fits_int64(hi, lo) & \
            i128_abs_fits_pow10(hi, lo, min(self._result.precision, 19))
        validity = validity & fits
        data = jnp.where(validity, i128_to_i64(hi, lo), jnp.int64(0))
        return DevVal(data, validity)


class DecimalSubtract(DecimalAdd):
    op_name = "dec_sub"
    _sign = -1


class DecimalMultiply(DecimalBinary):
    op_name = "dec_mul"

    def _result_type(self, a, b):
        return mul_result_type(a, b)

    @property
    def device_supported(self):
        raw_scale = self._ltype.scale + self._rtype.scale
        down = raw_scale - self._result.scale
        return (self._ltype.precision <= MAX_LONG_DIGITS
                and self._rtype.precision <= MAX_LONG_DIGITS
                and self._result.precision <= MAX_LONG_DIGITS
                and 0 <= down <= 18)

    def _host_op(self, lv, rv):
        raw = lv * rv  # scale s1+s2
        return rescale_int(raw, self._ltype.scale + self._rtype.scale,
                           self._result.scale)

    def eval_dev(self, ctx, child_vals, prep):
        lval, rval = child_vals
        hi, lo = i64_mul_to_i128(lval.data, rval.data)
        down = (self._ltype.scale + self._rtype.scale) - self._result.scale
        hi, lo = i128_div_pow10_half_up(hi, lo, down)
        validity = null_and(lval.validity, rval.validity)
        fits = i128_fits_int64(hi, lo) & \
            i128_abs_fits_pow10(hi, lo, self._result.precision)
        validity = validity & fits
        return DevVal(jnp.where(validity, i128_to_i64(hi, lo),
                                jnp.int64(0)), validity)


class DecimalDivide(DecimalBinary):
    op_name = "dec_div"

    def _result_type(self, a, b):
        return div_result_type(a, b)

    @property
    def device_supported(self):
        up = self._result.scale + self._rtype.scale - self._ltype.scale
        return (self._ltype.precision <= MAX_LONG_DIGITS
                and self._rtype.precision <= MAX_LONG_DIGITS
                and self._result.precision <= MAX_LONG_DIGITS
                and 0 <= up <= 18
                and self._ltype.precision + up <= 37)

    def _host_op(self, lv, rv):
        if rv == 0:
            return None  # Spark: null on division by zero (non-ANSI)
        up = self._result.scale + self._rtype.scale - self._ltype.scale
        if up < 0:
            return _round_half_up_div(lv, rv * _POW10[-up])
        return _round_half_up_div(lv * _POW10[up], rv)

    def eval_dev(self, ctx, child_vals, prep):
        lval, rval = child_vals
        up = self._result.scale + self._rtype.scale - self._ltype.scale
        zero_div = rval.data == 0
        divisor = jnp.where(zero_div, jnp.int64(1), rval.data)
        # numerator scaled up into 128 bits, then 128/64 signed division
        # with HALF_UP — via magnitude long division in 32-bit limbs
        nhi, nlo = i128_mul_pow10(
            jnp.where(lval.data < 0, jnp.int64(-1), jnp.int64(0)),
            lval.data.astype(jnp.uint64), up)
        ahi_s, alo, nneg = i128_abs(nhi, nlo)
        dneg = divisor < 0
        dmag = jnp.where(dneg, -divisor, divisor).astype(jnp.uint64)
        q, r = _u128_divmod_u64(ahi_s.astype(jnp.uint64), alo, dmag)
        round_up = r * jnp.uint64(2) >= dmag
        q = q + jnp.where(round_up, jnp.uint64(1), jnp.uint64(0))
        neg = nneg ^ dneg
        data = jnp.where(neg, -(q.astype(jnp.int64)), q.astype(jnp.int64))
        validity = null_and(lval.validity, rval.validity) & ~zero_div
        bound = jnp.int64(_POW10[min(self._result.precision,
                                     MAX_LONG_DIGITS)])
        validity = validity & (jnp.abs(data) < bound) & \
            (q <= jnp.uint64(0x7FFFFFFFFFFFFFFF))
        return DevVal(jnp.where(validity, data, jnp.int64(0)), validity)


def _u128_divmod_u64(hi, lo, d):
    """Unsigned (hi,lo) // d for arbitrary uint64 d, via binary long
    division over 128 bits (fori-free unrolled 128 steps would be huge;
    use 32-bit limb division when d < 2^31, else shift-subtract over the
    top 64 bits + hardware 64-bit division refinement).

    Implementation: classic Knuth base-2^32 short division when
    d < 2^32; otherwise 2-limb schoolbook with estimate-and-correct."""
    small = d < jnp.uint64(1 << 31)
    # path A: limb division (exact for d < 2^31)
    qa_hi, qa_lo, ra = u128_divmod_small(hi, lo, d)
    # path B: d >= 2^31 -> quotient fits in 64 bits iff hi < d (true for
    # our scaled decimals); use float-free iterative correction:
    qb, rb = _u128_div_u64_big(hi, lo, d)
    q = jnp.where(small, qa_lo, qb)
    r = jnp.where(small, ra, rb)
    return q, r


def _u128_div_u64_big(hi, lo, d):
    """(hi,lo) // d for d >= 2^31, assuming the quotient fits uint64
    (guaranteed by device_supported gates: |numerator| < 10^37 and
    d >= 2^31 -> q < 10^37/2^31 < 2^63). Shift-subtract long division
    over 128 bits, unrolled 64 steps on the high part collapsed via
    jnp arithmetic: process bit-by-bit is 128 iterations — instead use
    the standard two-digit base-2^32 Knuth D with a 64-bit hardware
    divide for the estimate."""
    # normalize d to have its top bit set
    # count leading zeros of d
    def clz64(x):
        n = jnp.zeros_like(x, dtype=jnp.int32)
        v = x
        for shift in (32, 16, 8, 4, 2, 1):
            big = v >= (jnp.uint64(1) << jnp.uint64(shift))
            n = n + jnp.where(big, 0, shift).astype(jnp.int32)
            v = jnp.where(big, v >> jnp.uint64(shift), v)
        return jnp.where(x == 0, jnp.int32(64), n)

    s = clz64(d).astype(jnp.uint64)
    dn = d << s
    # shifted 128-bit numerator (hi:lo) << s  (s < 64 since d >= 2^31 has
    # clz <= 33)
    hi_n = (hi << s) | jnp.where(s == 0, jnp.uint64(0), lo >> (jnp.uint64(64) - s))
    lo_n = lo << s
    dh = dn >> jnp.uint64(32)
    dl = dn & _MASK32
    # first digit q1 = [hi_n, top32(lo_n)] / dn
    u1 = hi_n
    u2 = lo_n >> jnp.uint64(32)
    q1 = u1 // dh
    q1 = jnp.minimum(q1, _MASK32)
    # correct q1: while q1*dl > ((u1 - q1*dh) << 32 | u2): q1 -= 1
    for _ in range(2):
        r1 = u1 - q1 * dh
        over = (r1 <= _MASK32) & (q1 * dl > ((r1 << jnp.uint64(32)) | u2))
        q1 = q1 - jnp.where(over, jnp.uint64(1), jnp.uint64(0))
    rem1 = ((u1 << jnp.uint64(32)) | u2) - q1 * dn
    # second digit q0 = [rem1, low32(lo_n)] / dn
    u3 = lo_n & _MASK32
    q0 = rem1 // dh
    q0 = jnp.minimum(q0, _MASK32)
    for _ in range(2):
        r0 = rem1 - q0 * dh
        over = (r0 <= _MASK32) & (q0 * dl > ((r0 << jnp.uint64(32)) | u3))
        q0 = q0 - jnp.where(over, jnp.uint64(1), jnp.uint64(0))
    rem0 = ((rem1 << jnp.uint64(32)) | u3) - q0 * dn
    q = (q1 << jnp.uint64(32)) | q0
    r = rem0 >> s
    return q, r


class UnscaledValue(UnaryExpression):
    """decimal -> its raw unscaled long (GpuUnscaledValue)."""

    @property
    def data_type(self):
        return T.LONG

    @property
    def device_supported(self):
        return self.child.data_type.precision <= MAX_LONG_DIGITS

    def eval_cpu(self, table):
        c = self.child.eval_cpu(table)
        data = np.asarray([int(v) for v in host_unscaled(c)],
                          dtype=np.int64)
        return HostColumn(T.LONG, data, c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep):
        (c,) = child_vals
        return DevVal(c.data, c.validity)


class MakeDecimal(UnaryExpression):
    """long unscaled -> decimal(p, s) (GpuMakeDecimal)."""

    def __init__(self, child: Expression, precision: int, scale: int):
        super().__init__(child)
        self._dtype = T.DecimalType(precision, scale)

    @property
    def data_type(self):
        return self._dtype

    def with_children(self, children):
        return MakeDecimal(children[0], self._dtype.precision,
                           self._dtype.scale)

    def key(self):
        return ("make_decimal", str(self._dtype), self.children[0].key())

    @property
    def device_supported(self):
        return self._dtype.precision <= MAX_LONG_DIGITS

    def eval_cpu(self, table):
        c = self.child.eval_cpu(table)
        bound = _POW10[self._dtype.precision]
        validity = c.validity & (np.abs(c.data) < bound)
        return HostColumn(self._dtype,
                          np.where(validity, c.data, 0).astype(np.int64),
                          validity)

    def eval_dev(self, ctx, child_vals, prep):
        (c,) = child_vals
        bound = jnp.int64(_POW10[self._dtype.precision])
        validity = c.validity & (jnp.abs(c.data) < bound)
        return DevVal(jnp.where(validity, c.data, jnp.int64(0)), validity)


class CheckOverflow(UnaryExpression):
    """Narrow a decimal to a target type, null on overflow (non-ANSI)."""

    def __init__(self, child: Expression, dtype: T.DecimalType):
        super().__init__(child)
        self._dtype = dtype

    @property
    def data_type(self):
        return self._dtype

    def with_children(self, children):
        return CheckOverflow(children[0], self._dtype)

    def key(self):
        return ("check_overflow", str(self._dtype), self.children[0].key())

    @property
    def device_supported(self):
        src = self.child.data_type
        return (src.precision <= MAX_LONG_DIGITS
                and self._dtype.precision <= MAX_LONG_DIGITS
                and abs(src.scale - self._dtype.scale) <= 18)

    def eval_cpu(self, table):
        c = self.child.eval_cpu(table)
        src: T.DecimalType = self.child.data_type
        validity = c.validity.copy()
        bound = _POW10[self._dtype.precision]
        out = [0] * len(c.data)
        vals = host_unscaled(c)
        for i in range(len(out)):
            if validity[i]:
                v = rescale_int(int(vals[i]), src.scale, self._dtype.scale)
                if abs(v) >= bound:
                    validity[i] = False
                else:
                    out[i] = v
        return host_store(out, validity, self._dtype)

    def eval_dev(self, ctx, child_vals, prep):
        (c,) = child_vals
        src: T.DecimalType = self.child.data_type
        return dev_rescale_checked(c.data, c.validity, src.scale,
                                   self._dtype.scale,
                                   self._dtype.precision)


class DecimalRemainder(DecimalBinary):
    """Java % over decimals: sign of the dividend; NULL on zero divisor.
    Result type (Spark DecimalPrecision): s = max(s1,s2),
    p = min(p1-s1, p2-s2) + s."""

    op_name = "dec_rem"
    _java_sign = True

    def _result_type(self, a, b):
        s = max(a.scale, b.scale)
        p = min(a.precision - a.scale, b.precision - b.scale) + s
        return T.DecimalType(*_adjust(max(p, 1), s))

    @property
    def device_supported(self):
        s = self._result.scale
        # both operands rescaled to the common scale must fit int64:
        # p - own_scale + s <= 18 digits
        return (self._ltype.precision - self._ltype.scale + s
                <= MAX_LONG_DIGITS
                and self._rtype.precision - self._rtype.scale + s
                <= MAX_LONG_DIGITS
                and self._ltype.precision <= MAX_LONG_DIGITS
                and self._rtype.precision <= MAX_LONG_DIGITS)

    @staticmethod
    def _java_mod(a: int, b: int) -> int:
        r = abs(a) % abs(b)
        return -r if a < 0 else r              # Java %: dividend sign

    def _mod(self, a: int, b: int) -> int:
        if self._java_sign:
            return self._java_mod(a, b)
        # Spark pmod: ((a % b) + b) % b with Java %
        return self._java_mod(self._java_mod(a, b) + b, b)

    def _host_op(self, lv, rv):
        if rv == 0:
            return None
        s = self._result.scale
        a = rescale_int(lv, self._ltype.scale, s)
        b = rescale_int(rv, self._rtype.scale, s)
        if b == 0:
            return None
        return self._mod(a, b)

    def eval_cpu(self, table: HostTable) -> HostColumn:
        # base class handles null-on-None via _host_op
        return super().eval_cpu(table)

    def eval_dev(self, ctx, child_vals, prep):
        lval, rval = child_vals
        s = self._result.scale
        a = lval.data * jnp.int64(_POW10[s - self._ltype.scale])
        b = rval.data * jnp.int64(_POW10[s - self._rtype.scale])
        zero = b == 0
        safe = jnp.where(zero, jnp.int64(1), b)

        def jmod(x, y):
            r = jnp.abs(x) % jnp.abs(y)
            return jnp.where(x < 0, -r, r)

        if self._java_sign:
            data = jmod(a, safe)
        else:
            # Spark pmod: ((a % b) + b) % b with Java %
            data = jmod(jmod(a, safe) + safe, safe)
        validity = null_and(lval.validity, rval.validity) & ~zero
        return DevVal(jnp.where(validity, data, jnp.int64(0)), validity)


class DecimalPmod(DecimalRemainder):
    """pmod: non-negative for positive divisor (divisor-sign semantics)."""

    op_name = "dec_pmod"
    _java_sign = False
