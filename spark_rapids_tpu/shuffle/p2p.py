"""P2P (cached, transport-served) shuffle mode.

Reference (SURVEY.md §2.6): UCX mode — ``RapidsCachingWriter``
(RapidsShuffleInternalManagerBase.scala:1078) keeps map output resident in
the ShuffleBufferCatalog instead of writing shuffle files; readers fetch
blocks from peer executors through RapidsShuffleClient/Server over the
transport, discovered via driver heartbeats.

TPU mapping: one ``P2PShuffleEnv`` per executor wires catalog + server +
transport + heartbeat endpoint. Within one engine process (one executor)
the fetch still runs the full client/server protocol over the in-process
transport (or TCP loopback), so the wire path is exercised in production
use, not just tests; multi-executor topologies connect the same pieces
over TCP (tests/test_shuffle_transport.py builds 2-3 executor meshes)."""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional, Set, Tuple

from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.conf import (
    RapidsConf,
    SHUFFLE_BOUNCE_ACQUIRE_TIMEOUT_MS,
    SHUFFLE_COMPRESSION_CODEC,
    SHUFFLE_CONNECT_TIMEOUT_MS,
    SHUFFLE_FETCH_BACKOFF_MULT,
    SHUFFLE_FETCH_MAX_RETRIES,
    SHUFFLE_FETCH_RETRY_WAIT_MS,
    P2P_BOUNCE_BUFFER_SIZE,
    P2P_BOUNCE_BUFFERS,
    P2P_CACHE_LIMIT,
    P2P_TRANSPORT,
)
from spark_rapids_tpu.errors import (
    ColumnarProcessingError,
    MapOutputLostError,
    ShuffleFetchError,
)
from spark_rapids_tpu.runtime.faults import RECOVERY, backoff_retry
from spark_rapids_tpu.shuffle.catalogs import (
    ShuffleBufferCatalog,
    ShuffleReceivedBufferCatalog,
)
from spark_rapids_tpu.shuffle.client_server import ShuffleClient, ShuffleServer
from spark_rapids_tpu.shuffle.heartbeat import (
    ShuffleHeartbeatEndpoint,
    ShuffleHeartbeatManager,
)
from spark_rapids_tpu.shuffle.manager import (
    _compress,
    decode_blob,
    resolve_codec,
)
from spark_rapids_tpu.shuffle.serializer import pack_table
from spark_rapids_tpu.shuffle.transport import (
    BounceBufferManager,
    Connection,
    InProcessTransport,
    PeerInfo,
    TcpShuffleServerListener,
    TcpTransport,
)


class P2PShuffleEnv:
    """Executor-side wiring of the p2p shuffle (GpuShuffleEnv analog for
    UCX mode). ``driver`` is the shared heartbeat manager; standalone use
    (single executor) creates a private one."""

    def __init__(self, conf: RapidsConf, executor_id: str = "exec-0",
                 driver: Optional[ShuffleHeartbeatManager] = None):
        self.executor_id = executor_id
        self.codec = resolve_codec(
            str(conf.get_entry(SHUFFLE_COMPRESSION_CODEC)).lower())
        bounce_size = int(conf.get_entry(P2P_BOUNCE_BUFFER_SIZE))
        bounce_n = int(conf.get_entry(P2P_BOUNCE_BUFFERS))
        acquire_timeout = conf.get_entry(
            SHUFFLE_BOUNCE_ACQUIRE_TIMEOUT_MS) / 1000.0
        self.catalog = ShuffleBufferCatalog(
            host_limit_bytes=int(conf.get_entry(P2P_CACHE_LIMIT)))
        self.send_pool = BounceBufferManager(
            bounce_size, bounce_n, default_timeout=acquire_timeout)
        self.recv_pool = BounceBufferManager(
            bounce_size, bounce_n, default_timeout=acquire_timeout)
        self.server = ShuffleServer(self.catalog, self.send_pool)
        self.window_size = bounce_size
        # fetch-retry policy (spark.rapids.shuffle.fetch.*)
        self.fetch_max_retries = int(conf.get_entry(
            SHUFFLE_FETCH_MAX_RETRIES))
        self.fetch_retry_wait_s = conf.get_entry(
            SHUFFLE_FETCH_RETRY_WAIT_MS) / 1000.0
        self.fetch_backoff_mult = float(conf.get_entry(
            SHUFFLE_FETCH_BACKOFF_MULT))

        kind = str(conf.get_entry(P2P_TRANSPORT)).lower()
        self._listener: Optional[TcpShuffleServerListener] = None
        if kind == "tcp":
            self._listener = TcpShuffleServerListener(self.server)
            self.transport = TcpTransport(
                self.recv_pool,
                connect_timeout=conf.get_entry(
                    SHUFFLE_CONNECT_TIMEOUT_MS) / 1000.0)
            self.me = PeerInfo(executor_id, self._listener.host,
                               self._listener.port)
        elif kind == "inprocess":
            InProcessTransport.register_server(executor_id, self.server)
            self.transport = InProcessTransport(self.recv_pool)
            self.me = PeerInfo(executor_id)
        else:
            raise ColumnarProcessingError(f"unknown p2p transport {kind}")

        self._peers: Dict[str, PeerInfo] = {}
        self._connections: Dict[str, Connection] = {}
        self._conn_lock = threading.Lock()
        self._shuffle_id_lock = threading.Lock()
        self._next_shuffle = 0
        # per-peer CUMULATIVE fetch-failure counts (session lifetime, not
        # per fetch): a peer is excluded from fetch targets when one
        # fetch exhausts its retries OR when its total failures cross the
        # chronic-flakiness budget (4x maxRetries) even though each fetch
        # eventually limped through — recompute beats endless backoff.
        # Cleared only by an actual re-registration (_on_new_peer).
        self._peer_failures: Dict[str, int] = {}
        self._excluded_peers: Set[str] = set()
        from spark_rapids_tpu.conf import HEARTBEAT_INTERVAL_S
        self.driver = driver or ShuffleHeartbeatManager()
        self.heartbeat = ShuffleHeartbeatEndpoint(
            self.driver, self.me, self._on_new_peer,
            interval_s=float(conf.get_entry(HEARTBEAT_INTERVAL_S)),
            on_evicted=self._rejoin_after_eviction)
        self.heartbeat.start()

    def _on_new_peer(self, peer: PeerInfo):
        """Normal heartbeat delivery: entries registered SINCE the last
        beat. For an excluded peer, seeing it here means it actually
        RE-registered with the driver — trust it again."""
        self._peers[peer.executor_id] = peer
        self._excluded_peers.discard(peer.executor_id)
        self._peer_failures.pop(peer.executor_id, None)

    def _rejoin_after_eviction(self):
        """OUR eviction, not theirs: re-register and re-DISCOVER the live
        peers, but keep our exclusion list — the driver's reply names
        every live peer, not peers that re-registered, so it proves
        nothing about a peer we excluded for failing fetches."""
        for peer in self.driver.register_executor(self.me):
            self._peers[peer.executor_id] = peer

    def on_peer_evicted(self, executor_id: str):
        """Driver-eviction hook: stop targeting the peer immediately; the
        next read that misses its blocks recomputes them from lineage."""
        if executor_id in self._excluded_peers:
            return
        self._excluded_peers.add(executor_id)
        RECOVERY.bump("peer_exclusions")

    def exclude_peer(self, executor_id: str):
        self.on_peer_evicted(executor_id)

    def connection_to(self, executor_id: str) -> Connection:
        with self._conn_lock:
            conn = self._connections.get(executor_id)
            if conn is not None and getattr(conn, "broken", False):
                # dead/desynced socket (ADVICE r2): evict so this fetch
                # reconnects instead of failing forever
                self._connections.pop(executor_id, None)
                conn = None
        if conn is not None:
            return conn
        peer = self.me if executor_id == self.executor_id \
            else self._peers.get(executor_id)
        if peer is None:
            raise ColumnarProcessingError(
                f"unknown peer {executor_id} (not heartbeat-discovered)")
        # connect OUTSIDE the lock: a slow/unreachable peer must not stall
        # connections to healthy ones (TCP connect can block for seconds)
        conn = self.transport.connect(peer)
        with self._conn_lock:
            existing = self._connections.get(executor_id)
            if existing is not None and getattr(existing, "broken", False):
                existing.close()
                existing = None
            if existing is None:
                self._connections[executor_id] = conn
                return conn
        # lost the race to a healthy connection: use it, free ours
        conn.close()
        return existing

    def client_for(self, executor_id: str) -> ShuffleClient:
        return ShuffleClient(self.connection_to(executor_id),
                             window_size=self.window_size)

    def peers(self) -> List[str]:
        return [ex for ex in self._peers if ex not in self._excluded_peers]

    def fetch_partition_with_retry(self, shuffle_id: int, partition_id: int,
                                   executor_id: str
                                   ) -> List[Tuple[tuple, int, HostTable]]:
        """One peer's blocks for a reduce partition, through the full
        client/server protocol, with exponential-backoff retry; returns
        (block_id, wire_bytes, table) triples. Deserialization runs INSIDE
        the retry so a corrupt frame (CRC mismatch) refetches. Exhaustion
        excludes the peer and raises MapOutputLostError naming the maps we
        know it held (the RapidsShuffleIterator retry + transport-error
        handling analog)."""
        local = executor_id == self.executor_id
        if not local and executor_id in self._excluded_peers:
            raise MapOutputLostError(
                f"peer {executor_id} is excluded (evicted or repeatedly "
                "failing)", executor_id=executor_id)
        state = {"known_maps": None, "chronic": False, "attempts": 0}

        def attempt():
            client = self.client_for(executor_id)
            blocks = client.fetch_metadata(shuffle_id, partition_id)
            if not blocks:
                return []
            state["known_maps"] = [bid[1] for bid, _ in blocks]
            received = ShuffleReceivedBufferCatalog()
            client.fetch_blocks(blocks, received)
            # decode inside the retry: a corrupt frame (CRC mismatch or
            # codec error — decode_blob normalizes both to the retryable
            # kind) refetches like any other failure
            return [(bid, len(blob), decode_blob(self.codec, blob))
                    for bid, blob in received.drain()]

        def on_failure(_exc, attempt_no):
            state["attempts"] = attempt_no
            total = self._peer_failures.get(executor_id, 0) + 1
            self._peer_failures[executor_id] = total
            state["chronic"] = (not local
                                and total > 4 * self.fetch_max_retries)
            return state["chronic"]  # budget blown: stop retrying now

        try:
            return backoff_retry(
                attempt, max_retries=self.fetch_max_retries,
                wait_s=self.fetch_retry_wait_s,
                backoff_mult=self.fetch_backoff_mult,
                retryable=ShuffleFetchError, on_failure=on_failure)
        except ShuffleFetchError as e:
            # the LOCAL executor is never excluded — after a recompute
            # rewrites its blocks, fetches must be able to target it again
            if not local:
                self.exclude_peer(executor_id)
            why = (f"{self._peer_failures.get(executor_id)} cumulative "
                   "failures (chronically flaky)" if state["chronic"]
                   else f"{state['attempts']} attempts")
            raise MapOutputLostError(
                f"fetch of shuffle {shuffle_id} partition {partition_id} "
                f"from {executor_id} failed after {why}: {e}",
                executor_id=executor_id,
                map_ids=state["known_maps"]) from e

    # -- engine ShuffleManager interface ------------------------------------
    def new_shuffle(self, num_partitions: int) -> "P2PWriteHandle":
        with self._shuffle_id_lock:
            sid = self._next_shuffle
            self._next_shuffle = sid + 1
        return P2PWriteHandle(self, sid, num_partitions)

    def reader(self, handle: "P2PWriteHandle") -> "P2PReadHandle":
        return P2PReadHandle(self, handle)

    def remove_shuffle(self, handle: "P2PWriteHandle"):
        self.catalog.remove_shuffle(handle.shuffle_id)

    def close(self):
        self.heartbeat.close()
        if self._listener is not None:
            self._listener.close()
        else:
            InProcessTransport.unregister_server(self.executor_id)


class P2PWriteHandle:
    """Caching writer: each batch's partition split lands in the local
    spillable catalog as one block per (map, partition)."""

    def __init__(self, env: P2PShuffleEnv, shuffle_id: int,
                 num_partitions: int):
        self.env = env
        self.shuffle_id = shuffle_id
        self.num_partitions = num_partitions
        self.num_maps = 0
        self.bytes_written = 0
        # map-output tracker slice: which (map, partition) blocks exist
        # (empty partitions write no block, so absence alone cannot
        # distinguish "empty" from "lost")
        self._written: Dict[int, Set[int]] = {}

    def write_partitions(self, partitions: List[HostTable]):
        """Idempotent under retry (ADVICE r2): all blobs are serialized
        BEFORE the map id is claimed or any block lands in the catalog, so
        a retryable failure mid-serialization leaves no partial map output
        and the replay starts clean (no duplicated partitions)."""
        if len(partitions) != self.num_partitions:
            raise ColumnarProcessingError("partition count mismatch")
        staged = []
        for p, table in enumerate(partitions):
            if table.num_rows == 0:
                continue
            staged.append((p, _compress(self.env.codec, pack_table(table))))
        map_id = self.num_maps
        added = []
        try:
            for p, blob in staged:
                bid = (self.shuffle_id, map_id, p)
                self.env.catalog.add_block(bid, blob)
                added.append(bid)
                self.bytes_written += len(blob)
        except BaseException:
            # leave no partial map output behind: a replay re-adds the
            # same (map, partition) block ids and must start clean
            for bid in added:
                self.env.catalog.remove_block(bid)
            self.bytes_written -= sum(len(b) for _, b in staged[:len(added)])
            raise
        self._written[map_id] = {p for p, _ in staged}
        self.num_maps += 1

    def rewrite_map(self, map_id: int, partitions: List[HostTable]):
        """Recompute path: replace one lost map output's blocks with
        freshly serialized copies in the LOCAL catalog (whether the
        originals lived here or on an evicted peer)."""
        if not 0 <= map_id < self.num_maps:
            raise ColumnarProcessingError(
                f"cannot rewrite unknown map output {map_id}")
        if len(partitions) != self.num_partitions:
            raise ColumnarProcessingError("partition count mismatch")
        for p in range(self.num_partitions):
            self.env.catalog.remove_block((self.shuffle_id, map_id, p))
        written = set()
        for p, table in enumerate(partitions):
            if table.num_rows == 0:
                continue
            blob = _compress(self.env.codec, pack_table(table))
            self.env.catalog.add_block((self.shuffle_id, map_id, p), blob)
            written.add(p)
        self._written[map_id] = written

    def expected_maps(self, partition_id: int) -> Set[int]:
        """Map ids that WROTE a block for this reduce partition — the
        completeness contract the reader verifies (a lost peer must not
        silently drop rows)."""
        return {m for m, parts in self._written.items()
                if partition_id in parts}

    @property
    def map_outputs(self):  # parity with ShuffleWriteHandle for metrics
        return list(range(self.num_maps))


class P2PReadHandle:
    """Reader: fetches a reduce partition through the full client/server
    protocol from every executor that holds blocks for it."""

    def __init__(self, env: P2PShuffleEnv, handle: P2PWriteHandle):
        self.env = env
        self.handle = handle
        self.bytes_read = 0

    def read_partition(self, p: int) -> Iterator[HostTable]:
        """Fetch a reduce partition from every live source with
        per-source retry, then verify COMPLETENESS against the write
        handle's map-output tracker: any locally-written map whose block
        did not arrive is reported lost (the exchange recomputes it) —
        a dead peer must fail loudly, never silently drop rows."""
        sources = [self.env.executor_id] + [
            ex for ex in self.env.peers() if ex != self.env.executor_id]
        got_maps = set()
        for executor_id in sources:
            for bid, nbytes, table in self.env.fetch_partition_with_retry(
                    self.handle.shuffle_id, p, executor_id):
                self.bytes_read += nbytes
                got_maps.add(bid[1])
                if table.num_rows > 0:
                    yield table
        missing = self.handle.expected_maps(p) - got_maps
        if missing:
            raise MapOutputLostError(
                f"shuffle {self.handle.shuffle_id} partition {p}: map "
                f"outputs {sorted(missing)} missing from every live "
                "source", map_ids=missing)


_P2P_ENVS: Dict[tuple, P2PShuffleEnv] = {}
_P2P_LOCK = threading.Lock()


def get_p2p_env(conf: RapidsConf) -> P2PShuffleEnv:
    key = (str(conf.get_entry(SHUFFLE_COMPRESSION_CODEC)).lower(),
           str(conf.get_entry(P2P_TRANSPORT)).lower(),
           int(conf.get_entry(P2P_BOUNCE_BUFFER_SIZE)),
           int(conf.get_entry(P2P_BOUNCE_BUFFERS)),
           int(conf.get_entry(P2P_CACHE_LIMIT)),
           int(conf.get_entry(SHUFFLE_FETCH_MAX_RETRIES)),
           conf.get_entry(SHUFFLE_FETCH_RETRY_WAIT_MS),
           float(conf.get_entry(SHUFFLE_FETCH_BACKOFF_MULT)))
    with _P2P_LOCK:
        env = _P2P_ENVS.get(key)
        if env is None:
            env = P2PShuffleEnv(conf, executor_id=f"exec-local-{len(_P2P_ENVS)}")
            _P2P_ENVS[key] = env
        return env
