"""Aggregate function expressions (reference: aggregateFunctions.scala,
GpuOverrides rules Sum Min Max Count Average First Last CollectList
CollectSet StddevPop StddevSamp VariancePop VarianceSamp PivotFirst ... —
SURVEY.md §2.3 / Appendix A).

These are declarations: row-wise eval is meaningless; the Aggregate plan
node (CPU path) and TpuHashAggregateExec (device path) interpret them.

Spark result-type rules implemented: sum(integral) -> LONG, sum(float/
double) -> DOUBLE, avg -> DOUBLE, count -> LONG (never null), min/max keep
the input type."""

from __future__ import annotations

from typing import Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.ops.expr import Expression


class AggregateFunction(Expression):
    """Base; child is the aggregated value expression (row-wise)."""

    def __init__(self, child: Optional[Expression] = None):
        self.children = (child,) if child is not None else ()

    @property
    def child(self):
        return self.children[0] if self.children else None

    def with_children(self, children):
        return type(self)(children[0]) if children else type(self)()

    @property
    def nullable(self):
        return True

    def over(self, spec):
        """agg OVER window-spec -> WindowExpression (ops/window.py)."""
        from spark_rapids_tpu.ops.window import WindowExpression
        return WindowExpression(self, spec)


class Sum(AggregateFunction):
    @property
    def data_type(self):
        ct = self.child.data_type
        if isinstance(ct, T.IntegralType):
            return T.LONG
        if isinstance(ct, (T.FloatType, T.DoubleType)):
            return T.DOUBLE
        if isinstance(ct, T.DecimalType):
            return T.DecimalType(min(ct.precision + 10, T.DecimalType.MAX_PRECISION), ct.scale)
        raise TypeError(f"sum of {ct}")


class Min(AggregateFunction):
    @property
    def data_type(self):
        return self.child.data_type


class Max(AggregateFunction):
    @property
    def data_type(self):
        return self.child.data_type


class Count(AggregateFunction):
    """count(expr); Count() with no child is COUNT(*)."""

    @property
    def data_type(self):
        return T.LONG

    @property
    def nullable(self):
        return False

    def key(self):
        return ("count", tuple(c.key() for c in self.children))


class Average(AggregateFunction):
    @property
    def data_type(self):
        return T.DOUBLE


class First(AggregateFunction):
    def __init__(self, child=None, ignore_nulls: bool = False):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    def with_children(self, children):
        return First(children[0], self.ignore_nulls)

    def key(self):
        return ("first", self.ignore_nulls, tuple(c.key() for c in self.children))

    @property
    def data_type(self):
        return self.child.data_type


class Last(AggregateFunction):
    def __init__(self, child=None, ignore_nulls: bool = False):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    def with_children(self, children):
        return Last(children[0], self.ignore_nulls)

    def key(self):
        return ("last", self.ignore_nulls, tuple(c.key() for c in self.children))

    @property
    def data_type(self):
        return self.child.data_type


class _CentralMoment(AggregateFunction):
    @property
    def data_type(self):
        return T.DOUBLE


class StddevPop(_CentralMoment):
    pass


class StddevSamp(_CentralMoment):
    pass


class VariancePop(_CentralMoment):
    pass


class VarianceSamp(_CentralMoment):
    pass


def is_aggregate(e: Expression) -> bool:
    from spark_rapids_tpu.ops.expr import Alias
    if isinstance(e, Alias):
        return is_aggregate(e.children[0])
    return isinstance(e, AggregateFunction)


class MergeMoments(AggregateFunction):
    """INTERNAL (streaming merge only, never planner-visible): combines
    per-batch moment partials. Children are (count, sum, m2) expressions
    over the concatenated partial table; the device kernels compute the
    numerically stable Chan combination
    ``m2_total = sum(m2_i) + sum(n_i * (mean_i - mean_total)^2)``
    (reference: GpuM2 merge aggregation buffers, aggregateFunctions.scala
    CudfMergeM2)."""

    def __init__(self, count_expr: Expression, sum_expr: Expression,
                 m2_expr: Expression):
        self.children = (count_expr, sum_expr, m2_expr)

    @property
    def data_type(self):
        return T.DOUBLE

    @property
    def child(self):
        # single-child accessors don't apply; the kernels special-case this
        return None

    def with_children(self, children):
        return MergeMoments(children[0], children[1], children[2])

    def key(self):
        return ("mergemoments", tuple(c.key() for c in self.children))


class CollectList(AggregateFunction):
    """collect_list(e) -> array of non-null values in input order
    (reference: GpuCollectList)."""

    @property
    def data_type(self):
        return T.ArrayType(self.child.data_type)

    @property
    def nullable(self):
        return False  # empty array, never null


class CollectSet(AggregateFunction):
    """collect_set(e) -> array of distinct non-null values
    (reference: GpuCollectSet; order unspecified, this engine emits
    value-sorted)."""

    @property
    def data_type(self):
        return T.ArrayType(self.child.data_type)

    @property
    def nullable(self):
        return False


class Percentile(AggregateFunction):
    """percentile(e, p) exact, with linear interpolation
    (reference: GpuPercentile / ApproximatePercentile's exact cousin)."""

    def __init__(self, child: Expression, percentage: float):
        super().__init__(child)
        self.percentage = float(percentage)
        if not (0.0 <= self.percentage <= 1.0):
            raise ValueError(
                f"percentile percentage must be in [0, 1], got {percentage}")

    def with_children(self, children):
        return Percentile(children[0], self.percentage)

    def key(self):
        return ("percentile", self.percentage,
                tuple(c.key() for c in self.children))

    @property
    def data_type(self):
        return T.DOUBLE
