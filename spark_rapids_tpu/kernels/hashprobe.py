"""Pallas hash-probe for the equi-join build/probe.

The sort-based probe (execs/join.JoinKernel) dense-ranks BOTH sides
through one shared code space — two full multi-operand sorts plus the
bincount/prefix chain — on every probe batch. For the dominant join
shape (a fact table probing a build side with UNIQUE keys: every
foreign-key join) none of that is needed: a bounded-attempt
open-addressing table over the two-limb key delivers each probe row's
build match in one pass.

  * BUILD (plain XLA, 32-bit scatters — scatters are the op Pallas is
    worst at): each valid build row tries ``attempts`` alternative
    slots (per-attempt multiplicative hashes over the (hi, lo) u32
    limbs); scatter-max arbitration picks one winner per slot per
    round. Rows still homeless after the last attempt, or duplicate
    build keys (detected by a self-probe: a placed row whose probe
    finds a DIFFERENT row holds a duplicated key), raise the device
    ``fail`` flag — the join validates it speculatively and replays on
    the sort-based probe, exactly the _DirectJoinKernel protocol.

  * PROBE (the Pallas kernel): the table lives in VMEM; each probe
    block computes its ``attempts`` candidate slots, gathers
    (rowid, key limbs) per attempt, and keeps the first limb-exact
    match — one pass over the probe side, zero sorts.

Outputs are shaped exactly like JoinKernel.probe's range form
(lo = matched build rowid, counts in {0,1}, rs_perm = identity), so
gather-map expansion, outer-join null handling and the full-outer
match bitmap all run unchanged — and, with unique build keys, produce
bit-identical join output.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from spark_rapids_tpu.kernels import KernelIneligible, config, interpret_mode
from spark_rapids_tpu.runtime.faults import fault_point

#: per-attempt hash salts (odd multiplicative constants; 8 attempts max)
_SALTS = ((0x9E3779B1, 0x85EBCA77), (0xC2B2AE3D, 0x27D4EB2F),
          (0x165667B1, 0x9E3779B9), (0xD6E8FEB9, 0xCA9B0A93),
          (0x2545F491, 0x8F4C2D17), (0xB5297A4D, 0x68E31DA5),
          (0x1B56C4E9, 0x7FEB352D), (0x846CA68B, 0xC2B2AE35))

MAX_ATTEMPTS = len(_SALTS)


def _slot(hi_u, lo_u, attempt: int, mask: int):
    """Slot for one attempt: a multiplicative mix of the two limbs.
    Pure u32 arithmetic — identical under XLA (build) and Pallas
    (probe). The hi limb arrives as i32 (ops/limbs.py layout); it is
    VIEWED as u32 first — mixed i32*u32 arithmetic would promote the
    whole chain to i64 under x64, which Mosaic cannot lower (and which
    is the exact emulation tax this layer exists to avoid)."""
    c1 = jnp.uint32(_SALTS[attempt][0])
    c2 = jnp.uint32(_SALTS[attempt][1])
    h = (hi_u.astype(jnp.uint32) * c1) ^ (lo_u.astype(jnp.uint32) * c2)
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    return (h & jnp.uint32(mask)).astype(jnp.int32)


def build_table(hi_u, lo_u, valid, H: int, attempts: int):
    """Open-addressing build in plain XLA. Returns (table_row i32 with
    -1 empties, table_hi, table_lo, fail_build)."""
    cap = hi_u.shape[0]
    mask = H - 1
    rowid = jnp.arange(cap, dtype=jnp.int32)
    table_row = jnp.full((H,), -1, jnp.int32)
    placed = jnp.zeros((cap,), jnp.bool_)
    myslot = jnp.zeros((cap,), jnp.int32)
    for a in range(attempts):
        slots = _slot(hi_u, lo_u, a, mask)
        occupied = table_row[slots] >= 0
        want = valid & ~placed & ~occupied
        tgt = jnp.where(want, slots, H)
        table_row = table_row.at[tgt].max(rowid, mode="drop")
        won = want & (table_row[slots] == rowid)
        placed = placed | won
        myslot = jnp.where(won, slots, myslot)
    fail_build = jnp.any(valid & ~placed)
    tslot = jnp.where(placed, myslot, H)
    table_hi = jnp.zeros((H,), hi_u.dtype).at[tslot].set(hi_u, mode="drop")
    table_lo = jnp.zeros((H,), lo_u.dtype).at[tslot].set(lo_u, mode="drop")
    return table_row, table_hi, table_lo, fail_build


def probe_rowids(p_hi, p_lo, valid, table_row, table_hi, table_lo,
                 attempts: int):
    """Pallas probe: per probe row the matching build rowid, -1 when
    unmatched. The (rowid, hi, lo) table is VMEM-resident per block."""
    fault_point("kernels.hashprobe")
    cfg = config()
    if attempts > MAX_ATTEMPTS:
        raise KernelIneligible(f"{attempts} attempts > {MAX_ATTEMPTS}")
    cap = int(p_hi.shape[0])
    H = int(table_row.shape[0])
    blk = cap
    for cand in (2048, 1024, 512, 256, 128):
        if cap % cand == 0:
            blk = cand
            break
    if cap % blk != 0:
        raise KernelIneligible(f"probe capacity {cap} does not tile")
    if (H * 12 + blk * 16) * 2 > cfg.vmem_budget:
        raise KernelIneligible("hash table exceeds the VMEM budget")
    nb = cap // blk
    mask = H - 1

    from spark_rapids_tpu.dispatch import pallas_program
    key = ("hashprobe", cap, H, blk, attempts, str(p_hi.dtype),
           str(p_lo.dtype))

    def build():
        def kernel(phi_ref, plo_ref, pvalid_ref, trow_ref, thi_ref,
                   tlo_ref, ri_ref):
            phi = phi_ref[:]
            plo = plo_ref[:]
            pvalid = pvalid_ref[:]
            trow = trow_ref[:]
            thi = thi_ref[:]
            tlo = tlo_ref[:]
            ri = jnp.full((blk,), -1, jnp.int32)
            found = jnp.zeros((blk,), jnp.bool_)
            for a in range(attempts):
                slots = _slot(phi, plo, a, mask)
                r = jnp.take(trow, slots)
                hit = (pvalid & ~found & (r >= 0)
                       & (jnp.take(thi, slots) == phi)
                       & (jnp.take(tlo, slots) == plo))
                ri = jnp.where(hit, r, ri)
                found = found | hit
            ri_ref[:] = ri

        return pl.pallas_call(
            kernel,
            grid=(nb,),
            in_specs=[pl.BlockSpec((blk,), lambda b: (b,))] * 3
            + [pl.BlockSpec((H,), lambda b: (0,))] * 3,
            out_specs=pl.BlockSpec((blk,), lambda b: (b,)),
            out_shape=jax.ShapeDtypeStruct((cap,), jnp.int32),
            interpret=interpret_mode())

    fn = pallas_program(key, build)
    from spark_rapids_tpu.kernels import note_used
    note_used("hashprobe")  # execute-time failure attribution (tpu_jit)
    return fn(p_hi, p_lo, valid, table_row, table_hi, table_lo)


def probe_ranges(lkey, rkey, live_l, live_r, H: int, attempts: int):
    """Build + probe + range-form packaging (see module doc). Returns
    (lo, counts, total, matched_l, rs_perm, fail)."""
    if not 1 <= attempts <= MAX_ATTEMPTS:
        # checked BEFORE build_table touches _SALTS[attempts-1]: an
        # out-of-range conf value is an ineligible call (clean HLO
        # fallback), never an IndexError that demotes the primitive
        raise KernelIneligible(
            f"kernels.hashprobe.attempts={attempts} outside "
            f"[1, {MAX_ATTEMPTS}]")
    (ld, lv), (rd, rv) = lkey, rkey
    from spark_rapids_tpu.ops.limbs import split_i64_hi_lo
    l_hi, l_lo = split_i64_hi_lo(ld)
    r_hi, r_lo = split_i64_hi_lo(rd)
    valid_r = rv & live_r
    valid_l = lv & live_l
    trow, thi, tlo, fail_build = build_table(r_hi, r_lo, valid_r, H,
                                             attempts)
    # duplicate-key detection: a placed row whose own probe resolves to
    # a DIFFERENT row shares its key with that row
    self_ri = probe_rowids(r_hi, r_lo, valid_r, trow, thi, tlo, attempts)
    rowid_r = jnp.arange(rd.shape[0], dtype=jnp.int32)
    dup = jnp.any(valid_r & (self_ri >= 0) & (self_ri != rowid_r))
    ri = probe_rowids(l_hi, l_lo, valid_l, trow, thi, tlo, attempts)
    matched = ri >= 0
    counts = matched.astype(jnp.int32)
    lo = jnp.where(matched, ri, 0)
    total = jnp.sum(counts.astype(jnp.int64))
    rs_perm = jnp.arange(rd.shape[0], dtype=jnp.int32)
    return lo, counts, total, matched, rs_perm, fail_build | dup
