"""Pandas/Arrow Python UDF plan nodes.

Reference (SURVEY.md §2.3 ``execution/python/``, 3,075 LoC):
``GpuArrowEvalPythonExec.scala`` (scalar pandas UDFs: device batch → Arrow
IPC → external Python worker → Arrow → device),
``GpuMapInPandasExec``/``GpuFlatMapGroupsInPandasExec``/
``GpuAggregateInPandasExec``, gated by ``PythonWorkerSemaphore``.

TPU mapping: the engine is already in-process Python, so the "worker" is
the user's function; the REAL boundary the reference models — device
columnar → Arrow host data → pandas and back — is preserved exactly
(execs/python_exec.py routes device batches through pyarrow), and
concurrent UDF evaluation is gated by the PythonWorkerSemaphore analog.
These nodes carry the plan shape + the CPU oracle path."""

from __future__ import annotations

from typing import Callable, Iterator, List, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.ops.expr import Expression
from spark_rapids_tpu.plan.nodes import PlanNode, Schema


_DDL_TYPES = {
    "boolean": T.BOOLEAN, "byte": T.BYTE, "short": T.SHORT,
    "int": T.INT, "integer": T.INT, "long": T.LONG, "bigint": T.LONG,
    "float": T.FLOAT, "double": T.DOUBLE, "string": T.STRING,
    "date": T.DATE, "timestamp": T.TIMESTAMP,
}


def _normalize_schema(schema) -> Schema:
    """Accept [(name, DataType)] or a 'name type, name type' DDL string."""
    if isinstance(schema, str):
        out = []
        for part in schema.split(","):
            name, _, tname = part.strip().partition(" ")
            tname = tname.strip().lower()
            if tname not in _DDL_TYPES:
                raise ColumnarProcessingError(
                    f"unknown type {tname!r} in schema string (supported: "
                    f"{sorted(_DDL_TYPES)})")
            out.append((name, _DDL_TYPES[tname]))
        return out
    return list(schema)


def _pandas_to_host(pdf, schema: Schema) -> HostTable:
    """pandas → HostTable coerced to the declared result schema (the
    reference's Arrow-read side enforces the UDF's declared return type)."""
    import pyarrow as pa

    from spark_rapids_tpu.io.arrow_convert import (
        decode_to_schema,
        spark_type_to_arrow,
    )
    fields = [pa.field(n, spark_type_to_arrow(dt)) for n, dt in schema]
    try:
        at = pa.Table.from_pandas(pdf, schema=pa.schema(fields),
                                  preserve_index=False)
    except (pa.ArrowInvalid, pa.ArrowTypeError, KeyError) as e:
        raise ColumnarProcessingError(
            f"pandas UDF result does not match declared schema "
            f"{[(n, dt.simple_string()) for n, dt in schema]}: {e}")
    return decode_to_schema(at, schema)


class MapInPandas(PlanNode):
    """df.map_in_pandas(fn, schema): fn(iterator of pandas DataFrames) ->
    iterator of pandas DataFrames (Spark mapInPandas contract)."""

    def __init__(self, child: PlanNode, fn: Callable, schema):
        self.children = (child,)
        self.fn = fn
        self.schema = _normalize_schema(schema)

    def output_schema(self) -> Schema:
        return self.schema

    def execute_cpu(self) -> Iterator[HostTable]:
        def pdfs():
            for batch in self.children[0].execute_cpu():
                yield batch.to_pandas()
        for out in self.fn(pdfs()):
            yield _pandas_to_host(out, self.schema)

    def describe(self):
        return f"MapInPandas[{getattr(self.fn, '__name__', 'fn')}]"


class FlatMapGroupsInPandas(PlanNode):
    """df.group_by(keys).apply_in_pandas(fn, schema): fn(pandas DataFrame
    of one group) -> pandas DataFrame."""

    def __init__(self, child: PlanNode, keys: Sequence[str], fn: Callable,
                 schema):
        self.children = (child,)
        self.keys = list(keys)
        self.fn = fn
        self.schema = _normalize_schema(schema)
        child_names = {n for n, _ in child.output_schema()}
        for k in self.keys:
            if k not in child_names:
                raise ColumnarProcessingError(
                    f"grouping column {k!r} not in {sorted(child_names)}")

    def output_schema(self) -> Schema:
        return self.schema

    def _groups(self):
        batches = list(self.children[0].execute_cpu())
        if not batches:
            return
        pdf = HostTable.concat(batches).to_pandas()
        if len(pdf) == 0:
            return
        for _key, group in pdf.groupby(self.keys, dropna=False, sort=True):
            yield group.reset_index(drop=True)

    def execute_cpu(self) -> Iterator[HostTable]:
        for group in self._groups():
            out = self.fn(group)
            if len(out):
                yield _pandas_to_host(out, self.schema)

    def describe(self):
        return f"FlatMapGroupsInPandas[keys={self.keys}]"


class AggregateInPandas(PlanNode):
    """df.group_by(keys).agg(pandas grouped-agg UDFs): each UDF is
    fn(*pandas Series of the group) -> scalar."""

    def __init__(self, child: PlanNode, keys: Sequence[str],
                 aggs: Sequence[Tuple[str, Callable, T.DataType,
                                      Sequence[str]]]):
        self.children = (child,)
        self.keys = list(keys)
        self.aggs = list(aggs)  # (out_name, fn, return_type, arg_col_names)

    def output_schema(self) -> Schema:
        child_schema = dict(self.children[0].output_schema())
        return ([(k, child_schema[k]) for k in self.keys]
                + [(name, rt) for name, _fn, rt, _args in self.aggs])

    def execute_cpu(self) -> Iterator[HostTable]:
        import pandas as pd
        batches = list(self.children[0].execute_cpu())
        pdf = (HostTable.concat(batches).to_pandas() if batches
               else pd.DataFrame())
        rows = []
        if len(pdf):
            for key, group in pdf.groupby(self.keys, dropna=False,
                                          sort=True):
                if not isinstance(key, tuple):
                    key = (key,)
                row = dict(zip(self.keys, key))
                for name, fn, _rt, args in self.aggs:
                    row[name] = fn(*[group[a] for a in args])
                rows.append(row)
        out = pd.DataFrame(rows, columns=[n for n, _ in
                                          self.output_schema()])
        yield _pandas_to_host(out, self.output_schema())

    def describe(self):
        return f"AggregateInPandas[keys={self.keys}]"


class MapInArrow(PlanNode):
    """df.map_in_arrow(fn, schema): fn(iterator of pyarrow RecordBatches)
    -> iterator of pyarrow RecordBatches (Spark mapInArrow contract;
    reference: GpuMapInArrowExec in execution/python/)."""

    def __init__(self, child: PlanNode, fn: Callable, schema):
        self.children = (child,)
        self.fn = fn
        self.schema = _normalize_schema(schema)

    def output_schema(self) -> Schema:
        return self.schema

    def execute_cpu(self) -> Iterator[HostTable]:
        from spark_rapids_tpu.io.arrow_convert import host_table_to_arrow

        def rbs():
            for batch in self.children[0].execute_cpu():
                for rb in host_table_to_arrow(batch).to_batches():
                    yield rb
        for out in self.fn(rbs()):
            host = arrow_batch_to_host(out, self.schema)
            if host.num_rows:
                yield host

    def describe(self):
        return f"MapInArrow[{getattr(self.fn, '__name__', 'fn')}]"


def arrow_batch_to_host(rb, schema: Schema) -> HostTable:
    """pyarrow RecordBatch/Table → HostTable coerced to the declared
    schema (the Arrow-read side of the MapInArrow boundary)."""
    import pyarrow as pa

    from spark_rapids_tpu.io.arrow_convert import (
        decode_to_schema,
        spark_type_to_arrow,
    )
    if isinstance(rb, pa.RecordBatch):
        rb = pa.Table.from_batches([rb])
    fields = [pa.field(n, spark_type_to_arrow(dt)) for n, dt in schema]
    try:
        rb = rb.select([n for n, _ in schema]).cast(pa.schema(fields))
    except (pa.ArrowInvalid, pa.ArrowTypeError, KeyError) as e:
        raise ColumnarProcessingError(
            f"mapInArrow result does not match declared schema "
            f"{[(n, dt.simple_string()) for n, dt in schema]}: {e}")
    return decode_to_schema(rb, schema)


def _drain_to_pandas(child: PlanNode):
    """Drain a plan node's CPU path into ONE pandas frame; an empty
    result keeps the child's column names."""
    import pandas as pd
    batches = list(child.execute_cpu())
    if not batches:
        return pd.DataFrame(columns=[n for n, _ in child.output_schema()])
    return HostTable.concat(batches).to_pandas()


def align_cogroups(left_pdf, right_pdf, left_keys, right_keys):
    """Full outer alignment of two grouped frames by key (Spark cogroup
    semantics: the UDF sees every key present on either side, with an
    empty frame for the absent side)."""
    import pandas as pd

    def _norm(k):
        # NaN != NaN would keep null-key groups from matching across
        # sides; normalize to None so nulls cogroup (Spark semantics)
        k = k if isinstance(k, tuple) else (k,)
        return tuple(None if pd.isna(v) else v for v in k)

    lgroups = ({_norm(k): g.reset_index(drop=True)
                for k, g in left_pdf.groupby(left_keys, dropna=False,
                                             sort=True)}
               if len(left_pdf) else {})
    rgroups = ({_norm(k): g.reset_index(drop=True)
                for k, g in right_pdf.groupby(right_keys, dropna=False,
                                              sort=True)}
               if len(right_pdf) else {})
    lempty = left_pdf.iloc[0:0]
    rempty = right_pdf.iloc[0:0]
    for key in sorted(set(lgroups) | set(rgroups), key=repr):
        yield lgroups.get(key, lempty), rgroups.get(key, rempty)


class FlatMapCoGroupsInPandas(PlanNode):
    """df1.group_by(k).cogroup(df2.group_by(k)).apply_in_pandas(fn,
    schema): fn(left pandas DataFrame, right pandas DataFrame of one
    cogrouped key) -> pandas DataFrame. Reference:
    execution/python/GpuFlatMapCoGroupsInPandasExec.scala."""

    def __init__(self, left: PlanNode, right: PlanNode,
                 left_keys: Sequence[str], right_keys: Sequence[str],
                 fn: Callable, schema):
        if len(left_keys) != len(right_keys):
            raise ColumnarProcessingError(
                "cogroup key lists must have the same arity "
                f"({list(left_keys)} vs {list(right_keys)})")
        self.children = (left, right)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.fn = fn
        self.schema = _normalize_schema(schema)

    def output_schema(self) -> Schema:
        return self.schema

    def execute_cpu(self) -> Iterator[HostTable]:
        left_pdf = _drain_to_pandas(self.children[0])
        right_pdf = _drain_to_pandas(self.children[1])
        for lg, rg in align_cogroups(left_pdf, right_pdf,
                                     self.left_keys, self.right_keys):
            out = self.fn(lg, rg)
            if len(out):
                yield _pandas_to_host(out, self.schema)

    def describe(self):
        return f"FlatMapCoGroupsInPandas[keys={self.left_keys}]"


class WindowInPandas(PlanNode):
    """Window-function pandas UDFs: child columns pass through, each UDF
    column appends fn evaluated over the row's window frame (reference:
    execution/python/GpuWindowInPandasExec.scala). ``udfs`` entries are
    (out_name, fn, return_type, arg col names, WindowSpec)."""

    def __init__(self, child: PlanNode, udfs):
        self.children = (child,)
        self.udfs = list(udfs)
        child_names = {n for n, _ in child.output_schema()}
        for name, _fn, _rt, args, spec in self.udfs:
            keys = list(args) + [getattr(e, "col_name", None)
                                 for e in spec.partition_exprs] \
                + [getattr(o.expr, "col_name", None) for o in spec.orders]
            for k in keys:
                if k not in child_names:
                    raise ColumnarProcessingError(
                        f"window pandas UDF {name}: column {k!r} not in "
                        f"{sorted(child_names)}")

    def output_schema(self) -> Schema:
        return (list(self.children[0].output_schema())
                + [(name, rt) for name, _f, rt, _a, _s in self.udfs])

    def execute_cpu(self) -> Iterator[HostTable]:
        import pandas as pd
        pdf = _drain_to_pandas(self.children[0])
        out_schema = self.output_schema()
        if len(pdf) == 0:
            yield _pandas_to_host(
                pd.DataFrame(columns=[n for n, _ in out_schema]),
                out_schema)
            return
        for name, fn, rt, args, spec in self.udfs:
            pdf[name] = eval_window_udf(pdf, fn, args, spec)
        yield _pandas_to_host(pdf, out_schema)

    def describe(self):
        return f"WindowInPandas[{[n for n, *_ in self.udfs]}]"


def _window_col_name(e) -> str:
    name = getattr(e, "col_name", None)
    if name is None:
        raise ColumnarProcessingError(
            "window pandas UDF partition/order keys must be plain "
            f"columns, got expression {e}")
    return name


def eval_window_udf(pdf, fn, arg_names, spec):
    """Evaluate one window pandas UDF over every partition of ``pdf``.

    Whole-partition (unbounded) frames call fn ONCE per partition
    (series in, scalar or aligned series out); the default ORDER BY
    frame (RANGE UNBOUNDED PRECEDING..CURRENT ROW) is a running
    aggregate whose frame ends at the last PEER of each row; bounded
    rows frames slice per row — the same frame taxonomy the reference
    implements in GpuWindowInPandasExec."""
    import numpy as np
    import pandas as pd

    part_cols = [_window_col_name(e) for e in spec.partition_exprs]
    kind, lo, hi = spec.resolved_frame()
    running_range = kind == "range" and lo is None and hi == 0
    if kind == "range" and lo is None and hi is None:
        kind = "rows"  # RANGE fully unbounded == whole partition
        lo = hi = None
    elif kind == "range" and not running_range:
        raise ColumnarProcessingError(
            "window pandas UDFs support unbounded, running (default "
            "ORDER BY), or rows-based frames (Spark restriction)")

    out = pd.Series(index=pdf.index, dtype=object)
    groups = (pdf.groupby(part_cols, dropna=False, sort=False).groups.items()
              if part_cols else [((), pdf.index)])
    for _key, idx in groups:
        g = pdf.loc[idx]
        if len(g) == 0:
            continue
        by = [_window_col_name(o.expr) for o in spec.orders]
        if by:
            asc = [o.ascending for o in spec.orders]
            g = g.sort_values(by=by, ascending=asc, kind="stable")
        arg_series = [g[a] for a in arg_names]
        n = len(g)
        if kind == "rows" and (lo is not None or hi is not None):
            vals = []
            for i in range(n):
                a = 0 if lo is None else max(0, min(n, i + lo))
                b = n if hi is None else max(0, min(n, i + hi + 1))
                vals.append(fn(*[s.iloc[a:max(a, b)] for s in arg_series]))
            res = pd.Series(vals, index=g.index)
        elif running_range and by:
            # frame ends at the last peer (rows tied on ALL order keys
            # share one result — Spark RANGE CURRENT ROW semantics)
            keys = g[by]
            shifted = keys.shift()
            # nulls are peers of each other (Spark null ordering)
            new_grp = np.array((keys.ne(shifted)
                                & ~(keys.isna() & shifted.isna())).any(
                                    axis=1))
            new_grp[0] = True
            grp_ids = np.cumsum(new_grp) - 1
            ends = np.zeros(grp_ids[-1] + 1, dtype=np.int64)
            np.maximum.at(ends, grp_ids, np.arange(n) + 1)
            vals = [fn(*[s.iloc[0:e] for s in arg_series])
                    for e in ends]
            res = pd.Series([vals[gi] for gi in grp_ids], index=g.index)
        else:
            r = fn(*arg_series)
            res = (pd.Series(r, index=g.index) if np.ndim(r) else
                   pd.Series([r] * n, index=g.index))
        out.loc[res.index] = res
    return out


class ArrowEvalPython(PlanNode):
    """Scalar pandas UDFs appended as extra columns: each UDF is
    fn(*pandas Series) -> pandas Series aligned with the input
    (GpuArrowEvalPythonExec: child columns pass through, UDF results
    append)."""

    def __init__(self, child: PlanNode,
                 udfs: Sequence[Tuple[str, Callable, T.DataType,
                                      Sequence[Expression]]]):
        from spark_rapids_tpu.ops.expr import bind
        self.children = (child,)
        schema = child.output_schema()
        self.udfs = [(name, fn, rt, [bind(a, schema) for a in args])
                     for name, fn, rt, args in udfs]

    def output_schema(self) -> Schema:
        return (list(self.children[0].output_schema())
                + [(name, rt) for name, _f, rt, _a in self.udfs])

    def execute_cpu(self) -> Iterator[HostTable]:
        import pandas as pd
        for batch in self.children[0].execute_cpu():
            extra_schema = []
            frames = {}
            for name, fn, rt, args in self.udfs:
                arg_series = [pd.Series(a.eval_cpu(batch).to_pylist())
                              for a in args]
                result = fn(*arg_series)
                if len(result) != batch.num_rows:
                    raise ColumnarProcessingError(
                        f"scalar pandas UDF {name} returned {len(result)} "
                        f"rows for a {batch.num_rows}-row batch")
                frames[name] = result
                extra_schema.append((name, rt))
            extra = _pandas_to_host(pd.DataFrame(frames), extra_schema)
            yield HostTable(list(batch.names) + list(extra.names),
                            list(batch.columns) + list(extra.columns))

    def describe(self):
        return f"ArrowEvalPython[{[n for n, *_ in self.udfs]}]"


class PandasUDFExpr(Expression):
    """Marker expression produced by functions.pandas_udf(...); extracted
    by the DataFrame layer into ArrowEvalPython / AggregateInPandas nodes
    (the reference's GpuOverrides splits PythonUDF out of projects the
    same way). Never evaluated directly."""

    def __init__(self, fn: Callable, return_type: T.DataType,
                 children: Sequence[Expression], kind: str,
                 udf_name: str = ""):
        self.fn = fn
        self._return_type = return_type
        self.children = tuple(children)
        self.kind = kind  # "scalar" | "grouped_agg"
        self.udf_name = udf_name or getattr(fn, "__name__", "pandas_udf")

    @property
    def data_type(self) -> T.DataType:
        return self._return_type

    @property
    def name(self) -> str:
        return self.udf_name

    def with_children(self, children):
        return PandasUDFExpr(self.fn, self._return_type, children,
                             self.kind, self.udf_name)

    def key(self):
        return ("PandasUDF", id(self.fn),
                tuple(c.key() for c in self.children))

    def eval_cpu(self, table):
        raise ColumnarProcessingError(
            f"pandas UDF {self.udf_name} must appear as a top-level select/"
            "agg expression (optionally aliased), not nested inside other "
            "expressions")

    def over(self, spec) -> "WindowedPandasUDF":
        """Spark semantics: a GROUPED_AGG pandas UDF applied .over(window)
        becomes a window pandas UDF (GpuWindowInPandasExec)."""
        if self.kind != "grouped_agg":
            raise ColumnarProcessingError(
                "only grouped_agg pandas UDFs can be used over a window "
                "(Spark restriction)")
        return WindowedPandasUDF(self, spec)

    device_supported = False


class WindowedPandasUDF:
    """Marker produced by PandasUDFExpr.over(spec); consumed by
    DataFrame.with_windows, which plans a WindowInPandas node."""

    def __init__(self, udf: PandasUDFExpr, spec):
        self.udf = udf
        self.spec = spec


def pandas_udf(return_type, function_type: str = "scalar"):
    """Decorator/factory: F.pandas_udf(T.DOUBLE)(fn) or
    @F.pandas_udf("double"). Scalar UDFs take/return pandas Series per
    batch; grouped_agg UDFs take Series per group and return a scalar."""
    rt = _normalize_schema(f"x {return_type}")[0][1] \
        if isinstance(return_type, str) else return_type
    if function_type not in ("scalar", "grouped_agg"):
        raise ColumnarProcessingError(
            f"unknown pandas UDF function_type {function_type!r}")

    def wrap(fn):
        def call(*args):
            from spark_rapids_tpu.ops.expr import col
            exprs = [col(a) if isinstance(a, str) else a for a in args]
            return PandasUDFExpr(fn, rt, exprs, function_type)
        call.__name__ = getattr(fn, "__name__", "pandas_udf")
        call._is_pandas_udf = True
        call._function_type = function_type
        return call
    return wrap


def _strip_alias(e: Expression):
    from spark_rapids_tpu.ops.expr import Alias
    if isinstance(e, Alias):
        return e.children[0], e
    return e, None


def extract_scalar_udfs(plan: PlanNode, exprs: List[Expression],
                        names: List[str]):
    """DataFrame.select hook: if top-level scalar pandas UDFs appear,
    plan ArrowEvalPython(child) + Project; returns (plan, rewritten
    exprs) — the rewrite replaces each UDF with a column reference to the
    appended result column."""
    from spark_rapids_tpu.ops.expr import col
    udfs = []
    rewritten = []
    for e, out_name in zip(exprs, names):
        inner, _alias = _strip_alias(e)
        if isinstance(inner, PandasUDFExpr):
            if inner.kind != "scalar":
                raise ColumnarProcessingError(
                    f"grouped_agg pandas UDF {inner.udf_name} is only "
                    "valid in group_by(...).agg(...)")
            slot = f"__pandas_udf_{len(udfs)}__{out_name}"
            udfs.append((slot, inner.fn, inner.data_type,
                         list(inner.children)))
            rewritten.append(col(slot).alias(out_name))
        else:
            _reject_nested_udf(e)
            rewritten.append(e)
    if not udfs:
        return plan, exprs
    return ArrowEvalPython(plan, udfs), rewritten


def _reject_nested_udf(e: Expression):
    if isinstance(e, PandasUDFExpr):
        raise ColumnarProcessingError(
            f"pandas UDF {e.udf_name} must be a top-level select "
            "expression (optionally aliased)")
    for c in e.children:
        _reject_nested_udf(c)
