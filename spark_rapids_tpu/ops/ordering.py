"""Order-isomorphic native-width sort operands.

TPU ALUs are 32-bit: i64 and f64 are storage-native but every compare/sort
op decomposes into emulated multi-op sequences, making ``lax.sort`` over
64-bit keys ~2x slower (and f64 bitcasts are NOT supported under the x64
rewrite at all — a single-operand f64 sort key cannot even be built the
cuDF way). Every sort/rank/compare in the engine therefore decomposes each
logical key into a LIST of <=32-bit operands whose lexicographic order
equals the value order:

  i64  -> (hi = x >> 32 as i32, lo = x & 0xffffffff as u32)
  f64  -> canonicalize (-0.0 -> 0.0, NaN -> one pattern), exact hi/lo f32
          split (TPU f64 IS an (f32, f32) pair), each component mapped to a
          monotone u32 (sign-flip trick; NaN sorts greater than +inf, which
          is Spark's NaN-last total order)
  f32  -> canonicalize + monotone u32
  bool -> i32
  <=32-bit ints / dictionary codes -> unchanged

On CPU backends f64 is native and the pair decomposition would LOSE
precision (two distinct f64 can share one (f32, f32) pair), so f64 there
uses the classic single-operand sortable-bits i64 bitcast instead.

(reference: SortUtils.scala / cuDF lexicographic comparators; the
decomposition itself is the TPU-native replacement for cuDF's typed
comparators.)"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from spark_rapids_tpu.ops.limbs import (
    f32_sortable_u32 as _f32_sortable_u32,
    split_f64_hi_lo,
    split_i64_hi_lo,
)


def _canon_float(d):
    d = jnp.where(d == 0.0, jnp.zeros_like(d), d)  # -0.0 == 0.0
    return jnp.where(jnp.isnan(d), jnp.full_like(d, jnp.nan), d)


def zero_invalid(data, validity):
    """jnp.where(validity, data, 0) with 2-D (dec128 limb) broadcasting."""
    v = validity[:, None] if getattr(data, "ndim", 1) == 2 else validity
    return jnp.where(v, data, jnp.zeros_like(data))


def comparable_operands(data) -> List[jax.Array]:
    """Decompose one key column into ascending-order operands. Callers add
    their own null-placement flag operand; invalid slots should be zeroed
    first (zero_invalid)."""
    d = data
    if getattr(d, "ndim", 1) == 2 and d.dtype == jnp.int64:
        # DECIMAL128 two-limb storage: signed high limb orders first,
        # then the unsigned low limb as two u32 words
        hi, lo = d[:, 0], d[:, 1]
        return [(hi >> 32).astype(jnp.int32),
                (hi & 0xFFFFFFFF).astype(jnp.uint32),
                ((lo >> 32) & 0xFFFFFFFF).astype(jnp.uint32),
                (lo & 0xFFFFFFFF).astype(jnp.uint32)]
    if d.dtype == jnp.int64:
        return list(split_i64_hi_lo(d))
    if d.dtype == jnp.float64:
        d = _canon_float(d)
        if jax.default_backend() == "cpu":
            # classic sortable-bits over the exact f64 pattern (CPU f64 is
            # native; the f32-pair split would merge distinct values):
            # negatives complement, positives flip the sign bit -> u64
            # order, emitted as a (u32 hi, u32 lo) word pair
            raw = jax.lax.bitcast_convert_type(d, jnp.int64)
            bits = jnp.where(raw < 0, ~raw,
                             raw ^ jnp.int64(-0x8000000000000000))
            return [((bits >> 32) & 0xFFFFFFFF).astype(jnp.uint32),
                    (bits & 0xFFFFFFFF).astype(jnp.uint32)]
        hi, lo = split_f64_hi_lo(d)
        return [_f32_sortable_u32(hi), _f32_sortable_u32(lo)]
    if d.dtype == jnp.float32:
        return [_f32_sortable_u32(_canon_float(d))]
    if d.dtype == jnp.bool_:
        return [d.astype(jnp.int32)]
    return [d]


def descending_operands(ops: List[jax.Array]) -> List[jax.Array]:
    """Order-reverse a comparable-operand list: bitwise complement reverses
    both signed i32 and unsigned u32 order component-wise, and equal tuples
    stay equal — so lexicographic order reverses exactly."""
    return [~o for o in ops]


def lex_sort(operands: List[jax.Array], payload: jax.Array) -> List[jax.Array]:
    """THE engine-wide lexicographic sort dispatch point:
    ``jax.lax.sort(operands + [payload], num_keys=len(operands))`` with
    the Pallas multi-column sort kernel substituted when the ``sort``
    primitive is enabled and the shape qualifies (kernels/sort.py).

    ``payload`` must be a UNIQUE i32 row-index iota (every call site
    passes ``jnp.arange(capacity)``): lax.sort is stable, and the
    bitonic kernel recovers exactly the stable order by using the
    payload as the final tiebreak key — so the two paths are
    bit-identical. Callers whose jitted kernels embed this choice must
    fold ``kernels.trace_token()`` into their trace cache keys."""
    from spark_rapids_tpu import kernels

    def hlo():
        return jax.lax.sort(list(operands) + [payload],
                            num_keys=len(operands))

    def kern():
        from spark_rapids_tpu.kernels import sort as ksort
        return ksort.sort_with_payload(list(operands), payload)

    return kernels.dispatch("sort", kern, hlo)


def operands_equal_adjacent(ops: List[jax.Array]) -> jax.Array:
    """rows[i] == rows[i-1] over the operand tuple (row 0 compares against
    the rolled-around last row; callers mask it)."""
    eq = None
    for o in ops:
        e = o == jnp.roll(o, 1)
        eq = e if eq is None else (eq & e)
    return eq
