"""Regressions for the round-2/round-3 advisor findings (ADVICE.md):
P2P write idempotency + dead-connection eviction, exact integral
RoundCeil/RoundFloor, speculative aggregate shrink, aborted-attempt
speculation-flag cleanup, embed-by-bytes collect sizing."""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.session import TpuSession


# -- P2P shuffle (ADVICE r2: shuffle/p2p.py) ---------------------------------

def _p2p_env():
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.shuffle.p2p import P2PShuffleEnv
    return P2PShuffleEnv(RapidsConf({}), executor_id="exec-advice-test")


def _tables(n_parts, rows=8, seed=0):
    from spark_rapids_tpu.columnar import HostColumn, HostTable
    rng = np.random.default_rng(seed)
    out = []
    for p in range(n_parts):
        out.append(HostTable(["a"], [HostColumn(
            T.LONG, rng.integers(0, 100, rows).astype(np.int64))]))
    return out


def test_p2p_write_partitions_idempotent_under_failure():
    """A failure mid-write must leave no partial map output; the replay's
    rows must appear exactly once (ADVICE r2: non-idempotent
    write_partitions)."""
    env = _p2p_env()
    try:
        handle = env.new_shuffle(3)
        parts = _tables(3)
        # inject a failure on the SECOND add_block of the first attempt
        real_add = env.catalog.add_block
        calls = {"n": 0}

        def flaky(bid, data):
            calls["n"] += 1
            if calls["n"] == 2:
                raise OSError("injected mid-write failure")
            return real_add(bid, data)

        env.catalog.add_block = flaky
        with pytest.raises(OSError):
            handle.write_partitions(parts)
        env.catalog.add_block = real_add
        assert handle.num_maps == 0  # attempt left nothing behind
        assert env.catalog.host_bytes == 0
        handle.write_partitions(parts)  # replay
        assert handle.num_maps == 1

        reader = env.reader(handle)
        total = sum(t.num_rows for p in range(3)
                    for t in reader.read_partition(p))
        assert total == sum(t.num_rows for t in parts)
    finally:
        env.close()


def test_p2p_broken_connection_evicted():
    """A TX_ERROR transport fault marks the connection broken and the env
    reconnects on the next fetch (ADVICE r2: dead sockets cached
    forever)."""
    env = _p2p_env()
    try:
        handle = env.new_shuffle(1)
        handle.write_partitions(_tables(1))
        c1 = env.connection_to(env.executor_id)
        c1.broken = True  # simulate a transport fault
        c2 = env.connection_to(env.executor_id)
        assert c2 is not c1
        rows = sum(t.num_rows for t in env.reader(handle).read_partition(0))
        assert rows == 8
    finally:
        env.close()


def test_tcp_connection_marks_broken_on_socket_error():
    import socket
    from spark_rapids_tpu.shuffle.transport import (
        BounceBufferManager,
        _TcpConnection,
    )
    a, b = socket.socketpair()
    conn = _TcpConnection(a, BounceBufferManager(1 << 16, 2))
    b.close()  # peer dies
    tx = conn.request(1, b"payload")
    assert tx.status == "ERROR"
    assert conn.broken


# -- exact integral RoundCeil/RoundFloor (ADVICE r2: ops/math.py) ------------

def test_round_ceil_floor_exact_above_2_53():
    from spark_rapids_tpu.ops.math import RoundCeil, RoundFloor
    big = 2**60 + 7  # not representable in float64
    vals = np.array([big, -big, 12345, -12345, 0, 999], dtype=np.int64)
    tpu = TpuSession()
    cpu = TpuSession({"spark.rapids.sql.enabled": "false"})
    for sess in (tpu, cpu):
        df = sess.create_dataframe({"x": vals})
        got = df.select(
            RoundCeil(col("x"), lit(-2)).alias("c"),
            RoundFloor(col("x"), lit(-2)).alias("f")).collect()
        for (c, f), x in zip(got, vals.tolist()):
            assert c == -((-x) // 100) * 100, (x, c)
            assert f == (x // 100) * 100, (x, f)


# -- speculative aggregate shrink (ADVICE r3: aggregate.py) ------------------

def test_speculative_shrink_output_correct_and_replays_on_miss():
    """High-reduction sorted-path aggregates shrink speculatively; an
    all-distinct-keys aggregate (speculation miss) replays and still
    returns exact results."""
    n = 200_000  # capacity 262144 > EMBED_NROWS_CAP -> speculation applies
    rng = np.random.default_rng(5)
    tpu = TpuSession()
    cpu = TpuSession({"spark.rapids.sql.enabled": "false"})

    # high reduction: few distinct int keys (sorted path, shrink fits)
    data = {"k": rng.integers(0, 50, n).astype(np.int64),
            "v": rng.random(n)}
    q = lambda s: sorted(s.create_dataframe(data).group_by("k")
                         .agg(F.count().alias("c")).collect())
    assert q(tpu) == q(cpu)

    # no reduction: every key distinct -> ngroups > spec bucket -> replay
    data2 = {"k": np.arange(n, dtype=np.int64),
             "v": rng.random(n)}
    q2 = lambda s: sorted(s.create_dataframe(data2).group_by("k")
                          .agg(F.count().alias("c")).collect())[:5]
    assert q2(tpu) == q2(cpu)


# -- aborted-attempt speculation flags (ADVICE r3: join.py/retry) ------------

def test_oom_retry_drops_aborted_attempt_flags():
    """An injected OOM inside a speculative join must not leave the
    aborted attempt's flag pending (a stale True flag would spuriously
    blocklist the site)."""
    rng = np.random.default_rng(9)
    n = 5000
    data = {"k": rng.integers(0, 100, n).astype(np.int64),
            "v": rng.random(n)}
    dim = {"k": np.arange(100, dtype=np.int64),
           "w": np.arange(100, dtype=np.int64) * 2}
    tpu = TpuSession({"spark.rapids.sql.test.injectRetryOOM": "retry:1"})
    cpu = TpuSession({"spark.rapids.sql.enabled": "false"})
    q = lambda s: sorted(
        s.create_dataframe(data).join(s.create_dataframe(dim), on="k",
                                      how="inner")
        .group_by("w").agg(F.count().alias("c")).collect())
    assert q(tpu) == q(cpu)


# -- embed-by-bytes collect sizing (ADVICE r3: table.py) ---------------------

def test_wide_table_collect_skips_padded_embed():
    """A wide schema whose padded bucket exceeds EMBED_MAX_BYTES takes the
    row-count sync instead of a multi-MB padded fetch — results equal
    either way."""
    from spark_rapids_tpu.columnar.table import DeviceTable
    n = 40_000  # bucket 65536 == EMBED_NROWS_CAP
    rng = np.random.default_rng(11)
    data = {f"c{i}": rng.random(n) for i in range(16)}  # 16 f64 cols
    bytes_per_row = (4 * 2 + 1) * 16
    assert 65536 * bytes_per_row > DeviceTable.EMBED_MAX_BYTES
    tpu = TpuSession()
    cpu = TpuSession({"spark.rapids.sql.enabled": "false"})
    q = lambda s: s.create_dataframe(data).filter(
        col("c0") > lit(0.99)).collect()
    got, want = q(tpu), q(cpu)
    assert len(got) == len(want)


# -- persistent compile cache on auto-detected TPU hosts (ADVICE r5) ---------

def test_compile_cache_defers_to_default_backend(monkeypatch):
    """An unset JAX_PLATFORMS must NOT mean 'cpu, no cache': the decision
    defers to jax.default_backend() at runtime init, so auto-detected
    TPU hosts get the persistent cache. Explicit/effective cpu stays
    uncached (CPU AOT segfault hazard)."""
    import jax

    import spark_rapids_tpu as st

    monkeypatch.setattr(st, "_compile_cache_enabled", False)

    # explicit cpu config: never enables, never probes the backend
    monkeypatch.setattr(st, "_configured_platform", lambda: "cpu")
    assert st.ensure_compile_cache() is False

    # unset config, auto-detection resolved to cpu: stays uncached
    monkeypatch.setattr(st, "_configured_platform", lambda: "")
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert st.ensure_compile_cache() is False

    # unset config, auto-detection resolved to a device backend:
    # the cache turns on and the dir is host-fingerprint-namespaced
    cache_root = None
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("SPARK_RAPIDS_TPU_CACHE", "/tmp/_sr_tpu_cache_test")
    seen = {}
    real_update = jax.config.update

    def spy_update(key, value):
        seen[key] = value
        if key == "jax_compilation_cache_dir":
            return  # don't mutate real config in the test process
        return real_update(key, value)

    monkeypatch.setattr(jax.config, "update", spy_update)
    assert st.ensure_compile_cache() is True
    cache_root = seen.get("jax_compilation_cache_dir")
    assert cache_root and cache_root.startswith("/tmp/_sr_tpu_cache_test")
    assert cache_root != "/tmp/_sr_tpu_cache_test"  # fingerprint subdir
    monkeypatch.setattr(st, "_compile_cache_enabled", False)
