"""Minimal Iceberg table BUILDER for tests (the reference generates
Iceberg test tables with Spark+Iceberg; neither is in this image).
Builds the v2 protocol shape the scan consumes: metadata JSON,
manifest-list Avro, manifest Avro with nested data_file records,
parquet data + delete files."""

import json
import os
import uuid

import pyarrow as pa
import pyarrow.parquet as pq

from tests.avro_util import write_avro

_ICEBERG_TYPES = {"int64": "long", "int32": "int", "double": "double",
                  "float": "float", "bool": "boolean", "string": "string",
                  "large_string": "string"}

MANIFEST_ENTRY_SCHEMA = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int"},
        {"name": "sequence_number", "type": ["null", "long"]},
        {"name": "data_file", "type": {
            "type": "record", "name": "r2", "fields": [
                {"name": "content", "type": "int"},
                {"name": "file_path", "type": "string"},
                {"name": "file_format", "type": "string"},
                {"name": "record_count", "type": "long"},
                {"name": "file_size_in_bytes", "type": "long"},
                {"name": "equality_ids",
                 "type": ["null", {"type": "array", "items": "int"}]},
            ]}},
    ]}

MANIFEST_LIST_SCHEMA = {
    "type": "record", "name": "manifest_file", "fields": [
        {"name": "manifest_path", "type": "string"},
        {"name": "manifest_length", "type": "long"},
        {"name": "content", "type": "int"},
    ]}


class IcebergTableBuilder:
    def __init__(self, path: str, arrow_schema: pa.Schema):
        self.path = path
        self.arrow_schema = arrow_schema
        self.entries = []          # manifest entries (dicts)
        self.snapshot_id = 1
        os.makedirs(os.path.join(path, "data"), exist_ok=True)
        os.makedirs(os.path.join(path, "metadata"), exist_ok=True)

    def add_data_file(self, table: pa.Table, sequence_number=1) -> str:
        rel = f"data/{uuid.uuid4().hex}.parquet"
        full = os.path.join(self.path, rel)
        pq.write_table(table, full)
        self.entries.append({
            "status": 1, "sequence_number": sequence_number,
            "data_file": {
                "content": 0, "file_path": full,
                "file_format": "PARQUET",
                "record_count": table.num_rows,
                "file_size_in_bytes": os.path.getsize(full),
                "equality_ids": None}})
        return full

    def add_position_deletes(self, deletes, sequence_number=2):
        """deletes: list of (data_file_path, row_pos)."""
        t = pa.table({"file_path": [p for p, _ in deletes],
                      "pos": pa.array([i for _, i in deletes],
                                      type=pa.int64())})
        rel = f"data/{uuid.uuid4().hex}-deletes.parquet"
        full = os.path.join(self.path, rel)
        pq.write_table(t, full)
        self.entries.append({
            "status": 1, "sequence_number": sequence_number,
            "data_file": {
                "content": 1, "file_path": full,
                "file_format": "PARQUET", "record_count": t.num_rows,
                "file_size_in_bytes": os.path.getsize(full),
                "equality_ids": None}})

    def add_equality_deletes(self, table: pa.Table, equality_ids,
                             sequence_number=2):
        rel = f"data/{uuid.uuid4().hex}-eqdeletes.parquet"
        full = os.path.join(self.path, rel)
        pq.write_table(table, full)
        self.entries.append({
            "status": 1, "sequence_number": sequence_number,
            "data_file": {
                "content": 2, "file_path": full,
                "file_format": "PARQUET", "record_count": table.num_rows,
                "file_size_in_bytes": os.path.getsize(full),
                "equality_ids": list(equality_ids)}})

    def commit(self):
        mdir = os.path.join(self.path, "metadata")
        manifest = os.path.join(mdir, f"manifest-{uuid.uuid4().hex}.avro")
        write_avro(manifest, MANIFEST_ENTRY_SCHEMA, self.entries)
        mlist = os.path.join(mdir, f"snap-{self.snapshot_id}.avro")
        write_avro(mlist, MANIFEST_LIST_SCHEMA, [{
            "manifest_path": manifest,
            "manifest_length": os.path.getsize(manifest),
            "content": 0}])
        fields = []
        for i, f in enumerate(self.arrow_schema):
            fields.append({"id": i + 1, "name": f.name, "required": False,
                           "type": _ICEBERG_TYPES[str(f.type)]})
        meta = {
            "format-version": 2,
            "table-uuid": uuid.uuid4().hex,
            "location": self.path,
            "schemas": [{"schema-id": 0, "type": "struct",
                         "fields": fields}],
            "current-schema-id": 0,
            "partition-specs": [{"spec-id": 0, "fields": []}],
            "default-spec-id": 0,
            "current-snapshot-id": self.snapshot_id,
            "snapshots": [{"snapshot-id": self.snapshot_id,
                           "manifest-list": mlist,
                           "timestamp-ms": 0}],
        }
        with open(os.path.join(mdir, "v1.metadata.json"), "w") as f:
            json.dump(meta, f)
        with open(os.path.join(mdir, "version-hint.text"), "w") as f:
            f.write("1")
        return self.path
