"""RL-OBS-PASSIVE — the telemetry sampler (``obs/telemetry.py``) runs
on a background thread BETWEEN queries by design: it may not touch the
device (no jax/jnp at all, no host syncs, no ``finalize_observation``
— that forces the deferred row-count fetch), may not drive query
execution (``execute``/``collect*``), and may not take the query-path
locks (the device semaphore, the scheduler condition, the session obs
lock) — sampling must never perturb the execution it observes."""

from __future__ import annotations

import ast
from typing import List, Optional

from spark_rapids_tpu.lint.diagnostics import Diagnostic, make
from spark_rapids_tpu.lint.rules.common import (_attr_chain,
                                                _host_sync_call)

#: the module RL-OBS-PASSIVE governs (the telemetry sampler + flight
#: recorder — both run off the query path by contract)
_OBS_PASSIVE_MODULE = "spark_rapids_tpu/obs/telemetry.py"

#: sanctioned exceptions: "<rel>:<qualified function>" -> justification
_OBS_PASSIVE_ALLOWLIST: dict = {}

#: lock-name fragments that mark a QUERY-PATH lock (the device
#: semaphore, the scheduler's condition, the session's obs lock) —
#: the sampler's own ring lock and the snapshot surfaces' internal
#: locks are fine (each bounds its hold to a dict copy)
_OBS_PASSIVE_LOCK_TOKENS = ("semaphore", "_cond", "_obs_lock")

#: call names that DRIVE execution — the passive module may read
#: state, never create it
_OBS_PASSIVE_EXEC_CALLS = {"execute", "execute_cpu", "execute_masked",
                           "collect", "collect_table", "collect_cpu"}


def _check_obs_passive(rel: str, tree: ast.AST,
                       diags: List[Diagnostic]):
    """RL-OBS-PASSIVE: the telemetry sampler thread may not call
    host_fetch/device syncs, touch jax at all, drive query execution,
    or take query-path locks — sampling must never perturb the
    execution it observes."""
    if rel != _OBS_PASSIVE_MODULE:
        return

    def flag(node, what: str, func: Optional[str]):
        if f"{rel}:{func}" in _OBS_PASSIVE_ALLOWLIST:
            return
        diags.append(make(
            "RL-OBS-PASSIVE", f"{rel}:{node.lineno}",
            f"{what} in the passive telemetry module"
            + (f" (function {func!r})" if func else " (module level)")
            + " — the sampler must never perturb execution: read the "
            "bounded snapshot surfaces only, or allowlist the function "
            "in _OBS_PASSIVE_ALLOWLIST with a justification"))

    def _names_query_lock(expr: ast.AST) -> Optional[str]:
        chain = _attr_chain(expr)
        if isinstance(expr, ast.Call):
            chain = _attr_chain(expr.func)
        low = chain.lower()
        for tok in _OBS_PASSIVE_LOCK_TOKENS:
            if tok in low:
                return chain
        return None

    def walk(node, func: Optional[str]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            func = f"{func}.{node.name}" if func else node.name
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            mod = getattr(node, "module", None) or ""
            names = [a.name for a in node.names]
            if mod == "jax" or mod.startswith("jax.") \
                    or any(n == "jax" or n.startswith("jax.")
                           for n in names):
                flag(node, "jax import (device work)", func)
        elif isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain.startswith(("jax.", "jnp.")):
                flag(node, f"{chain}() (device work)", func)
            elif _host_sync_call(chain):
                flag(node, f"{chain}() (host sync)", func)
            elif chain.split(".")[-1] == "finalize_observation":
                flag(node, f"{chain}() (forces the deferred device "
                           "row-count fetch)", func)
            elif chain.split(".")[-1] in _OBS_PASSIVE_EXEC_CALLS:
                flag(node, f"{chain}() (drives query execution)", func)
            elif chain.split(".")[-1] == "acquire":
                locked = _names_query_lock(node.func.value) \
                    if isinstance(node.func, ast.Attribute) else None
                if locked:
                    flag(node, f"{chain}() (query-path lock)", func)
        elif isinstance(node, ast.With):
            for item in node.items:
                locked = _names_query_lock(item.context_expr)
                if locked:
                    flag(node, f"with {locked} (query-path lock)", func)
        for child in ast.iter_child_nodes(node):
            walk(child, func)

    walk(tree, None)
