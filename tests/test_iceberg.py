"""Iceberg read-path tests (reference: iceberg suite — scan, snapshot
selection, positional + equality deletes, nested-avro manifests)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.ops.expr import col
from tests.iceberg_util import IcebergTableBuilder


def _arrow(n, base=0, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({
        "id": pa.array(np.arange(base, base + n), type=pa.int64()),
        "k": pa.array(rng.integers(0, 5, n), type=pa.int64()),
        "v": pa.array(rng.standard_normal(n), type=pa.float64()),
        "s": pa.array([f"s{i % 10}" for i in range(n)])})


def test_basic_scan(tmp_path, session, cpu_session):
    b = IcebergTableBuilder(str(tmp_path / "t"), _arrow(1).schema)
    b.add_data_file(_arrow(300, 0, seed=1))
    b.add_data_file(_arrow(200, 300, seed=2))
    b.commit()
    df = session.read_iceberg(str(tmp_path / "t"))
    assert df.count() == 500
    assert sorted(r[0] for r in df.select("id").collect()) == \
        list(range(500))
    assert sorted(session.read_iceberg(str(tmp_path / "t")).collect()) == \
        sorted(cpu_session.read_iceberg(str(tmp_path / "t")).collect())


def test_positional_deletes(tmp_path, session):
    b = IcebergTableBuilder(str(tmp_path / "t"), _arrow(1).schema)
    f1 = b.add_data_file(_arrow(100, 0))
    f2 = b.add_data_file(_arrow(100, 100))
    b.add_position_deletes([(f1, 0), (f1, 1), (f2, 99)])
    b.commit()
    rows = sorted(r[0] for r in session.read_iceberg(str(tmp_path / "t"))
                  .select("id").collect())
    assert len(rows) == 197
    assert 0 not in rows and 1 not in rows and 199 not in rows
    assert 2 in rows and 198 in rows


def test_equality_deletes_respect_sequence_numbers(tmp_path, session):
    b = IcebergTableBuilder(str(tmp_path / "t"), _arrow(1).schema)
    b.add_data_file(_arrow(100, 0), sequence_number=1)     # old data
    b.add_data_file(_arrow(100, 100), sequence_number=3)   # NEWER than del
    # delete ids 5 and 105 by equality on "id" (field id 1), seq=2
    b.add_equality_deletes(
        pa.table({"id": pa.array([5, 105], type=pa.int64())}),
        equality_ids=[1], sequence_number=2)
    b.commit()
    rows = sorted(r[0] for r in session.read_iceberg(str(tmp_path / "t"))
                  .select("id").collect())
    assert 5 not in rows          # old data: delete applies
    assert 105 in rows            # newer data: delete does NOT apply
    assert len(rows) == 199


def test_column_pruning_and_engine_ops(tmp_path, session, cpu_session):
    b = IcebergTableBuilder(str(tmp_path / "t"), _arrow(1).schema)
    b.add_data_file(_arrow(400, 0, seed=3))
    b.commit()

    def q(s):
        return (s.read_iceberg(str(tmp_path / "t"), columns=["k", "v"])
                .filter(col("v") > 0)
                .group_by("k").agg(F.count("v").alias("c"),
                                   F.sum("v").alias("sv")))

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g[0] == w[0] and g[1] == w[1]
        assert abs(g[2] - w[2]) <= 1e-6 * max(1.0, abs(w[2]))


def test_equality_delete_columns_beyond_projection(tmp_path, session):
    """Equality delete on a column NOT in the projection still applies."""
    b = IcebergTableBuilder(str(tmp_path / "t"), _arrow(1).schema)
    b.add_data_file(_arrow(100, 0), sequence_number=1)
    b.add_equality_deletes(
        pa.table({"s": pa.array(["s3"])}), equality_ids=[4],
        sequence_number=2)
    b.commit()
    rows = session.read_iceberg(str(tmp_path / "t"),
                                columns=["id"]).collect()
    assert len(rows) == 90  # every 10th row had s == "s3"


def test_not_an_iceberg_table(tmp_path, session):
    with pytest.raises(ColumnarProcessingError, match="not an iceberg"):
        session.read_iceberg(str(tmp_path))


def test_snapshot_selection_unknown(tmp_path, session):
    b = IcebergTableBuilder(str(tmp_path / "t"), _arrow(1).schema)
    b.add_data_file(_arrow(10, 0))
    b.commit()
    with pytest.raises(ColumnarProcessingError, match="no iceberg snapshot"):
        session.read_iceberg(str(tmp_path / "t"), snapshot_id=999)
