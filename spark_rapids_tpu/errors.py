"""Exception hierarchy, mirroring the reference's OOM/retry protocol.

Reference: spark-rapids-jni exception types (SURVEY.md §2.9) --
GpuRetryOOM / GpuSplitAndRetryOOM / CpuRetryOOM / CpuSplitAndRetryOOM /
GpuOOM -- thrown by the RmmSpark per-thread state machine and caught by
RmmRapidsRetryIterator.withRetry (RmmRapidsRetryIterator.scala:33-757).

On TPU the analogs are raised when a PJRT/XLA device allocation fails (or
when the runtime's HBM budget tracker decides a batch will not fit), and by
the test-only OOM injection hooks.
"""

from __future__ import annotations


class RapidsTpuError(Exception):
    """Base for all engine errors."""


class RetryOOM(RapidsTpuError):
    """Device allocation failed; caller should spill and replay the same
    input (reference: GpuRetryOOM)."""


class SplitAndRetryOOM(RapidsTpuError):
    """Device allocation failed and replay alone will not help; caller should
    split the input (halve rows) and replay (reference: GpuSplitAndRetryOOM)."""


class CpuRetryOOM(RapidsTpuError):
    """Host allocation failed; spill host buffers and replay."""


class CpuSplitAndRetryOOM(RapidsTpuError):
    """Host allocation failed; split input and replay."""


class FatalDeviceOOM(RapidsTpuError):
    """Unrecoverable device OOM after retries exhausted (reference: GpuOOM)."""


class ColumnarProcessingError(RapidsTpuError):
    """An operator failed on device in a way that is not an OOM."""


class KernelCrashError(ColumnarProcessingError):
    """A device kernel failed with a non-OOM runtime fault (injected by the
    chaos harness, or a real XLA INTERNAL-class failure re-raised with op
    attribution). Carries ``fault_op`` — the plan-node class name of the
    nearest enclosing operator — which feeds the runtime circuit breaker
    (runtime/faults.py)."""

    def __init__(self, message: str, fault_op=None):
        super().__init__(message)
        if fault_op is not None:
            self.fault_op = fault_op


class ShuffleFetchError(ColumnarProcessingError):
    """A shuffle block fetch failed in a RETRYABLE way (peer error frame,
    short transfer, bounce-pool exhaustion, injected fetch fault). The
    fetch-retry loop (shuffle manager / p2p env) replays the fetch with
    exponential backoff before declaring the map output lost."""


class ShuffleTransportError(ShuffleFetchError):
    """The transport connection itself failed (socket error, peer
    disconnect, protocol desync). Retryable like a fetch error, but the
    connection is evicted so the retry reconnects."""


class CorruptFrameError(ShuffleFetchError):
    """A serialized shuffle frame failed integrity checks (bad TPAK
    magic/version, CRC mismatch, truncated buffer). Retryable: the source
    of truth (catalog blob / shuffle file / upstream lineage) is intact,
    so a refetch or recompute recovers."""


class MapOutputLostError(RapidsTpuError):
    """Shuffle map output is unreachable — a fetch exhausted its retries or
    the owning peer was evicted. Carries ``executor_id`` (the lost peer,
    '' when local) and ``map_ids`` (the missing map outputs; None =
    unknown, recompute everything). The shuffle exchange catches this and
    re-runs the missing upstream partitions from the retained plan
    lineage instead of failing the query."""

    def __init__(self, message: str, executor_id: str = "",
                 map_ids=None):
        super().__init__(message)
        self.executor_id = executor_id
        self.map_ids = None if map_ids is None else sorted(set(map_ids))


class UnsupportedOnTpu(RapidsTpuError):
    """Raised when an operator/expression is asked to run on device but was
    tagged unsupported; indicates a bug in the plan-rewrite layer (normal
    operation converts such nodes back to CPU)."""


class PlanVerificationError(RapidsTpuError):
    """A converted plan violated a structural invariant
    (spark.rapids.sql.planVerify.mode=error). Carries the structured
    diagnostics in ``.diagnostics``; the message lists rule id + plan
    path per finding."""

    def __init__(self, diagnostics):
        self.diagnostics = list(diagnostics)
        super().__init__(
            "plan verification failed:\n" +
            "\n".join(f"  {d}" for d in self.diagnostics))


class DeviceLostError(RapidsTpuError):
    """The device (or its PJRT tunnel) was lost mid-query: a fatal
    non-OOM runtime failure classified by
    ``runtime.crash_handler.is_fatal_device_error``. RETRYABLE — by the
    time the caller sees this, the health monitor (runtime/health.py)
    has already reinitialized the backend and invalidated every cache
    that referenced dead device state, so a resubmission plans and
    traces fresh. The query service requeues these automatically."""


class MeshDeviceLostError(DeviceLostError):
    """PARTIAL device loss: one device of the execution mesh died (or
    its ICI link to it) while the backend as a whole is still alive —
    classified DISTINCTLY from whole-backend :class:`DeviceLostError`
    so recovery can walk the mesh degradation ladder
    (runtime/health.py ``on_mesh_device_loss``: retry → re-land
    single-device → mesh reconfiguration onto surviving devices →
    full backend reinit → CPU-only latch) instead of jumping straight
    to a backend reinitialization. Carries ``device_id`` when the
    failing device is known (None for injected losses — the ladder
    then excludes the mesh's last device)."""

    def __init__(self, message: str, device_id=None):
        super().__init__(message)
        self.device_id = device_id


class HostLostError(DeviceLostError):
    """A whole executor HOST (process) of the cluster died or went
    unreachable — a dead dispatch socket, a missed-heartbeat eviction,
    or an injected ``device_lost`` at a ``host.*`` fault point.
    Classified DISTINCTLY from whole-backend :class:`DeviceLostError`
    (the local backend is fine) and from partial
    :class:`MeshDeviceLostError` (a device died, not a process):
    recovery walks the HOST degradation ladder (runtime/health.py
    ``on_host_loss``: retry → re-land the dead host's shards onto
    survivors → shrink the dcn axis → single-process fallback →
    escalate to the whole-backend ladder). Carries ``host_id`` when
    the failing host is known (None for injected losses — the ladder
    then marks the last usable host)."""

    def __init__(self, message: str, host_id=None):
        super().__init__(message)
        self.host_id = host_id


class MeshGatherError(KernelCrashError):
    """The row-count + checksum validation at a mesh gather boundary
    (MeshReland / the ICI exchange's live-count fetch — the TPAK-v2
    frame-CRC pattern applied to device-to-device relands) kept
    failing past ``spark.rapids.mesh.maxShardRetries`` local
    re-gathers. A KernelCrashError subclass on purpose: the still-
    sharded source (or the still-resident device value) is intact, so
    the query-replay machinery re-lands from the scan cache rather
    than surfacing silently wrong results."""


class SpillCorruptionError(KernelCrashError):
    """A disk-tier spill frame failed its CRC footer on unspill (bit
    rot, a torn write, or an injected ``mem.unspill`` corruption). A
    KernelCrashError subclass on purpose — the MeshGatherError
    pattern: the corrupt frame is dropped (never served), and the
    query-replay machinery re-lands the data from the scan cache /
    source lineage rather than surfacing silently wrong bytes."""


class WorkerLostError(RapidsTpuError):
    """The service worker executing this query died (its runner
    machinery raised outside the query) or was abandoned by the
    watchdog. The pool respawned a replacement; the query itself was
    requeued up to its replay budget before this error surfaced."""


class SemaphoreTimeoutError(RapidsTpuError, TimeoutError):
    """TpuSemaphore acquisition timed out: ``max_tasks`` queries already
    hold device residency and none released within the caller's timeout.
    A typed signal (not a bare TimeoutError, though it still IS one for
    callers catching broadly) so the query service can report
    backpressure distinctly from deadline expiry."""


class QueryRejectedError(RapidsTpuError):
    """The query service refused admission — the target pool's queue is
    at ``spark.rapids.service.queueDepth``. Carries ``retry_after_ms``,
    the service's backpressure hint for when capacity is likely free
    (the HTTP 429 Retry-After analog)."""

    def __init__(self, message: str, retry_after_ms: int = 100):
        super().__init__(message)
        self.retry_after_ms = int(retry_after_ms)


class QueryCancelledError(RapidsTpuError):
    """The query was cancelled via ``QueryHandle.cancel()``. Raised
    cooperatively between batches at the exec boundary (service/query.py
    install_cancellation), so a running plan stops at the next pull
    instead of after the query."""


class QueryTimeoutError(RapidsTpuError):
    """The query's deadline (submit time + timeout) expired — while
    queued, or cooperatively between batches while running."""


class HardTimeoutError(QueryTimeoutError):
    """The watchdog's HARD wall limit
    (``spark.rapids.service.hardTimeoutMs``) expired while the query was
    RUNNING. Distinct from the cooperative deadline: that one fires at
    exec-boundary batch pulls, so a worker wedged INSIDE a single
    dispatch never observes it — the watchdog abandons that worker,
    respawns a replacement, and fails the handle with this error."""


class QueryQuarantinedError(RapidsTpuError):
    """The query's template was quarantined: plans with this structural
    fingerprint killed workers or the device
    ``spark.rapids.service.quarantine.maxStrikes`` times, so the service
    refuses to run it again. Carries ``strikes`` — the recorded strike
    history (list of reason strings) — so the submitter can see what the
    template did."""

    def __init__(self, message: str, strikes=None):
        super().__init__(message)
        self.strikes = list(strikes or ())


class AnsiViolation(RapidsTpuError, ArithmeticError):
    """ANSI mode (spark.sql.ansi.enabled) runtime error: overflow, divide
    by zero, invalid cast, or array index out of bounds — the engine's
    SparkArithmeticException. Device kernels record the violation as a
    device flag that rides the collect fetch (like speculation flags);
    the CPU oracle raises at evaluation."""
