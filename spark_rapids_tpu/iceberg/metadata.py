"""Iceberg table metadata + manifest parsing.

Reference: the Iceberg library side the plugin binds (table metadata JSON,
manifest-list Avro, manifest Avro with nested ``data_file`` records) —
``IcebergProviderImpl.scala`` wires it, ``GpuIcebergReader.java`` consumes
the planned file tasks. Here the protocol is parsed natively: metadata
JSON (v1 ``schema`` / v2 ``schemas``), snapshot selection, manifest-list →
manifests → data/delete file entries with sequence numbers."""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.errors import ColumnarProcessingError

_PRIMITIVES = {
    "boolean": T.BOOLEAN, "int": T.INT, "long": T.LONG, "float": T.FLOAT,
    "double": T.DOUBLE, "string": T.STRING, "date": T.DATE,
    "timestamp": T.TIMESTAMP, "timestamptz": T.TIMESTAMP,
}

DATA_CONTENT = 0
POSITION_DELETES = 1
EQUALITY_DELETES = 2


def _schema_from_iceberg(fields: List[dict]) -> List[Tuple[str, T.DataType]]:
    out = []
    for f in fields:
        t = f["type"]
        if not isinstance(t, str) or t not in _PRIMITIVES:
            raise ColumnarProcessingError(
                f"iceberg column {f['name']!r} type {t!r} is not supported "
                "on this engine")
        out.append((f["name"], _PRIMITIVES[t]))
    return out


@dataclass
class DataFileEntry:
    content: int           # 0 data, 1 position deletes, 2 equality deletes
    file_path: str
    file_format: str
    record_count: int
    sequence_number: int = 0
    equality_ids: List[int] = field(default_factory=list)


@dataclass
class IcebergSnapshot:
    snapshot_id: int
    manifest_list: str
    data_files: List[DataFileEntry] = field(default_factory=list)
    delete_files: List[DataFileEntry] = field(default_factory=list)


@dataclass
class IcebergTableMetadata:
    location: str
    schema: List[Tuple[str, T.DataType]]
    field_ids: Dict[int, str]        # iceberg field id -> column name
    current_snapshot_id: Optional[int]
    snapshots: List[dict]

    def snapshot_entry(self, snapshot_id: Optional[int] = None) -> dict:
        sid = snapshot_id if snapshot_id is not None \
            else self.current_snapshot_id
        if sid is None:
            raise ColumnarProcessingError("iceberg table has no snapshot")
        for s in self.snapshots:
            if s["snapshot-id"] == sid:
                return s
        raise ColumnarProcessingError(f"no iceberg snapshot {sid}")


def _resolve_path(table_path: str, p: str) -> str:
    """Iceberg stores absolute URIs; map file:// and table-relative."""
    if p.startswith("file://"):
        return p[len("file://"):]
    if os.path.isabs(p):
        return p
    return os.path.join(table_path, p)


def load_table_metadata(table_path: str) -> IcebergTableMetadata:
    meta_dir = os.path.join(table_path, "metadata")
    if not os.path.isdir(meta_dir):
        raise ColumnarProcessingError(
            f"{table_path} is not an iceberg table (no metadata/)")
    hint = os.path.join(meta_dir, "version-hint.text")
    meta_file = None
    if os.path.exists(hint):
        with open(hint) as f:
            v = f.read().strip()
        for cand in (f"v{v}.metadata.json", f"{v}.metadata.json"):
            if os.path.exists(os.path.join(meta_dir, cand)):
                meta_file = os.path.join(meta_dir, cand)
                break
    if meta_file is None:
        versions = []
        for fn in os.listdir(meta_dir):
            m = re.match(r"v?(\d+)(?:-[0-9a-f-]+)?\.metadata\.json$", fn)
            if m:
                versions.append((int(m.group(1)), fn))
        if not versions:
            raise ColumnarProcessingError(
                f"no metadata json under {meta_dir}")
        meta_file = os.path.join(meta_dir, max(versions)[1])

    with open(meta_file) as f:
        meta = json.load(f)

    if "schemas" in meta:  # v2
        sid = meta.get("current-schema-id", 0)
        schema_obj = next(s for s in meta["schemas"]
                          if s.get("schema-id", 0) == sid)
    else:  # v1
        schema_obj = meta["schema"]
    schema = _schema_from_iceberg(schema_obj["fields"])
    field_ids = {f["id"]: f["name"] for f in schema_obj["fields"]}
    return IcebergTableMetadata(
        location=meta.get("location", table_path),
        schema=schema,
        field_ids=field_ids,
        current_snapshot_id=meta.get("current-snapshot-id"),
        snapshots=meta.get("snapshots", []))


def load_snapshot(table_path: str, meta: IcebergTableMetadata,
                  snapshot_id: Optional[int] = None) -> IcebergSnapshot:
    from spark_rapids_tpu.io.avro import decode_records
    entry = meta.snapshot_entry(snapshot_id)
    manifest_list = _resolve_path(table_path, entry["manifest-list"])
    with open(manifest_list, "rb") as f:
        manifests = decode_records(f.read())

    snap = IcebergSnapshot(entry["snapshot-id"], manifest_list)
    for m in manifests:
        mpath = _resolve_path(table_path, m["manifest_path"])
        with open(mpath, "rb") as f:
            entries = decode_records(f.read())
        for e in entries:
            status = e.get("status", 1)
            if status == 2:  # DELETED entry
                continue
            df = e["data_file"]
            entry_obj = DataFileEntry(
                content=df.get("content", DATA_CONTENT) or DATA_CONTENT,
                file_path=_resolve_path(table_path, df["file_path"]),
                file_format=(df.get("file_format") or "PARQUET").upper(),
                record_count=df.get("record_count", 0) or 0,
                sequence_number=e.get("sequence_number") or 0,
                equality_ids=list(df.get("equality_ids") or []))
            if entry_obj.file_format != "PARQUET":
                raise ColumnarProcessingError(
                    f"iceberg file format {entry_obj.file_format} not "
                    "supported (parquet only)")
            if entry_obj.content == DATA_CONTENT:
                snap.data_files.append(entry_obj)
            else:
                snap.delete_files.append(entry_obj)
    snap.data_files.sort(key=lambda d: d.file_path)
    return snap
