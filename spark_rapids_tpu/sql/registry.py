"""SQL function-name resolution.

Maps SQL call syntax onto the SAME expression builders the DataFrame API
exposes in ``spark_rapids_tpu.functions`` (so a SQL query and its DSL
form build identical expression trees and share compiled kernels).
Lookup order in the analyzer: session catalog functions (registered
Python UDFs) -> global registrations (``functions.register_sql_function``)
-> this builtin table -> Hive UDF registry (``hive_udf.py``)."""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from spark_rapids_tpu.ops.expr import Expression, Literal, lit
from spark_rapids_tpu.sql.errors import SqlAnalysisError

Builder = Callable[[List[Expression]], Expression]


def _need(args: Sequence, lo: int, hi: Optional[int], name: str) -> None:
    hi_txt = "+" if hi is None else (f"-{hi}" if hi != lo else "")
    if len(args) < lo or (hi is not None and len(args) > hi):
        raise SqlAnalysisError(
            f"function {name} expects {lo}{hi_txt} argument(s), "
            f"got {len(args)}")


def _lit_value(e: Expression, name: str, what: str):
    """Unwrap a literal argument (offsets, counts, seeds — parameters the
    underlying builders take as plain Python values)."""
    if not isinstance(e, Literal):
        raise SqlAnalysisError(
            f"function {name}: {what} must be a literal")
    return e.value


def _build_table() -> Dict[str, Builder]:
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.ops import aggregates as _agg
    from spark_rapids_tpu.ops import collections as _coll
    from spark_rapids_tpu.ops import conditional as _cond
    from spark_rapids_tpu.ops import datetime as _dt
    from spark_rapids_tpu.ops import math as _math
    from spark_rapids_tpu.ops import misc as _misc
    from spark_rapids_tpu.ops import nested as _nested
    from spark_rapids_tpu.ops import predicates as _pred
    from spark_rapids_tpu.ops import strings as _str
    from spark_rapids_tpu.ops import window as _win
    from spark_rapids_tpu.ops.arithmetic import Abs
    from spark_rapids_tpu.ops.hashfns import Murmur3Hash, XxHash64
    from spark_rapids_tpu.ops.json_structs import StructsToJson

    T: Dict[str, Builder] = {}

    def reg(names, fn, lo, hi=-1):
        """hi: -1 = exactly lo, None = unbounded."""
        high = lo if hi == -1 else hi
        if isinstance(names, str):
            names = (names,)

        def build(args, _name=names[0], _fn=fn, _lo=lo, _hi=high):
            _need(args, _lo, _hi, _name)
            return _fn(*args)
        for n in names:
            T[n] = build

    # aggregates (DEVICE_SUPPORTED_AGGS + CPU-path ones; overrides tag
    # fallback per instance exactly as for the DSL)
    reg("sum", _agg.Sum, 1)
    reg("min", _agg.Min, 1)
    reg("max", _agg.Max, 1)
    reg(("avg", "mean"), _agg.Average, 1)
    reg("count", lambda e: _agg.Count(e), 1)
    reg("first", lambda e: _agg.First(e, False), 1)
    reg("last", lambda e: _agg.Last(e, False), 1)
    reg("collect_list", _agg.CollectList, 1)
    reg("collect_set", _agg.CollectSet, 1)
    reg(("stddev", "stddev_samp", "std"), _agg.StddevSamp, 1)
    reg("stddev_pop", _agg.StddevPop, 1)
    reg(("variance", "var_samp"), _agg.VarianceSamp, 1)
    reg("var_pop", _agg.VariancePop, 1)
    T["percentile"] = lambda args: (
        _need(args, 2, 2, "percentile") or
        _agg.Percentile(args[0],
                        _lit_value(args[1], "percentile", "percentage")))
    T["approx_percentile"] = lambda args: (
        _need(args, 2, 3, "approx_percentile") or
        _agg.Percentile(args[0], _lit_value(args[1], "approx_percentile",
                                            "percentage")))

    # conditionals / null handling
    reg("coalesce", _cond.Coalesce, 1, None)
    reg(("nvl", "ifnull"), _cond.Coalesce, 2)
    reg("greatest", _cond.Greatest, 2, None)
    reg("least", _cond.Least, 2, None)
    reg("nanvl", _cond.NaNvl, 2)
    reg("if", _cond.If, 3)
    reg("isnull", _pred.IsNull, 1)
    reg("isnotnull", _pred.IsNotNull, 1)
    reg("isnan", _pred.IsNaN, 1)

    # math
    reg("sqrt", _math.Sqrt, 1)
    reg("exp", _math.Exp, 1)
    reg(("log", "ln"), _math.Log, 1)
    reg("log10", _math.Log10, 1)
    reg("log2", _math.Log2, 1)
    reg(("pow", "power"), _math.Pow, 2)
    reg("abs", Abs, 1)
    reg(("ceil", "ceiling"), _math.Ceil, 1)
    reg("floor", _math.Floor, 1)
    reg("round", lambda e, s=None: _math.Round(e, s or lit(0)), 1, 2)
    reg("bround", lambda e, s=None: _math.BRound(e, s or lit(0)), 1, 2)
    reg(("signum", "sign"), _math.Signum, 1)
    reg("shiftleft", _math.ShiftLeft, 2)
    reg("shiftright", _math.ShiftRight, 2)

    # strings
    reg(("upper", "ucase"), _str.Upper, 1)
    reg(("lower", "lcase"), _str.Lower, 1)
    reg(("length", "char_length", "character_length"), _str.Length, 1)
    reg("bit_length", _str.BitLength, 1)
    reg("octet_length", _str.OctetLength, 1)
    reg("ascii", _str.Ascii, 1)
    reg("reverse", _str.Reverse, 1)
    reg("initcap", _str.InitCap, 1)
    reg("trim", _str.StringTrim, 1)
    reg("ltrim", _str.StringTrimLeft, 1)
    reg("rtrim", _str.StringTrimRight, 1)
    reg(("substring", "substr"), _str.Substring, 3)
    reg("repeat", _str.StringRepeat, 2)
    reg("replace", lambda e, s, r=None:
        _str.StringReplace(e, s, r or lit("")), 2, 3)
    reg("lpad", lambda e, n, p=None:
        _str.StringLPad(e, n, p or lit(" ")), 2, 3)
    reg("rpad", lambda e, n, p=None:
        _str.StringRPad(e, n, p or lit(" ")), 2, 3)
    reg("substring_index", _str.SubstringIndex, 3)
    reg("translate", _str.StringTranslate, 3)
    reg("concat", _str.Concat, 1, None)
    reg("contains", _str.Contains, 2)
    reg("startswith", _str.StartsWith, 2)
    reg("endswith", _str.EndsWith, 2)
    reg("instr", _str.StringInstr, 2)
    reg("locate", lambda s, e, p=None:
        _str.StringLocate(s, e, p or lit(1)), 2, 3)
    reg("regexp_replace", _str.RegExpReplace, 3)
    reg("regexp_extract", lambda e, p, i=None:
        _str.RegExpExtract(e, p, i or lit(1)), 2, 3)
    T["concat_ws"] = lambda args: (
        _need(args, 1, None, "concat_ws") or
        _misc.ConcatWs(*args))

    # datetime
    reg("year", _dt.Year, 1)
    reg("month", _dt.Month, 1)
    reg(("day", "dayofmonth"), _dt.DayOfMonth, 1)
    reg("dayofweek", _dt.DayOfWeek, 1)
    reg("weekday", _dt.WeekDay, 1)
    reg("dayofyear", _dt.DayOfYear, 1)
    reg("quarter", _dt.Quarter, 1)
    reg("last_day", _dt.LastDay, 1)
    reg("date_add", _dt.DateAdd, 2)
    reg("date_sub", _dt.DateSub, 2)
    reg("datediff", _dt.DateDiff, 2)
    reg("add_months", _dt.AddMonths, 2)
    reg("hour", _dt.Hour, 1)
    reg("minute", _dt.Minute, 1)
    reg("second", _dt.Second, 1)
    reg(("to_unix_timestamp", "unix_timestamp"),
        _dt.UnixTimestampFromTs, 1)
    reg("timestamp_seconds", _dt.SecondsToTimestamp, 1)
    reg("timestamp_millis", _dt.MillisToTimestamp, 1)
    reg("timestamp_micros", _dt.MicrosToTimestamp, 1)
    reg("to_date", _dt.TsToDate, 1)
    reg("from_utc_timestamp", _misc.FromUTCTimestamp, 2)
    reg("to_utc_timestamp", _misc.ToUTCTimestamp, 2)

    # hash / misc
    reg("hash", Murmur3Hash, 1, None)
    reg("xxhash64", XxHash64, 1, None)
    reg("md5", _misc.Md5, 1)
    reg("monotonically_increasing_id",
        _misc.MonotonicallyIncreasingID, 0)
    reg("spark_partition_id", _misc.SparkPartitionID, 0)
    T["rand"] = lambda args: (
        _need(args, 0, 1, "rand") or
        _misc.Rand(_lit_value(args[0], "rand", "seed") if args else 0))

    # collections / nested
    reg(("size", "cardinality"), _coll.Size, 1)
    reg("array", _coll.CreateArray, 1, None)
    reg("array_contains", _coll.ArrayContains, 2)
    reg("array_min", _coll.ArrayMin, 1)
    reg("array_max", _coll.ArrayMax, 1)
    reg("sort_array", lambda e, a=None:
        _coll.SortArray(e, a or lit(True)), 1, 2)
    reg(("get_item", "element_at"), _coll.GetArrayItem, 2)
    reg("sequence", _coll.Sequence, 2, 3)
    reg("explode", _coll.Explode, 1)
    reg("explode_outer", _coll.ExplodeOuter, 1)
    reg("posexplode", _coll.PosExplode, 1)
    reg("posexplode_outer", _coll.PosExplodeOuter, 1)
    T["struct"] = lambda args: F.struct(*args)
    reg("named_struct", lambda *a: F.named_struct(
        *[x.value if isinstance(x, Literal) and i % 2 == 0 else x
          for i, x in enumerate(a)]), 2, None)
    reg("map_keys", _nested.MapKeys, 1)
    reg("map_values", _nested.MapValues, 1)
    reg("map_entries", _nested.MapEntries, 1)
    reg("to_json", StructsToJson, 1)

    # window functions (rank family / offsets); aggregate functions used
    # with OVER come from the aggregate entries above
    reg("row_number", _win.RowNumber, 0)
    reg("rank", _win.Rank, 0)
    reg("dense_rank", _win.DenseRank, 0)
    reg("percent_rank", _win.PercentRank, 0)
    T["nth_value"] = lambda args: (
        _need(args, 2, 2, "nth_value") or
        _win.NthValue(args[0], _lit_value(args[1], "nth_value", "n")))

    def _offset_fn(cls, name):
        def build(args):
            _need(args, 1, 3, name)
            off = (_lit_value(args[1], name, "offset")
                   if len(args) > 1 else 1)
            default = (_lit_value(args[2], name, "default")
                       if len(args) > 2 else None)
            return cls(args[0], off, default)
        return build

    T["lag"] = _offset_fn(_win.Lag, "lag")
    T["lead"] = _offset_fn(_win.Lead, "lead")
    return T


_BUILTINS: Optional[Dict[str, Builder]] = None


def builtin(name: str) -> Optional[Builder]:
    global _BUILTINS
    if _BUILTINS is None:
        _BUILTINS = _build_table()
    return _BUILTINS.get(name.lower())


def lookup(name: str, session=None) -> Optional[Callable]:
    """Resolve a SQL function name. Returns a callable taking a list of
    lowered Expression args, or None when nothing matches."""
    key = name.lower()
    # 1. session catalog (registered Python UDFs / per-session overrides)
    if session is not None:
        cat = getattr(session, "_catalog", None)
        if cat is not None:
            fn = cat.lookup_function(key)
            if fn is not None:
                return lambda args: fn(*args)
    # 2. global registrations (functions.register_sql_function)
    from spark_rapids_tpu import functions as F
    fn = F.registered_sql_function(key)
    if fn is not None:
        return lambda args: fn(*args)
    # 3. builtins
    b = builtin(key)
    if b is not None:
        return b
    # 4. Hive UDFs (CREATE TEMPORARY FUNCTION analog)
    from spark_rapids_tpu.hive_udf import _HIVE_FUNCTIONS, hive_udf
    if key in _HIVE_FUNCTIONS:
        call = hive_udf(key)
        return lambda args: call(*args)
    return None
