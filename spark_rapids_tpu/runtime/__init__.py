"""Runtime layer: device manager, task semaphore, spill catalog, OOM retry
(reference: GpuDeviceManager / GpuSemaphore / RapidsBufferCatalog /
RmmRapidsRetryIterator — SURVEY.md §2.5)."""
