"""Project/filter/expression oracle tests (reference analog:
integration_tests arithmetic_ops_test.py / cmp_test.py / conditionals_test.py)."""

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu import functions as F
from spark_rapids_tpu.ops.expr import col, lit

from tests.asserts import assert_tpu_and_cpu_are_equal, assert_runs_on_tpu
from tests.data_gen import (
    BooleanGen, ByteGen, DateGen, DoubleGen, FloatGen, IntGen, LongGen,
    ShortGen, StringGen, TimestampGen, gen_table, numeric_gens,
)


def _df(sess, gens, n=500, seed=7, num_batches=1):
    from spark_rapids_tpu.plan import from_host_table
    return from_host_table(gen_table(gens, n, seed), sess, num_batches)


@pytest.mark.parametrize("gen", numeric_gens, ids=lambda g: g.dtype.simple_string())
def test_add_sub_mul(session, cpu_session, gen):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"a": gen, "b": gen}).select(
            (col("a") + col("b")).alias("add"),
            (col("a") - col("b")).alias("sub"),
            (col("a") * col("b")).alias("mul"),
        ),
        session, cpu_session)


@pytest.mark.parametrize("gen", numeric_gens, ids=lambda g: g.dtype.simple_string())
def test_division(session, cpu_session, gen):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"a": gen, "b": gen}).select(
            (col("a") / col("b")).alias("div"),
            (col("a") % col("b")).alias("mod"),
        ),
        session, cpu_session, approximate_float=True)


def test_integral_divide(session, cpu_session):
    from spark_rapids_tpu.ops.arithmetic import IntegralDivide, Pmod
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"a": LongGen(), "b": IntGen(min_val=-100, max_val=100)}).select(
            IntegralDivide(col("a"), col("b")).alias("div"),
            Pmod(col("a"), col("b").cast(T.LONG)).alias("pmod"),
        ),
        session, cpu_session)


@pytest.mark.parametrize("gen", [IntGen(), LongGen(), DoubleGen(), StringGen(),
                                 BooleanGen(), DateGen(), TimestampGen()],
                         ids=lambda g: g.dtype.simple_string())
def test_comparisons(session, cpu_session, gen):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"a": gen, "b": gen}).select(
            (col("a") == col("b")).alias("eq"),
            (col("a") < col("b")).alias("lt"),
            (col("a") <= col("b")).alias("le"),
            (col("a") > col("b")).alias("gt"),
            (col("a") >= col("b")).alias("ge"),
        ),
        session, cpu_session)


def test_boolean_logic_kleene(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"a": BooleanGen(), "b": BooleanGen()}).select(
            (col("a") & col("b")).alias("and"),
            (col("a") | col("b")).alias("or"),
            (~col("a")).alias("not"),
            col("a").isnull().alias("isnull"),
            col("a").isnotnull().alias("isnotnull"),
        ),
        session, cpu_session)


def test_filter_basic(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"a": IntGen(), "b": DoubleGen()})
        .filter((col("a") > 0) & col("b").isnotnull()),
        session, cpu_session)


def test_filter_string(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"s": StringGen(cardinality=20)})
        .filter(col("s") > lit("H")),
        session, cpu_session)


def test_conditionals(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"a": IntGen(), "b": IntGen(), "c": BooleanGen()}).select(
            F.if_(col("c"), col("a"), col("b")).alias("iff"),
            F.when(col("a") > 0, col("a")).when(col("b") > 0, col("b")).otherwise(lit(0)).alias("cw"),
            F.coalesce(col("a"), col("b"), lit(-1)).alias("coal"),
            F.greatest(col("a"), col("b")).alias("gr"),
            F.least(col("a"), col("b")).alias("ls"),
        ),
        session, cpu_session)


def test_conditionals_string(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"a": StringGen(cardinality=10), "b": StringGen(cardinality=10),
                          "c": BooleanGen()}).select(
            F.if_(col("c"), col("a"), col("b")).alias("iff"),
            F.coalesce(col("a"), col("b")).alias("coal"),
            F.greatest(col("a"), col("b")).alias("gr"),
        ),
        session, cpu_session)


def test_in_expr(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"a": IntGen(min_val=0, max_val=10)}).select(
            F.is_in(col("a"), 1, 3, 5).alias("in135"),
            F.is_in(col("a"), 2, lit(None)).alias("in_null"),
        ),
        session, cpu_session)


def test_math_unary(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"a": DoubleGen()}).select(
            F.sqrt(F.abs(col("a"))).alias("sqrt"),
            F.log(F.abs(col("a")) + 1).alias("log"),
            F.exp(col("a") / lit(1e7)).alias("exp"),
            F.floor(col("a")).alias("floor"),
            F.ceil(col("a")).alias("ceil"),
            F.signum(col("a")).alias("sign"),
        ),
        session, cpu_session, approximate_float=True)


def test_casts_numeric(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"a": DoubleGen(), "b": LongGen(), "c": IntGen()}).select(
            col("a").cast(T.INT).alias("d2i"),
            col("a").cast(T.LONG).alias("d2l"),
            col("a").cast(T.FLOAT).alias("d2f"),
            col("b").cast(T.INT).alias("l2i"),
            col("b").cast(T.DOUBLE).alias("l2d"),
            col("c").cast(T.BYTE).alias("i2b"),
            col("c").cast(T.BOOLEAN).alias("i2bool"),
        ),
        session, cpu_session)


def test_whole_plan_on_tpu(session):
    assert_runs_on_tpu(
        lambda s: _df(s, {"a": IntGen(), "b": DoubleGen()})
        .filter(col("a") > 0)
        .select((col("a") * 2).alias("x"), col("b")),
        session)


def test_multi_batch(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"a": IntGen(), "s": StringGen(cardinality=8)}, n=1000, num_batches=4)
        .filter(col("a") > 0).select(col("s"), (col("a") + 1).alias("a1")),
        session, cpu_session)


def test_range(session, cpu_session):
    def build(s):
        from spark_rapids_tpu.plan import range_df
        return range_df(0, 1000, 3, session=s).select((col("id") * 2).alias("x"))
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_limit(session, cpu_session):
    def build(s):
        from spark_rapids_tpu.plan import range_df
        return range_df(0, 1000, session=s).limit(17)
    assert_tpu_and_cpu_are_equal(build, session, cpu_session, ignore_order=False)


def test_union(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"a": IntGen()}, seed=1).union(_df(s, {"a": IntGen()}, seed=2)),
        session, cpu_session)
