"""Type support matrix (reference: TypeChecks.scala / TypeSig — SURVEY.md
§2.2). Each operator rule declares the Spark types it supports on device;
anything else tags the node for CPU fallback with a reason."""

from __future__ import annotations

from typing import Iterable

from spark_rapids_tpu import types as T


class TypeSig:
    def __init__(self, *type_classes, max_decimal_precision: int = T.DecimalType.MAX_LONG_DIGITS):
        self.type_classes = tuple(type_classes)
        self.max_decimal_precision = max_decimal_precision

    def supports(self, dt: T.DataType) -> bool:
        if isinstance(dt, T.DecimalType):
            return (T.DecimalType in self.type_classes
                    and dt.precision <= self.max_decimal_precision)
        return any(type(dt) is tc for tc in self.type_classes)

    def reason_if_unsupported(self, dt: T.DataType, what: str) -> str:
        if self.supports(dt):
            return ""
        return f"{what} has unsupported type {dt.simple_string()}"

    def __add__(self, other: "TypeSig") -> "TypeSig":
        return TypeSig(*(set(self.type_classes) | set(other.type_classes)),
                       max_decimal_precision=max(self.max_decimal_precision,
                                                 other.max_decimal_precision))


_COMMON = (T.BooleanType, T.ByteType, T.ShortType, T.IntegerType, T.LongType,
           T.FloatType, T.DoubleType, T.DateType, T.TimestampType,
           T.StringType, T.DecimalType)

#: types fully supported by the device columnar representation today.
#: Decimals ride the DECIMAL64 tier (p<=18, int64 unscaled storage —
#: reference's original device tier); p>18 tags fallback.
COMMON = TypeSig(*_COMMON)
NUMERIC = TypeSig(T.ByteType, T.ShortType, T.IntegerType, T.LongType,
                  T.FloatType, T.DoubleType)
INTEGRAL = TypeSig(T.ByteType, T.ShortType, T.IntegerType, T.LongType)
ORDERABLE = COMMON
ALL = COMMON  # grows as nested/decimal device support lands


class ArrayFixedSig(TypeSig):
    """Arrays of fixed-width elements (the device (offsets, values,
    validity) representation — columnar/column.py)."""

    def __init__(self):
        super().__init__()

    def supports(self, dt: T.DataType) -> bool:
        from spark_rapids_tpu.ops.collections import is_fixed_array
        return is_fixed_array(dt)


class AnyOfSig(TypeSig):
    """Union of signatures."""

    def __init__(self, *sigs):
        super().__init__()
        self.sigs = sigs

    def supports(self, dt: T.DataType) -> bool:
        return any(s.supports(dt) for s in self.sigs)


ARRAY_FIXED = ArrayFixedSig()


class StructFixedSig(TypeSig):
    """Structs whose fields are all fixed-width (the device field-bundle
    representation — columnar/nested.py)."""

    def __init__(self):
        super().__init__()

    def supports(self, dt: T.DataType) -> bool:
        from spark_rapids_tpu.columnar.nested import struct_device_supported
        return (isinstance(dt, T.StructType)
                and struct_device_supported(dt))


class MapFixedSig(TypeSig):
    """Maps with fixed-width keys and values (the device split-stream
    representation — columnar/nested.py)."""

    def __init__(self):
        super().__init__()

    def supports(self, dt: T.DataType) -> bool:
        from spark_rapids_tpu.columnar.nested import map_device_supported
        return isinstance(dt, T.MapType) and map_device_supported(dt)


STRUCT_FIXED = StructFixedSig()
MAP_FIXED = MapFixedSig()

class ExprChecks:
    """Per-PARAMETER input signatures + an output signature for one
    expression rule (reference: ExprChecks in TypeChecks.scala — the
    per-param matrix is what keeps `Acos | STRING` honest: the OUTPUT of
    Acos is always DOUBLE, so only an input-position check can reject a
    string argument).

    ``param_sigs``: leading per-child signatures; children beyond them
    check against ``rest`` (None = no check, COMMON-equivalent docs).
    Output types stay in the _EXPR_SIGS registry — one source of truth."""

    def __init__(self, param_sigs: Iterable[TypeSig] = (),
                 rest: TypeSig = None):
        self.param_sigs = tuple(param_sigs)
        self.rest = rest

    def param_sig(self, i: int):
        if i < len(self.param_sigs):
            return self.param_sigs[i]
        return self.rest

    def doc_param_rows(self):
        """(label, sig) rows for the generated matrix."""
        rows = [(f"param {i}", s) for i, s in enumerate(self.param_sigs)]
        if self.rest is not None:
            rows.append(("param *", self.rest))
        return rows


def lookup_mro(registry: dict, cls: type):
    """First MRO hit in a class-keyed registry (shared by fallback
    checking and doc generation so lookup semantics can't diverge)."""
    for klass in cls.__mro__:
        if klass in registry:
            return registry[klass]
    return None


#: full-precision decimals (p<=38): two-limb (capacity, 2) int64 device
#: storage (reference DECIMAL_128 tier — TypeChecks.scala:613)
DEC128 = TypeSig(T.DecimalType,
                 max_decimal_precision=T.DecimalType.MAX_PRECISION)

#: COMMON widened to full decimal precision — the surface that flows
#: through storage-level machinery (scan/filter/sort/join/group keys,
#: compare, shuffle); ARITHMETIC on p>18 still falls back per-op
COMMON_128 = AnyOfSig(COMMON, DEC128)

#: scalar COMMON plus fixed-element arrays — the surface Scan/Project/
#: Generate handle on device (other execs keep COMMON: their kernels
#: compact/gather/sort flat buffers only)
COMMON_PLUS_ARRAYS = AnyOfSig(COMMON, ARRAY_FIXED)

#: ...plus fixed-field structs and fixed-width maps — Scan/Project only
#: (joins/sorts/aggs over raw nested columns tag fallback, like the
#: reference's per-op nested carve-outs in TypeChecks.scala)
COMMON_PLUS_NESTED = AnyOfSig(COMMON, ARRAY_FIXED, STRUCT_FIXED, MAP_FIXED)

#: nested surface widened to full decimal precision (column references,
#: aliases, scans)
NESTED_128 = AnyOfSig(COMMON_PLUS_NESTED, DEC128)
