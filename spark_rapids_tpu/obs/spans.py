"""Thread-aware host-side span tracer + exec-boundary instrumentation.

Reference (SURVEY.md §5): NVTX ranges (``NvtxWithMetrics.scala``) put
operator ranges on the DEVICE timeline; nothing in the reference shows
where HOST wall time goes — which on the tunneled TPU is where queries
actually live (transfers, shuffle IO, serialization, spill). This
tracer records host spans (enter/exit wall times, thread, parent,
query/op attribution) and exports Chrome trace-event JSON, so a host
timeline loads in Perfetto/chrome://tracing NEXT TO the Xprof device
trace the profiler collects.

Two layers:

* :class:`SpanTracer` / the process-wide :data:`TRACER` — collection is
  enabled per query by the session (``spark.rapids.trace.enabled``, or
  implicitly while the event log needs attribution). Disabled cost is
  one attribute read per site.
* :func:`install_observation` — the per-query exec-boundary wrapper
  (the ``install_fault_boundaries`` threading pattern from PR 3): every
  device exec's ``execute``/``execute_masked`` and the ``DeviceToHost``
  root get (a) a span per batch pull when tracing, and (b) the
  ESSENTIAL ``opTime``/``numOutputRows``/``numOutputBatches`` metrics
  ALWAYS — row counts that only exist on device are deferred and
  resolved in ONE batched fetch by :func:`finalize_observation`, never
  a per-batch sync.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

from spark_rapids_tpu.conf import bool_conf, str_conf

TRACE_ENABLED = bool_conf(
    "spark.rapids.trace.enabled", False,
    "Collect host-side spans for every query and export a Chrome "
    "trace-event JSON per query under spark.rapids.trace.dir — load it "
    "in Perfetto next to the Xprof device trace.")

TRACE_DIR = str_conf(
    "spark.rapids.trace.dir", "/tmp/rapids_tpu_trace",
    "Directory for exported Chrome trace JSON files (one "
    "query_<N>.trace.json per traced query).")

#: hard cap on buffered spans per query (a runaway batch loop must
#: degrade the trace, not the process); dropped spans are counted
_MAX_SPANS = 200_000


class Span:
    __slots__ = ("sid", "name", "cat", "t0", "t1", "tid", "tname",
                 "parent", "args")

    def __init__(self, sid, name, cat, t0, tid, tname, parent, args):
        self.sid = sid
        self.name = name
        self.cat = cat
        self.t0 = t0
        self.t1 = None
        self.tid = tid
        self.tname = tname
        self.parent = parent
        self.args = args

    @property
    def dur(self) -> float:
        return (self.t1 - self.t0) if self.t1 is not None else 0.0


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _LiveSpan:
    __slots__ = ("tracer", "span")

    def __init__(self, tracer, span):
        self.tracer = tracer
        self.span = span

    def __enter__(self):
        return self.span

    def __exit__(self, *exc):
        self.tracer.end(self.span)
        return False


class SpanTracer:
    """Process-wide span collector. ``enabled`` gates every record path;
    spans buffer between ``begin_query``/``end_query`` and drain into
    the caller (the session's event-log writer / trace exporter)."""

    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()
        self._spans: List[Span] = []
        self._dropped = 0
        self._next_id = 0
        self._tls = threading.local()
        self.query_id: Optional[int] = None
        self.main_tid: Optional[int] = None
        self._query_t0: Optional[float] = None

    # -- per-thread span stack ---------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    # -- collection --------------------------------------------------------
    def begin_query(self, query_id: int) -> None:
        # a failed prior query can leave unclosed spans on this thread's
        # stack (exception unwound mid-phase); start clean
        self._stack().clear()
        with self._lock:
            self._spans = []
            self._dropped = 0
            self.query_id = query_id
            self.main_tid = threading.get_ident()
            self._query_t0 = time.perf_counter()
            self.enabled = True

    def end_query(self) -> List[Span]:
        """Stop collecting and return the query's finished spans."""
        with self._lock:
            self.enabled = False
            spans = [s for s in self._spans if s.t1 is not None]
            self._spans = []
            self.query_id = None
            return spans

    @property
    def dropped(self) -> int:
        return self._dropped

    def begin(self, name: str, cat: str = "op", **args) -> Optional[Span]:
        if not self.enabled:
            return None
        st = self._stack()
        parent = st[-1].sid if st else None
        tid = threading.get_ident()
        with self._lock:
            if len(self._spans) >= _MAX_SPANS:
                self._dropped += 1
                return None
            self._next_id += 1
            sp = Span(self._next_id, name, cat, time.perf_counter(), tid,
                      threading.current_thread().name, parent, args or None)
            self._spans.append(sp)
        st.append(sp)
        return sp

    def end(self, span: Optional[Span]) -> None:
        if span is None or span.t1 is not None:
            return  # idempotent: an error path may re-end a closed span
        span.t1 = time.perf_counter()
        st = self._stack()
        if st and st[-1] is span:
            st.pop()
        elif span in st:        # exception unwound past nested spans
            while st and st[-1] is not span:
                st.pop().t1 = span.t1
            if st:
                st.pop()

    def span(self, name: str, cat: str = "op", **args):
        """Context manager; zero-allocation no-op when disabled."""
        if not self.enabled:
            return _NOOP
        return _LiveSpan(self, self.begin(name, cat, **args))


TRACER = SpanTracer()


def span(name: str, cat: str = "op", **args):
    return TRACER.span(name, cat, **args)


# ---------------------------------------------------------------------------
# Chrome trace-event export
# ---------------------------------------------------------------------------


def to_chrome_trace(spans: List[Span], query_id=None) -> dict:
    """Chrome trace-event JSON (the ``traceEvents`` array form) — loads
    in Perfetto / chrome://tracing. Timestamps are microseconds on the
    perf_counter clock; complete events (``ph: "X"``) carry durations."""
    events = []
    threads = {}
    for s in spans:
        threads.setdefault(s.tid, s.tname)
        ev = {"name": s.name, "cat": s.cat, "ph": "X",
              "ts": round(s.t0 * 1e6, 3), "dur": round(s.dur * 1e6, 3),
              "pid": 1, "tid": s.tid}
        if s.args:
            ev["args"] = dict(s.args)
        events.append(ev)
    for tid, tname in sorted(threads.items()):
        events.append({"name": "thread_name", "ph": "M", "pid": 1,
                       "tid": tid, "args": {"name": tname}})
    trace = {"traceEvents": events, "displayTimeUnit": "ms"}
    if query_id is not None:
        trace["otherData"] = {"query": query_id}
    return trace


def write_chrome_trace(path: str, spans: List[Span], query_id=None) -> str:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(spans, query_id), f)
    return path


# ---------------------------------------------------------------------------
# Span aggregation (the event record's span summary)
# ---------------------------------------------------------------------------


def union_seconds(intervals) -> float:
    """Total length covered by at least one [t0, t1) interval."""
    total = 0.0
    end = None
    for t0, t1 in sorted(intervals):
        if end is None or t0 > end:
            total += t1 - t0
            end = t1
        elif t1 > end:
            total += t1 - end
            end = t1
    return total


def summarize_spans(spans: List[Span], main_tid: Optional[int],
                    wall_s: float) -> dict:
    """Per-query span summary: category totals (union per category, so
    nesting never double-counts), attribution of the query wall to
    NAMED spans on the query's main thread, and worker-thread totals."""
    by_cat: Dict[str, list] = {}
    main_intervals = []
    worker: Dict[str, list] = {}
    for s in spans:
        by_cat.setdefault(s.cat, []).append((s.t0, s.t1))
        if s.tid == main_tid:
            if s.cat != "query":
                main_intervals.append((s.t0, s.t1))
        else:
            worker.setdefault(s.cat, []).append((s.t0, s.t1))
    attributed = min(union_seconds(main_intervals), wall_s)
    return {
        "byCategoryS": {c: round(union_seconds(iv), 6)
                        for c, iv in sorted(by_cat.items())},
        "workerByCategoryS": {c: round(union_seconds(iv), 6)
                              for c, iv in sorted(worker.items())},
        "attributedS": round(attributed, 6),
        "untrackedS": round(max(wall_s - attributed, 0.0), 6),
        "spanCount": len(spans),
    }


# ---------------------------------------------------------------------------
# Exec-boundary instrumentation
# ---------------------------------------------------------------------------


def _observed(fn, e, name: str, count_output: bool):
    """Wrap one execute/execute_masked with per-pull spans + metrics.
    The per-instance ``_obs_depth`` guard keeps the two protocol layers
    of one exec (execute() delegating to execute_masked() or vice
    versa, both instance-wrapped) from double-counting a batch."""

    def wrapped(*args, **kwargs):
        it = fn(*args, **kwargs)
        while True:
            if e._obs_depth:
                # inner protocol layer of the SAME exec: pass through
                try:
                    batch = next(it)
                except StopIteration:
                    return
                yield batch
                continue
            e._obs_depth = 1
            t0 = time.perf_counter()
            sp = TRACER.begin(name, "exec") if TRACER.enabled else None
            stop = False
            try:
                try:
                    batch = next(it)
                except StopIteration:
                    stop = True
            finally:
                TRACER.end(sp)
                e._obs_depth = 0
                e.metrics.add("opTime", time.perf_counter() - t0)
            if stop:
                if count_output:
                    # presence contract: an exec that ran to exhaustion
                    # always reports its output counts, even when zero
                    e.metrics.add("numOutputBatches", 0)
                    e.metrics.add("numOutputRows", 0)
                return
            if count_output:
                e.metrics.add("numOutputBatches", 1)
                nh = getattr(batch, "_nrows_host", None)
                if nh is not None:
                    e.metrics.add("numOutputRows", int(nh))
                else:
                    nd = getattr(batch, "nrows_dev", None)
                    if nd is not None:
                        # defer: nrows_dev is a tiny standalone device
                        # scalar — holding it pins ~4 bytes, not the
                        # table; finalize_observation fetches ALL
                        # pending counts in one host round trip
                        e._obs_pending_rows.append(nd)
                    else:
                        e.metrics.add("numOutputRows",
                                      int(getattr(batch, "num_rows", 0)))
            yield batch

    return wrapped


def install_observation(executable) -> None:
    """Wrap every device exec (and the DeviceToHost root) in the
    converted tree with the observation boundary. Installed per query by
    the session AFTER install_fault_boundaries, so spans/metrics see the
    fault-injected failures too. Idempotent per instance."""
    from spark_rapids_tpu.execs.base import DeviceToHost, TpuExec
    from spark_rapids_tpu.lore import _iter_tree
    for e in _iter_tree(executable):
        if getattr(e, "_obs_installed", False):
            continue
        if isinstance(e, TpuExec):
            e._obs_installed = True
            e._obs_depth = 0
            e._obs_pending_rows = []
            name = type(e).__name__
            e.execute = _observed(e.execute, e, name, count_output=True)
            e.execute_masked = _observed(e.execute_masked, e, name,
                                         count_output=True)
        elif isinstance(e, DeviceToHost):
            # DeviceToHost counts its own output rows on host (they are
            # free there) — the wrapper only adds opTime + the span
            e._obs_installed = True
            e._obs_depth = 0
            e._obs_pending_rows = []
            e.execute_cpu = _observed(e.execute_cpu, e, "DeviceToHost",
                                      count_output=False)


def finalize_observation(executable) -> None:
    """Resolve every deferred device row count in the tree with ONE
    batched host fetch (a single tunnel round trip however many execs
    deferred), folding the sums into each exec's ``numOutputRows``.
    Called lazily — by the event-log writer, ``session.last_metrics``
    and the metrics audit — so a query nobody inspects never pays the
    sync."""
    from spark_rapids_tpu.lore import _iter_tree
    owners = []
    scalars = []
    for e in _iter_tree(executable):
        pend = getattr(e, "_obs_pending_rows", None)
        if pend:
            owners.append((e, len(pend)))
            scalars.extend(pend)
            e._obs_pending_rows = []
    if not scalars:
        return
    from spark_rapids_tpu.dispatch import host_fetch
    fetched = host_fetch(scalars)
    i = 0
    for e, n in owners:
        total = sum(int(v) for v in fetched[i:i + n])
        i += n
        e.metrics.add("numOutputRows", total)
