"""The ``streaming`` metric scope (event-log schema v11).

Six counters, snapshotted/diffed per query by the event log like every
other scope, plus surfaced as per-record top-level fields
(``microBatches`` … ``sinkReplays``) so the tools can attribute
streaming work to individual envelopes.
"""

from __future__ import annotations

from spark_rapids_tpu.obs.metrics import metric_scope, register_metric

__all__ = ["STREAM_METRICS"]

register_metric("microBatches", "count", "ESSENTIAL",
                "micro-batches executed end-to-end (offsets logged, "
                "batch run, sink committed)")
register_metric("mvRefreshes", "count", "ESSENTIAL",
                "materialized-view refreshes of any strategy")
register_metric("mvIncrementalRefreshes", "count", "MODERATE",
                "MV refreshes served by delta recomputation "
                "(append or re-aggregate strategy)")
register_metric("mvFullRecomputes", "count", "MODERATE",
                "MV refreshes that fell back to a full recompute")
register_metric("sinkCommits", "count", "ESSENTIAL",
                "streaming sink transactional commits")
register_metric("sinkReplays", "count", "MODERATE",
                "replayed micro-batch sink commits skipped by the txn "
                "watermark (exactly-once dedupe)")

STREAM_METRICS = metric_scope("streaming")
