"""Columnar data model: host (numpy/Arrow) and device (XLA buffer) columns.

Reference surface being replaced: ai.rapids.cudf Table / ColumnVector /
HostColumnVector (SURVEY.md §2.9). TPU-first redesign:

* Static shapes: device columns are padded to lane-aligned power-of-two
  "buckets" so XLA compiles one program per (schema, bucket) instead of one
  per row count. The live row count rides along as a traced int32 scalar.
* Strings are order-preserving dictionary encoded per batch: the device only
  ever touches fixed-width int32 codes; the (small) dictionary stays on the
  host where variable-length work is cheap. Comparisons, sorts, group-bys and
  joins ride the code path; per-entry derived values (hashes, lengths,
  transformed strings) are computed host-side over the dictionary and
  gathered on device.
"""

from spark_rapids_tpu.columnar.column import (  # noqa: F401
    HostColumn,
    DeviceColumn,
    bucket_for,
    MIN_BUCKET,
)
from spark_rapids_tpu.columnar.table import (  # noqa: F401
    HostTable,
    DeviceTable,
)
